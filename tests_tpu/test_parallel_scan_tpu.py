"""REAL-TPU parallel-scan BPTT gate (ops/parallel_scan.py): compile the
associative-scan backward on the actual chip, assert gradient parity
against the sequential VJP, and measure warm train-step throughput
assoc vs sequential on the T=400 bucket.

This closes the CPU blind spot the same way
tests_tpu/test_pallas_decode_tpu.py does for the serve plane: the CPU
suite proves the ALGEBRA (tests/test_parallel_scan.py — grads allclose
at fp64-validated tolerances), but the perf claim is about the
accelerator's latency-bound sequential chain. On CPU the assoc path's
extra dense-compose FLOPs usually lose (the honest ratio lives in
BENCH_train_scan_r01.json); on TPU the log-depth tree of MXU matmuls
must be at least break-even at T=400 or the plan/tile is mis-chosen.

Perf gate: assoc tokens/s >= 1.0x sequential (median of warm repeats,
same jitted step, same data). The measured ratio prints either way —
the trajectory datapoint for the training-perf trendline.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lstm_tensorspark_tpu.models import LMConfig, init_lm
from lstm_tensorspark_tpu.models.lstm_lm import lm_loss
from lstm_tensorspark_tpu.ops import parallel_scan

pytestmark = pytest.mark.skipif(
    jax.default_backend() != "tpu", reason="requires a real TPU"
)

# the T=400 bucket (the IMDB sequence length — ROADMAP open item 2(b));
# H sized so the dense chunk-operator plan fits the default budget
B, T, V, H, L = 16, 400, 1024, 128, 1


def _step_fn(bptt):
    cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=L,
                   compute_dtype="bfloat16", bptt=bptt)

    @jax.jit
    def step(params, batch):
        (loss, _), grads = jax.value_and_grad(
            lambda p: lm_loss(p, batch, cfg), has_aux=True)(params)
        return loss, grads

    return cfg, step


def _batch(rng):
    toks = rng.randint(0, V, size=(B, T + 1)).astype(np.int32)
    return {"inputs": jnp.asarray(toks[:, :-1]),
            "targets": jnp.asarray(toks[:, 1:])}


def test_assoc_backward_compiles_and_matches_on_tpu():
    assert parallel_scan.plan_fits(B, T, H), (
        "gate config must fit the assoc plan — shrink H/B or raise "
        "LSTM_TSP_ASSOC_BUDGET_MB")
    rng = np.random.RandomState(0)
    batch = _batch(rng)
    cfg, step = _step_fn("assoc")
    params = init_lm(jax.random.PRNGKey(3), cfg)
    loss_a, grads_a = step(params, batch)
    _, step_s = _step_fn("sequential")
    loss_s, grads_s = step_s(params, batch)
    np.testing.assert_allclose(float(loss_a), float(loss_s),
                               rtol=1e-5, atol=1e-5)
    for a, b in zip(jax.tree.leaves(grads_a), jax.tree.leaves(grads_s)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=3e-2, atol=3e-3)


def test_train_step_perf_gate_t400():
    """Warm train-step throughput at T=400, assoc vs sequential — the
    parallel-scan backward must not be SLOWER than the chain it replaces
    (>= 1.0x tokens/s; the measured ratio prints as the trajectory
    datapoint either way)."""
    rng = np.random.RandomState(1)
    batch = _batch(rng)
    results = {}
    for mode in ("sequential", "assoc"):
        cfg, step = _step_fn(mode)
        params = init_lm(jax.random.PRNGKey(3), cfg)
        loss, grads = step(params, batch)   # compile + warm
        jax.block_until_ready(loss)
        times = []
        for _ in range(20):
            t0 = time.perf_counter()
            loss, grads = step(params, batch)
            jax.block_until_ready(loss)
            times.append(time.perf_counter() - t0)
        times.sort()
        med = times[len(times) // 2]
        results[mode] = B * T / med
    ratio = results["assoc"] / results["sequential"]
    print(f"\nassoc bptt T={T} B={B} H={H}: {results['assoc']:,.0f} tok/s "
          f"vs sequential {results['sequential']:,.0f} ({ratio:.2f}x)")
    assert ratio >= 1.0, (
        f"assoc backward SLOWER than sequential ({ratio:.2f}x) — re-plan "
        "the tile (pick_tile) or pin --bptt-mode sequential and investigate")
