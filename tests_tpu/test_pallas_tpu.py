"""REAL-TPU Pallas kernel tests: compile the forward and fused-backward
kernels through Mosaic on the actual chip and assert numeric parity against
the pure-jax scan, plus a short train-loss-trajectory match.

This closes the interpret-mode blind spot (VERDICT r1 weak #3): the CPU
suite runs every kernel with ``interpret=True``, which cannot catch a Mosaic
miscompile — in particular the tiled kernels' dynamically-indexed
``(K, B, tile)`` scratch reads, the one construct interpret mode cannot
vouch for. Each parametrized case pins the strategy it expects from the
VMEM cost model, so resident, tiled and padded paths are all compiled.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lstm_tensorspark_tpu.ops import init_lstm_params, lstm_scan
from lstm_tensorspark_tpu.ops.pallas_lstm import (
    _pad_to_lane,
    _plan_bwd,
    _plan_fwd,
    pallas_lstm_scan,
    supported,
)

pytestmark = pytest.mark.skipif(
    jax.default_backend() != "tpu", reason="requires a real TPU"
)


# (H, B, T, D, expected fwd strategy at padded H, f32)
CASES = [
    pytest.param(128, 8, 16, 32, "resident", id="resident-h128"),
    pytest.param(650, 8, 8, 48, "resident", id="padded-h650"),
    pytest.param(1024, 8, 8, 32, "tiled", id="tiled-h1024"),
    pytest.param(650, 64, 8, 48, "tiled", id="tiled-h650-b64"),
]


@pytest.mark.parametrize("H,B,T,D,strategy", CASES)
def test_mosaic_forward_parity(H, B, T, D, strategy):
    assert supported(B, H)
    hp = _pad_to_lane(H)
    assert _plan_fwd(B, hp, 4, save_residuals=False)[0] == strategy
    params = init_lstm_params(jax.random.PRNGKey(0), D, H)
    xs = jax.random.normal(jax.random.PRNGKey(1), (B, T, D))
    (hT, cT), ys = jax.jit(lambda p, x: pallas_lstm_scan(p, x))(params, xs)

    # The sharpest miscompile check: Mosaic must match interpret mode (the
    # SAME algorithm, same summation order) exactly.
    (hTi, cTi), ysi = pallas_lstm_scan(params, xs, interpret=True)
    np.testing.assert_array_equal(np.asarray(ys), np.asarray(ysi))
    np.testing.assert_array_equal(np.asarray(hT), np.asarray(hTi))
    np.testing.assert_array_equal(np.asarray(cT), np.asarray(cTi))

    # Scan parity at a tolerance admitting f32 non-associativity: the tiled
    # kernel sums K partial dots where the scan does one fused dot, and the
    # ~1e-7 rounding difference amplifies through the recurrence (measured
    # worst case ~1e-4 over T=8 on sensitive trajectories).
    (hT2, cT2), ys2 = jax.jit(lambda p, x: lstm_scan(p, x))(params, xs)
    np.testing.assert_allclose(ys, ys2, rtol=1e-4, atol=5e-4)
    np.testing.assert_allclose(hT, hT2, rtol=1e-4, atol=5e-4)
    np.testing.assert_allclose(cT, cT2, rtol=1e-4, atol=5e-4)


@pytest.mark.parametrize("H,B,T,D,strategy", CASES)
def test_mosaic_grad_parity(H, B, T, D, strategy):
    hp = _pad_to_lane(H)
    assert _plan_bwd(B, hp, 4) is not None  # fused backward compiles too
    params = init_lstm_params(jax.random.PRNGKey(2), D, H)
    xs = jax.random.normal(jax.random.PRNGKey(3), (B, T, D))

    def lp(p, x):
        return jnp.mean(pallas_lstm_scan(p, x)[1] ** 2)

    def lr(p, x):
        return jnp.mean(lstm_scan(p, x)[1] ** 2)

    g1 = jax.jit(jax.grad(lp, argnums=(0, 1)))(params, xs)
    g2 = jax.jit(jax.grad(lr, argnums=(0, 1)))(params, xs)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-4),
        g1, g2,
    )


@pytest.mark.parametrize("H,B,chunk", [
    pytest.param(650, 64, 2, id="bf16-resident-h768-b64"),   # config 3 layer
    pytest.param(1024, 32, 2, id="bf16-resident-h1024-b32"),  # config 5 layer
])
def test_mosaic_bf16_resident_bigh_vmem_pressure(H, B, chunk):
    """The r4 chunk-flexible plan flip ON SILICON (VERDICT r4 weak #1
    caveat): under bf16 streams, the bench configs 3/5 layer shapes plan
    the U-RESIDENT pair (U^T alone ~4.7/8.4 MiB bf16 against the 12 MiB
    budget). If the cost model under-counts VMEM, THIS case is where
    Mosaic fails to allocate — a compile failure here means the planner
    must fall back to tiled for these shapes, not that the test is wrong."""
    from lstm_tensorspark_tpu.ops.pallas_lstm import chosen_bwd_strategy

    T, D = 6, 32
    hp = _pad_to_lane(H)
    assert _plan_fwd(B, hp, 2, save_residuals=True)[0] == "resident"
    assert _plan_bwd(B, hp, 2) == ("resident", chunk)
    assert chosen_bwd_strategy(B, T, hp, 2) == "resident"

    params = init_lstm_params(jax.random.PRNGKey(6), D, H)
    xs = jax.random.normal(jax.random.PRNGKey(7), (B, T, D))

    def lp(p):
        return jnp.mean(pallas_lstm_scan(
            p, xs, compute_dtype=jnp.bfloat16)[1] ** 2)

    def lr(p):
        return jnp.mean(lstm_scan(p, xs, compute_dtype=jnp.bfloat16)[1] ** 2)

    # fwd+bwd compile through Mosaic at the REAL bench shape and stay
    # within bf16 tolerance of the reference scan
    g1 = jax.jit(jax.grad(lp))(params)
    g2 = jax.jit(jax.grad(lr))(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=0.1, atol=0.02,
        ),
        g1, g2,
    )


def test_mosaic_bf16_grad_tolerance():
    """bf16 matmuls through Mosaic stay within bf16 tolerance of f32 scan."""
    params = init_lstm_params(jax.random.PRNGKey(4), 64, 1024)
    xs = jax.random.normal(jax.random.PRNGKey(5), (8, 8, 64))

    def lp(p):
        return jnp.mean(
            pallas_lstm_scan(p, xs, compute_dtype=jnp.bfloat16)[1] ** 2
        )

    def lr(p):
        return jnp.mean(lstm_scan(p, xs)[1] ** 2)

    g1 = jax.jit(jax.grad(lp))(params)
    g2 = jax.jit(jax.grad(lr))(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=0.1, atol=0.02,
        ),
        g1, g2,
    )


def test_train_loss_trajectory_matches_scan():
    """Short LM training: the pallas step and the scan step must produce
    matching loss trajectories (same init, same data) on the real chip —
    the end-to-end check that the custom VJP plugs into the optimizer
    correctly under Mosaic."""
    from lstm_tensorspark_tpu.models import LMConfig, init_lm, lm_loss
    from lstm_tensorspark_tpu.train import make_optimizer, make_train_step
    from lstm_tensorspark_tpu.train.loop import init_train_state

    V, B, T = 32, 16, 32

    def run(use_pallas):
        cfg = LMConfig(vocab_size=V, hidden_size=128, num_layers=1,
                       use_pallas=use_pallas)
        params = init_lm(jax.random.PRNGKey(6), cfg)
        opt = make_optimizer("sgd", 0.5)

        def loss_fn(p, batch, rng):
            return lm_loss(p, batch, cfg, dropout_rng=rng, deterministic=True)

        step = make_train_step(loss_fn, opt)
        state = init_train_state(params, opt, jax.random.PRNGKey(7))
        data = jax.random.randint(jax.random.PRNGKey(8), (B, T + 1), 0, V)
        batch = {"inputs": data[:, :-1], "targets": data[:, 1:]}
        losses = []
        for _ in range(10):
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        return losses

    lp = run(True)
    lr = run(False)
    np.testing.assert_allclose(lp, lr, rtol=2e-3, atol=2e-3)
    assert lp[-1] < lp[0]  # it actually learns


# ---------------------------------------------------------------------------
# masked / reversed kernels on-chip (round 3: the configs-2/4 fused paths)
# ---------------------------------------------------------------------------


def _lengths_mask(key, b, t):
    lengths = jax.random.randint(key, (b,), 1, t + 1)
    return jnp.arange(t)[None, :] < lengths[:, None]


MASKED_CASES = [
    pytest.param(128, 8, 16, 32, id="masked-resident-h128"),
    pytest.param(256, 64, 16, 64, id="masked-resident-h256-b64"),  # config-2 shape class
    pytest.param(1024, 8, 8, 32, id="masked-tiled-h1024"),
    pytest.param(650, 8, 8, 48, id="masked-padded-h650"),
]


@pytest.mark.parametrize("H,B,T,D", MASKED_CASES)
def test_mosaic_masked_parity(H, B, T, D):
    """Masked forward+backward through Mosaic: bit-match interpret mode,
    tolerance-match the scan (the lane-broadcast mask read `[:, :1]` is the
    new construct interpret mode cannot vouch for)."""
    assert supported(B, H, has_mask=True)
    params = init_lstm_params(jax.random.PRNGKey(0), D, H)
    xs = jax.random.normal(jax.random.PRNGKey(1), (B, T, D))
    mask = _lengths_mask(jax.random.PRNGKey(2), B, T)

    (hT, cT), ys = jax.jit(
        lambda p, x: pallas_lstm_scan(p, x, mask=mask)
    )(params, xs)
    (hTi, cTi), ysi = pallas_lstm_scan(params, xs, mask=mask, interpret=True)
    np.testing.assert_array_equal(np.asarray(ys), np.asarray(ysi))
    np.testing.assert_array_equal(np.asarray(hT), np.asarray(hTi))
    np.testing.assert_array_equal(np.asarray(cT), np.asarray(cTi))

    (hT2, cT2), ys2 = jax.jit(lambda p, x: lstm_scan(p, x, mask=mask))(params, xs)
    np.testing.assert_allclose(ys, ys2, rtol=1e-4, atol=5e-4)
    np.testing.assert_allclose(hT, hT2, rtol=1e-4, atol=5e-4)
    np.testing.assert_allclose(cT, cT2, rtol=1e-4, atol=5e-4)

    def lp(p, x):
        return jnp.mean(pallas_lstm_scan(p, x, mask=mask)[1] ** 2)

    def lr(p, x):
        return jnp.mean(lstm_scan(p, x, mask=mask)[1] ** 2)

    g1 = jax.jit(jax.grad(lp, argnums=(0, 1)))(params, xs)
    g2 = jax.jit(jax.grad(lr, argnums=(0, 1)))(params, xs)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-4),
        g1, g2,
    )


def test_mosaic_masked_reverse_parity():
    """The bi-LSTM backward direction on-chip: reversed masked scan."""
    H, B, T, D = 256, 64, 32, 64
    params = init_lstm_params(jax.random.PRNGKey(3), D, H)
    xs = jax.random.normal(jax.random.PRNGKey(4), (B, T, D))
    mask = _lengths_mask(jax.random.PRNGKey(5), B, T)

    def lp(p, x):
        (hT, cT), ys = pallas_lstm_scan(p, x, mask=mask, reverse=True)
        return jnp.mean(ys**2) + jnp.sum(hT * 0.3) + jnp.sum(cT * 0.1)

    def lr(p, x):
        (hT, cT), ys = lstm_scan(p, x, mask=mask, reverse=True)
        return jnp.mean(ys**2) + jnp.sum(hT * 0.3) + jnp.sum(cT * 0.1)

    np.testing.assert_allclose(
        jax.jit(lp)(params, xs), jax.jit(lr)(params, xs), rtol=1e-4, atol=1e-4
    )
    # atol 2e-3: f32 non-associativity (kernel vs scan summation order)
    # amplified over the T=32 recurrence — interpret mode on CPU shows the
    # SAME ~1.3e-3 worst case vs the scan, so this is algorithmic, not a
    # Mosaic miscompile (Mosaic≡interpret stays the bit-exact check above)
    g1 = jax.jit(jax.grad(lp, argnums=(0, 1)))(params, xs)
    g2 = jax.jit(jax.grad(lr, argnums=(0, 1)))(params, xs)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-3, atol=2e-3),
        g1, g2,
    )


def test_classifier_pallas_train_trajectory():
    """Config-2-class bi-LSTM: use_pallas vs scan training trajectories
    must match on-chip (end-to-end check of both directions' fused paths)."""
    from lstm_tensorspark_tpu.models.classifier import (
        ClassifierConfig, classifier_loss, init_classifier,
    )
    from lstm_tensorspark_tpu.train import make_optimizer, make_train_step
    from lstm_tensorspark_tpu.train.loop import init_train_state

    V, B, T = 64, 32, 40

    def run(use_pallas):
        cfg = ClassifierConfig(vocab_size=V, hidden_size=128,
                               use_pallas=use_pallas)
        params = init_classifier(jax.random.PRNGKey(6), cfg)
        opt = make_optimizer("sgd", 0.5)

        def loss_fn(p, batch, rng):
            return classifier_loss(p, batch, cfg, dropout_rng=rng,
                                   deterministic=True)

        step = make_train_step(loss_fn, opt)
        state = init_train_state(params, opt, jax.random.PRNGKey(7))
        tokens = jax.random.randint(jax.random.PRNGKey(8), (B, T), 0, V)
        lengths = jax.random.randint(jax.random.PRNGKey(9), (B,), 1, T + 1)
        labels = jax.random.randint(jax.random.PRNGKey(10), (B,), 0, 2)
        batch = {"tokens": tokens, "lengths": lengths, "labels": labels,
                 "valid": jnp.ones((B,), jnp.float32)}
        losses = []
        for _ in range(8):
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        return losses

    lp = run(True)
    lr = run(False)
    np.testing.assert_allclose(lp, lr, rtol=2e-3, atol=2e-3)
    assert lp[-1] < lp[0]


def test_seq2seq_pallas_train_trajectory():
    """Config-4-class seq2seq: use_pallas vs scan trajectories on-chip."""
    from lstm_tensorspark_tpu.models.seq2seq import (
        Seq2SeqConfig, init_seq2seq, seq2seq_loss,
    )
    from lstm_tensorspark_tpu.train import make_optimizer, make_train_step
    from lstm_tensorspark_tpu.train.loop import init_train_state

    B, T, F, HZ = 16, 48, 8, 8

    def run(use_pallas):
        cfg = Seq2SeqConfig(num_features=F, hidden_size=128, horizon=HZ,
                            use_pallas=use_pallas)
        params = init_seq2seq(jax.random.PRNGKey(11), cfg)
        opt = make_optimizer("sgd", 0.1)

        def loss_fn(p, batch, rng):
            return seq2seq_loss(p, batch, cfg, dropout_rng=rng,
                                deterministic=True)

        step = make_train_step(loss_fn, opt)
        state = init_train_state(params, opt, jax.random.PRNGKey(12))
        ctx = jax.random.normal(jax.random.PRNGKey(13), (B, T, F))
        tgt = jax.random.normal(jax.random.PRNGKey(14), (B, HZ, F)) * 0.1
        batch = {"context": ctx, "targets": tgt}
        losses = []
        for _ in range(8):
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        return losses

    lp = run(True)
    lr = run(False)
    np.testing.assert_allclose(lp, lr, rtol=2e-3, atol=2e-3)
    assert lp[-1] < lp[0]


def test_pp_wavefront_with_pallas_compiles_on_chip():
    """PP wavefront with fused stage interiors through Mosaic: a pp=1 mesh
    (one real chip) still runs pp_lm_loss's shard_map + pallas_call
    composition — the construct the CPU-mesh test can only interpret.
    Parity against the plain-scan PP step on the same mesh."""
    from lstm_tensorspark_tpu.models import LMConfig, init_lm
    from lstm_tensorspark_tpu.parallel import make_mesh
    from lstm_tensorspark_tpu.parallel.pipeline_parallel import (
        make_pp_lm_train_step, place_pp_lm_params, stack_lm_params,
    )
    from lstm_tensorspark_tpu.train import make_optimizer
    from lstm_tensorspark_tpu.train.loop import init_train_state

    V, H, B, T = 64, 256, 16, 32

    def run(use_pallas):
        cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=2,
                       use_pallas=use_pallas)
        opt = make_optimizer("sgd", 0.5)
        params = init_lm(jax.random.PRNGKey(15), cfg)
        mesh = make_mesh(dp=1, pp=1)
        stacked = stack_lm_params(params)
        placed = place_pp_lm_params(stacked, mesh)
        step = make_pp_lm_train_step(cfg, opt, mesh, stacked,
                                     microbatches=2, donate=False)
        s = init_train_state(placed, opt, jax.random.PRNGKey(16))
        data = jax.random.randint(jax.random.PRNGKey(17), (B, T + 1), 0, V)
        batch = {"inputs": data[:, :-1], "targets": data[:, 1:]}
        losses = []
        for _ in range(6):
            s, m = step(s, batch)
            losses.append(float(m["loss"]))
        return losses

    lp = run(True)
    lr = run(False)
    np.testing.assert_allclose(lp, lr, rtol=2e-3, atol=2e-3)
    assert lp[-1] < lp[0]


def test_mosaic_residentx_long_sequence_parity():
    """The fully-fused residentx pair through Mosaic at its REAL activation
    shape (config-2 class: T=400 >= _FUSEDX_MIN_T, masked): in-kernel
    projection forward + recompute-z backward must match the scan."""
    from lstm_tensorspark_tpu.ops.pallas_lstm import _FUSEDX_MIN_T, _plan_bwd

    H, B, T, D = 256, 64, 400, 256
    assert T >= _FUSEDX_MIN_T
    assert _plan_bwd(B, H, 4, True, 256)[0] == "residentx"
    params = init_lstm_params(jax.random.PRNGKey(20), D, H)
    xs = jax.random.normal(jax.random.PRNGKey(21), (B, T, D)) * 0.3
    mask = _lengths_mask(jax.random.PRNGKey(22), B, T)

    (hT, cT), ys = jax.jit(lambda p, x: pallas_lstm_scan(p, x, mask=mask))(params, xs)
    # NOT bit-exact vs interpret (unlike the hoisted kernels): the in-kernel
    # chunk projection's K-dim accumulation order differs between the MXU
    # and interpret's CPU dot; ~1e-7 rounding amplifies over T=400.
    (hTi, cTi), ysi = pallas_lstm_scan(params, xs, mask=mask, interpret=True)
    np.testing.assert_allclose(np.asarray(hT), np.asarray(hTi),
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(np.asarray(cT), np.asarray(cTi),
                               rtol=1e-3, atol=1e-3)

    def lp(p, x):
        return jnp.mean(pallas_lstm_scan(p, x, mask=mask)[1] ** 2)

    def lr(p, x):
        return jnp.mean(lstm_scan(p, x, mask=mask)[1] ** 2)

    g1 = jax.jit(jax.grad(lp, argnums=(0, 1)))(params, xs)
    g2 = jax.jit(jax.grad(lr, argnums=(0, 1)))(params, xs)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-3, atol=2e-3),
        g1, g2,
    )



def test_mosaic_bilstm_stacked_directions_parity():
    """The stacked-direction bi-LSTM kernel (ops/pallas_bilstm.py) through
    Mosaic at config 2's real shape class (T=400 masked, H=256, B=64):
    forward AND recompute-z backward of BOTH chains in one pallas_call
    must match the two-call pure-jax reference."""
    from lstm_tensorspark_tpu.ops.pallas_bilstm import (
        bilstm_supported, pallas_bilstm_scan,
    )

    H, B, T, D = 256, 64, 400, 256
    assert bilstm_supported(B, H, D, T, has_mask=True)
    pf = init_lstm_params(jax.random.PRNGKey(30), D, H)
    pb = init_lstm_params(jax.random.PRNGKey(31), D, H)
    xs = jax.random.normal(jax.random.PRNGKey(32), (B, T, D)) * 0.3
    mask = _lengths_mask(jax.random.PRNGKey(33), B, T)

    got = jax.jit(
        lambda pf, pb, x: pallas_bilstm_scan(pf, pb, x, mask=mask)
    )(pf, pb, xs)
    want_f = lstm_scan(pf, xs, mask=mask)
    want_b = lstm_scan(pb, xs, mask=mask, reverse=True)
    for (g, w) in ((got[0], want_f), (got[1], want_b)):
        np.testing.assert_allclose(np.asarray(g[1]), np.asarray(w[1]),
                                   rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(np.asarray(g[0][0]), np.asarray(w[0][0]),
                                   rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(np.asarray(g[0][1]), np.asarray(w[0][1]),
                                   rtol=1e-3, atol=1e-3)

    def lp(pf, pb, x):
        ((hf, _), ysf), ((_, cb), ysb) = pallas_bilstm_scan(
            pf, pb, x, mask=mask)
        return jnp.mean(ysf ** 2) + jnp.mean(ysb ** 2) + jnp.mean(hf + cb)

    def lr(pf, pb, x):
        (hf, _), ysf = lstm_scan(pf, x, mask=mask)
        (_, cb), ysb = lstm_scan(pb, x, mask=mask, reverse=True)
        return jnp.mean(ysf ** 2) + jnp.mean(ysb ** 2) + jnp.mean(hf + cb)

    g1 = jax.jit(jax.grad(lp, argnums=(0, 1, 2)))(pf, pb, xs)
    g2 = jax.jit(jax.grad(lr, argnums=(0, 1, 2)))(pf, pb, xs)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-3, atol=2e-3),
        g1, g2,
    )


def test_sp_wavefront_with_pallas_compiles_on_chip():
    """SP x Pallas (VERDICT r3 item 4): the fused kernel inside the
    sequence-parallel wavefront's ALL-manual shard_map must Mosaic-compile
    and train. One chip => sp=1 mesh: the wavefront machinery runs (manual
    axes, ppermute elided at S=1), isolating the kernel-inside-shard_map
    surface that scales to real sp>1 meshes unchanged (chunks are
    collective-free)."""
    import optax

    from lstm_tensorspark_tpu.models import LMConfig, init_lm
    from lstm_tensorspark_tpu.parallel import make_mesh
    from lstm_tensorspark_tpu.parallel.train_step import (
        make_sharded_lm_train_step,
    )
    from lstm_tensorspark_tpu.train.loop import init_train_state

    V, B, T = 50, 16, 32
    mesh = make_mesh(dp=1, tp=1, sp=1, devices=jax.devices()[:1])
    data = jax.random.randint(jax.random.PRNGKey(40), (B, T + 1), 0, V)
    batch = {"inputs": data[:, :-1], "targets": data[:, 1:]}

    def run(use_pallas):
        cfg = LMConfig(vocab_size=V, hidden_size=128, num_layers=1,
                       use_pallas=use_pallas)
        params = init_lm(jax.random.PRNGKey(41), cfg)
        opt = optax.sgd(0.3)
        step = make_sharded_lm_train_step(cfg, opt, mesh, params,
                                          microbatches=2, donate=False)
        state = init_train_state(params, opt, jax.random.PRNGKey(42))
        losses = []
        for _ in range(6):
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        return losses

    lp = run(True)
    lr = run(False)
    np.testing.assert_allclose(lp, lr, rtol=2e-3, atol=2e-3)
    assert lp[-1] < lp[0]
