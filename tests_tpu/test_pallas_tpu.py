"""REAL-TPU Pallas kernel tests: compile the forward and fused-backward
kernels through Mosaic on the actual chip and assert numeric parity against
the pure-jax scan, plus a short train-loss-trajectory match.

This closes the interpret-mode blind spot (VERDICT r1 weak #3): the CPU
suite runs every kernel with ``interpret=True``, which cannot catch a Mosaic
miscompile — in particular the tiled kernels' dynamically-indexed
``(K, B, tile)`` scratch reads, the one construct interpret mode cannot
vouch for. Each parametrized case pins the strategy it expects from the
VMEM cost model, so resident, tiled and padded paths are all compiled.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from lstm_tensorspark_tpu.ops import init_lstm_params, lstm_scan
from lstm_tensorspark_tpu.ops.pallas_lstm import (
    _pad_to_lane,
    _plan_bwd,
    _plan_fwd,
    pallas_lstm_scan,
    supported,
)

pytestmark = pytest.mark.skipif(
    jax.default_backend() != "tpu", reason="requires a real TPU"
)


# (H, B, T, D, expected fwd strategy at padded H, f32)
CASES = [
    pytest.param(128, 8, 16, 32, "resident", id="resident-h128"),
    pytest.param(650, 8, 8, 48, "resident", id="padded-h650"),
    pytest.param(1024, 8, 8, 32, "tiled", id="tiled-h1024"),
    pytest.param(650, 64, 8, 48, "tiled", id="tiled-h650-b64"),
]


@pytest.mark.parametrize("H,B,T,D,strategy", CASES)
def test_mosaic_forward_parity(H, B, T, D, strategy):
    assert supported(B, H)
    hp = _pad_to_lane(H)
    assert _plan_fwd(B, hp, 4, save_residuals=False)[0] == strategy
    params = init_lstm_params(jax.random.PRNGKey(0), D, H)
    xs = jax.random.normal(jax.random.PRNGKey(1), (B, T, D))
    (hT, cT), ys = jax.jit(lambda p, x: pallas_lstm_scan(p, x))(params, xs)

    # The sharpest miscompile check: Mosaic must match interpret mode (the
    # SAME algorithm, same summation order) exactly.
    (hTi, cTi), ysi = pallas_lstm_scan(params, xs, interpret=True)
    np.testing.assert_array_equal(np.asarray(ys), np.asarray(ysi))
    np.testing.assert_array_equal(np.asarray(hT), np.asarray(hTi))
    np.testing.assert_array_equal(np.asarray(cT), np.asarray(cTi))

    # Scan parity at a tolerance admitting f32 non-associativity: the tiled
    # kernel sums K partial dots where the scan does one fused dot, and the
    # ~1e-7 rounding difference amplifies through the recurrence (measured
    # worst case ~1e-4 over T=8 on sensitive trajectories).
    (hT2, cT2), ys2 = jax.jit(lambda p, x: lstm_scan(p, x))(params, xs)
    np.testing.assert_allclose(ys, ys2, rtol=1e-4, atol=5e-4)
    np.testing.assert_allclose(hT, hT2, rtol=1e-4, atol=5e-4)
    np.testing.assert_allclose(cT, cT2, rtol=1e-4, atol=5e-4)


@pytest.mark.parametrize("H,B,T,D,strategy", CASES)
def test_mosaic_grad_parity(H, B, T, D, strategy):
    hp = _pad_to_lane(H)
    assert _plan_bwd(B, hp, 4) is not None  # fused backward compiles too
    params = init_lstm_params(jax.random.PRNGKey(2), D, H)
    xs = jax.random.normal(jax.random.PRNGKey(3), (B, T, D))

    def lp(p, x):
        return jnp.mean(pallas_lstm_scan(p, x)[1] ** 2)

    def lr(p, x):
        return jnp.mean(lstm_scan(p, x)[1] ** 2)

    g1 = jax.jit(jax.grad(lp, argnums=(0, 1)))(params, xs)
    g2 = jax.jit(jax.grad(lr, argnums=(0, 1)))(params, xs)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-3, atol=1e-4),
        g1, g2,
    )


def test_mosaic_bf16_grad_tolerance():
    """bf16 matmuls through Mosaic stay within bf16 tolerance of f32 scan."""
    params = init_lstm_params(jax.random.PRNGKey(4), 64, 1024)
    xs = jax.random.normal(jax.random.PRNGKey(5), (8, 8, 64))

    def lp(p):
        return jnp.mean(
            pallas_lstm_scan(p, xs, compute_dtype=jnp.bfloat16)[1] ** 2
        )

    def lr(p):
        return jnp.mean(lstm_scan(p, xs)[1] ** 2)

    g1 = jax.jit(jax.grad(lp))(params)
    g2 = jax.jit(jax.grad(lr))(params)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=0.1, atol=0.02,
        ),
        g1, g2,
    )


def test_train_loss_trajectory_matches_scan():
    """Short LM training: the pallas step and the scan step must produce
    matching loss trajectories (same init, same data) on the real chip —
    the end-to-end check that the custom VJP plugs into the optimizer
    correctly under Mosaic."""
    from lstm_tensorspark_tpu.models import LMConfig, init_lm, lm_loss
    from lstm_tensorspark_tpu.train import make_optimizer, make_train_step
    from lstm_tensorspark_tpu.train.loop import init_train_state

    V, B, T = 32, 16, 32

    def run(use_pallas):
        cfg = LMConfig(vocab_size=V, hidden_size=128, num_layers=1,
                       use_pallas=use_pallas)
        params = init_lm(jax.random.PRNGKey(6), cfg)
        opt = make_optimizer("sgd", 0.5)

        def loss_fn(p, batch, rng):
            return lm_loss(p, batch, cfg, dropout_rng=rng, deterministic=True)

        step = make_train_step(loss_fn, opt)
        state = init_train_state(params, opt, jax.random.PRNGKey(7))
        data = jax.random.randint(jax.random.PRNGKey(8), (B, T + 1), 0, V)
        batch = {"inputs": data[:, :-1], "targets": data[:, 1:]}
        losses = []
        for _ in range(10):
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        return losses

    lp = run(True)
    lr = run(False)
    np.testing.assert_allclose(lp, lr, rtol=2e-3, atol=2e-3)
    assert lp[-1] < lp[0]  # it actually learns
