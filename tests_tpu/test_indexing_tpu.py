"""REAL-TPU tests for the vocabulary-indexing and logits-dtype paths
(ops/embedding.py, LMConfig.logits_dtype) — the round-3 perf work that is
platform-gated (selected_logits takes the one-hot form on TPU at ANY vocab
size) and therefore not fully exercised by the CPU suite.

Pins on hardware: one-hot ≡ gather bit-equality at a word-LM vocab, the
embedding custom-VJP matmul backward vs the scatter formulation, and the
bf16-logits loss staying within bf16 rounding of the f32 loss on the same
batch (the property the +25% config-3 win rests on).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.skipif(
    jax.default_backend() != "tpu", reason="requires a real TPU"
)


def test_selected_logits_onehot_matches_gather_large_vocab_on_tpu():
    V, B, T = 33_278, 8, 12
    k1, k2 = jax.random.split(jax.random.PRNGKey(0))
    logits = jax.random.normal(k1, (B, T, V), jnp.float32)
    tgt = jax.random.randint(k2, (B, T), 0, V, jnp.int32)

    from lstm_tensorspark_tpu.ops.embedding import selected_logits

    got = jax.jit(selected_logits)(logits, tgt)  # one-hot path on TPU
    ref = jnp.take_along_axis(logits, tgt[..., None], axis=-1)[..., 0]
    np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_embed_lookup_matmul_grad_on_tpu():
    V, E, N = 512, 128, 1024
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(1), 3)
    emb = jax.random.normal(k1, (V, E), jnp.float32)
    toks = jax.random.randint(k2, (N,), 0, V, jnp.int32)
    cot = jax.random.normal(k3, (N, E), jnp.float32)

    from lstm_tensorspark_tpu.ops.embedding import embed_lookup

    g_fast = jax.jit(jax.grad(
        lambda e: jnp.vdot(embed_lookup(e, toks), cot)))(emb)
    g_ref = jax.jit(jax.grad(
        lambda e: jnp.vdot(jnp.take(e, toks, axis=0), cot)))(emb)
    np.testing.assert_allclose(np.asarray(g_fast), np.asarray(g_ref),
                               rtol=2e-5, atol=2e-5)


def test_bf16_logits_loss_close_on_tpu():
    from lstm_tensorspark_tpu.models import LMConfig, init_lm, lm_loss

    mk = lambda ld: LMConfig(vocab_size=1000, hidden_size=64,  # noqa: E731
                             compute_dtype="bfloat16", logits_dtype=ld)
    params = init_lm(jax.random.PRNGKey(2), mk("float32"))
    toks = jax.random.randint(jax.random.PRNGKey(3), (8, 16 + 1), 0, 1000,
                              jnp.int32)
    batch = {"inputs": toks[:, :-1], "targets": toks[:, 1:]}

    l32 = jax.jit(lambda p, b: lm_loss(p, b, mk("float32"))[0])(params, batch)
    l16 = jax.jit(lambda p, b: lm_loss(p, b, mk("bfloat16"))[0])(params, batch)
    np.testing.assert_allclose(np.asarray(l16), np.asarray(l32), rtol=2e-2)
