"""REAL-TPU decode-window kernel gate (ops/pallas_decode.py): compile the
fused window kernel through Mosaic on the actual chip, assert token
parity against the `lax.scan` window and `models/generate.py`, and
measure the windowed decode throughput pallas vs scan.

This closes the interpret-mode blind spot for the SERVE plane the same
way tests_tpu/test_pallas_tpu.py does for training: the CPU suite
(tests/test_pallas_decode.py) runs the kernel with ``interpret=True``,
which cannot catch a Mosaic miscompile — in particular the unrolled
K-step one-hot/argmax chain and the int32 latch vectors, the constructs
this kernel adds over the training kernels.

Perf gate: the fused window must not be SLOWER than the scan window on
the same bucket (>= 1.0x tokens/s, measured warm, median of repeats) —
the kernel deletes K-1 per-step HBM round-trips of carries and logits,
so parity-at-best would mean the kernel is mis-planned. The measured
ratio prints either way (the honest datapoint for BENCH trajectories).
"""

import time

import jax
import numpy as np
import pytest

from lstm_tensorspark_tpu.models import LMConfig, init_lm, make_generate_fn
from lstm_tensorspark_tpu.serve import ServeEngine
from lstm_tensorspark_tpu.serve.engine import GREEDY, SamplingParams

pytestmark = pytest.mark.skipif(
    jax.default_backend() != "tpu", reason="requires a real TPU"
)

# (vocab, hidden, layers, batch, K) — small + a serving-realistic shape
CASES = [
    pytest.param(89, 128, 2, 8, 8, id="v89-h128-b8-k8"),
    pytest.param(1024, 256, 2, 16, 8, id="v1024-h256-b16-k8"),
]


def _engines(cfg, params, batch):
    kw = dict(num_slots=batch * 2, prefill_buckets=(8, 16),
              batch_buckets=(1, batch))
    return (ServeEngine(params, cfg, decode_kernel="pallas", **kw),
            ServeEngine(params, cfg, decode_kernel="scan", **kw))


@pytest.mark.parametrize("vocab,hidden,layers,batch,k", CASES)
def test_compiled_window_token_parity(vocab, hidden, layers, batch, k):
    cfg = LMConfig(vocab_size=vocab, hidden_size=hidden, num_layers=layers)
    params = init_lm(jax.random.PRNGKey(3), cfg)
    ep, es = _engines(cfg, params, batch)
    assert not ep._pallas_interpret  # compiled Mosaic, not interpret
    rng = np.random.RandomState(7)
    prompts = [rng.randint(0, vocab, size=6).astype(np.int32)
               for _ in range(batch)]
    outs = {}
    for name, e in (("pallas", ep), ("scan", es)):
        slots = []
        for i, p in enumerate(prompts):
            slot, _ = e.cache.acquire(f"s{i}")
            slots.append(slot)
        first = e.prefill([(s, True, p) for s, p in zip(slots, prompts)])
        win = e.decode_window(slots, [int(t) for t in first],
                              [2 * k] * batch, window=k)
        win = e.decode_window_next(win)
        toks, rem, alive = e.fetch_window_summary(win)
        outs[name] = ([int(t) for t in first], toks.tolist(),
                      rem.tolist(), alive.tolist())
    assert outs["pallas"] == outs["scan"]
    assert any(key[0] == "decode_window_pallas"
               for key in ep.compile_counts)
    # and against the uninterrupted reference program for row 0
    gen = make_generate_fn(cfg, max_new_tokens=2 * k + 1, greedy=True)
    ref = np.asarray(gen(params, prompts[0][None, :],
                         jax.random.PRNGKey(0)))[0, prompts[0].size:]
    first, toks, _, _ = outs["pallas"]
    # second window's row 0 = tokens k..2k of the continuation
    np.testing.assert_array_equal(np.asarray(toks[0]), ref[k + 1:])


def test_compiled_window_sampled_parity():
    cfg = LMConfig(vocab_size=89, hidden_size=128, num_layers=2)
    params = init_lm(jax.random.PRNGKey(3), cfg)
    samp = SamplingParams(temperature=0.8)
    ep, es = _engines(cfg, params, 8)
    outs = {}
    for name, e in (("pallas", ep), ("scan", es)):
        slot, _ = e.cache.acquire("s")
        first = e.prefill([(slot, True, np.arange(1, 7, dtype=np.int32))],
                          samp)
        win = e.decode_window([slot], [int(first[0])], [8], sampling=samp,
                              window=8)
        outs[name] = ([int(first[0])],
                      ServeEngine.fetch_window(win).tolist())
    assert outs["pallas"] == outs["scan"]


@pytest.mark.parametrize("vocab,hidden,layers,batch,k", CASES)
def test_windowed_decode_perf_gate(vocab, hidden, layers, batch, k):
    """Warm windowed-decode throughput, pallas vs scan, same bucket —
    the fused kernel must be >= 1.0x (it deletes the per-step HBM
    round-trips; the measured ratio prints as the trajectory datapoint)."""
    cfg = LMConfig(vocab_size=vocab, hidden_size=hidden, num_layers=layers)
    params = init_lm(jax.random.PRNGKey(3), cfg)
    ep, es = _engines(cfg, params, batch)

    def run(e, reps=30):
        slots = []
        for i in range(batch):
            slot, _ = e.cache.acquire(f"p{i}")
            slots.append(slot)
        e.warmup(GREEDY, prompt_lens=(8,), batch_sizes=(batch,),
                 windows=(k,))
        toks = [0] * batch
        times = []
        for _ in range(reps):
            t0 = time.perf_counter()
            win = e.decode_window(slots, toks, [10 * k] * batch, window=k)
            ServeEngine.fetch_window(win)
            times.append(time.perf_counter() - t0)
        times.sort()
        med = times[len(times) // 2]
        return batch * k / med  # tokens/s

    tps_scan = run(es)
    tps_pallas = run(ep)
    ratio = tps_pallas / tps_scan
    print(f"\npallas decode window {vocab=} {hidden=} {batch=} {k=}: "
          f"{tps_pallas:,.0f} tok/s vs scan {tps_scan:,.0f} "
          f"({ratio:.2f}x)")
    assert ratio >= 1.0, (
        f"fused window SLOWER than scan ({ratio:.2f}x) — mis-planned "
        "kernel; pin --decode-kernel scan and investigate")
