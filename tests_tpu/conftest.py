"""On-TPU test suite — runs on the real chip (NO platform forcing here,
unlike tests/conftest.py which pins the 8-device CPU mesh).

Run: ``python -m pytest tests_tpu/ -x -q`` on a machine with a TPU attached.
Every module skips itself when no TPU is present, so this directory is safe
to include in any environment.

WEDGE-PROOF COLLECTION: each module's skip check calls
``jax.default_backend()`` at import time, which blocks forever inside
backend init when the tunneled chip is wedged (observed repeatedly on this
environment) — a plain ``pytest tests_tpu/`` would hang before a single
skip could fire. So this conftest first probes the backend in a SUBPROCESS
with a hard timeout; on timeout it ignores every test module (collection
finds nothing, the run exits in ~60 s). A cleanly-failing TPU init is NOT
ignored here: jax falls back to CPU, the probe completes, and the modules'
own ``default_backend() != "tpu"`` marks skip them the normal, visible way.
"""

import os
import subprocess
import sys
import warnings


def _backend_init_completes(timeout_s: float = 60.0) -> bool:
    probe = ("import jax, jax.numpy as jnp; "
             "x = jnp.ones((8, 8)); float((x @ x).sum())")
    child = subprocess.Popen(
        [sys.executable, "-c", probe],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    try:
        child.wait(timeout=timeout_s)  # polls WNOHANG: D-state safe
        return True
    except subprocess.TimeoutExpired:
        child.kill()
        try:
            child.wait(timeout=5)  # reap a normal child; bounded so a
        except subprocess.TimeoutExpired:  # D-state one cannot block us
            pass
        return False


collect_ignore_glob: list = []
if os.environ.get("LSTM_TSP_SKIP_TPU_PROBE") != "1" and (
        not _backend_init_completes()):
    warnings.warn(
        "tests_tpu: backend init did not complete within 60s — the TPU "
        "looks WEDGED; ignoring all on-TPU test modules so collection "
        "does not hang. Re-run when the chip recovers."
    )
    collect_ignore_glob = ["test_*.py"]
