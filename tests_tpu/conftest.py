"""On-TPU test suite — runs on the real chip (NO platform forcing here,
unlike tests/conftest.py which pins the 8-device CPU mesh).

Run: ``python -m pytest tests_tpu/ -x -q`` on a machine with a TPU attached.
Every module skips itself when no TPU is present, so this directory is safe
to include in any environment.
"""
