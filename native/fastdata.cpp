// Native data-pipeline kernels: corpus tokenization and encoding.
//
// Reference parity: SURVEY.md §2 "Data pipeline" — the reference leans on
// Spark/JVM (netty, executors) for corpus handling; its native capability is
// dependency-provided. Here the host-side hot loops (byte->id mapping, word
// tokenization against a vocabulary) are C++ behind ctypes, with a pure
// Python fallback (data/native.py). Device-side work stays in XLA.
//
// Build: make -C native   (g++ -O3 -shared -fPIC fastdata.cpp)

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>

static inline bool is_ws(char c) {
  // Python str.split() whitespace for ASCII text: \t\n\v\f\r space and
  // the \x1c-\x1f separators (all satisfy str.isspace()).
  const unsigned char u = static_cast<unsigned char>(c);
  return u == ' ' || u == '\t' || u == '\n' || u == '\r' || u == '\f' ||
         u == '\v' || (u >= 0x1c && u <= 0x1f);
}

extern "C" {

// Map each byte through a 256-entry table -> int32 ids (char-level encoding).
void encode_bytes(const uint8_t* text, int64_t n, const int32_t* table,
                  int32_t* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = table[text[i]];
}

// Count ASCII-whitespace-separated tokens.
int64_t count_words(const char* text, int64_t n) {
  int64_t count = 0;
  bool in_tok = false;
  for (int64_t i = 0; i < n; ++i) {
    const bool ws = is_ws(text[i]);
    if (!ws && !in_tok) ++count;
    in_tok = !ws;
  }
  return count;
}

// Encode whitespace-separated words against a vocabulary.
// vocab_buf: '\0'-joined words in id order (ids are positions + id_base).
// Unknown words map to unk_id. Returns number of tokens written (<= out_cap).
int64_t encode_words(const char* text, int64_t n, const char* vocab_buf,
                     int64_t vocab_len, int32_t n_vocab, int32_t id_base,
                     int32_t unk_id, int32_t* out, int64_t out_cap) {
  std::unordered_map<std::string, int32_t> vocab;
  vocab.reserve(static_cast<size_t>(n_vocab) * 2);
  {
    int64_t pos = 0;
    for (int32_t id = 0; id < n_vocab && pos < vocab_len; ++id) {
      const char* w = vocab_buf + pos;
      const size_t len = strnlen(w, vocab_len - pos);
      vocab.emplace(std::string(w, len), id + id_base);
      pos += static_cast<int64_t>(len) + 1;
    }
  }
  int64_t written = 0;
  int64_t i = 0;
  while (i < n && written < out_cap) {
    while (i < n && is_ws(text[i])) ++i;
    if (i >= n) break;
    const int64_t start = i;
    while (i < n && !is_ws(text[i])) ++i;
    const auto it = vocab.find(std::string(text + start, i - start));
    out[written++] = it == vocab.end() ? unk_id : it->second;
  }
  return written;
}

}  // extern "C"
