// Native data-pipeline kernels: corpus tokenization and encoding.
//
// Reference parity: SURVEY.md §2 "Data pipeline" — the reference leans on
// Spark/JVM (netty, executors) for corpus handling; its native capability is
// dependency-provided. Here the host-side hot loops (byte->id mapping, word
// tokenization against a vocabulary) are C++ behind ctypes, with a pure
// Python fallback (data/native.py). Device-side work stays in XLA.
//
// Build: make -C native   (g++ -O3 -shared -fPIC fastdata.cpp)

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

static inline bool is_ws(char c) {
  // Python str.split() whitespace for ASCII text: \t\n\v\f\r space and
  // the \x1c-\x1f separators (all satisfy str.isspace()).
  const unsigned char u = static_cast<unsigned char>(c);
  return u == ' ' || u == '\t' || u == '\n' || u == '\r' || u == '\f' ||
         u == '\v' || (u >= 0x1c && u <= 0x1f);
}

extern "C" {

// Map each byte through a 256-entry table -> int32 ids (char-level encoding).
void encode_bytes(const uint8_t* text, int64_t n, const int32_t* table,
                  int32_t* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = table[text[i]];
}

// Count ASCII-whitespace-separated tokens.
int64_t count_words(const char* text, int64_t n) {
  int64_t count = 0;
  bool in_tok = false;
  for (int64_t i = 0; i < n; ++i) {
    const bool ws = is_ws(text[i]);
    if (!ws && !in_tok) ++count;
    in_tok = !ws;
  }
  return count;
}

// Encode whitespace-separated words against a vocabulary.
// vocab_buf: '\0'-joined words in id order (ids are positions + id_base).
// Unknown words map to unk_id. Returns number of tokens written (<= out_cap).
int64_t encode_words(const char* text, int64_t n, const char* vocab_buf,
                     int64_t vocab_len, int32_t n_vocab, int32_t id_base,
                     int32_t unk_id, int32_t* out, int64_t out_cap) {
  std::unordered_map<std::string, int32_t> vocab;
  vocab.reserve(static_cast<size_t>(n_vocab) * 2);
  {
    int64_t pos = 0;
    for (int32_t id = 0; id < n_vocab && pos < vocab_len; ++id) {
      const char* w = vocab_buf + pos;
      const size_t len = strnlen(w, vocab_len - pos);
      vocab.emplace(std::string(w, len), id + id_base);
      pos += static_cast<int64_t>(len) + 1;
    }
  }
  int64_t written = 0;
  int64_t i = 0;
  while (i < n && written < out_cap) {
    while (i < n && is_ws(text[i])) ++i;
    if (i >= n) break;
    const int64_t start = i;
    while (i < n && !is_ws(text[i])) ++i;
    const auto it = vocab.find(std::string(text + start, i - start));
    out[written++] = it == vocab.end() ? unk_id : it->second;
  }
  return written;
}

// ---- vocabulary building (frequency count + most-common ordering) ----
//
// Handle-based API: vocab_build tokenizes and counts; vocab_fill streams the
// words (\0-joined, most-common-first with first-occurrence tie-break — the
// exact order of Python collections.Counter.most_common) and their counts
// into caller-allocated buffers; vocab_free releases the handle.

struct VocabCount {
  std::vector<std::string> words;   // most-common-first
  std::vector<int64_t> counts;
  int64_t words_bytes = 0;          // total \0-joined byte length
};

void* vocab_build(const char* text, int64_t n) {
  struct Entry { int64_t count; int64_t first; };
  std::unordered_map<std::string, Entry> counts;
  counts.reserve(1 << 16);
  int64_t i = 0, order = 0;
  while (i < n) {
    while (i < n && is_ws(text[i])) ++i;
    if (i >= n) break;
    const int64_t start = i;
    while (i < n && !is_ws(text[i])) ++i;
    auto [it, inserted] =
        counts.try_emplace(std::string(text + start, i - start), Entry{0, order});
    if (inserted) ++order;
    ++it->second.count;
  }
  std::vector<std::pair<const std::string*, Entry>> items;
  items.reserve(counts.size());
  for (const auto& kv : counts) items.push_back({&kv.first, kv.second});
  std::sort(items.begin(), items.end(), [](const auto& a, const auto& b) {
    if (a.second.count != b.second.count) return a.second.count > b.second.count;
    return a.second.first < b.second.first;  // Counter.most_common tie order
  });
  auto* out = new VocabCount();
  out->words.reserve(items.size());
  out->counts.reserve(items.size());
  for (const auto& it : items) {
    out->words.push_back(*it.first);
    out->counts.push_back(it.second.count);
    out->words_bytes += static_cast<int64_t>(it.first->size()) + 1;
  }
  return out;
}

int64_t vocab_size(const void* handle) {
  return static_cast<int64_t>(static_cast<const VocabCount*>(handle)->words.size());
}

int64_t vocab_words_bytes(const void* handle) {
  return static_cast<const VocabCount*>(handle)->words_bytes;
}

// words_buf must hold vocab_words_bytes(); counts_buf vocab_size() int64s.
void vocab_fill(const void* handle, char* words_buf, int64_t* counts_buf) {
  const auto* v = static_cast<const VocabCount*>(handle);
  char* p = words_buf;
  for (size_t i = 0; i < v->words.size(); ++i) {
    std::memcpy(p, v->words[i].data(), v->words[i].size());
    p += v->words[i].size();
    *p++ = '\0';
    counts_buf[i] = v->counts[i];
  }
}

void vocab_free(void* handle) { delete static_cast<VocabCount*>(handle); }

}  // extern "C"
