// Native data-pipeline kernels: corpus tokenization and encoding.
//
// Reference parity: SURVEY.md §2 "Data pipeline" — the reference leans on
// Spark/JVM (netty, executors) for corpus handling; its native capability is
// dependency-provided. Here the host-side hot loops (byte->id mapping, word
// tokenization against a vocabulary) are C++ behind ctypes, with a pure
// Python fallback (data/native.py). Device-side work stays in XLA.
//
// Build: make -C native   (g++ -O3 -shared -fPIC fastdata.cpp)

#include <algorithm>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <locale.h>
#include <string>
#include <unordered_map>
#include <vector>

static inline bool is_ws(char c) {
  // Python str.split() whitespace for ASCII text: \t\n\v\f\r space and
  // the \x1c-\x1f separators (all satisfy str.isspace()).
  const unsigned char u = static_cast<unsigned char>(c);
  return u == ' ' || u == '\t' || u == '\n' || u == '\r' || u == '\f' ||
         u == '\v' || (u >= 0x1c && u <= 0x1f);
}

extern "C" {

// Map each byte through a 256-entry table -> int32 ids (char-level encoding).
void encode_bytes(const uint8_t* text, int64_t n, const int32_t* table,
                  int32_t* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = table[text[i]];
}

// Count ASCII-whitespace-separated tokens.
int64_t count_words(const char* text, int64_t n) {
  int64_t count = 0;
  bool in_tok = false;
  for (int64_t i = 0; i < n; ++i) {
    const bool ws = is_ws(text[i]);
    if (!ws && !in_tok) ++count;
    in_tok = !ws;
  }
  return count;
}

// Encode whitespace-separated words against a vocabulary.
// vocab_buf: '\0'-joined words in id order (ids are positions + id_base).
// Unknown words map to unk_id. Returns number of tokens written (<= out_cap).
int64_t encode_words(const char* text, int64_t n, const char* vocab_buf,
                     int64_t vocab_len, int32_t n_vocab, int32_t id_base,
                     int32_t unk_id, int32_t* out, int64_t out_cap) {
  std::unordered_map<std::string, int32_t> vocab;
  vocab.reserve(static_cast<size_t>(n_vocab) * 2);
  {
    int64_t pos = 0;
    for (int32_t id = 0; id < n_vocab && pos < vocab_len; ++id) {
      const char* w = vocab_buf + pos;
      const size_t len = strnlen(w, vocab_len - pos);
      vocab.emplace(std::string(w, len), id + id_base);
      pos += static_cast<int64_t>(len) + 1;
    }
  }
  int64_t written = 0;
  int64_t i = 0;
  while (i < n && written < out_cap) {
    while (i < n && is_ws(text[i])) ++i;
    if (i >= n) break;
    const int64_t start = i;
    while (i < n && !is_ws(text[i])) ++i;
    const auto it = vocab.find(std::string(text + start, i - start));
    out[written++] = it == vocab.end() ? unk_id : it->second;
  }
  return written;
}

// ---- vocabulary building (frequency count + most-common ordering) ----
//
// Handle-based API: vocab_build tokenizes and counts; vocab_fill streams the
// words (\0-joined, most-common-first with first-occurrence tie-break — the
// exact order of Python collections.Counter.most_common) and their counts
// into caller-allocated buffers; vocab_free releases the handle.

struct VocabCount {
  std::vector<std::string> words;   // most-common-first
  std::vector<int64_t> counts;
  int64_t words_bytes = 0;          // total \0-joined byte length
};

void* vocab_build(const char* text, int64_t n) {
  struct Entry { int64_t count; int64_t first; };
  std::unordered_map<std::string, Entry> counts;
  counts.reserve(1 << 16);
  int64_t i = 0, order = 0;
  while (i < n) {
    while (i < n && is_ws(text[i])) ++i;
    if (i >= n) break;
    const int64_t start = i;
    while (i < n && !is_ws(text[i])) ++i;
    auto [it, inserted] =
        counts.try_emplace(std::string(text + start, i - start), Entry{0, order});
    if (inserted) ++order;
    ++it->second.count;
  }
  std::vector<std::pair<const std::string*, Entry>> items;
  items.reserve(counts.size());
  for (const auto& kv : counts) items.push_back({&kv.first, kv.second});
  std::sort(items.begin(), items.end(), [](const auto& a, const auto& b) {
    if (a.second.count != b.second.count) return a.second.count > b.second.count;
    return a.second.first < b.second.first;  // Counter.most_common tie order
  });
  auto* out = new VocabCount();
  out->words.reserve(items.size());
  out->counts.reserve(items.size());
  for (const auto& it : items) {
    out->words.push_back(*it.first);
    out->counts.push_back(it.second.count);
    out->words_bytes += static_cast<int64_t>(it.first->size()) + 1;
  }
  return out;
}

int64_t vocab_size(const void* handle) {
  return static_cast<int64_t>(static_cast<const VocabCount*>(handle)->words.size());
}

int64_t vocab_words_bytes(const void* handle) {
  return static_cast<const VocabCount*>(handle)->words_bytes;
}

// words_buf must hold vocab_words_bytes(); counts_buf vocab_size() int64s.
void vocab_fill(const void* handle, char* words_buf, int64_t* counts_buf) {
  const auto* v = static_cast<const VocabCount*>(handle);
  char* p = words_buf;
  for (size_t i = 0; i < v->words.size(); ++i) {
    std::memcpy(p, v->words[i].data(), v->words[i].size());
    p += v->words[i].size();
    *p++ = '\0';
    counts_buf[i] = v->counts[i];
  }
}

void vocab_free(void* handle) { delete static_cast<VocabCount*>(handle); }

// ---- semicolon-separated decimal-comma CSV (UCI LD2011_2014) ----
//
// The forecaster's real-data loader (data/datasets.py _uci_real) parses a
// European-locale CSV: per line, a timestamp field then per-customer loads
// with DECIMAL COMMAS ("3,1415"). The Python per-value
// float(v.replace(",", ".")) loop is the slowest host step on the real
// ~700 MB file; this kernel parses the same format at memory speed.
//
// Semantics mirror the Python loader EXACTLY:
//   - caller strips the header line (Python reads it for the column count);
//   - a line with fewer than take+1 fields is skipped, not an error;
//   - an empty value parses as 0.0 (`float(v.replace(...) or 0.0)`);
//   - any other unparsable value returns -2 (the Python fallback then
//     raises the same ValueError the pure loader always raised).
// Returns rows written (row-major [rows, take] floats into out), -1 if
// out_cap is too small, -2 on a value Python's float() would reject.
int64_t csv_decimal_comma(const char* buf, int64_t len, int32_t take,
                          float* out, int64_t out_cap) {
  int64_t rows = 0;
  int64_t i = 0;
  char field[64];
  while (i < len) {
    const int64_t line_start = i;
    // Universal-newline row structure, matching the Python fallback's
    // text-mode read: '\n', '\r\n', and LONE '\r' all terminate a line
    // (ADVICE r3: '\n'-only splitting diverged on stray '\r's, and a
    // CRLF row with an empty last field carried a '\r' into the field,
    // kicking the whole file onto the slow path).
    while (i < len && buf[i] != '\n' && buf[i] != '\r') ++i;
    const int64_t line_end = i;  // excl. terminator
    if (i < len) {               // skip terminator ('\r\n' counts as one)
      if (buf[i] == '\r' && i + 1 < len && buf[i + 1] == '\n') i += 2;
      else ++i;
    }
    // count fields (separator count + 1 on a non-empty split result —
    // Python "".split(";") -> [""] has 1 field)
    int64_t nfields = 1;
    for (int64_t j = line_start; j < line_end; ++j)
      if (buf[j] == ';') ++nfields;
    if (nfields < take + 1) continue;  // short row: skipped, like Python
    if (rows * take + take > out_cap) return -1;
    // walk fields 1..take (field 0 is the timestamp)
    int64_t p = line_start;
    while (p < line_end && buf[p] != ';') ++p;  // skip timestamp
    for (int32_t k = 0; k < take; ++k) {
      ++p;  // skip ';'
      int64_t q = p;
      while (q < line_end && buf[q] != ';') ++q;
      const int64_t raw_flen = q - p;
      int64_t flen = raw_flen;
      // strip whitespace the way float() does (CRLF '\r' never reaches a
      // field now — lines terminate on it — this handles in-field blanks)
      while (flen > 0 && is_ws(buf[p])) { ++p; --flen; }
      while (flen > 0 && is_ws(buf[p + flen - 1])) --flen;
      float v = 0.0f;
      if (flen == 0) {
        // only a TRULY empty field is 0.0 (`v or 0.0` on the raw string);
        // a whitespace-only field reaches float(" ") in Python and raises
        if (raw_flen != 0) return -2;
      } else {
        if (flen >= static_cast<int64_t>(sizeof(field))) return -2;
        for (int64_t j = 0; j < flen; ++j) {
          const char c = buf[p + j];
          // strtod accepts a SUPERSET of float()'s grammar: hex floats
          // ("0x10") and "nan(chars)". Reject their marker chars so such
          // fields take the -2 fallback (where Python raises).
          if (c == 'x' || c == 'X' || c == '(') return -2;
          field[j] = c == ',' ? '.' : c;
        }
        field[flen] = '\0';
        char* end = nullptr;
        // parse as double THEN cast, exactly like the Python loop
        // (float(v) builds a double; np.float32 casts) — strtof's direct
        // single rounding can differ in the last ulp. strtod_l against a
        // cached C locale: plain strtod reads LC_NUMERIC, and a host app
        // that setlocale()'d to a comma-decimal locale would reject
        // every '.'-converted field and silently disable this kernel.
        static locale_t c_loc = newlocale(LC_ALL_MASK, "C", nullptr);
        if (!c_loc) return -2;  // strtod_l(.., 0) is UB — fall back instead
        v = static_cast<float>(strtod_l(field, &end, c_loc));
        if (end != field + flen) return -2;  // float() would raise
      }
      out[rows * take + k] = v;
      p = q;
    }
    ++rows;
  }
  return rows;
}

}  // extern "C"
