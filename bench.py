#!/usr/bin/env python
"""Benchmark: PTB char-LSTM training throughput (BASELINE.md north-star).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

value     = sequences/sec/chip for the full train step (fwd+BPTT+update) on
            config 1 (1-layer, hidden=128, char vocab) on the default device.
baseline  = the same config run single-process on CPU float32 — the accepted
            stand-in for the reference's Spark-CPU executor throughput
            (BASELINE.md: "Spark-CPU baseline ... to be measured"; Spark is
            not installable offline). Measured once and cached in
            BASELINE_MEASURED.json; delete that file to re-measure.
"""

import json
import os
import subprocess
import sys
import time

B, T, HIDDEN, LAYERS, STEPS, WARMUP = 64, 64, 128, 1, 100, 10
UNROLL = 8  # lax.scan unroll for the TPU run (measured best on v5e; the
            # CPU baseline keeps unroll=1, faithful to the reference's
            # step-at-a-time unroll)
K = 32    # steps per dispatch for the TPU run (train/multistep.py): the
          # per-step host dispatch over the tunneled chip (~150us) dwarfs
          # this config's ~25us of compute, so the TPU measurement scans K
          # steps per call. The CPU baseline keeps one-dispatch-per-step —
          # faithful to the reference's one-Spark-round-per-step structure.
REPS = 5  # report the best rep (the shared/tunneled chip is very noisy)
CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BASELINE_MEASURED.json")


def measure(compute_dtype: str, steps: int, warmup: int, *,
            unroll: int = 1, reps: int = 1, steps_per_call: int = 1) -> float:
    """Train-step throughput (seq/sec) on the current default backend.

    ``steps``/``warmup`` count optimizer steps; with ``steps_per_call=K`` they
    are grouped into K-step dispatches (batch stacking stays inside the timed
    loop — the feed is part of the step cost)."""
    import jax
    import numpy as np

    from lstm_tensorspark_tpu.data import (
        get_dataset, lm_batch_stream, stacked_batches,
    )
    from lstm_tensorspark_tpu.models import LMConfig, init_lm, lm_loss
    from lstm_tensorspark_tpu.train import (
        make_multi_train_step, make_optimizer, make_train_step,
    )
    from lstm_tensorspark_tpu.train.loop import init_train_state

    data = get_dataset("ptb_char")
    cfg = LMConfig(
        vocab_size=len(data["vocab"]),
        hidden_size=HIDDEN,
        num_layers=LAYERS,
        compute_dtype=compute_dtype,
        scan_unroll=unroll,
    )

    def loss_fn(params, batch, rng):
        return lm_loss(params, batch, cfg)

    opt = make_optimizer("sgd", 0.5)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    state = init_train_state(params, opt, jax.random.PRNGKey(1))

    k = steps_per_call
    if k > 1:
        step = make_multi_train_step(loss_fn, opt)
        it = stacked_batches(lm_batch_stream(data["train"], B, T), k)
    else:
        step = make_train_step(loss_fn, opt)
        it = lm_batch_stream(data["train"], B, T)
    calls, warm_calls = max(steps // k, 1), max(warmup // k, 1)

    for _ in range(warm_calls):
        state, m = step(state, next(it))
    jax.block_until_ready(m["loss"])
    best = 0.0
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(calls):
            state, m = step(state, next(it))
        jax.block_until_ready(m["loss"])
        dt = time.perf_counter() - t0
        best = max(best, B * calls * k / dt)
    return best


def cpu_baseline() -> float:
    """Single-process CPU float32 reference throughput, cached."""
    if os.path.exists(CACHE):
        with open(CACHE) as f:
            return json.load(f)["cpu_seq_per_sec"]
    # fresh interpreter so the CPU platform can be forced cleanly
    code = (
        "import jax, json;"
        "jax.config.update('jax_platforms','cpu');"
        "import bench;"
        "print('CPUBASE', bench.measure('float32', steps=10, warmup=2))"
    )
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, cwd=os.path.dirname(CACHE) or ".",
    )
    line = [l for l in out.stdout.splitlines() if l.startswith("CPUBASE")]
    if not line:
        raise RuntimeError(f"cpu baseline failed: {out.stderr[-2000:]}")
    value = float(line[0].split()[1])
    with open(CACHE, "w") as f:
        json.dump({"cpu_seq_per_sec": value, "config": {
            "B": B, "T": T, "hidden": HIDDEN, "layers": LAYERS,
            "dtype": "float32", "note": "single-process CPU stand-in for Spark-CPU baseline",
        }}, f, indent=1)
    return value


def main() -> int:
    baseline = cpu_baseline()
    value = measure(
        "bfloat16", STEPS * K, WARMUP * K,
        unroll=UNROLL, reps=REPS, steps_per_call=K,
    )
    print(json.dumps({
        "metric": "ptb_char_lstm_train_seq_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "seq/sec",
        "vs_baseline": round(value / baseline, 2),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
