#!/usr/bin/env python
"""Benchmark harness: all five BASELINE.md configs at REAL model dimensions,
with model-FLOPs and MFU accounting.

Prints ONE JSON line (driver contract): {"metric", "value", "unit",
"vs_baseline"} for the headline config-1 throughput, plus a compact
"configs" map {name: {seq_s, tok_s, tflops, mfu}}. The full per-config
table (dims, flops accounting, measurement notes) is written to
BENCH_TABLE.json next to this file.

Model scale honesty (VERDICT r1): configs 2-5 are measured at their TRUE
dimensions — vocab 33,278 (WikiText-2) / 50,000 (WikiText-103) embedding +
softmax rows, IMDB bi-LSTM 256 over seq-400, UCI seq2seq over all 370
customer series — with synthetic token/value DATA (no network), which does
not change the compute. MFU uses matmul-only model FLOPs (the standard
accounting: train = 3x forward) against the chip's published bf16 peak.
"""

import datetime
import json
import os
import subprocess
import sys
import time

# STEPS counts K-step DISPATCHES for the headline run (calls = STEPS*K/K):
# sized so one timed rep runs ~2.5 s at the measured ~22 ms/dispatch, so the
# tunneled backend's ~65 ms fixed fetch latency (see _two_point) stays <3%
# of the rep. The CPU baseline subprocess overrides steps=10 explicitly
# (cpu_baseline), unaffected.
B, T, HIDDEN, LAYERS, STEPS, WARMUP = 64, 64, 128, 1, 120, 10
UNROLL = 8  # lax.scan unroll (used by the Pallas backward's recompute scan;
            # the CPU baseline keeps unroll=1, faithful to the reference's
            # step-at-a-time unroll)
K = 512   # steps per dispatch for the TPU run (train/multistep.py): one
          # jitted program runs K optimizer steps, so the host dispatch and
          # tunnel round-trip amortise. K=32 was device-bound at the old
          # 148 us/step; after the one-hot indexing fix (ops/embedding.py)
          # the step runs ~78 us device-side and 32-step dispatches went
          # HOST-bound (~2 ms/dispatch tunnel cost ate the win). Measured
          # sweeps: K=32 ~421k, K=64 ~593k, K=256 ~750k seq/s; same-day
          # 256/512/1024 sweep on the quiet chip: 797k/814k/817k — K=512
          # takes the remaining dispatch amortisation, K=1024's extra
          # +0.4% isn't worth doubling the dispatch granularity. The CPU
          # baseline keeps one-dispatch-per-step — faithful to the
          # reference's one-Spark-round-per-step structure.
DEVICE_DATA = True  # TPU run stages the corpus in HBM and slices windows
          # on-device (train/device_step.py): per-dispatch host traffic is
          # one scalar. This mirrors the reference's cached-RDD locality
          # (executors iterate a RESIDENT shard; Spark moves only params/
          # grads per round). The CPU baseline keeps the host-fed path.
PALLAS = True  # fused Pallas recurrence kernel for the TPU forward
          # (ops/pallas_lstm.py) — measured fastest honest config on v5e;
          # auto-falls back to lax.scan off-TPU, so the CPU baseline is
          # unaffected.
REPS = 3  # report the best rep (the shared/tunneled chip is noisy)
# MEASUREMENT HONESTY: this environment's tunneled TPU backend absorbs
# thousands of dispatches into an async queue and `block_until_ready` can
# return before real execution completes, inflating short-window timings by
# >100x. The ONLY reliable barrier is fetching a value to the host, so each
# timed rep ends with float(loss), and reps are long so the queue cannot
# hide real work.
_DIR = os.path.dirname(os.path.abspath(__file__))
CACHE = os.path.join(_DIR, "BASELINE_MEASURED.json")
TABLE = os.path.join(_DIR, "BENCH_TABLE.json")

# FLOPs accounting + bf16 peak: ONE source shared with the runtime's
# --log-flops (lstm_tensorspark_tpu/utils/flops.py).
from lstm_tensorspark_tpu.resilience.exit_codes import LIVENESS_RC  # noqa: E402
from lstm_tensorspark_tpu.utils.flops import (  # noqa: E402
    PEAK_TFLOPS,
    TRAIN_FLOPS_MULTIPLIER,
    classifier_fwd_flops_per_token as _classifier_fwd_flops_per_token,
    lm_fwd_flops_per_token as _lm_fwd_flops_per_token,
    seq2seq_fwd_flops_per_seq as _seq2seq_flops_per_seq,
)


# ---------------------------------------------------------------------------
# The five BASELINE.md configs at REAL model dimensions.
# B/T are the measurement batch shapes (documented in BENCH_TABLE.json);
# dims (V/H/L/T) are the config-defining sizes and are NOT scaled down.
# ---------------------------------------------------------------------------
CONFIGS = {
    "ptb_char": dict(kind="lm", V=50, H=128, L=1, B=64, T=64),
    "imdb_bilstm": dict(kind="classifier", V=25_000, H=256, L=1, B=64, T=400),
    # word LMs: bf16 logits (--logits-dtype) — every HBM pass over the
    # [B,T,V] array halves; validated to reach the same ppl target at the
    # same step as f32 (quality_curves comparison in DESIGN round-3 notes)
    "wikitext2": dict(kind="lm", V=33_278, H=650, L=2, B=64, T=35,
                      logits_dtype="bfloat16"),
    "uci_seq2seq": dict(kind="seq2seq", F=370, H=256, L=2, B=64, T=168,
                        horizon=24),
    "wikitext103": dict(kind="lm", V=50_000, H=1024, L=4, B=32, T=64,
                        logits_dtype="bfloat16"),
}


def measure(compute_dtype: str, steps: int, warmup: int, *,
            unroll: int = 1, reps: int = 1, steps_per_call: int = 1,
            device_data: bool = False, use_pallas: bool = False) -> float:
    """Config-1 train-step throughput (seq/sec) on the current default
    backend — the headline metric, kept measurement-identical to round 1.

    ``steps``/``warmup`` count optimizer steps; with ``steps_per_call=K`` they
    are grouped into K-step dispatches. Host-fed mode keeps batch stacking
    inside the timed loop (the feed is part of the step cost);
    ``device_data`` stages the corpus in HBM once (outside the timed loop,
    like Spark's one-time RDD cache) and feeds one scalar per dispatch."""
    import jax

    from lstm_tensorspark_tpu.data import (
        get_dataset, lm_batch_stream, stacked_batches, stage_lm_data,
        window_index_stream,
    )
    from lstm_tensorspark_tpu.models import LMConfig, init_lm, lm_loss
    from lstm_tensorspark_tpu.train import (
        make_device_lm_train_step, make_multi_train_step, make_optimizer,
        make_train_step,
    )
    from lstm_tensorspark_tpu.train.loop import init_train_state

    data = get_dataset("ptb_char")
    cfg = LMConfig(
        vocab_size=len(data["vocab"]),
        hidden_size=HIDDEN,
        num_layers=LAYERS,
        compute_dtype=compute_dtype,
        scan_unroll=unroll,
        use_pallas=use_pallas,
    )

    def loss_fn(params, batch, rng):
        return lm_loss(params, batch, cfg)

    opt = make_optimizer("sgd", 0.5)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    state = init_train_state(params, opt, jax.random.PRNGKey(1))

    k = steps_per_call
    if device_data:
        staged = stage_lm_data(data["train"], B, T)
        dstep = make_device_lm_train_step(loss_fn, opt, staged, steps_per_call=k)
        step = lambda s, w0: dstep(s, staged.arrays, w0)  # noqa: E731
        it = window_index_stream(staged, k)
    elif k > 1:
        step = make_multi_train_step(loss_fn, opt)
        it = stacked_batches(lm_batch_stream(data["train"], B, T), k)
    else:
        step = make_train_step(loss_fn, opt)
        it = lm_batch_stream(data["train"], B, T)
    calls, warm_calls = max(steps // k, 1), max(warmup // k, 1)

    for _ in range(warm_calls):
        state, m = step(state, next(it))
    float(m["loss"])  # TRUE barrier (see MEASUREMENT HONESTY above)
    best = 0.0
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(calls):
            state, m = step(state, next(it))
        float(m["loss"])  # value fetch = the only trustworthy sync here
        dt = time.perf_counter() - t0
        best = max(best, B * calls * k / dt)
    return best


def _rand_batch(kind: str, c: dict, key):
    """One synthetic batch at REAL model dims (random data, true compute)."""
    import jax
    import jax.numpy as jnp

    B_, T_ = c["B"], c["T"]
    if kind == "lm":
        toks = jax.random.randint(key, (B_, T_ + 1), 0, c["V"], jnp.int32)
        return {"inputs": toks[:, :-1], "targets": toks[:, 1:]}
    if kind == "classifier":
        return {
            "tokens": jax.random.randint(key, (B_, T_), 0, c["V"], jnp.int32),
            "lengths": jnp.full((B_,), T_, jnp.int32),
            "labels": jax.random.randint(key, (B_,), 0, 2, jnp.int32),
            "valid": jnp.ones((B_,), jnp.float32),
        }
    if kind == "seq2seq":
        k1, k2 = jax.random.split(key)
        return {
            "context": jax.random.normal(k1, (B_, T_, c["F"]), jnp.float32),
            "targets": jax.random.normal(k2, (B_, c["horizon"], c["F"]), jnp.float32),
        }
    raise ValueError(kind)


def measure_config(name: str, *, warmup: int = 64,
                   steps_per_call: int = 32, reps: int = 2) -> dict:
    """Throughput + MFU for one named config at real model dimensions.

    The K-stacked synthetic batch is staged on device ONCE and re-fed every
    dispatch (throughput measurement — the data values don't change the
    compute). Returns the BENCH_TABLE.json record.

    Rep length is SELF-CALIBRATING: this environment's tunneled backend has
    ~65 ms fixed fetch latency plus ~0.2 ms per queued dispatch (measured),
    which at a fixed 64-step rep contaminated small configs by up to
    1 ms/step. A short probe separates fixed vs per-call cost, then the
    timed rep is sized so the fixed cost is <5% of the measurement."""
    import jax
    import jax.numpy as jnp

    from lstm_tensorspark_tpu.train import make_multi_train_step, make_optimizer
    from lstm_tensorspark_tpu.train.loop import init_train_state

    c = CONFIGS[name]
    kind = c["kind"]
    B_, T_ = c["B"], c["T"]

    if kind == "lm":
        from lstm_tensorspark_tpu.models import LMConfig, init_lm, lm_loss

        cfg = LMConfig(vocab_size=c["V"], hidden_size=c["H"],
                       num_layers=c["L"], compute_dtype="bfloat16",
                       logits_dtype=c.get("logits_dtype", "float32"),
                       use_pallas=PALLAS and jax.default_backend() == "tpu")
        params = init_lm(jax.random.PRNGKey(0), cfg)
        loss_fn = lambda p, b, r: lm_loss(p, b, cfg)  # noqa: E731
        fwd_flops_step = _lm_fwd_flops_per_token(c["V"], c["H"], c["L"]) * B_ * T_
        tokens_per_step = B_ * T_
    elif kind == "classifier":
        from lstm_tensorspark_tpu.models import (
            ClassifierConfig, classifier_loss, init_classifier,
        )

        cfg = ClassifierConfig(vocab_size=c["V"], hidden_size=c["H"],
                               num_layers=c["L"], compute_dtype="bfloat16",
                               use_pallas=PALLAS and jax.default_backend() == "tpu")
        params = init_classifier(jax.random.PRNGKey(0), cfg)
        loss_fn = lambda p, b, r: classifier_loss(p, b, cfg)  # noqa: E731
        fwd_flops_step = (
            _classifier_fwd_flops_per_token(c["V"], c["H"], c["L"]) * B_ * T_
        )
        tokens_per_step = B_ * T_
    elif kind == "seq2seq":
        from lstm_tensorspark_tpu.models import (
            Seq2SeqConfig, init_seq2seq, seq2seq_loss,
        )

        cfg = Seq2SeqConfig(num_features=c["F"], hidden_size=c["H"],
                            num_layers=c["L"], horizon=c["horizon"],
                            compute_dtype="bfloat16",
                            use_pallas=PALLAS and jax.default_backend() == "tpu")
        params = init_seq2seq(jax.random.PRNGKey(0), cfg)
        loss_fn = lambda p, b, r: seq2seq_loss(p, b, cfg)  # noqa: E731
        fwd_flops_step = _seq2seq_flops_per_seq(
            c["F"], c["H"], c["L"], T_, c["horizon"]) * B_
        tokens_per_step = B_ * (T_ + c["horizon"])
    else:
        raise ValueError(kind)

    opt = make_optimizer("sgd", 0.1)
    state = init_train_state(params, opt, jax.random.PRNGKey(1))
    step = make_multi_train_step(loss_fn, opt)
    kk = steps_per_call
    batch = _rand_batch(kind, c, jax.random.PRNGKey(2))
    stacked = jax.tree.map(
        lambda a: jnp.broadcast_to(a[None], (kk, *a.shape)), batch
    )
    stacked = jax.device_put(stacked)  # staged once, outside the timed loop

    for _ in range(max(warmup // kk, 1)):
        state, m = step(state, stacked)
    float(m["loss"])  # true barrier (tunneled-TPU honesty)

    def probe(k):
        nonlocal state, m
        for _ in range(k):
            state, m = step(state, stacked)
        float(m["loss"])

    fixed, per_call = _two_point(probe, 8)
    if per_call is None:  # every probe rep collapsed: be conservative
        fixed, per_call = 0.065, 0.05
    # rep long enough that the fixed cost is <5%, bounded in wall time so a
    # mis-probe can never turn one config into a multi-minute runaway
    calls = int(min(max(20.0 * fixed / per_call, 8), 3000,
                    10.0 / per_call + 1))

    best = 0.0
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(calls):
            state, m = step(state, stacked)
        float(m["loss"])
        dt = time.perf_counter() - t0
        best = max(best, calls * kk / dt)  # optimizer steps / sec

    # fwd + bwd(2x) matmul accounting — the shared policy constant
    train_flops_step = TRAIN_FLOPS_MULTIPLIER * fwd_flops_step
    tflops = best * train_flops_step / 1e12
    rec = {
        "kind": kind,
        "train_flops_step": train_flops_step,
        "dims": {k: v for k, v in c.items() if k != "kind"},
        "seq_per_sec": round(best * B_, 2),
        "tokens_per_sec": round(best * tokens_per_step, 1),
        "model_tflops_per_sec": round(tflops, 3),
        "mfu_vs_bf16_peak": round(tflops / PEAK_TFLOPS, 4),
        "compute_dtype": "bfloat16",
        "steps_per_call": kk,
        "note": "real model dims, synthetic data; train FLOPs = 3x fwd matmuls",
    }
    return rec


def _two_point(run, n: int, reps: int = 3):
    """Split the tunnel's fixed dispatch+fetch latency from real per-call
    cost: ``t1 = fixed + d``, ``tn = fixed + n*d`` ⇒ ``d = (tn-t1)/(n-1)``.

    ``run(k)`` must execute k queued dispatches then fetch one value. The
    difference estimator is noise-sensitive (fixed-latency jitter can rival
    the signal), so each probe repeats ``reps`` times, reps where the
    difference collapses (tn <= t1: a latency spike ate the signal) are
    REJECTED, and the MEDIAN d wins — min-of-reps would select the
    worst-case underestimate. Returns (fixed, d), or (None, None) when
    every rep collapsed (caller must treat the probe as failed)."""
    pairs = []
    for _ in range(reps):
        t0 = time.perf_counter()
        run(1)
        t1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        run(n)
        tn = time.perf_counter() - t0
        if tn > t1:
            pairs.append(((tn - t1) / (n - 1), t1))
    if not pairs:
        return None, None
    d = sorted(p[0] for p in pairs)[len(pairs) // 2]
    t1_med = sorted(p[1] for p in pairs)[len(pairs) // 2]
    return max(t1_med - d, 0.0), d


def measure_roofline(name: str, *, chains: int = 256, reps: int = 3) -> dict:
    """Sequential-recurrence roofline for one config (VERDICT r2 item 4).

    An LSTM train step cannot beat its DEPENDENT chain: T forward steps of
    ``h @ U`` + gates, then the T-step cotangent chain backward — no batching
    or fusion removes that serialization. The bound is built from MEASURED
    latency, not FLOPs: ``chain_sec`` times the fastest implementation we
    have of the full gated chain (the fused Pallas forward at this config's
    local (B, H, T_chain)), k-chained hT→h0 inside ONE jitted fori_loop so
    the tunnel dispatch amortises away. Then

        bound_sec = 2*chain_sec                (fwd chain + bwd chain)
                  + (train_flops - 3*chain_flops) / peak   (everything else,
                    assumed perfectly parallel — other layers/directions
                    COULD overlap the chain, so the bound is a true floor)

    and ``fraction_of_bound = bound_sec / measured_sec_per_step``: 1.0 means
    the step runs AT the recurrence bound — the remaining MFU gap is the
    serial chain's arithmetic-intensity floor, not implementation slack.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from lstm_tensorspark_tpu.ops import init_lstm_params
    from lstm_tensorspark_tpu.ops.pallas_lstm import pallas_lstm_scan, supported

    c = CONFIGS[name]
    B_, H_ = c["B"], c["H"]
    kind = c["kind"]
    # critical-path length: layers/directions can pipeline (path T + L - 1
    # ≈ T); the seq2seq decoder chain EXTENDS the encoder's (dependent)
    T_chain = c["T"] + (c["horizon"] if kind == "seq2seq" else 0)
    if not supported(B_, H_):
        return {"error": f"no fused kernel plan for B={B_}, H={H_}"}

    D = 32  # input width is irrelevant to the chain; keep xproj tiny
    params = init_lstm_params(jax.random.PRNGKey(0), D, H_)
    xs = jax.random.normal(jax.random.PRNGKey(1), (B_, T_chain, D))

    def chained(params, xs, h0, c0):
        def body(_, carry):
            (hT, cT), _ys = pallas_lstm_scan(
                params, xs, carry, compute_dtype=jnp.bfloat16
            )
            return (hT, cT)
        hT, cT = lax.fori_loop(0, chains, body, (h0, c0))
        return hT, cT, jnp.sum(hT)  # sum on-device: ONE tiny fetch suffices

    h0 = jnp.zeros((B_, H_), jnp.float32)
    c0 = jnp.zeros((B_, H_), jnp.float32)
    run = jax.jit(chained)
    # The tunneled backend has ~65 ms FIXED dispatch+fetch latency — orders
    # above a chain's real cost, and it poisons naive division (measured:
    # it made a 14 µs chain read as 270 µs). `_two_point` removes it with
    # median-robust calibration; `chains` is large enough per dispatch that
    # the ~0.2 ms queue overhead per dispatch is <5% of the signal.
    hT, cT, s = run(params, xs, h0, c0)
    float(s)  # warm + true barrier (tunneled-TPU honesty)

    def probe(k):
        out = None
        for _ in range(k):
            out = run(params, xs, h0, c0)
        float(out[2])

    _, d = _two_point(probe, 16, reps=reps)
    if d is None:
        return {"error": "calibration collapsed (tunnel latency jitter ate "
                         "the signal in every probe rep)"}
    chain_sec = d / chains
    chain_flops = 8.0 * B_ * H_ * H_ * T_chain  # the chain's h@U matmuls
    return {
        "chain": {"B": B_, "H": H_, "T": T_chain},
        "chain_sec": chain_sec,
        "per_step_latency_us": round(chain_sec / T_chain * 1e6, 3),
        "chain_flops": chain_flops,
    }


def measure_hbm_bw(mb: int = 128, iters: int = 8, reps: int = 3) -> dict:
    """Measured HBM bandwidth: an elementwise pass over a ``mb``-MiB f32
    array, ``iters``-chained inside ONE jitted fori_loop (each iteration
    reads + writes the full array — the carry dependency stops XLA fusing
    across iterations, so every pass is real HBM traffic). `_two_point`
    strips the tunnel's fixed dispatch+fetch latency as everywhere else.
    This is the denominator of the r4 bandwidth bound — measured on THIS
    chip, not a datasheet number."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    n = mb * 2**20 // 4
    x = jnp.arange(n, dtype=jnp.float32) * 1e-6  # not constant-foldable

    def body(_, a):
        return a * 1.0000001 + 1.0

    run = jax.jit(lambda a: lax.fori_loop(0, iters, body, a))
    y = run(x)
    float(y[0])  # warm + true barrier (tunneled-TPU honesty)

    def probe(k):
        out = x
        for _ in range(k):
            out = run(out)
        float(out[0])

    _, d = _two_point(probe, 4, reps=reps)
    if d is None:
        return {"error": "calibration collapsed (tunnel latency jitter)"}
    moved = 2.0 * n * 4 * iters  # read + write per iteration
    return {
        "array_mib": mb,
        "iters": iters,
        "gb_per_sec": round(moved / d / 1e9, 2),
    }


def _scan_stream_bytes(strategy: str, T_s: int, D_s: int, B: int, H: int,
                       pbytes: int) -> float:
    """Estimated HBM bytes ONE optimizer step moves for ONE sequential
    scan under ``strategy`` — the numerator of the r4 bandwidth bound.

    Inventory (A = T_s*B rows; r = stream-dtype bytes, 4 = f32):
    resident/tiled — fwd: xs read (f32, by the xproj producer), xproj
    write+read (r), ys write, z write (r), cs write; bwd kernel: z read
    (r), dys + cs reads, dz write (r); outside: dz read 4x (dU, dW, db,
    dxs — separate contractions), ys read (h_prev for dU), xs read
    (dW), dxs write. tiled additionally RE-STREAMS U every step (fwd)
    and U^T (bwd) — the strategy's defining cost at H where U exceeds
    VMEM. residentx — no xproj/z anywhere: xs streamed once per kernel
    (r) in fwd AND bwd (z recomputed in-kernel), cs the only residual;
    same dz and outside traffic. Estimates deliberately EXCLUDE the
    non-scan model (embedding/head/optimizer) — those FLOPs-side costs
    sit in the impl bound's parallel term; mask streams are negligible
    (LANE wide). An estimate, not a meter: good to ~10-20%, enough to
    say which side of the bandwidth roof a config sits on."""
    from lstm_tensorspark_tpu.ops.pallas_lstm import _pad_to_lane, _rbytes

    r = _rbytes(pbytes)
    A = T_s * B
    Hp = _pad_to_lane(H)
    z4 = A * 4 * Hp  # elements of one [T,B,4H] stream
    s1 = A * Hp      # elements of one [T,B,H] stream
    xs_f32 = A * D_s * 4
    dz_outside = 4 * z4 * r + s1 * 4 + xs_f32 + A * D_s * 4  # dU/dW/db/dxs
    if strategy == "residentx":
        xs_r = A * _pad_to_lane(D_s) * r
        fwd = xs_r + s1 * 4 * 2            # xs in; ys + cs out
        bwd = xs_r + s1 * 4 * 2 + z4 * r   # xs + dys + cs in; dz out
        return fwd + bwd + dz_outside
    fwd = xs_f32 + z4 * r * 2 + s1 * 4 + z4 * r + s1 * 4  # xproj w+r, ys, z, cs
    bwd = z4 * r + s1 * 4 * 2 + z4 * r                    # z, dys, cs in; dz out
    total = fwd + bwd + dz_outside
    if strategy == "tiled":
        total += T_s * 2 * 4 * Hp * Hp * pbytes  # U fwd + U^T bwd re-streamed
    return total


def _config_scans(name: str) -> list:
    """(T, input_width, has_mask, dirs) for EVERY sequential scan one
    optimizer step of this config runs — the per-scan inventory
    `_impl_bound` plans over. ``dirs=2`` marks a scan the runtime runs
    through the stacked-direction kernel (both bi-LSTM chains advance in
    ONE serialized pass; traffic of two). LM: embed output (width H)
    feeds layer 0, H feeds deeper layers (models/lstm_lm.py).
    Classifier: two directions per layer; embed (width H) feeds layer 0,
    the 2H direction-concat feeds deeper layers (models/classifier.py:61).
    Seq2seq: encoder scans at T then decoder scans at horizon, F feeding
    both layer 0s (models/seq2seq.py:48-51)."""
    c = CONFIGS[name]
    kind, H_, L_ = c["kind"], c["H"], c["L"]
    if kind == "lm":
        return [(c["T"], H_, False, 1)] * L_
    if kind == "classifier":
        # mirror the runtime's dispatch (ops/scan.py bidir_lstm_scan): a
        # layer whose shape fits the stacked-direction kernel advances
        # BOTH chains in one pass — one serialized scan, but the traffic
        # of two (the stacked entry below carries dirs=2 for the
        # bandwidth accounting). Honors the same A/B lever.
        import os

        from lstm_tensorspark_tpu.ops.pallas_bilstm import bilstm_supported

        pbytes = 2 if c.get("compute_dtype", "bfloat16") == "bfloat16" else 4
        fuse_ok = os.environ.get("LSTM_TSP_NO_BIDIR_FUSE") != "1"
        scans = []
        for layer in range(L_):
            D = H_ if layer == 0 else 2 * H_
            if fuse_ok and bilstm_supported(
                    c["B"], H_, D, c["T"], platform="tpu",
                    param_dtype_bytes=pbytes, has_mask=True):
                scans.append((c["T"], D, True, 2))  # stacked: dirs share
            else:
                scans += [(c["T"], D, True, 1)] * 2  # two serialized scans
        return scans
    if kind == "seq2seq":
        def width(layer):
            return c["F"] if layer == 0 else H_
        return ([(c["T"], width(l), False, 1) for l in range(L_)]
                + [(c["horizon"], width(l), False, 1) for l in range(L_)])
    raise ValueError(kind)


def _impl_bound(name: str, rl: dict, rec: dict, measured: float) -> dict:
    """Strategy-aware serialized-chain bound for one measured config.

    Counts the sequential in-chain steps THIS implementation runs per
    optimizer step, each costing ~chain_sec/T_chain (every in-chain MXU
    op — ``h@U``, z recompute, ``dz@U^T`` — moves the same 8BH² FLOPs
    per step, so per-step chain latency is the right unit): each scan
    contributes its OWN length times (1 + its backward strategy's
    in-chain multiplier). dU/dW/dxs are OUTSIDE the chain (contracted
    from streamed dz) and so stay in the parallel term. ``measured`` is
    the UNROUNDED s/step (the rounded copy in ``rl`` would skew the
    fraction by up to 0.6% at config-1 step times).

    Per-scan derivation (ADVICE r3): the strategy comes from the
    runtime's own `chosen_bwd_strategy` evaluated at EACH scan's
    (T, input width) — a heterogeneous config (seq2seq's short-horizon
    decoder, a stacked classifier whose layer-1 input is 2H) no longer
    inherits the layer-0 label. When every scan plans the same strategy
    the legacy `impl_bwd_strategy` string is that name; otherwise it is
    "mixed" and `impl_bwd_strategies` carries the per-strategy scan
    counts."""
    from lstm_tensorspark_tpu.ops.pallas_lstm import (
        _FUSEDX_MIN_T, _pad_to_lane, chosen_bwd_strategy,
    )

    c = CONFIGS[name]
    B_, H_ = c["B"], c["H"]
    kind = c["kind"]
    Hp = _pad_to_lane(H_)
    # pbytes from the config's compute dtype, exactly as the runtime gate
    # derives it from the fused kernel dtype (all table configs are bf16
    # today; an f32 row would flip the VMEM plans at 4 bytes)
    pbytes = 2 if c.get("compute_dtype", "bfloat16") == "bfloat16" else 4
    MULT = {"residentx": 2, "resident": 1, "tiled": 1, "recompute": 2}
    serial_steps = 0
    stream_bytes = 0.0
    strategy_counts: dict = {}
    for T_s, D_s, has_mask, dirs in _config_scans(name):
        if dirs == 2:
            # stacked-direction kernel (ops/pallas_bilstm.py): residentx
            # pair by construction — ONE serialized chain of T steps for
            # both directions, traffic of two residentx scans (2B rows)
            s = "residentx"
            stream_bytes += 2 * _scan_stream_bytes(s, T_s, D_s, B_, H_,
                                                   pbytes)
        else:
            Dp = _pad_to_lane(D_s) if T_s >= _FUSEDX_MIN_T else None
            s = chosen_bwd_strategy(B_, T_s, Hp, pbytes,
                                    has_mask=has_mask, Dp=Dp)
            stream_bytes += _scan_stream_bytes(s, T_s, D_s, B_, H_, pbytes)
        serial_steps += T_s * (1 + MULT[s])
        strategy_counts[s] = strategy_counts.get(s, 0) + 1
    # chain-latency units: the roofline's chain covers T_chain steps
    T_chain = c["T"] + (c["horizon"] if kind == "seq2seq" else 0)
    passes = serial_steps / T_chain
    parallel = max(
        rec["train_flops_step"] - passes * rl["chain_flops"], 0.0
    ) / (PEAK_TFLOPS * 1e12)
    bound = passes * rl["chain_sec"] + parallel
    out = {
        "impl_serial_steps": serial_steps,
        "impl_serial_passes": round(passes, 4),
        "impl_bwd_strategy": (next(iter(strategy_counts))
                              if len(strategy_counts) == 1 else "mixed"),
        "impl_bound_sec_per_step": round(bound, 6),
        "fraction_of_impl_bound": round(bound / measured, 4),
        # numerator of the r4 bandwidth bound (estimate; see
        # _scan_stream_bytes) — main() divides by the MEASURED HBM BW and
        # publishes the max(compute-bound, bandwidth-bound) floor
        "stream_bytes_per_step": int(stream_bytes),
    }
    if len(strategy_counts) > 1:
        out["impl_bwd_strategies"] = strategy_counts
    return out


def measure_generation(*, new_tokens: int = 512, batch: int = 64,
                       reps: int = 3) -> dict:
    """Autoregressive decode throughput (the inference surface, SURVEY.md §2
    "Eval / inference" row): config-1-class LM, batched greedy decode of
    ``new_tokens`` continuations in ONE jitted prefill+decode program
    (models/generate.py). Tokens/sec counts generated tokens only."""
    import jax
    import jax.numpy as jnp

    from lstm_tensorspark_tpu.models import LMConfig, init_lm, make_generate_fn

    cfg = LMConfig(vocab_size=50, hidden_size=HIDDEN, num_layers=LAYERS,
                   compute_dtype="bfloat16")
    params = init_lm(jax.random.PRNGKey(0), cfg)
    gen = make_generate_fn(cfg, max_new_tokens=new_tokens, greedy=True)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (batch, 32), 0, 50,
                                jnp.int32)
    rng = jax.random.PRNGKey(2)
    out = gen(params, prompt, rng)
    int(out[0, -1])  # true barrier (tunneled-TPU honesty)

    def probe(k):
        o = None
        for _ in range(k):
            o = gen(params, prompt, rng)
        int(o[0, -1])

    _, d = _two_point(probe, 8, reps=reps)
    if d is None:
        return {"error": "calibration collapsed (tunnel latency jitter)"}
    return {
        "model": {"V": 50, "H": HIDDEN, "L": LAYERS},
        "batch": batch,
        "prompt_len": 32,
        "new_tokens": new_tokens,
        "decode": "greedy, single jitted prefill+decode program",
        "tokens_per_sec": round(batch * new_tokens / d, 1),
        "sec_per_token_per_seq": round(d / new_tokens * 1e6, 2),
    }


def measure_pp_config5(*, steps: int = 48, warmup: int = 8) -> dict:
    """Config-5-shape (H=1024, L=4) training under the PIPELINE wavefront,
    fused Pallas stage interiors vs plain lax.scan (VERDICT r2 item 3).

    One real chip ⇒ a pp=1 mesh: the full shard_map wavefront machinery runs
    (manual axes, ppermute elided at S=1), so the measured delta isolates
    the stage-interior kernel — the part that scales to real pp>1 meshes
    unchanged (stage interiors are collective-free). Single-step dispatches
    (the PP step has no K-step variant), so tunnel dispatch overhead is part
    of both numbers; noted in the record."""
    import jax
    import jax.numpy as jnp

    from lstm_tensorspark_tpu.models import LMConfig, init_lm
    from lstm_tensorspark_tpu.parallel import make_mesh
    from lstm_tensorspark_tpu.parallel.pipeline_parallel import (
        make_pp_lm_train_step, place_pp_lm_params, stack_lm_params,
    )
    from lstm_tensorspark_tpu.train import make_optimizer
    from lstm_tensorspark_tpu.train.loop import init_train_state

    c = CONFIGS["wikitext103"]
    B_, T_ = c["B"], c["T"]

    def run(use_pallas: bool) -> float:
        cfg = LMConfig(vocab_size=c["V"], hidden_size=c["H"],
                       num_layers=c["L"], compute_dtype="bfloat16",
                       logits_dtype=c.get("logits_dtype", "float32"),
                       use_pallas=use_pallas)
        params = init_lm(jax.random.PRNGKey(0), cfg)
        opt = make_optimizer("sgd", 0.1)
        mesh = make_mesh(dp=1, pp=1)
        stacked = stack_lm_params(params)
        placed = place_pp_lm_params(stacked, mesh)
        step = make_pp_lm_train_step(cfg, opt, mesh, stacked,
                                     microbatches=2, donate=False)
        state = init_train_state(placed, opt, jax.random.PRNGKey(1))
        toks = jax.random.randint(jax.random.PRNGKey(2), (B_, T_ + 1), 0,
                                  c["V"], jnp.int32)
        batch = jax.device_put(
            {"inputs": toks[:, :-1], "targets": toks[:, 1:]}
        )
        for _ in range(warmup):
            state, m = step(state, batch)
        float(m["loss"])  # true barrier (tunneled-TPU honesty)
        t0 = time.perf_counter()
        for _ in range(steps):
            state, m = step(state, batch)
        float(m["loss"])
        return steps / (time.perf_counter() - t0)

    scan_sps = run(False)
    pallas_sps = run(True)
    return {
        "shape": {k: v for k, v in c.items() if k != "kind"},
        "mesh": "dp=1,pp=1 (one chip; wavefront machinery live, ppermute "
                "elided at S=1)",
        "microbatches": 2,
        "scan_seq_per_sec": round(scan_sps * B_, 2),
        "pallas_seq_per_sec": round(pallas_sps * B_, 2),
        "pallas_speedup": round(pallas_sps / scan_sps, 3),
        "note": "single-step dispatches; tunnel overhead in both numbers",
    }


def cpu_baseline() -> float:
    """Single-process CPU float32 reference throughput, cached."""
    if os.path.exists(CACHE):
        with open(CACHE) as f:
            return json.load(f)["cpu_seq_per_sec"]
    # fresh interpreter so the CPU platform can be forced cleanly
    code = (
        "import jax, json;"
        "jax.config.update('jax_platforms','cpu');"
        "import bench;"
        "print('CPUBASE', bench.measure('float32', steps=10, warmup=2))"
    )
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, cwd=_DIR,
    )
    line = [l for l in out.stdout.splitlines() if l.startswith("CPUBASE")]
    if not line:
        raise RuntimeError(f"cpu baseline failed: {out.stderr[-2000:]}")
    value = float(line[0].split()[1])
    with open(CACHE, "w") as f:
        json.dump({"cpu_seq_per_sec": value, "config": {
            "B": B, "T": T, "hidden": HIDDEN, "layers": LAYERS,
            "dtype": "float32", "note": "single-process CPU stand-in for Spark-CPU baseline",
        }}, f, indent=1)
    return value


def _last_good() -> dict | None:
    """Last complete measurement's {value, captured_at, commit} — so a
    wedged round's failure record carries evidence instead of a bare zero
    (VERDICT r4). Value and provenance stay COHERENT: when git history is
    available the value is read from the committed blob the commit/date
    describe (`git show`); without git — or when that blob is unusable —
    it falls back to the on-disk table with no provenance attached.
    Never raises."""
    name = os.path.basename(TABLE)
    commit = captured_at = value = None
    try:
        rec = subprocess.run(
            ["git", "log", "-1", "--format=%H %cI", "--", name],
            capture_output=True, text=True, cwd=_DIR, timeout=30,
        ).stdout.split()
        if len(rec) == 2:
            commit, captured_at = rec
            text = subprocess.run(
                ["git", "show", f"{commit}:{name}"],
                capture_output=True, text=True, cwd=_DIR, timeout=30,
            ).stdout
            value = float(json.loads(text)["headline_seq_per_sec"])
    except Exception:
        commit = captured_at = value = None  # blob unusable: try the disk
    if value is None:
        try:
            with open(TABLE) as f:
                value = float(json.load(f)["headline_seq_per_sec"])
        except Exception:
            return None
    out = {"value": value, "unit": "seq/sec"}
    if commit:
        out["commit"], out["captured_at"] = commit, captured_at
    return out


def _fail_json(error: str) -> None:
    """The driver's zero-value failure contract — SAME metric/unit strings
    as the success line (main), so the failure is recorded as a 0-value
    datapoint of the tracked metric, not an unknown one (value stays an
    honest 0.0; `last_good` carries the stale-but-real number). Exits
    LIVENESS_RC (resilience/exit_codes.py) — a DEDICATED code, so
    tools/chip_recovery.py routes a wedge-shaped bench failure on the rc
    alone instead of scanning stdout for a marker string (the old rc=3
    collided with the regression gate). ONE copy, used by the start-of-run
    liveness probe and the whole-run watchdog."""
    record = {
        "metric": "ptb_char_lstm_train_seq_per_sec_per_chip",
        "value": 0.0,
        "unit": "seq/sec",
        "vs_baseline": 0.0,
        "error": f"{error}; see BENCH_TABLE.json for the last complete "
                 "measurement",
    }
    last = _last_good()
    if last is not None:
        record["last_good"] = last
    print(json.dumps(record), flush=True)
    os._exit(LIVENESS_RC)


def _probe_once(timeout_s: float = 60.0) -> str | None:
    """One liveness attempt: tiny matmul + value fetch in a subprocess with
    a hard timeout. Returns None on success, else a failure description.

    Two deliberate details: (a) the probe prints its backend platform and
    the parent REQUIRES "tpu" unless the caller explicitly exported a CPU
    platform — a cleanly-FAILING TPU init silently falls back to CPU,
    where the matmul would succeed and main() would then publish a CPU
    number under the TPU metric; (b) the child is managed with Popen +
    poll, never a blocking communicate after kill — the documented wedge
    leaves children in uninterruptible driver calls where even SIGKILL
    cannot reap them, and waiting on one would burn the watchdog budget
    this probe exists to save."""
    # exact match only: "tpu,cpu" (fallback-ordering syntax) must NOT
    # disable the TPU guard or force the probe onto CPU
    cpu_ok = os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu"
    probe = (
        "import jax; "
        # sitecustomize overrides JAX_PLATFORMS to prefer the TPU plugin;
        # when the caller explicitly asked for CPU, re-assert it at the
        # config level BEFORE the first device query (verify-skill gotcha)
        + ("jax.config.update('jax_platforms', 'cpu'); " if cpu_ok else "")
        + "import jax.numpy as jnp; "
          "x = jnp.ones((128, 128)); float((x @ x).sum()); "
          "print('platform=' + jax.devices()[0].platform, flush=True)"
    )
    child = subprocess.Popen(
        [sys.executable, "-c", probe],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
    )
    deadline = time.monotonic() + timeout_s
    while child.poll() is None and time.monotonic() < deadline:
        time.sleep(0.5)
    if child.poll() is None:
        child.kill()  # may not reap a D-state child — do NOT wait on it
        return f"probe matmul did not complete in {timeout_s:.0f}s"
    out = (child.stdout.read() or "") if child.stdout else ""
    if child.returncode != 0:
        return f"probe exited rc={child.returncode}"
    platform = out.strip().rsplit("platform=", 1)[-1] if "platform=" in out else "?"
    # the tunneled plugin reports an experimental platform name ("axon"),
    # not "tpu" — accept anything that is not the silent CPU fallback
    if platform == "cpu" and not cpu_ok:
        return ("TPU init failed and jax silently fell back to CPU "
                "(probe platform=cpu without JAX_PLATFORMS=cpu); "
                "refusing to publish a CPU number under the TPU metric")
    return None


def _liveness_probe(timeout_s: float = 60.0,
                    window_s: float | None = None) -> None:
    """Bounded-retry liveness gate (VERDICT r3: a single 60 s probe gave
    the driver a zero with no second chance on a TRANSIENT wedge).

    Re-probes every ``timeout_s`` until one attempt succeeds or the retry
    window closes — default 720 s, well inside the 2400 s whole-run
    watchdog so a recovered-late chip still leaves ~28 min of bench
    budget. Each attempt is a FRESH subprocess: the documented wedge
    poisons backend init in the process that touched it, so retrying
    inside one interpreter would never observe a recovery. Window
    override: LSTM_TSP_BENCH_LIVENESS_WINDOW_S (<= 0 means one attempt,
    the pre-r4 fast-fail behavior). On exhaustion, the LAST failure
    reason and the attempt count go into the 0-value contract line."""
    if window_s is None:
        raw = os.environ.get("LSTM_TSP_BENCH_LIVENESS_WINDOW_S", "720")
        try:
            window_s = float(raw)
        except ValueError:
            # a typo'd override must not crash the bench before the JSON
            # contract line can be emitted — ignore it, keep the default
            print(f"bench: ignoring malformed "
                  f"LSTM_TSP_BENCH_LIVENESS_WINDOW_S={raw!r}",
                  file=sys.stderr)
            window_s = 720.0
    window_s = max(window_s, 0.0)
    deadline = time.monotonic() + window_s
    attempts = 0
    while True:
        attempts += 1
        t0 = time.monotonic()
        err = _probe_once(timeout_s)
        if err is None:
            return
        # a fast clean failure (init error, CPU fallback) burns almost no
        # budget — pace retries to ~timeout_s so the window isn't spent
        # spinning on instant failures
        if time.monotonic() >= deadline:
            _fail_json("TPU backend unreachable/wedged at benchmark start "
                       f"({attempts} probe attempts over "
                       f"{window_s:.0f}s retry window): {err}")
        elapsed = time.monotonic() - t0
        if elapsed < timeout_s:
            time.sleep(min(timeout_s - elapsed,
                           max(deadline - time.monotonic(), 0.0)))


def main() -> int:
    _liveness_probe()
    baseline = cpu_baseline()
    try:
        hbm = measure_hbm_bw()
    except Exception as e:  # the BW probe failing must not kill the bench
        hbm = {"error": f"{type(e).__name__}: {e}"}
    value = measure(
        "bfloat16", STEPS * K, WARMUP * K,
        unroll=UNROLL, reps=REPS, steps_per_call=K, device_data=DEVICE_DATA,
        use_pallas=PALLAS,
    )

    table = {}
    compact = {}
    for name in CONFIGS:
        try:
            # ptb_char's post-indexing-fix step (~78 us device) is host-
            # bound at 32-step dispatches; the bigger configs are device-
            # bound at K=32 already (>= 1 ms/step)
            rec = measure_config(
                name, steps_per_call=K if name == "ptb_char" else 32)
        except Exception as e:  # a config failing must not kill the headline
            rec = {"error": f"{type(e).__name__}: {e}"}
        if "error" not in rec:
            # sequential-recurrence roofline: is the residual MFU gap
            # implementation slack or the chain's latency floor?
            try:
                rl = measure_roofline(name)
            except Exception as e:
                rl = {"error": f"{type(e).__name__}: {e}"}
            if "error" not in rl:
                measured = CONFIGS[name]["B"] / rec["seq_per_sec"]  # s/step
                parallel = max(
                    rec["train_flops_step"]
                    - TRAIN_FLOPS_MULTIPLIER * rl["chain_flops"], 0.0
                ) / (PEAK_TFLOPS * 1e12)
                bound = 2.0 * rl["chain_sec"] + parallel
                rl.update(
                    measured_sec_per_step=round(measured, 6),
                    bound_sec_per_step=round(bound, 6),
                    fraction_of_bound=round(bound / measured, 4),
                )
                # Second, STRATEGY-AWARE bound: the floor above assumes one
                # fwd + one bwd chain with everything else perfectly
                # parallel. THIS implementation serializes layers,
                # directions, and the chosen backward kernel's in-chain MXU
                # ops (residentx recomputes z: 2 chain-latency units/step;
                # resident/tiled stream z: 1; recompute fallback re-runs
                # the forward: 2). fraction_of_impl_bound ≈ 1 therefore
                # means "the step runs at the speed of ITS OWN serialized
                # structure" — remaining MFU gap is the structure, not
                # kernel slack; the gap between the two bounds is the
                # (theoretical) prize for overlapping layers/directions.
                try:
                    rl.update(_impl_bound(name, rl, rec, measured))
                    # r4 bandwidth floor: a step can be slower than its
                    # serialized-chain bound simply because its residual
                    # streams saturate HBM. The COMBINED floor is the max
                    # of the two; fraction ≈ 1 against it means the step
                    # runs at the speed of its own structure AND traffic.
                    if "gb_per_sec" in hbm:
                        bw_sec = (rl["stream_bytes_per_step"]
                                  / (hbm["gb_per_sec"] * 1e9))
                        bound2 = max(rl["impl_bound_sec_per_step"], bw_sec)
                        rl.update(
                            bw_bound_sec_per_step=round(bw_sec, 6),
                            bound_binding=("bandwidth"
                                           if bw_sec
                                           > rl["impl_bound_sec_per_step"]
                                           else "serial-chain"),
                            impl_bound2_sec_per_step=round(bound2, 6),
                            fraction_of_impl_bound2=round(
                                bound2 / measured, 4),
                        )
                except Exception as e:
                    rl["impl_bound_error"] = f"{type(e).__name__}: {e}"
            rec["roofline"] = rl
        table[name] = rec
        if "error" not in rec:
            compact[name] = {
                "seq_s": rec["seq_per_sec"],
                "tok_s": rec["tokens_per_sec"],
                "tflops": rec["model_tflops_per_sec"],
                "mfu": rec["mfu_vs_bf16_peak"],
                "bound_frac": rec["roofline"].get("fraction_of_bound"),
            }
        else:
            compact[name] = rec
    try:
        pp_rec = measure_pp_config5()
    except Exception as e:  # PP delta failing must not kill the headline
        pp_rec = {"error": f"{type(e).__name__}: {e}"}
    try:
        gen_rec = measure_generation()
    except Exception as e:
        gen_rec = {"error": f"{type(e).__name__}: {e}"}
    try:
        head = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], capture_output=True,
            text=True, cwd=_DIR, timeout=30).stdout.strip() or None
    except Exception:
        head = None
    with open(TABLE, "w") as f:
        json.dump({
            "peak_tflops_bf16": PEAK_TFLOPS,
            "hbm_bandwidth": hbm,
            "headline_seq_per_sec": round(value, 2),
            "vs_cpu_baseline": round(value / baseline, 2),
            # self-describing provenance: readme_table._vintage reads these
            # (git history would misattribute a fresh uncommitted table to
            # the PREVIOUS measurement's commit)
            "captured_at": datetime.datetime.now(
                datetime.timezone.utc).isoformat(timespec="seconds"),
            "measured_at_commit": head,
            "configs": table,
            "pp_pallas_config5": pp_rec,
            "generation": gen_rec,
        }, f, indent=1)

    print(json.dumps({
        "metric": "ptb_char_lstm_train_seq_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "seq/sec",
        "vs_baseline": round(value / baseline, 2),
        "configs": compact,
        "pp_pallas_speedup_config5": pp_rec.get("pallas_speedup"),
    }))
    return 0


def _watchdog(seconds: float) -> None:
    """Hard wall-clock bound on the whole benchmark. The tunneled chip has
    been observed to WEDGE indefinitely (a jit dispatch that never
    returns); without a bound the driver's end-of-round bench would hang
    the round. On expiry: print the one-line JSON contract with value 0
    and an explicit error so the failure is recorded, then hard-exit (the
    wedged runtime cannot be interrupted from Python)."""
    import threading

    def expire():
        _fail_json(f"benchmark exceeded {seconds:.0f}s — TPU backend "
                   "unreachable/wedged")

    t = threading.Timer(seconds, expire)
    t.daemon = True
    t.start()


if __name__ == "__main__":
    _wd = float(os.environ.get("LSTM_TSP_BENCH_WATCHDOG_S", 2400))
    if _wd > 0:  # <= 0 disables (conventional no-timeout meaning)
        _watchdog(_wd)
    sys.exit(main())
