#!/usr/bin/env python
"""Benchmark: PTB char-LSTM training throughput (BASELINE.md north-star).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

value     = sequences/sec/chip for the full train step (fwd+BPTT+update) on
            config 1 (1-layer, hidden=128, char vocab) on the default device.
baseline  = the same config run single-process on CPU float32 — the accepted
            stand-in for the reference's Spark-CPU executor throughput
            (BASELINE.md: "Spark-CPU baseline ... to be measured"; Spark is
            not installable offline). Measured once and cached in
            BASELINE_MEASURED.json; delete that file to re-measure.
"""

import json
import os
import subprocess
import sys
import time

B, T, HIDDEN, LAYERS, STEPS, WARMUP = 64, 64, 128, 1, 100, 10
UNROLL = 8  # lax.scan unroll (used by the Pallas backward's recompute scan;
            # the CPU baseline keeps unroll=1, faithful to the reference's
            # step-at-a-time unroll)
K = 32    # steps per dispatch for the TPU run (train/multistep.py): one
          # jitted program runs K optimizer steps, so the host dispatch and
          # tunnel round-trip amortise. The CPU baseline keeps
          # one-dispatch-per-step — faithful to the reference's
          # one-Spark-round-per-step structure.
DEVICE_DATA = True  # TPU run stages the corpus in HBM and slices windows
          # on-device (train/device_step.py): per-dispatch host traffic is
          # one scalar. This mirrors the reference's cached-RDD locality
          # (executors iterate a RESIDENT shard; Spark moves only params/
          # grads per round). The CPU baseline keeps the host-fed path.
PALLAS = True  # fused Pallas recurrence kernel for the TPU forward
          # (ops/pallas_lstm.py) — measured fastest honest config on v5e;
          # auto-falls back to lax.scan off-TPU, so the CPU baseline is
          # unaffected.
REPS = 3  # report the best rep (the shared/tunneled chip is noisy)
# MEASUREMENT HONESTY: this environment's tunneled TPU backend absorbs
# thousands of dispatches into an async queue and `block_until_ready` can
# return before real execution completes, inflating short-window timings by
# >100x. The ONLY reliable barrier is fetching a value to the host, so each
# timed rep ends with float(loss), and reps are long (STEPS*K optimizer
# steps) so the queue cannot hide real work.
CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BASELINE_MEASURED.json")


def measure(compute_dtype: str, steps: int, warmup: int, *,
            unroll: int = 1, reps: int = 1, steps_per_call: int = 1,
            device_data: bool = False, use_pallas: bool = False) -> float:
    """Train-step throughput (seq/sec) on the current default backend.

    ``steps``/``warmup`` count optimizer steps; with ``steps_per_call=K`` they
    are grouped into K-step dispatches. Host-fed mode keeps batch stacking
    inside the timed loop (the feed is part of the step cost);
    ``device_data`` stages the corpus in HBM once (outside the timed loop,
    like Spark's one-time RDD cache) and feeds one scalar per dispatch."""
    import jax
    import numpy as np

    from lstm_tensorspark_tpu.data import (
        get_dataset, lm_batch_stream, stacked_batches, stage_lm_data,
        window_index_stream,
    )
    from lstm_tensorspark_tpu.models import LMConfig, init_lm, lm_loss
    from lstm_tensorspark_tpu.train import (
        make_device_lm_train_step, make_multi_train_step, make_optimizer,
        make_train_step,
    )
    from lstm_tensorspark_tpu.train.loop import init_train_state

    data = get_dataset("ptb_char")
    cfg = LMConfig(
        vocab_size=len(data["vocab"]),
        hidden_size=HIDDEN,
        num_layers=LAYERS,
        compute_dtype=compute_dtype,
        scan_unroll=unroll,
        use_pallas=use_pallas,
    )

    def loss_fn(params, batch, rng):
        return lm_loss(params, batch, cfg)

    opt = make_optimizer("sgd", 0.5)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    state = init_train_state(params, opt, jax.random.PRNGKey(1))

    k = steps_per_call
    if device_data:
        staged = stage_lm_data(data["train"], B, T)
        dstep = make_device_lm_train_step(loss_fn, opt, staged, steps_per_call=k)
        step = lambda s, w0: dstep(s, staged.arrays, w0)  # noqa: E731
        it = window_index_stream(staged, k)
    elif k > 1:
        step = make_multi_train_step(loss_fn, opt)
        it = stacked_batches(lm_batch_stream(data["train"], B, T), k)
    else:
        step = make_train_step(loss_fn, opt)
        it = lm_batch_stream(data["train"], B, T)
    calls, warm_calls = max(steps // k, 1), max(warmup // k, 1)

    for _ in range(warm_calls):
        state, m = step(state, next(it))
    float(m["loss"])  # TRUE barrier (see MEASUREMENT HONESTY above)
    best = 0.0
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(calls):
            state, m = step(state, next(it))
        float(m["loss"])  # value fetch = the only trustworthy sync here
        dt = time.perf_counter() - t0
        best = max(best, B * calls * k / dt)
    return best


def cpu_baseline() -> float:
    """Single-process CPU float32 reference throughput, cached."""
    if os.path.exists(CACHE):
        with open(CACHE) as f:
            return json.load(f)["cpu_seq_per_sec"]
    # fresh interpreter so the CPU platform can be forced cleanly
    code = (
        "import jax, json;"
        "jax.config.update('jax_platforms','cpu');"
        "import bench;"
        "print('CPUBASE', bench.measure('float32', steps=10, warmup=2))"
    )
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, cwd=os.path.dirname(CACHE) or ".",
    )
    line = [l for l in out.stdout.splitlines() if l.startswith("CPUBASE")]
    if not line:
        raise RuntimeError(f"cpu baseline failed: {out.stderr[-2000:]}")
    value = float(line[0].split()[1])
    with open(CACHE, "w") as f:
        json.dump({"cpu_seq_per_sec": value, "config": {
            "B": B, "T": T, "hidden": HIDDEN, "layers": LAYERS,
            "dtype": "float32", "note": "single-process CPU stand-in for Spark-CPU baseline",
        }}, f, indent=1)
    return value


def main() -> int:
    baseline = cpu_baseline()
    value = measure(
        "bfloat16", STEPS * K, WARMUP * K,
        unroll=UNROLL, reps=REPS, steps_per_call=K, device_data=DEVICE_DATA,
        use_pallas=PALLAS,
    )
    print(json.dumps({
        "metric": "ptb_char_lstm_train_seq_per_sec_per_chip",
        "value": round(value, 2),
        "unit": "seq/sec",
        "vs_baseline": round(value / baseline, 2),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
