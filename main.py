#!/usr/bin/env python
"""Reference-parity entrypoint (SURVEY.md §1 L5: single main script at repo
root). Where the reference ran ``spark-submit main.py --flags``, this runs the
same CLI surface on the TPU mesh: ``python main.py --flags``."""

from lstm_tensorspark_tpu.cli import main

if __name__ == "__main__":
    raise SystemExit(main())
