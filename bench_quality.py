#!/usr/bin/env python
"""Wall-clock-to-quality: the QUALITY half of the north star, ALL 5 configs.

``BASELINE.json:metric`` is "seq/sec/chip; wall-clock to reference
perplexity" — this harness measures the second half for every BASELINE.md
config: train the IDENTICAL config (same synthetic corpus, same seed, same
hyperparameters) on the TPU and on single-process CPU (the offline stand-in
for the reference's Spark-CPU executors), log the task's eval-quality curve
to JSONL, and record the first wall-clock time each run reaches each target.

Per-config quality metric (VERDICT r2 item 2):
- configs 1/3/5 (LM): eval perplexity, lower is better;
- config 2 (IMDB bi-LSTM): eval accuracy, higher is better;
- config 4 (UCI seq2seq): free-running eval MSE, lower is better.

Outputs:
- ``quality_curves/<config>_<platform>.jsonl`` — full metric curves (the
  CLI's own JSONL: {"t": seconds, "step", <metric>, ...});
- ``BASELINE_MEASURED.json`` gains a "quality" section:
  time-to-target per config/platform + the TPU speedup at the tightest
  target both platforms reached.

Timing honesty: "t" counts from process logger start (includes compile —
the launch-to-quality number); "t_train" additionally subtracts the time of
the first logged training record (post-compile steady-state). Both are
reported. The tunneled-TPU async-queue caveat does not bite here: each eval
fetches loss values to the host, a true barrier.

Each platform runs its FASTEST HONEST configuration of the same model/data/
optimizer (identical math; trajectories agree to float tolerance): the TPU
legs add --use-pallas (fused recurrence kernels; no-op fallback on CPU),
K-step dispatch batching where the tunnel dispatch would otherwise dominate
(tests/test_multistep.py proves K-step parity), and --device-data
--fused-eval (the eval pass runs inside the train executable on
device-resident eval data — identical eval math, tests/test_fused_eval.py,
but zero train/eval executable swaps: the swap cost ~3.3 s/eval on the
tunneled chip and DOMINATED the small configs); the CPU legs stay per-step —
compute-bound, and faithful to the reference's one-Spark-round-per-step.
NOTE: with --steps-per-call K, --log-every/--eval-every count CALLS
(train_loop contract), so TPU cadences are pre-divided by K below;
--num-steps still counts optimizer steps.

Each config/platform additionally measures a WARM-CACHE leg: a few-step
run populates a fresh --compilation-cache directory (same program shapes →
the same executables compile and cache), then a full run against it gives
the launch-to-quality number a REPEAT run sees — XLA compilation is a
once-per-program-shape cost, so cold (first-ever run) and warm (every run
after) are both honest, and both are reported (``summary.speedup`` cold,
``summary.speedup_warm`` warm).

Run: ``python bench_quality.py [config ...]`` (TPU visible; CPU leg runs in
a subprocess with the platform forced before any device query).
"""

from __future__ import annotations

import datetime
import json
import os
import shutil
import subprocess
import sys

_DIR = os.path.dirname(os.path.abspath(__file__))
CURVES = os.path.join(_DIR, "quality_curves")
CACHE = os.path.join(_DIR, "BASELINE_MEASURED.json")
LEG_TIMEOUT_S = 2200  # > 2x the slowest expected leg (config2 CPU ~1000 s
                      # at the r4 discriminating-task step budget)

# Targets are ordered loose → tight; the summary reports the tightest one
# BOTH platforms reached inside the step budget. r4 (VERDICT r3 weak 2):
# the synthetic tasks were hardened (controlled-entropy word corpora,
# low-SNR classifier — data/corpus.py synthetic_word_corpus,
# datasets.py imdb(signal=...)) so curves decline across hundreds of
# steps, and the target lists are DENSE so the tightest common target
# lands mid-curve wherever the plateau turns out to be.
PPL_TARGETS = [12.0, 10.0, 8.0, 6.0, 5.0, 4.5, 4.0, 3.5, 3.0, 2.5, 2.0]

CONFIGS = {
    "config1_ptb_char": dict(
        metric="eval_ppl", mode="min", targets=PPL_TARGETS,
        argv=[
            "--dataset", "ptb_char", "--hidden-units", "128",
            "--num-layers", "1", "--batch-size", "64", "--seq-len", "64",
            "--learning-rate", "1.0", "--num-steps", "800",
            "--log-every", "50", "--eval-every", "100", "--backend", "single",
        ],
        # --fused-eval: the eval pass runs INSIDE the train executable on a
        # device-resident valid stream (no train/eval program swap — the
        # swap cost ~3.3 s on the tunneled chip and DOMINATED this tiny
        # config). Eval cadence 4 calls = 100 steps, matching the CPU
        # leg's --eval-every 100 exactly: both platforms can detect a
        # target crossing at the same optimizer steps (unequal cadences
        # would bias time-to-target toward the finer-grained leg)
        tpu_extra=["--use-pallas", "--steps-per-call", "25",
                   "--device-data", "--fused-eval",
                   "--log-every", "2", "--eval-every", "4"],
    ),
    # signal=0.25 synthetic task (datasets.py): accuracy climbs over
    # ~200+ steps instead of saturating at step 40 — the race spends its
    # wall-clock training on both platforms
    "config2_imdb": dict(
        metric="eval_accuracy", mode="max",
        targets=[0.55, 0.60, 0.65, 0.70, 0.75, 0.80, 0.85, 0.90, 0.95],
        argv=[
            "--dataset", "imdb", "--hidden-units", "256", "--num-layers", "1",
            "--batch-size", "64", "--seq-len", "400",
            "--learning-rate", "0.2", "--num-steps", "240",
            "--log-every", "20", "--eval-every", "20", "--backend", "single",
        ],
        tpu_extra=["--use-pallas", "--steps-per-call", "10",
                   "--device-data", "--fused-eval",
                   "--log-every", "2", "--eval-every", "2"],
    ),
    # controlled-entropy 1,000-word stand-in: ppl descends through the
    # unigram level (~hundreds) into the bigram structure over 400 steps
    "config3_wikitext2": dict(
        metric="eval_ppl", mode="min",
        targets=[300.0, 200.0, 150.0, 100.0, 80.0, 60.0, 50.0, 40.0, 30.0,
                 25.0, 20.0, 15.0, 12.0, 10.0, 8.0, 6.0, 5.0, 4.0, 3.0],
        argv=[
            "--dataset", "wikitext2", "--hidden-units", "650",
            "--num-layers", "2", "--batch-size", "64", "--seq-len", "35",
            "--learning-rate", "1.0", "--num-steps", "400",
            "--log-every", "25", "--eval-every", "50", "--backend", "single",
        ],
        # eval cadence 2 calls = 50 steps = the CPU leg's --eval-every 50
        tpu_extra=["--use-pallas", "--steps-per-call", "25",
                   "--device-data", "--fused-eval",
                   "--log-every", "1", "--eval-every", "2"],
    ),
    "config4_uci": dict(
        metric="eval_mse", mode="min",
        targets=[0.5, 0.3, 0.2, 0.15, 0.12, 0.10, 0.08, 0.05],
        argv=[
            "--dataset", "uci_electricity", "--hidden-units", "256",
            "--num-layers", "2", "--batch-size", "64", "--seq-len", "168",
            "--learning-rate", "0.05", "--num-steps", "150",
            "--log-every", "15", "--eval-every", "15", "--backend", "single",
        ],
        tpu_extra=["--use-pallas", "--steps-per-call", "15",
                   "--device-data", "--fused-eval",
                   "--log-every", "1", "--eval-every", "1"],
    ),
    # bounded-step time-to-ppl at WT-103-class scale: 100 steps is the
    # bound (CPU ~7-9 s/step at these dims with the 5,000-word stand-in);
    # dense targets from the ~5,000 init ppl down through the unigram
    # level so the tightest common target lands mid-curve;
    # lr 0.5 — 1.0 diverges at H=1024/L=4 bf16
    "config5_wikitext103": dict(
        metric="eval_ppl", mode="min",
        targets=[3000.0, 2000.0, 1500.0, 1000.0, 700.0, 500.0, 400.0,
                 300.0, 250.0, 200.0, 150.0, 120.0, 100.0, 80.0, 60.0,
                 50.0, 40.0, 30.0, 25.0, 20.0, 15.0, 12.0, 10.0],
        argv=[
            "--dataset", "wikitext103", "--hidden-units", "1024",
            "--num-layers", "4", "--batch-size", "32", "--seq-len", "64",
            "--learning-rate", "0.5", "--num-steps", "100",
            "--log-every", "10", "--eval-every", "20",
            "--eval-batches", "4", "--backend", "single",
        ],
        tpu_extra=["--use-pallas", "--steps-per-call", "5",
                   "--device-data", "--fused-eval",
                   "--log-every", "2", "--eval-every", "4"],
    ),
}


def run_leg(name: str, platform: str, *,
            cache_dir: str | None = None, num_steps: int | None = None,
            tag: str = "") -> str:
    """Run one training leg, return the JSONL path.

    ``cache_dir`` passes --compilation-cache; ``tag`` suffixes the output
    curve filename (warm/populate legs must NOT clobber the cold curve).
    ``num_steps`` overrides the step budget (used for the cheap
    cache-populate run: same program SHAPES, so the same executables
    compile and cache, but only a few optimizer steps execute)."""
    os.makedirs(CURVES, exist_ok=True)
    jsonl = os.path.join(CURVES, f"{name}_{platform}{tag}.jsonl")
    if os.path.exists(jsonl):
        os.remove(jsonl)
    spec = CONFIGS[name]
    argv = list(spec["argv"])
    if platform == "tpu":
        argv += spec.get("tpu_extra", [])
    if cache_dir:
        argv += ["--compilation-cache", cache_dir]
    if num_steps is not None:
        argv += ["--num-steps", str(num_steps)]
    argv += ["--jsonl", jsonl]
    if platform == "cpu":
        code = (
            "import sys, jax;"
            "jax.config.update('jax_platforms','cpu');"
            "from lstm_tensorspark_tpu.cli import main;"
            f"sys.exit(main({argv!r}))"
        )
        cmd = [sys.executable, "-c", code]
    else:
        cmd = [sys.executable, "main.py", *argv]
    try:
        # the tunneled chip can wedge indefinitely on an executable swap —
        # bound every leg so one hang cannot stall the whole bench
        proc = subprocess.run(cmd, cwd=_DIR, capture_output=True, text=True,
                              timeout=LEG_TIMEOUT_S)
    except subprocess.TimeoutExpired:
        raise RuntimeError(
            f"{name}/{platform}{tag} hung past {LEG_TIMEOUT_S}s (tunnel "
            "wedge?) — killed; curve so far is on disk"
        )
    if proc.returncode != 0:
        raise RuntimeError(
            f"{name}/{platform}{tag} failed rc={proc.returncode}: "
            f"{proc.stderr[-2000:]}"
        )
    return jsonl


def time_to_targets(jsonl: str, metric: str, mode: str, targets) -> dict:
    """Scan the curve: first wall-clock at/beyond each quality target."""
    evals = []
    first_step_t = None
    for line in open(jsonl):
        r = json.loads(line)
        if first_step_t is None and "loss" in r and "step" in r:
            first_step_t = r["t"]
        if metric in r:
            evals.append((r["t"], r[metric], r.get("step")))
    out = {"metric": metric, "targets": {},
           "final": evals[-1][1] if evals else None,
           "first_step_t": first_step_t}
    reached = (
        (lambda v, tgt: v <= tgt) if mode == "min"
        else (lambda v, tgt: v >= tgt)
    )
    for tgt in targets:
        hit = next((e for e in evals if reached(e[1], tgt)), None)
        if hit:
            out["targets"][str(tgt)] = {
                "t": hit[0],
                "t_train": round(hit[0] - (first_step_t or 0.0), 3),
                "step": hit[2],
            }
    return out


def _tightest_common(spec, a: dict, b: dict):
    """Tightest target reached by BOTH platforms' target maps, or None."""
    both = [t for t in map(str, spec["targets"])
            if t in a["targets"] and t in b["targets"]]
    return both[-1] if both else None


def _write_cache(results) -> None:
    """Write results incrementally (after EVERY config) so a hung or killed
    leg loses at most the config in flight."""
    cache = {}
    if os.path.exists(CACHE):
        with open(CACHE) as f:
            cache = json.load(f)
    cache["quality"] = {
        "note": ("wall-clock to quality target (ppl / accuracy / mse per "
                 "task), identical config+data+seed on TPU vs single-process "
                 "CPU (Spark-CPU stand-in); t includes compile, t_train is "
                 "post-compile; *_warm legs repeat the run against a "
                 "populated --compilation-cache (launch-to-quality without "
                 "the once-per-shape XLA compile)"),
        "results": results,
    }
    with open(CACHE, "w") as f:
        json.dump(cache, f, indent=1)


def _summarize(name, spec, results) -> None:
    """Recompute the config's cold + warm summaries from its target maps."""
    entry = results[name]
    if "tpu" in entry and "cpu" in entry:
        tight = _tightest_common(spec, entry["tpu"], entry["cpu"])
        if tight:
            tt = entry["tpu"]["targets"][tight]
            tc = entry["cpu"]["targets"][tight]
            entry["summary"] = {
                "metric": spec["metric"],
                "target": float(tight),
                "tpu_seconds": tt["t"],
                "cpu_seconds": tc["t"],
                "speedup": round(tc["t"] / tt["t"], 2),
                "tpu_seconds_train": tt["t_train"],
                "cpu_seconds_train": tc["t_train"],
                "speedup_train": round(
                    tc["t_train"] / max(tt["t_train"], 1e-9), 2),
            }
            print(f"[bench_quality] {name}: {spec['metric']} @ {tight} "
                  f"TPU {tt['t']:.1f}s vs CPU {tc['t']:.1f}s "
                  f"({entry['summary']['speedup']}x; "
                  f"post-compile {entry['summary']['speedup_train']}x)",
                  flush=True)
    if "tpu_warm" in entry and "cpu_warm" in entry:
        tight_w = _tightest_common(spec, entry["tpu_warm"], entry["cpu_warm"])
        if tight_w:
            tt = entry["tpu_warm"]["targets"][tight_w]
            tc = entry["cpu_warm"]["targets"][tight_w]
            entry.setdefault("summary", {}).update({
                "warm_target": float(tight_w),
                "tpu_seconds_warm": tt["t"],
                "cpu_seconds_warm": tc["t"],
                "speedup_warm": round(tc["t"] / tt["t"], 2),
            })
            print(f"[bench_quality] {name} warm launch-to-target @ "
                  f"{tight_w}: TPU {tt['t']:.1f}s vs CPU {tc['t']:.1f}s "
                  f"({entry['summary']['speedup_warm']}x)", flush=True)


def main(only: list[str] | None = None, *, mode: str = "full",
         platforms=("tpu", "cpu")) -> int:
    """mode: "full" = run cold + warm legs; "warm" = run only the
    populate+warm legs (cold results recomputed from existing curves);
    "recompute" = no runs, rebuild every result from the curves on disk.
    ``platforms`` restricts which legs RUN (results for the other platform
    are still recomputed from curves on disk when present) — lets the CPU
    halves bank while the TPU is unavailable, and vice versa."""
    # merge into any existing results so single-config reruns keep the rest
    results = {}
    if os.path.exists(CACHE):
        with open(CACHE) as f:
            results = json.load(f).get("quality", {}).get("results", {})
    for name in (only or CONFIGS):
        spec = CONFIGS[name]
        # PRESERVE previously persisted results: a warm-only/recompute pass
        # on a machine missing some curve files must not erase the entries
        # it cannot rebuild — only overwrite what this pass measured/reread
        results[name] = {**(results.get(name) or {}),
                         "metric": spec["metric"]}
        for platform in ("tpu", "cpu"):
            run_this = platform in platforms
            cold_jsonl = os.path.join(CURVES, f"{name}_{platform}.jsonl")
            if mode == "full" and run_this:
                print(f"[bench_quality] {name} on {platform} ...", flush=True)
                cold_jsonl = run_leg(name, platform)
                # per-leg vintage: tools/readme_quality.py renders it so
                # every published number carries when it was measured
                results[name][platform + "_measured_at"] = (
                    datetime.date.today().isoformat())
                if platform == "tpu":
                    # a fresh TPU measurement resolves any r5
                    # task-change invalidation marker (the marker means
                    # "the TPU half predates the current task")
                    results[name].pop("invalidated", None)
            if os.path.exists(cold_jsonl):
                results[name][platform] = time_to_targets(
                    cold_jsonl, spec["metric"], spec["mode"], spec["targets"]
                )
            warm_jsonl = os.path.join(CURVES, f"{name}_{platform}_warm.jsonl")
            if mode in ("full", "warm") and run_this:
                # warm-cache leg: the LAUNCH-to-quality number a repeat run
                # sees with --compilation-cache. Populate the cache with a
                # few-step run (same program shapes → same executables
                # compile+cache), then measure a full run against it.
                cache = os.path.join(CURVES, f".xla_{name}_{platform}")
                shutil.rmtree(cache, ignore_errors=True)
                print(f"[bench_quality] {name} on {platform} (warm cache) "
                      "...", flush=True)
                k = next((int(spec["tpu_extra"][i + 1])
                          for i, a in enumerate(spec.get("tpu_extra", []))
                          if a == "--steps-per-call"), 1) \
                    if platform == "tpu" else 1
                run_leg(name, platform, cache_dir=cache, num_steps=2 * k,
                        tag="_populate")
                warm_jsonl = run_leg(name, platform, cache_dir=cache,
                                     tag="_warm")
            if os.path.exists(warm_jsonl):
                results[name][platform + "_warm"] = time_to_targets(
                    warm_jsonl, spec["metric"], spec["mode"], spec["targets"]
                )
        _summarize(name, spec, results)
        _write_cache(results)

    print(json.dumps({"quality": {
        n: r.get("summary", "no common target") for n, r in results.items()
    }}))
    return 0


if __name__ == "__main__":
    argv = sys.argv[1:]
    mode = "full"
    for flag, m in (("--recompute", "recompute"), ("--warm-only", "warm")):
        if flag in argv:
            mode = m
            argv.remove(flag)
    platforms = ("tpu", "cpu")
    if "--platform" in argv:
        i = argv.index("--platform")
        if i + 1 >= len(argv) or argv[i + 1] not in ("tpu", "cpu"):
            raise SystemExit("--platform takes exactly one of: tpu, cpu")
        platforms = (argv[i + 1],)
        del argv[i:i + 2]
    sys.exit(main(argv or None, mode=mode, platforms=platforms))
