#!/bin/sh
# BASELINE config 4: UCI-Electricity seq2seq forecaster (168h context -> 24h)
exec python main.py --dataset uci_electricity --hidden-units 128 --num-layers 1 \
  --batch-size 64 --seq-len 168 --epochs 5 --optimizer adam --learning-rate 1e-3 \
  --clip-norm 1.0 --compute-dtype bfloat16 --eval-every 200 \
  ${DATA:+--data-path "$DATA"} "$@"
