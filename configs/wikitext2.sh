#!/bin/sh
# BASELINE config 3: WikiText-2 word LM, 2x650 stacked
exec python main.py --dataset wikitext2 --hidden-units 650 --num-layers 2 \
  --batch-size 32 --seq-len 70 --epochs 10 --optimizer sgd --learning-rate 2.0 \
  --clip-norm 0.25 --dropout 0.5 --stateful --compute-dtype bfloat16 \
  --logits-dtype bfloat16 \
  --eval-every 1000 ${DATA:+--data-path "$DATA"} "$@"
