#!/bin/sh
# BASELINE config 5: WikiText-103 LM 4x1024, data-parallel across a pod slice.
# Multi-host: run once per host with --coordinator/--num-processes/--process-id.
exec python main.py --dataset wikitext103 --hidden-units 1024 --num-layers 4 \
  --batch-size 256 --seq-len 128 --epochs 1 --optimizer adam --learning-rate 1e-3 \
  --clip-norm 1.0 --dropout 0.2 --stateful --compute-dtype bfloat16 \
  --logits-dtype bfloat16 \
  --remat-chunk 32 --eval-every 1000 ${DATA:+--data-path "$DATA"} "$@"
