#!/bin/sh
# BASELINE config 2: IMDB bi-LSTM hidden=256 seq-len=400
exec python main.py --dataset imdb --hidden-units 256 --num-layers 1 \
  --batch-size 32 --seq-len 400 --epochs 3 --optimizer adam \
  --learning-rate 1e-3 --clip-norm 1.0 --dropout 0.2 \
  --compute-dtype bfloat16 --remat-chunk 50 --eval-every 200 \
  ${DATA:+--data-path "$DATA"} "$@"
