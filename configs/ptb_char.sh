#!/bin/sh
# BASELINE config 1: PTB char-LSTM 1x128 (single chip)
exec python main.py --dataset ptb_char --hidden-units 128 --num-layers 1 \
  --batch-size 64 --seq-len 64 --epochs 5 --learning-rate 0.5 --stateful \
  --compute-dtype bfloat16 --eval-every 500 ${DATA:+--data-path "$DATA"} "$@"
