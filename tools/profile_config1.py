#!/usr/bin/env python
"""Per-kernel device-time breakdown of the config-1 (PTB char) K-step train
program on the real chip: trace a few dispatches with jax.profiler, parse
the xplane with jax.profiler.ProfileData, aggregate kernel durations per
optimizer step.

This is the diagnostic that found the vocabulary-indexing bottleneck
(ops/embedding.py): before the fix it showed 43 us/step in the target-logit
gather and 28 us/step in the embedding-grad scatter vs 29 us/step for the
fused Pallas recurrence pair — 48% of the step in indexing. After the fix
the same trace reads ~78 us/step total with both kernels gone. Rerun it
whenever a config's measured step time drifts from its roofline bound
(BENCH_TABLE.json:roofline) to see where the slack actually is.
"""

import collections
import glob
import os
import shutil
import sys
import time

import jax

PROF_DIR = "/tmp/prof_config1"
K = 32  # dispatch size for the trace (per-step aggregation divides it out;
        # bench.py's headline K differs — this only sets trace granularity)
B, T, HIDDEN, LAYERS = 64, 64, 128, 1


def build_step():
    from lstm_tensorspark_tpu.data import (
        get_dataset, stage_lm_data, window_index_stream,
    )
    from lstm_tensorspark_tpu.models import LMConfig, init_lm, lm_loss
    from lstm_tensorspark_tpu.train import make_device_lm_train_step, make_optimizer
    from lstm_tensorspark_tpu.train.loop import init_train_state

    data = get_dataset("ptb_char")
    cfg = LMConfig(vocab_size=len(data["vocab"]), hidden_size=HIDDEN,
                   num_layers=LAYERS, compute_dtype="bfloat16",
                   scan_unroll=8, use_pallas=True)

    def loss_fn(params, batch, rng):
        return lm_loss(params, batch, cfg)

    opt = make_optimizer("sgd", 0.5)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    state = init_train_state(params, opt, jax.random.PRNGKey(1))
    staged = stage_lm_data(data["train"], B, T)
    dstep = make_device_lm_train_step(loss_fn, opt, staged, steps_per_call=K)
    it = window_index_stream(staged, K)
    return (lambda s, w0: dstep(s, staged.arrays, w0)), state, it


def main():
    step, state, it = build_step()
    # warm: compile + a few executions
    for _ in range(4):
        state, m = step(state, next(it))
    float(m["loss"])

    shutil.rmtree(PROF_DIR, ignore_errors=True)
    calls = 8
    with jax.profiler.trace(PROF_DIR):
        for _ in range(calls):
            state, m = step(state, next(it))
        float(m["loss"])

    paths = glob.glob(os.path.join(PROF_DIR, "**", "*.xplane.pb"),
                      recursive=True)
    if not paths:
        print("no xplane written", file=sys.stderr)
        return 1
    pd = jax.profiler.ProfileData.from_file(paths[0])
    plane_names = [pl.name for pl in pd.planes]
    print("planes:", plane_names, file=sys.stderr)

    # Device plane(s): aggregate total duration + occurrence count per kernel.
    for pl in pd.planes:
        if "TPU" not in pl.name and "Device" not in pl.name:
            continue
        agg = collections.defaultdict(lambda: [0.0, 0])
        t_min, t_max = float("inf"), 0.0
        for line in pl.lines:
            for ev in line.events:
                name = ev.name
                dur = (ev.duration_ns or 0) / 1e3  # us
                agg[name][0] += dur
                agg[name][1] += 1
                if ev.start_ns:
                    t_min = min(t_min, ev.start_ns)
                    t_max = max(t_max, ev.start_ns + (ev.duration_ns or 0))
        steps_total = calls * K
        span_us = (t_max - t_min) / 1e3 if t_max > t_min else 0.0
        print(f"\n=== plane {pl.name}: {steps_total} optimizer steps, "
              f"trace span {span_us:.0f} us "
              f"({span_us / steps_total:.2f} us/step) ===")
        rows = sorted(agg.items(), key=lambda kv: -kv[1][0])
        total = sum(v[0] for _, v in rows)
        print(f"{'us/step':>9} {'count/step':>11} {'pct':>5}  kernel")
        for name, (dur, cnt) in rows[:40]:
            print(f"{dur / steps_total:9.3f} {cnt / steps_total:11.2f} "
                  f"{100 * dur / total:5.1f}  {name[:100]}")
        print(f"{total / steps_total:9.3f} {'':>11} 100.0  TOTAL device time")
    return 0


if __name__ == "__main__":
    sys.exit(main())
