"""``warmup-coverage``: every compile-key family must be reachable from a
``warmup`` method.

PR 4 fixed this class twice: compile keys (``("decode_window", bucket,
K, sampling)``-style tuples bumped into ``compile_counts`` at trace
time) that no warmup path dispatched meant the FIRST traffic burst paid
a mid-run XLA compile — 8x latency on the victim request, invisible in
any unit test that reuses a warm engine.

Statically: a **family** is the leading string of a tuple literal that
ends up keying ``compile_counts`` (``count_key = ("prefill", ...)`` ...
``self.compile_counts[count_key] += 1``, or the subscript written with
the tuple inline). A family is **covered** when its defining function is
reachable — through resolvable ``self.x()`` / typed-attribute calls —
from any method named ``warmup`` in the analyzed tree. Uncovered
families fail the gate: either warm them or explain why in a
suppression/baseline entry.
"""

from __future__ import annotations

import ast

from .core import Finding, Rule, register
from .model import ClassInfo, ModuleInfo, Project, local_alias_types


def _family_of_tuple(node: ast.AST) -> str | None:
    if (isinstance(node, ast.Tuple) and node.elts
            and isinstance(node.elts[0], ast.Constant)
            and isinstance(node.elts[0].value, str)):
        return node.elts[0].value
    return None


def _compile_count_subscripted(fn: ast.FunctionDef, var: str) -> bool:
    """Does ``fn`` (or a nested def) subscript ``*.compile_counts`` with
    ``var``?"""
    for sub in ast.walk(fn):
        if (isinstance(sub, ast.Subscript)
                and isinstance(sub.value, ast.Attribute)
                and sub.value.attr == "compile_counts"
                and isinstance(sub.slice, ast.Name)
                and sub.slice.id == var):
            return True
    return False


def _families_in_method(fn: ast.FunctionDef) -> list[tuple[str, int]]:
    """(family, line) for compile-key tuples defined in this method."""
    out: list[tuple[str, int]] = []
    for sub in ast.walk(fn):
        # count_key = ("prefill", ...) later keying compile_counts
        if (isinstance(sub, ast.Assign) and len(sub.targets) == 1
                and isinstance(sub.targets[0], ast.Name)):
            fam = _family_of_tuple(sub.value)
            if fam is not None and _compile_count_subscripted(
                    fn, sub.targets[0].id):
                out.append((fam, sub.lineno))
        # self.compile_counts[("prefill", ...)] += 1 inline
        if (isinstance(sub, ast.Subscript)
                and isinstance(sub.value, ast.Attribute)
                and sub.value.attr == "compile_counts"):
            fam = _family_of_tuple(sub.slice)
            if fam is not None:
                out.append((fam, sub.lineno))
    return out


@register
class WarmupCoverageRule(Rule):
    id = "warmup-coverage"
    doc = ("Compile-key families (leading strings of compile_counts key "
           "tuples) whose defining method is not reachable from any "
           "warmup() method — those programs compile mid-traffic, "
           "charging a real request the XLA compile.")

    def run(self, project: Project) -> list[Finding]:
        # (family, owning method) -> (module, line): EVERY defining
        # method is tracked — two methods sharing a family string are two
        # program sets, and each must be warmable on its own
        families: dict[tuple[str, tuple[str, str]],
                       tuple[ModuleInfo, int]] = {}
        for module in project.modules:
            for cls in module.classes.values():
                for meth_name, meth in cls.methods.items():
                    for fam, line in _families_in_method(meth):
                        families.setdefault(
                            (fam, (cls.name, meth_name)), (module, line))
        if not families:
            return []
        reachable = self._reachable_from_warmups(project)
        findings: list[Finding] = []
        for (fam, owner), (module, line) in sorted(families.items()):
            if owner not in reachable:
                findings.append(Finding(
                    self.id, module.rel, line,
                    f"compile-key family {fam!r} (defined in "
                    f"{owner[0]}.{owner[1]}) is not reachable from any "
                    "warmup() — it will compile mid-traffic"))
        return findings

    @staticmethod
    def _reachable_from_warmups(project: Project) -> set[tuple[str, str]]:
        roots: list[tuple[ClassInfo, ModuleInfo]] = []
        for module in project.modules:
            for cls in module.classes.values():
                if "warmup" in cls.methods:
                    roots.append((cls, module))
        seen: set[tuple[str, str]] = set()
        stack: list[tuple[ClassInfo, ModuleInfo, str]] = [
            (cls, module, "warmup") for cls, module in roots]
        while stack:
            cls, module, meth_name = stack.pop()
            key = (cls.name, meth_name)
            if key in seen or meth_name not in cls.methods:
                continue
            seen.add(key)
            meth = cls.methods[meth_name]
            local_types = local_alias_types(meth, project, cls)
            for sub in ast.walk(meth):
                if not isinstance(sub, ast.Call):
                    continue
                resolved = project.resolve_call(sub, module, cls,
                                                local_types)
                if resolved is None or resolved[0] is None:
                    continue
                owner, callee = resolved
                stack.append((owner, owner.module, callee.name))
        return seen
