"""graftlint — AST-based invariant analyzer for this repo.

Stdlib-``ast`` only (no new dependencies). ``python -m tools.lint`` runs
every registered rule over ``lstm_tensorspark_tpu/`` + ``tools/`` and
gates on tools/lint_baseline.txt exactly like tools/tier1_diff.py gates
tier-1: exit ``REGRESSION_RC`` (3) only on NEW findings. Rule catalogue,
suppression policy and how to add a rule: docs/LINT.md.
"""

from . import core, model  # noqa: F401
# importing the rule modules populates core.RULES
from . import (  # noqa: F401
    rules_except,
    rules_hostsync,
    rules_hygiene,
    rules_iolock,
    rules_locks,
    rules_metrics,
    rules_resources,
    rules_threads,
    rules_toctou,
    rules_warmup,
)
from .core import RULES, Finding, run_rules  # noqa: F401
from .model import load_project  # noqa: F401
