"""``host-sync-in-hot-path``: device→host synchronisation inside code
that must never block on the device.

Three hot scopes, matching how this repo actually loses its async
pipeline (PR 3's whole tentpole was deleting one stray per-token
``np.asarray``):

1. **jit-traced bodies** — functions decorated with ``@jax.jit`` /
   ``@partial(jax.jit, ...)`` or passed to a ``jit(...)`` call in the
   same scope (the ``fn = jax.jit(prefill_fn)`` idiom in serve/engine.py);
2. **lax.scan bodies** — functions passed as the first argument to a
   ``lax.scan``/``jax.lax.scan`` call (window/step bodies);
3. **the scheduler loop** — methods of scheduler classes (``Batcher``,
   and the tiered cache's spill worker ``SessionTiers``) reachable from
   ``run``/``step``/``drain``: the continuous-batching loop where one
   blocking fetch serialises every session's decode, and the spill
   thread whose job is to keep the ONE device→host fetch of the spill
   plane off the scheduler.

Flagged syncs: ``np.asarray``/``np.array``, ``jax.device_get``,
``.item()``, ``.block_until_ready()``. In a traced body these are
either a tracer error waiting to happen or a silent constant-fold; in
the scheduler loop they stall the pipeline. The designated fetch points
(``fetch_window`` — the documented ONLY sync of the windowed path;
``fetch_detached`` — the spill worker's single designated device→host
fetch, StateCache.fetch_detached — and the prefill/decode return
fetches in the engine, which are outside these scopes) stay legal;
anything else needs an explicit suppression with a reason.
"""

from __future__ import annotations

import ast

from .core import Finding, Rule, register
from .model import ModuleInfo, Project, self_call_closure

#: classes whose run/step/drain closure is the serving hot loop (the
#: batcher's scheduler iteration, the tiered cache's spill worker — its
#: whole point is owning the spill plane's one designated sync — and
#: the remote-replica RPC shim's heartbeat poller, serve/remote.py:
#: a scheduler thread by contract that must never touch the device)
SCHEDULER_CLASSES = {"Batcher", "SessionTiers", "RemoteBatcher"}
_SCHEDULER_ENTRIES = {"run", "step", "drain"}
#: attribute-call names that ARE the designated sync points — a direct
#: np.asarray around them is the blessed fetch, not a stray sync
#: (fetch_window: the windowed-decode readback; fetch_window_summary:
#: the same single sync extended with the per-row on-device scheduler
#: summary the fused Pallas decode window latches — one device_get for
#: tokens + remaining + alive; fetch_detached: the spill worker's
#: single device→host fetch, StateCache.fetch_detached)
_FETCH_ALLOWLIST = {"fetch_window", "fetch_window_summary",
                    "fetch_detached"}
_SYNC_ATTR_CALLS = {"item", "block_until_ready"}


def _is_jit_func(expr: ast.AST) -> bool:
    """``jit`` / ``jax.jit`` as a call target."""
    if isinstance(expr, ast.Name):
        return expr.id == "jit"
    if isinstance(expr, ast.Attribute):
        return expr.attr == "jit"
    return False


def _decorated_jit(fn: ast.FunctionDef) -> bool:
    for dec in fn.decorator_list:
        if _is_jit_func(dec):
            return True
        if isinstance(dec, ast.Call):
            if _is_jit_func(dec.func):
                return True
            # partial(jax.jit, ...) / functools.partial(jit, ...)
            fname = (dec.func.attr if isinstance(dec.func, ast.Attribute)
                     else dec.func.id if isinstance(dec.func, ast.Name)
                     else "")
            if fname == "partial" and dec.args and _is_jit_func(dec.args[0]):
                return True
    return False


def _is_scan_call(call: ast.Call) -> bool:
    f = call.func
    return (isinstance(f, ast.Attribute) and f.attr == "scan"
            and (isinstance(f.value, ast.Name) and f.value.id in ("lax",)
                 or isinstance(f.value, ast.Attribute)
                 and f.value.attr == "lax"))


def _hot_functions(tree: ast.AST) -> dict[ast.FunctionDef, str]:
    """FunctionDef -> reason ('jit' | 'scan-body') for every traced-body
    function in a module, resolved lexically: a Name passed to jit()/
    lax.scan() binds to the nearest enclosing-scope def with that name."""
    hot: dict[ast.FunctionDef, str] = {}

    def scope_walk(node: ast.AST, defs: dict[str, ast.FunctionDef]) -> None:
        local_defs = dict(defs)
        body = getattr(node, "body", [])
        for stmt in body if isinstance(body, list) else []:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                local_defs[stmt.name] = stmt
                if _decorated_jit(stmt):
                    hot[stmt] = "jit"
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            if _is_jit_func(sub.func):
                for arg in sub.args[:1]:
                    if isinstance(arg, ast.Name) and arg.id in local_defs:
                        hot.setdefault(local_defs[arg.id], "jit")
            elif _is_scan_call(sub):
                if (sub.args and isinstance(sub.args[0], ast.Name)
                        and sub.args[0].id in local_defs):
                    hot.setdefault(local_defs[sub.args[0].id], "scan-body")
        for stmt in body if isinstance(body, list) else []:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                scope_walk(stmt, local_defs)

    scope_walk(tree, {})
    return hot


def _sync_calls(fn: ast.FunctionDef, *, include_np: bool = True
                ) -> list[tuple[int, str]]:
    out: list[tuple[int, str]] = []
    for sub in ast.walk(fn):
        if not isinstance(sub, ast.Call):
            continue
        f = sub.func
        if isinstance(f, ast.Attribute):
            if f.attr in _SYNC_ATTR_CALLS:
                out.append((sub.lineno, f".{f.attr}()"))
            elif (f.attr in ("asarray", "array") and include_np
                  and isinstance(f.value, ast.Name)
                  and f.value.id in ("np", "numpy")
                  and not _wraps_fetch(sub)):
                out.append((sub.lineno, f"np.{f.attr}"))
            elif (f.attr == "device_get"
                  and isinstance(f.value, ast.Name)
                  and f.value.id == "jax"):
                out.append((sub.lineno, "jax.device_get"))
    return out


def _wraps_fetch(call: ast.Call) -> bool:
    """np.asarray(<something>.fetch_window(...)) is the designated fetch."""
    for arg in call.args:
        for sub in ast.walk(arg):
            if (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in _FETCH_ALLOWLIST):
                return True
    return False


def _calls_fetch(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in _FETCH_ALLOWLIST)


@register
class HostSyncRule(Rule):
    id = "host-sync"
    doc = ("Host synchronisation (np.asarray/np.array, .item(), "
           ".block_until_ready(), jax.device_get) inside jit-traced "
           "functions, lax.scan bodies, or the scheduler hot loop — "
           "outside the designated fetch points (fetch_window).")

    def run(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        for module in project.modules:
            for fn, reason in _hot_functions(module.tree).items():
                for line, what in _sync_calls(fn):
                    findings.append(Finding(
                        self.id, module.rel, line,
                        f"{what} inside {reason} function {fn.name}() — "
                        "forces a device sync / breaks tracing"))
            findings.extend(self._scheduler_findings(module))
        return findings

    def _scheduler_findings(self, module: ModuleInfo) -> list[Finding]:
        findings: list[Finding] = []
        for cls in module.classes.values():
            if cls.name not in SCHEDULER_CLASSES:
                continue
            sched = self._closure(cls)
            for meth_name in sorted(sched):
                meth = cls.methods.get(meth_name)
                if meth is None:
                    continue
                for line, what in _sync_calls(meth):
                    findings.append(Finding(
                        self.id, module.rel, line,
                        f"{what} in scheduler hot path "
                        f"{cls.name}.{meth_name}() — only the designated "
                        "fetch points may block on the device"))
        return findings

    @staticmethod
    def _closure(cls) -> set[str]:
        return self_call_closure(cls, _SCHEDULER_ENTRIES)
