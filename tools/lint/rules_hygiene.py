"""Hygiene rules: ``exit-code-literal``, ``wallclock-timing``,
``mid-file-import``.

- **exit-code-literal** — exit codes are a cross-process protocol
  (resilience/exit_codes.py is the ONE table; the supervisor, bench,
  chip tooling and verify gates all route on them). An integer literal
  in ``sys.exit``/``os._exit``/``SystemExit`` re-creates the collision
  class PR 2 spent a whole table killing (bench's liveness rc=3 vs the
  regression gate's rc=3).
- **wallclock-timing** — ``time.time()`` is subject to NTP slews and
  clock steps; every latency/duration/backoff measurement must use
  ``time.monotonic()`` (or ``perf_counter``). Legit wall-clock uses
  (comparing against file mtimes, stamping records for humans) carry a
  suppression with the reason.
- **mid-file-import** — a module-level import after the import section
  ends (first def/class/real statement). PR 4 hoisted a stray mid-file
  ``import os`` from train/loop.py; this keeps the class extinct. The
  import section tolerates the repo's sanctioned preambles: docstring,
  ``__future__``, try/except import shims (the jax ``shard_map``
  compatibility dance), and the tools/ ``sys.path`` bootstrap pattern
  (simple assignments + ``sys.``/``os.`` calls before the imports they
  enable).
"""

from __future__ import annotations

import ast

from .core import Finding, Rule, register
from .model import Project

_EXIT_CALLS = {
    ("sys", "exit"), ("os", "_exit"),
}
#: the one module allowed to spell exit codes as integers
_EXIT_TABLE_SUFFIX = "resilience/exit_codes.py"


def _int_literal(node: ast.AST) -> int | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, int) \
            and not isinstance(node.value, bool):
        return node.value
    if (isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub)
            and isinstance(node.operand, ast.Constant)
            and isinstance(node.operand.value, int)):
        return -node.operand.value
    return None


@register
class ExitCodeLiteralRule(Rule):
    id = "exit-code-literal"
    doc = ("Integer literals in sys.exit/os._exit/SystemExit outside "
           "resilience/exit_codes.py — exit codes are a cross-process "
           "protocol and must come from the one table.")

    def run(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        for module in project.modules:
            if module.rel.endswith(_EXIT_TABLE_SUFFIX):
                continue
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                f = node.func
                named = None
                if (isinstance(f, ast.Attribute)
                        and isinstance(f.value, ast.Name)
                        and (f.value.id, f.attr) in _EXIT_CALLS):
                    named = f"{f.value.id}.{f.attr}"
                elif isinstance(f, ast.Name) and f.id == "SystemExit":
                    named = "SystemExit"
                if named is None:
                    continue
                val = _int_literal(node.args[0])
                if val is None or val == 0:
                    continue  # exit(0) is the one universal constant
                findings.append(Finding(
                    self.id, module.rel, node.lineno,
                    f"{named}({val}) uses a magic exit code — import the "
                    "named constant from resilience/exit_codes.py"))
        return findings


def _is_timedelta(node: ast.AST) -> bool:
    """``timedelta(...)`` / ``datetime.timedelta(...)`` — subtracting a
    timedelta from now() computes a wall-clock INSTANT (age gates,
    retention cutoffs), not a duration; that is the legitimate use."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    name = (f.attr if isinstance(f, ast.Attribute)
            else f.id if isinstance(f, ast.Name) else "")
    return name == "timedelta"


def _is_datetime_now(call: ast.Call) -> bool:
    """``datetime.now()`` / ``datetime.datetime.now()`` / ``utcnow()``."""
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr in ("now", "utcnow")):
        return False
    recv = f.value
    return ((isinstance(recv, ast.Name) and recv.id == "datetime")
            or (isinstance(recv, ast.Attribute)
                and recv.attr == "datetime"))


@register
class WallclockTimingRule(Rule):
    id = "wallclock-timing"
    doc = ("time.time() — also via `from time import time` aliases — "
           "and datetime.now() subtractions in measurement code: "
           "durations, latencies and backoff must use time.monotonic()/"
           "perf_counter() (wall clock slews under NTP). Suppress with "
           "a reason where wall-clock semantics are the point "
           "(file-mtime comparisons, record timestamps for humans).")

    def run(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        for module in project.modules:
            # `from time import time [as alias]`: the bare-name spelling
            # of the same wall-clock read must not dodge the rule
            aliases: set[str] = set()
            for node in ast.walk(module.tree):
                if isinstance(node, ast.ImportFrom) \
                        and node.module == "time":
                    for a in node.names:
                        if a.name == "time":
                            aliases.add(a.asname or a.name)
            # names bound to a datetime.now() result, PER FUNCTION scope
            # (name reuse across functions must not cross-contaminate)
            now_names: dict[int, set[str]] = {}
            scopes: list[ast.AST] = [module.tree]
            scopes.extend(n for n in ast.walk(module.tree)
                          if isinstance(n, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)))
            owner: dict[int, int] = {}  # id(node) -> scope index
            for i, scope in enumerate(scopes):
                names: set[str] = set()
                for node in self._scope_walk(scope):
                    owner.setdefault(id(node), i)
                    if (isinstance(node, ast.Assign)
                            and isinstance(node.value, ast.Call)
                            and _is_datetime_now(node.value)):
                        names.update(t.id for t in node.targets
                                     if isinstance(t, ast.Name))
                now_names[i] = names
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Call):
                    if (isinstance(node, ast.BinOp)
                            and isinstance(node.op, ast.Sub)
                            and not any(_is_timedelta(side) for side
                                        in (node.left, node.right))
                            and any(
                                (isinstance(side, ast.Call)
                                 and _is_datetime_now(side))
                                or (isinstance(side, ast.Name)
                                    and side.id in now_names.get(
                                        owner.get(id(node), 0), set()))
                                for side in (node.left, node.right))):
                        findings.append(Finding(
                            self.id, module.rel, node.lineno,
                            "datetime.now() used for a duration "
                            "(subtraction) — wall clock slews; use "
                            "time.monotonic()/perf_counter()"))
                    continue
                if (isinstance(node.func, ast.Attribute)
                        and node.func.attr == "time"
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "time"):
                    findings.append(Finding(
                        self.id, module.rel, node.lineno,
                        "time.time() — use time.monotonic() (or "
                        "perf_counter) unless wall-clock semantics are "
                        "required (then suppress with the reason)"))
                elif (isinstance(node.func, ast.Name)
                      and node.func.id in aliases):
                    findings.append(Finding(
                        self.id, module.rel, node.lineno,
                        f"{node.func.id}() is `from time import time` — "
                        "the same wall-clock read; use time.monotonic() "
                        "(or perf_counter)"))
        return findings

    @staticmethod
    def _scope_walk(scope: ast.AST):
        """Walk a scope's own nodes without descending into nested
        function definitions (their locals are their own)."""
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            node = stack.pop()
            yield node
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.Lambda)):
                stack.extend(ast.iter_child_nodes(node))


def _is_import_section_stmt(stmt: ast.stmt, *, first: bool) -> bool:
    """Statements that keep the import section open."""
    if isinstance(stmt, (ast.Import, ast.ImportFrom)):
        return True
    if first and isinstance(stmt, ast.Expr) and isinstance(
            stmt.value, ast.Constant) and isinstance(stmt.value.value, str):
        return True  # module docstring
    if isinstance(stmt, (ast.Try, ast.If)):
        # import shims (`try: from jax import shard_map`) and guarded
        # bootstraps (`if _DIR not in sys.path: sys.path.insert(...)`):
        # every statement inside must itself be import-section material
        body_stmts = []
        for attr in ("body", "orelse", "finalbody"):
            body_stmts.extend(getattr(stmt, attr, []) or [])
        for handler in getattr(stmt, "handlers", []) or []:
            body_stmts.extend(handler.body)
        return all(
            isinstance(s, (ast.Pass, ast.Raise))
            or _is_import_section_stmt(s, first=False)
            for s in body_stmts
        )
    # bootstrap preamble: `_HERE = os.path...` / `sys.path.insert(...)` /
    # `os.environ.setdefault(...)` / `__version__ = "..."` — simple
    # assignments and sys/os calls that make the subsequent imports work
    if isinstance(stmt, ast.Assign) and all(
            isinstance(t, ast.Name) for t in stmt.targets):
        return True
    if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
        f = stmt.value.func
        root = f
        while isinstance(root, ast.Attribute):
            root = root.value
        return isinstance(root, ast.Name) and root.id in ("sys", "os",
                                                          "warnings")
    return False


@register
class MidFileImportRule(Rule):
    id = "mid-file-import"
    doc = ("Module-level import after the import section ended (first "
           "def/class/non-bootstrap statement). Hoist it — lazy imports "
           "belong inside functions, not between definitions.")

    def run(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        for module in project.modules:
            section_open = True
            for i, stmt in enumerate(module.tree.body):
                if section_open:
                    if not _is_import_section_stmt(stmt, first=(i == 0)):
                        section_open = False
                    continue
                if isinstance(stmt, (ast.Import, ast.ImportFrom)):
                    names = ", ".join(
                        a.name for a in stmt.names) or "*"
                    findings.append(Finding(
                        self.id, module.rel, stmt.lineno,
                        f"module-level import of {names} after the import "
                        "section — hoist to the header"))
        return findings
