"""``resource-pairing``: acquired resources must be released on every
path out of the function — including exception exits.

The resource registry (tools/lint/model.py ``RESOURCE_PAIRS``) pairs the
acquire/release call shapes this repo's serve plane lives on:

- **pinned slots** — ``pin(key)`` / ``acquire_pinned(key)`` paired with
  ``unpin(key)`` / ``release(key)``. A pinned slot is unevictable, so a
  path that exits without releasing wedges a cache slot forever — the
  PR 7 "leaked pins" class (fail_inflight had to release every admitted
  session's pin by hand after review caught it).
- **in-flight counters** — ``self.x += e`` paired with ``self.x -= e``
  in the same function. A raising call between the two skips the
  decrement and wedges whoever waits on the counter — the PR 8 class
  where a failed disk write could wedge ``flush()`` until the decrement
  moved into ``run()``'s ``finally``.
- **file handles** — ``f = open(...)`` paired with ``f.close()`` (the
  ``with open(...)`` form never enters the analysis).

Plain ``acquire``/``release`` is in the registry but deliberately NOT
leak-tracked: StateCache.acquire transfers ownership to the cache's own
LRU table, where an unpinned slot is always reclaimable — "acquired and
not released" is the normal ownership transfer for kept sessions, not a
leak.

Per function: build the CFG-lite (model.py), then a may-analysis over
it — a token is a finding when SOME path reaches the function's normal
or exception exit still holding it. Exception edges carry ``pre ∩
post`` state, so an acquire that raises was never acquired and a
release that raises still counts as released (both under-approximate).

Silence rules (under-approximate on purpose — docs/LINT.md):
- counters activate only when the SAME function contains both the
  ``+=`` and the ``-=`` of one attribute;
- an acquire whose HANDLE (assignment result) is returned/yielded,
  stored into an attribute/subscript, or passed to an unresolvable call
  has transferred ownership and goes silent;
- a KEY (e.g. the sid) that is returned/yielded or stored escapes too;
  a key merely passed to calls stays tracked — unless the callee is
  resolvable and its transitive closure contains a matching release
  shape, in which case that call site counts as the release.
"""

from __future__ import annotations

import ast

from .core import Finding, Rule, register
from .model import (
    CFG,
    CFG_EXIT,
    CFG_RAISE,
    RESOURCE_PAIRS,
    ClassInfo,
    ModuleInfo,
    Project,
    local_alias_types,
)


class _Site:
    """One tracked acquire site inside a function."""

    __slots__ = ("kind", "key", "handles", "line", "display",
                 "release_calls")

    def __init__(self, kind: str, key: str | None, handles: set[str],
                 line: int, display: str):
        self.kind = kind
        self.key = key          # ast.dump of the key expr (None: handle-only)
        self.handles = handles  # local names bound to the acquire result
        self.line = line
        self.display = display
        #: ids of Call nodes that count as this site's release (resolvable
        #: callees whose closure releases the kind)
        self.release_calls: set[int] = set()

    def token(self) -> tuple:
        return (self.kind, self.key, self.line)


def _call_name(call: ast.Call) -> str | None:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _key_of(call: ast.Call) -> str | None:
    if call.args:
        return ast.dump(call.args[0])
    return None


def _key_root(expr: ast.AST) -> str | None:
    """Root Name of a key expression (``entry.sid`` -> 'entry')."""
    while isinstance(expr, (ast.Attribute, ast.Subscript)):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


class _FnAnalysis:
    """Escape/release classification + dataflow for one function."""

    def __init__(self, project: Project, module: ModuleInfo,
                 cls: ClassInfo | None, fn: ast.FunctionDef):
        self.project = project
        self.module = module
        self.cls = cls
        self.fn = fn
        self.local_types = local_alias_types(fn, project, cls)
        self._release_closure_memo: dict[tuple, bool] = {}

    # -- interprocedural release resolution --------------------------------

    def _closure_releases(self, kind: str, fn: ast.FunctionDef,
                          cls: ClassInfo | None, module: ModuleInfo,
                          _depth: int = 0) -> bool:
        """Does ``fn`` (transitively, through resolvable calls) perform a
        release-shape call for ``kind``?"""
        key = (module.rel, cls.name if cls else None, fn.name, kind)
        if key in self._release_closure_memo:
            return self._release_closure_memo[key]
        self._release_closure_memo[key] = False  # cut cycles
        names = RESOURCE_PAIRS[kind]["release"]
        found = False
        if _depth <= 4:
            ltypes = local_alias_types(fn, self.project, cls)
            for sub in ast.walk(fn):
                if not isinstance(sub, ast.Call):
                    continue
                if _call_name(sub) in names:
                    found = True
                    break
                resolved = self.project.resolve_call(sub, module, cls,
                                                     ltypes)
                if resolved is not None:
                    owner, callee = resolved
                    if self._closure_releases(
                            kind, callee, owner,
                            owner.module if owner else module,
                            _depth + 1):
                        found = True
                        break
        self._release_closure_memo[key] = found
        return found

    # -- site collection ---------------------------------------------------

    def sites(self) -> list[_Site]:
        """Tracked acquire sites, with escapes already filtered out."""
        out: list[_Site] = []
        # call-shape acquires; counters are collected separately
        from .model import resource_kind_of_call
        for stmt in self._stmts():
            handles: set[str] = set()
            calls = [e for e in self._stmt_exprs(stmt)
                     if isinstance(e, ast.Call)]
            if isinstance(stmt, ast.Assign):
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        handles.add(tgt.id)
                    elif isinstance(tgt, ast.Tuple):
                        handles.update(e.id for e in tgt.elts
                                       if isinstance(e, ast.Name))
            for call in calls:
                got = resource_kind_of_call(call)
                if got is None or got[1] != "acquire":
                    continue
                kind = got[0]
                if kind == "handle" and not isinstance(stmt, ast.Assign):
                    continue  # bare open() expr: no handle to leak-track
                if (isinstance(stmt, ast.Assign)
                        and any(not isinstance(t, (ast.Name, ast.Tuple))
                                for t in stmt.targets)):
                    continue  # result stored straight into an attr: escapes
                key = None if kind == "handle" else _key_of(call)
                if kind != "handle" and key is None:
                    continue  # keyless pin: nothing to match a release on
                disp = (f"{_call_name(call)}"
                        f"({ast.unparse(call.args[0]) if call.args else ''})")
                out.append(_Site(kind, key, set(handles), call.lineno,
                                 disp))
        # counters: attr += e paired with attr -= e in the same function
        incs: dict[str, list[ast.AugAssign]] = {}
        decs: set[str] = set()
        for sub in ast.walk(self.fn):
            if (isinstance(sub, ast.AugAssign)
                    and isinstance(sub.target, ast.Attribute)):
                tgt = ast.dump(sub.target)
                if isinstance(sub.op, ast.Add):
                    incs.setdefault(tgt, []).append(sub)
                elif isinstance(sub.op, ast.Sub):
                    decs.add(tgt)
        for tgt, nodes in incs.items():
            if tgt not in decs:
                continue  # stats counter, not an in-flight gate
            for node in nodes:
                out.append(_Site("counter", tgt, set(), node.lineno,
                                 ast.unparse(node.target) + " +="))
        return self._filter_escapes(out)

    def _stmts(self):
        for sub in ast.walk(self.fn):
            if isinstance(sub, ast.stmt):
                yield sub

    @staticmethod
    def _stmt_exprs(stmt: ast.stmt):
        from .model import _own_exprs
        for expr in _own_exprs(stmt):
            yield from ast.walk(expr)

    def _filter_escapes(self, sites: list[_Site]) -> list[_Site]:
        tracked: list[_Site] = []
        for site in sites:
            if site.kind == "counter":
                tracked.append(site)
                continue
            root = None
            if site.key is not None:
                # recover the root name from any call arg matching the key
                for sub in ast.walk(self.fn):
                    if isinstance(sub, ast.Call) and sub.args \
                            and ast.dump(sub.args[0]) == site.key:
                        root = _key_root(sub.args[0])
                        break
            if self._escapes(site, root):
                continue
            tracked.append(site)
        return tracked

    def _escapes(self, site: _Site, key_root: str | None) -> bool:
        watched = set(site.handles)
        if key_root is not None:
            watched_key = {key_root}
        else:
            watched_key = set()
        release_names = RESOURCE_PAIRS[site.kind]["release"]
        acquire_names = RESOURCE_PAIRS[site.kind]["acquire"]
        for sub in ast.walk(self.fn):
            if isinstance(sub, (ast.Return, ast.Yield, ast.YieldFrom)):
                val = getattr(sub, "value", None)
                if val is not None and self._mentions(
                        val, watched | watched_key):
                    return True
            if isinstance(sub, ast.Assign):
                for tgt in sub.targets:
                    if isinstance(tgt, (ast.Attribute, ast.Subscript)) \
                            and (self._mentions(sub.value,
                                                watched | watched_key)
                                 or self._mentions(
                                     tgt, watched | watched_key)):
                        # handle/key stored into an attribute/container
                        # (value OR subscript key): ownership outlives
                        # this frame
                        return True
            if isinstance(sub, ast.Call):
                name = _call_name(sub)
                if name in release_names or name in acquire_names:
                    continue
                resolved = self.project.resolve_call(
                    sub, self.module, self.cls, self.local_types)
                involves_handle = any(
                    self._mentions(a, watched)
                    for a in [*sub.args,
                              *(kw.value for kw in sub.keywords)])
                involves_key = any(
                    self._mentions(a, watched_key) or (
                        sub.args and site.key is not None
                        and ast.dump(sub.args[0]) == site.key)
                    for a in sub.args) if sub.args else False
                if resolved is None:
                    # unresolvable call taking the handle: ownership may
                    # transfer through the object — go silent
                    if involves_handle:
                        return True
                    continue
                if involves_handle or involves_key:
                    owner, callee = resolved
                    if self._closure_releases(
                            site.kind, callee, owner,
                            owner.module if owner else self.module):
                        site.release_calls.add(id(sub))
        return False

    @staticmethod
    def _mentions(expr: ast.AST, names: set[str]) -> bool:
        if not names:
            return False
        return any(isinstance(s, ast.Name) and s.id in names
                   for s in ast.walk(expr))

    # -- dataflow ----------------------------------------------------------

    def leaks(self, sites: list[_Site]) -> list[tuple[_Site, str]]:
        """(site, 'return'|'exception') for tokens held at an exit."""
        if not sites:
            return []
        cfg = CFG(self.fn)
        by_token = {s.token(): s for s in sites}
        acq: list[set[tuple]] = []
        rel: list[set[tuple]] = []
        for stmt in cfg.stmts:
            a: set[tuple] = set()
            r: set[tuple] = set()
            self._transfer(stmt, sites, a, r)
            acq.append(a)
            rel.append(r)
        # may-analysis: IN = union over predecessor OUTs
        n = len(cfg.stmts)
        in_s: list[set] = [set() for _ in range(n)]
        exit_held: set[tuple] = set()
        raise_held: set[tuple] = set()
        # iterate to fixpoint (monotone may-analysis over finite tokens)
        changed = True
        guard = 0
        while changed and guard < 10 * (n + 1):
            changed = False
            guard += 1
            for nid in range(n):
                out = (in_s[nid] - rel[nid]) | acq[nid]
                exc_state = in_s[nid] & out  # pre ∩ post
                for succ in cfg.succ[nid]:
                    if succ == CFG_EXIT:
                        if not out <= exit_held:
                            exit_held |= out
                            changed = True
                    elif succ == CFG_RAISE:
                        if not out <= raise_held:
                            raise_held |= out
                            changed = True
                    elif not out <= in_s[succ]:
                        in_s[succ] |= out
                        changed = True
                for succ in cfg.exc_succ[nid]:
                    if succ == CFG_RAISE:
                        if not exc_state <= raise_held:
                            raise_held |= exc_state
                            changed = True
                    elif succ >= 0 and not exc_state <= in_s[succ]:
                        in_s[succ] |= exc_state
                        changed = True
        out = []
        for tok in sorted(exit_held | raise_held,
                          key=lambda t: (t[2], str(t))):
            kind = ("return" if tok in exit_held else "exception")
            out.append((by_token[tok], kind))
        return out

    def _transfer(self, stmt: ast.stmt, sites: list[_Site],
                  acq: set, rel: set) -> None:
        from .model import _own_exprs
        if isinstance(stmt, ast.AugAssign) \
                and isinstance(stmt.target, ast.Attribute):
            tgt = ast.dump(stmt.target)
            for site in sites:
                if site.kind != "counter" or site.key != tgt:
                    continue
                if isinstance(stmt.op, ast.Add) \
                        and stmt.lineno == site.line:
                    acq.add(site.token())
                elif isinstance(stmt.op, ast.Sub):
                    rel.add(site.token())
            return
        for expr in _own_exprs(stmt):
            for sub in ast.walk(expr):
                if not isinstance(sub, ast.Call):
                    continue
                name = _call_name(sub)
                key = _key_of(sub)
                for site in sites:
                    if site.kind == "counter":
                        continue
                    spec = RESOURCE_PAIRS[site.kind]
                    if name in spec["acquire"] and (
                            site.kind == "handle"
                            or key == site.key) \
                            and sub.lineno == site.line:
                        acq.add(site.token())
                    elif name in spec["release"]:
                        if site.kind == "handle":
                            # f.close(): receiver must be the handle
                            f = sub.func
                            if isinstance(f, ast.Attribute) \
                                    and isinstance(f.value, ast.Name) \
                                    and f.value.id in site.handles:
                                rel.add(site.token())
                        elif key == site.key:
                            rel.add(site.token())
                    elif id(sub) in site.release_calls:
                        rel.add(site.token())


@register
class ResourcePairingRule(Rule):
    id = "resource-pairing"
    doc = ("A pinned slot, in-flight counter increment, or opened file "
           "handle must be released on EVERY path out of the function, "
           "including exception exits (try/finally or with). Handles/"
           "keys that escape (returned, stored, passed to unresolvable "
           "calls) transfer ownership and are exempt.")

    def run(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        for module in project.modules:
            units: list[tuple[ClassInfo | None, ast.FunctionDef]] = []
            units.extend((None, fn) for fn in module.functions.values())
            for cls in module.classes.values():
                units.extend((cls, m) for m in cls.methods.values())
            for cls, fn in units:
                ana = _FnAnalysis(project, module, cls, fn)
                sites = ana.sites()
                if not sites:
                    continue
                where = f"{cls.name}.{fn.name}" if cls else fn.name
                for site, how in ana.leaks(sites):
                    noun = {"pin": "pinned slot", "counter": "counter",
                            "handle": "file handle"}[site.kind]
                    path = ("an exception" if how == "exception"
                            else "a return")
                    findings.append(Finding(
                        self.id, module.rel, site.line,
                        f"{noun} {site.display} acquired in {where}() is "
                        f"not released on {path} path — release in a "
                        "finally (or on every branch)"))
        return findings
