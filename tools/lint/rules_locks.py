"""Lock discipline rules: ``lock-order`` (ABBA cycles) and
``cross-thread-state`` (guarded attributes read without their lock).

Shared machinery — one static lock model over the project:

- **lock identities** are ``Class.attr`` keys union-found together across
  aliases: ``self._work = threading.Condition(self._lock)`` and
  ``self._lock = cache._lock`` (the PrefixCache/StateCache shared-RLock
  pattern) both MERGE identities, so a reentrant re-acquire of a shared
  RLock is not a cycle — that pattern exists precisely to avoid the ABBA
  the lock-order rule hunts;
- **acquisition graph**: walking each method with the statically-held
  lock set, an acquisition of B (directly, through a resolvable call's
  transitive closure, or through a registered listener/callback list —
  the ``StateCache.evict_listeners`` indirection that made PR 4's hazard
  invisible to review) while holding A adds edge A→B. Any cycle in the
  graph is a deadlock schedule some interleaving can realize; a
  self-edge on a non-reentrant lock is one no interleaving can avoid.
- **thread roles** (cross-thread-state): methods reachable from the
  scheduler entry points (``run``/``step``/``drain``) are
  scheduler-owned — the single-writer exemption; every other method is
  assumed callable from client/HTTP/supervise threads and must hold the
  class lock to touch any attribute that is WRITTEN under that lock
  somewhere (being written under the lock is the code declaring "this
  lock owns this attribute"). Methods named ``*_locked`` assert a
  held-lock calling contract and are exempt (docs/LINT.md).
"""

from __future__ import annotations

import ast

from .core import Finding, Rule, register
from .model import ClassInfo, ModuleInfo, Project, local_alias_types

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
_MUTATING_METHODS = {
    "append", "appendleft", "extend", "insert", "pop", "popleft", "remove",
    "clear", "add", "discard", "update", "setdefault", "move_to_end",
    "popitem", "sort",
}
_SCHEDULER_ENTRIES = {"run", "step", "drain"}


def _ctor_kind(value: ast.AST) -> tuple[str, ast.AST | None] | None:
    """('lock'|'rlock', condition-underlying-lock-expr|None) when
    ``value`` constructs a threading primitive."""
    if not isinstance(value, ast.Call):
        return None
    f = value.func
    name = f.id if isinstance(f, ast.Name) else (
        f.attr if isinstance(f, ast.Attribute) else None)
    if name not in _LOCK_CTORS:
        return None
    if name == "Condition":
        under = value.args[0] if value.args else None
        return ("lock", under)
    return ("rlock" if name == "RLock" else "lock", None)


class _LockWorld:
    """Union-found lock identities + kinds over the whole project.

    Identity keys are MODULE-QUALIFIED (``rel::Class.attr``) so two
    same-named classes in different files never alias; messages show the
    short ``Class.attr`` display name."""

    def __init__(self):
        self._parent: dict[str, str] = {}
        self._rlock: set[str] = set()
        self._display: dict[str, str] = {}

    def _key(self, cls: ClassInfo, attr: str) -> str:
        return f"{cls.module.rel}::{cls.name}.{attr}"

    def add(self, cls: ClassInfo, attr: str, kind: str) -> None:
        key = self._key(cls, attr)
        self._parent.setdefault(key, key)
        self._display.setdefault(key, f"{cls.name}.{attr}")
        if kind == "rlock":
            self._rlock.add(key)

    def merge(self, a: str, b: str) -> None:
        self._parent.setdefault(a, a)
        self._parent.setdefault(b, b)
        ra, rb = self.find(a), self.find(b)
        if ra != rb:
            # deterministic root: smallest name wins (stable messages)
            lo, hi = sorted((ra, rb))
            self._parent[hi] = lo

    def find(self, key: str) -> str:
        while self._parent.get(key, key) != key:
            self._parent[key] = self._parent.get(self._parent[key],
                                                 self._parent[key])
            key = self._parent[key]
        return key

    def known(self, cls: ClassInfo, attr: str) -> bool:
        return self._key(cls, attr) in self._parent

    def root(self, cls: ClassInfo, attr: str) -> str | None:
        key = self._key(cls, attr)
        if key not in self._parent:
            return None
        return self.find(key)

    def display(self, root: str) -> str:
        return self._display.get(root, root.split("::", 1)[-1])

    def is_rlock(self, root: str) -> bool:
        return any(self.find(k) == root for k in self._rlock)

    def class_lock_attrs(self, cls: ClassInfo) -> set[str]:
        prefix = f"{cls.module.rel}::{cls.name}."
        return {k[len(prefix):] for k in self._parent if k.startswith(prefix)}


def _attr_chain_lock(expr: ast.AST, project: Project, cls: ClassInfo | None,
                     local_types, world: _LockWorld) -> str | None:
    """Lock root for a with-target / alias expression, or None."""
    if not isinstance(expr, ast.Attribute):
        return None
    owner: ClassInfo | None
    if isinstance(expr.value, ast.Name) and expr.value.id == "self":
        owner = cls
    else:
        owner = project.resolve_receiver(expr.value, cls, local_types)
    if owner is None:
        return None
    return world.root(owner, expr.attr)


def build_lock_world(project: Project) -> _LockWorld:
    world = _LockWorld()
    pending_aliases: list[tuple[ClassInfo, ast.FunctionDef]] = []
    # pass 1: creations
    for module in project.modules:
        for cls in module.classes.values():
            for meth in cls.methods.values():
                pending_aliases.append((cls, meth))
                for sub in ast.walk(meth):
                    if not isinstance(sub, ast.Assign):
                        continue
                    got = _ctor_kind(sub.value)
                    if got is None:
                        continue
                    kind, _ = got
                    if isinstance(sub.value, ast.Call):
                        f = sub.value.func
                        if (isinstance(f, ast.Attribute)
                                and f.attr == "RLock") or (
                                isinstance(f, ast.Name) and f.id == "RLock"):
                            kind = "rlock"
                    for tgt in sub.targets:
                        if (isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"):
                            world.add(cls, tgt.attr, kind)
    # pass 2: aliases (Condition(self._lock), self._lock = other._lock)
    for cls, meth in pending_aliases:
        local_types = local_alias_types(meth, project, cls)
        for sub in ast.walk(meth):
            if not isinstance(sub, ast.Assign):
                continue
            targets = [t for t in sub.targets
                       if isinstance(t, ast.Attribute)
                       and isinstance(t.value, ast.Name)
                       and t.value.id == "self"]
            if not targets:
                continue
            got = _ctor_kind(sub.value)
            if got is not None and got[1] is not None:
                # self._work = threading.Condition(self._lock)
                under = _attr_chain_lock(got[1], project, cls, local_types,
                                         world)
                for tgt in targets:
                    world.add(cls, tgt.attr, "lock")
                    if under is not None:
                        world.merge(world._key(cls, tgt.attr), under)
                continue
            src = _attr_chain_lock(sub.value, project, cls, local_types,
                                   world)
            if src is not None:
                for tgt in targets:
                    world.add(cls, tgt.attr,
                              "rlock" if world.is_rlock(src) else "lock")
                    world.merge(world._key(cls, tgt.attr), src)
    return world


class _Access:
    __slots__ = ("attr", "write", "held", "line")

    def __init__(self, attr: str, write: bool, held: bool, line: int):
        self.attr = attr
        self.write = write
        self.held = held
        self.line = line


#: method identity: (module rel, class name or None, function name) —
#: module-qualified so same-named classes in different files never merge
_MethodKey = tuple[str, str | None, str]


class _MethodFacts:
    def __init__(self):
        self.acquisitions: list[tuple[str, tuple[str, ...], int]] = []
        self.calls: list[tuple[_MethodKey, tuple[str, ...], int]] = []
        self.callback_calls: list[tuple[str, tuple[str, ...], int]] = []
        self.accesses: list[_Access] = []


def _collect_facts(project: Project, module: ModuleInfo,
                   cls: ClassInfo | None, fn: ast.FunctionDef,
                   world: _LockWorld) -> _MethodFacts:
    facts = _MethodFacts()
    local_types = local_alias_types(fn, project, cls) if cls else {}
    # loop vars iterating a self.<listattr> — potential callback fan-out
    loop_cb: dict[str, str] = {}
    for sub in ast.walk(fn):
        if (isinstance(sub, ast.For) and isinstance(sub.target, ast.Name)
                and isinstance(sub.iter, ast.Attribute)
                and isinstance(sub.iter.value, ast.Name)
                and sub.iter.value.id == "self"):
            loop_cb[sub.target.id] = sub.iter.attr

    def record_attr(node: ast.Attribute, write: bool, held: tuple) -> None:
        if (isinstance(node.value, ast.Name) and node.value.id == "self"
                and cls is not None
                and not world.known(cls, node.attr)):
            facts.accesses.append(
                _Access(node.attr, write, bool(held), node.lineno))

    def walk(node: ast.AST, held: tuple[str, ...]) -> None:
        if isinstance(node, ast.With):
            acquired: list[str] = []
            for item in node.items:
                root = _attr_chain_lock(item.context_expr, project, cls,
                                        local_types, world)
                if root is None and isinstance(item.context_expr, ast.Call):
                    # `with lock.acquire_timeout()`-style: resolve the
                    # receiver of an .acquire() call too
                    f = item.context_expr.func
                    if isinstance(f, ast.Attribute):
                        root = _attr_chain_lock(f.value, project, cls,
                                                local_types, world)
                if root is not None:
                    facts.acquisitions.append((root, held, node.lineno))
                    acquired.append(root)
                else:
                    walk(item.context_expr, held)
            inner = held + tuple(a for a in acquired if a not in held)
            for stmt in node.body:
                walk(stmt, inner)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: a separate execution context (no lexical hold),
            # but its acquisitions count toward the enclosing method's
            # may-acquire closure (it is created — and usually called —
            # on this method's behalf, e.g. jit-traced bodies)
            for stmt in node.body:
                walk(stmt, ())
            return
        if isinstance(node, ast.Call):
            resolved = project.resolve_call(node, module, cls, local_types)
            if resolved is not None:
                owner, callee = resolved
                key: _MethodKey = (
                    (owner.module.rel, owner.name, callee.name)
                    if owner else (module.rel, None, callee.name))
                facts.calls.append((key, held, node.lineno))
            elif (isinstance(node.func, ast.Name)
                  and node.func.id in loop_cb):
                facts.callback_calls.append(
                    (loop_cb[node.func.id], held, node.lineno))
        if isinstance(node, ast.Attribute):
            write = isinstance(node.ctx, (ast.Store, ast.Del))
            record_attr(node, write, held)
        if isinstance(node, ast.Subscript):
            # self.x[i] = v / self.x[i] += v are writes THROUGH the attr
            if (isinstance(node.value, ast.Attribute)
                    and isinstance(node.ctx, (ast.Store, ast.Del))):
                record_attr(node.value, True, held)
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATING_METHODS
                and isinstance(node.func.value, ast.Attribute)):
            record_attr(node.func.value, True, held)
        for child in ast.iter_child_nodes(node):
            walk(child, held)

    for stmt in fn.body:
        walk(stmt, ())
    return facts


class _Analysis:
    """Facts + closures + edges for the whole project (built once, shared
    by both rules via ``analyze``)."""

    def __init__(self, project: Project):
        self.project = project
        self.world = build_lock_world(project)
        self.facts: dict[_MethodKey, _MethodFacts] = {}
        self.callbacks: dict[str, set[_MethodKey]] = {}
        for module in project.modules:
            for cls in module.classes.values():
                for meth in cls.methods.values():
                    key = (module.rel, cls.name, meth.name)
                    self.facts[key] = _collect_facts(
                        project, module, cls, meth, self.world)
                # callback registration: <obj>.<L>.append(self.<m>)
                for meth in cls.methods.values():
                    for sub in ast.walk(meth):
                        if not (isinstance(sub, ast.Call)
                                and isinstance(sub.func, ast.Attribute)
                                and sub.func.attr == "append"
                                and isinstance(sub.func.value, ast.Attribute)
                                and sub.args):
                            continue
                        arg = sub.args[0]
                        if (isinstance(arg, ast.Attribute)
                                and isinstance(arg.value, ast.Name)
                                and arg.value.id == "self"):
                            self.callbacks.setdefault(
                                sub.func.value.attr, set()).add(
                                (module.rel, cls.name, arg.attr))
        self._closure_memo: dict[_MethodKey, frozenset[str]] = {}

    def closure(self, key: _MethodKey,
                _stack: frozenset | None = None) -> frozenset[str]:
        """Locks a method may acquire, transitively."""
        if key in self._closure_memo:
            return self._closure_memo[key]
        stack = _stack or frozenset()
        if key in stack:
            return frozenset()
        stack = stack | {key}
        facts = self.facts.get(key)
        out: set[str] = set()
        if facts is None:
            self._closure_memo.setdefault(key, frozenset())
            return frozenset()
        out.update(root for root, _, _ in facts.acquisitions)
        for callee, _, _ in facts.calls:
            out.update(self.closure(callee, stack))
        for listattr, _, _ in facts.callback_calls:
            for target in self.callbacks.get(listattr, ()):
                out.update(self.closure(target, stack))
        result = frozenset(out)
        if _stack is None:  # only memoize complete (non-cut) closures
            self._closure_memo[key] = result
        return result

    def edges(self) -> dict[tuple[str, str], tuple[str, int, str]]:
        """(A, B) -> (rel, line, why): B acquired while A held."""
        out: dict[tuple[str, str], tuple[str, int, str]] = {}

        def add(a: str, b: str, rel: str, line: int, why: str) -> None:
            if a == b and self.world.is_rlock(a):
                return  # reentrant re-acquire of a shared RLock is the
                # sanctioned pattern, not a hazard
            out.setdefault((a, b), (rel, line, why))

        for (rel, cls_name, meth_name), facts in self.facts.items():
            where = f"{cls_name}.{meth_name}"
            for root, held, line in facts.acquisitions:
                for a in held:
                    add(a, root, rel, line, f"with in {where}")
            for callee, held, line in facts.calls:
                if not held:
                    continue
                callee_disp = (f"{callee[1]}.{callee[2]}" if callee[1]
                               else callee[2])
                for b in self.closure(callee):
                    for a in held:
                        add(a, b, rel, line,
                            f"{where} calls {callee_disp}")
            for listattr, held, line in facts.callback_calls:
                if not held:
                    continue
                for target in self.callbacks.get(listattr, ()):
                    for b in self.closure(target):
                        for a in held:
                            add(a, b, rel, line,
                                f"{where} fires {listattr} -> "
                                f"{target[1]}.{target[2]}")
        return out


def analyze(project: Project) -> _Analysis:
    cached = getattr(project, "_graftlint_lock_analysis", None)
    if cached is None:
        cached = _Analysis(project)
        project._graftlint_lock_analysis = cached  # type: ignore[attr-defined]
    return cached


@register
class LockOrderRule(Rule):
    id = "lock-order"
    doc = ("Cycles in the static lock-acquisition graph (ABBA deadlocks), "
           "including acquisitions reached through calls and registered "
           "listener callbacks; self-acquire of a non-reentrant lock.")

    def run(self, project: Project) -> list[Finding]:
        analysis = analyze(project)
        world = analysis.world
        edges = analysis.edges()
        findings: list[Finding] = []
        # self-edges on non-reentrant locks: unconditional deadlock
        for (a, b), (rel, line, why) in sorted(edges.items()):
            if a == b:
                findings.append(Finding(
                    self.id, rel, line,
                    f"non-reentrant lock {world.display(a)} re-acquired "
                    f"while held ({why})"))
        # cycles among distinct locks: iterative DFS per SCC would be
        # overkill at this scale — find one cycle per offending edge pair
        graph: dict[str, set[str]] = {}
        for (a, b) in edges:
            if a != b:
                graph.setdefault(a, set()).add(b)
        seen_cycles: set[frozenset[str]] = set()
        for start in sorted(graph):
            path: list[str] = []
            on_path: set[str] = set()

            def dfs(node: str) -> None:
                if node in on_path:
                    cyc = path[path.index(node):] + [node]
                    cid = frozenset(cyc)
                    if cid in seen_cycles:
                        return
                    seen_cycles.add(cid)
                    rel, line, why = edges[(cyc[0], cyc[1])]
                    findings.append(Finding(
                        self.id, rel, line,
                        "lock order cycle: "
                        + " -> ".join(world.display(n) for n in cyc)
                        + f" (first edge: {why})"))
                    return
                if node not in graph:
                    return
                path.append(node)
                on_path.add(node)
                for nxt in sorted(graph[node]):
                    dfs(nxt)
                path.pop()
                on_path.discard(node)

            dfs(start)
        return findings


@register
class CrossThreadStateRule(Rule):
    id = "cross-thread-state"
    doc = ("Attributes written under a class's lock are owned by it; "
           "reading or writing them WITHOUT the lock from methods "
           "reachable by client/HTTP/supervise threads (anything outside "
           "the run/step/drain scheduler closure) is a data race. "
           "Methods named *_locked assert a held-lock contract and are "
           "exempt, as is __init__ (pre-thread construction).")

    def run(self, project: Project) -> list[Finding]:
        analysis = analyze(project)
        world = analysis.world
        findings: list[Finding] = []
        for module in project.modules:
            for cls in module.classes.values():
                if not world.class_lock_attrs(cls):
                    continue
                guarded: set[str] = set()
                for meth_name in cls.methods:
                    for acc in analysis.facts[(module.rel, cls.name,
                                               meth_name)].accesses:
                        if acc.write and acc.held:
                            guarded.add(acc.attr)
                if not guarded:
                    continue
                sched = self._scheduler_closure(analysis, cls)
                for meth_name, meth in cls.methods.items():
                    if (meth_name in sched or meth_name == "__init__"
                            or meth_name.endswith("_locked")):
                        continue
                    for acc in analysis.facts[(module.rel, cls.name,
                                               meth_name)].accesses:
                        if acc.held or acc.attr not in guarded:
                            continue
                        findings.append(Finding(
                            self.id, module.rel, acc.line,
                            f"{cls.name}.{acc.attr} is written under the "
                            f"class lock elsewhere but "
                            f"{'written' if acc.write else 'read'} without "
                            f"it in {meth_name}()"))
        return findings

    @staticmethod
    def _scheduler_closure(analysis: _Analysis, cls: ClassInfo) -> set[str]:
        rel = cls.module.rel
        roots = _SCHEDULER_ENTRIES & set(cls.methods)
        out: set[str] = set()
        stack = list(roots)
        while stack:
            name = stack.pop()
            if name in out:
                continue
            out.add(name)
            for (crel, owner, callee), _, _ in analysis.facts[
                    (rel, cls.name, name)].calls:
                if (crel, owner) == (rel, cls.name) and callee not in out:
                    stack.append(callee)
        return out
