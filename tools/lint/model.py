"""graftlint's shared project model: one parse of the analyzed tree.

Every rule consumes the same :class:`Project` — modules parsed once,
classes/methods indexed, best-effort attribute types inferred from
constructor parameter annotations and constructor-call assignments — so
adding a rule never adds a parse pass, and cross-module resolution
(``self.engine.warmup`` → ``ServeEngine.warmup``) lives in ONE place.

The type inference here is deliberately shallow and under-approximate:
names it cannot resolve simply resolve to nothing, so rules built on it
miss, they do not false-positive. That is the right default for a gate
(tools/lint/core.py exits REGRESSION_RC on NEW findings): a silent miss
costs a review; a noisy false positive costs the gate's credibility.
"""

from __future__ import annotations

import ast
import os


class ModuleInfo:
    """One parsed source file."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel  # repo-relative, posix separators (finding identity)
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=rel)
        self.classes: dict[str, ClassInfo] = {}
        self.functions: dict[str, ast.FunctionDef] = {}
        for node in self.tree.body:
            if isinstance(node, ast.ClassDef):
                self.classes[node.name] = ClassInfo(node, self)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


def _classish(name: str) -> bool:
    """CamelCase-shaped identifier, tolerating the private-class
    convention (``_DiskTier``, ``_Session``)."""
    return name.lstrip("_")[:1].isupper()


def _annotation_names(node: ast.AST | None) -> list[str]:
    """Candidate class names in an annotation: ``Batcher | None`` →
    ["Batcher"], ``"ServeEngine"`` (string annotation) → ["ServeEngine"]."""
    if node is None:
        return []
    out: list[str] = []
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        try:
            node = ast.parse(node.value, mode="eval").body
        except SyntaxError:
            return []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and _classish(sub.id):
            out.append(sub.id)
        elif isinstance(sub, ast.Attribute) and _classish(sub.attr):
            out.append(sub.attr)
    return out


def _value_type_names(value: ast.AST, param_types: dict[str, list[str]]
                      ) -> list[str]:
    """Best-effort type candidates for an assigned value."""
    if isinstance(value, ast.Name):
        return param_types.get(value.id, [])
    if isinstance(value, ast.Call):
        f = value.func
        if isinstance(f, ast.Name) and _classish(f.id):
            return [f.id]
        if isinstance(f, ast.Attribute) and _classish(f.attr):
            return [f.attr]
        return []
    if isinstance(value, ast.IfExp):
        return (_value_type_names(value.body, param_types)
                or _value_type_names(value.orelse, param_types))
    if isinstance(value, ast.BoolOp):
        for v in value.values:
            got = _value_type_names(v, param_types)
            if got:
                return got
    return []


class ClassInfo:
    """A class, its directly-defined methods, and inferred attr types."""

    def __init__(self, node: ast.ClassDef, module: ModuleInfo):
        self.node = node
        self.name = node.name
        self.module = module
        self.methods: dict[str, ast.FunctionDef] = {}
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[item.name] = item
        #: attr name -> candidate class names ("self.engine" -> ["ServeEngine"])
        self.attr_types: dict[str, list[str]] = {}
        for meth in self.methods.values():
            param_types = {
                a.arg: _annotation_names(a.annotation)
                for a in (meth.args.posonlyargs + meth.args.args
                          + meth.args.kwonlyargs)
            }
            for sub in ast.walk(meth):
                if isinstance(sub, ast.Assign):
                    targets, names = sub.targets, _value_type_names(
                        sub.value, param_types)
                elif isinstance(sub, ast.AnnAssign):
                    # `self.tiers: SessionTiers | None = tiers` — the
                    # annotation is the declared type; fall back to the
                    # value's inferred type when the annotation names no
                    # project class
                    targets = [sub.target]
                    names = (_annotation_names(sub.annotation)
                             or (_value_type_names(sub.value, param_types)
                                 if sub.value is not None else []))
                else:
                    continue
                for tgt in targets:
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"
                            and tgt.attr not in self.attr_types):
                        if names:
                            self.attr_types[tgt.attr] = names


class Project:
    """All analyzed modules + cross-module class index."""

    def __init__(self, modules: list[ModuleInfo]):
        self.modules = modules
        self.by_rel = {m.rel: m for m in modules}
        self.classes_by_name: dict[str, list[ClassInfo]] = {}
        for m in modules:
            for c in m.classes.values():
                self.classes_by_name.setdefault(c.name, []).append(c)

    def find_class(self, name: str) -> ClassInfo | None:
        hits = self.classes_by_name.get(name)
        return hits[0] if hits else None

    # ---- call / attribute resolution (shared by rules) ----------------

    def attr_class(self, cls: ClassInfo, attr: str) -> ClassInfo | None:
        for name in cls.attr_types.get(attr, []):
            hit = self.find_class(name)
            if hit is not None:
                return hit
        return None

    def resolve_receiver(self, expr: ast.AST, cls: ClassInfo | None,
                         local_types: dict[str, list[str]] | None = None
                         ) -> ClassInfo | None:
        """Class of the object an attribute access hangs off: ``self`` →
        cls; ``self.a`` / ``self.a.b`` → chased through attr_types; a
        local name → its recorded candidate types."""
        if isinstance(expr, ast.Name):
            if expr.id == "self":
                return cls
            for name in (local_types or {}).get(expr.id, []):
                hit = self.find_class(name)
                if hit is not None:
                    return hit
            return None
        if isinstance(expr, ast.Attribute):
            owner = self.resolve_receiver(expr.value, cls, local_types)
            if owner is not None:
                return self.attr_class(owner, expr.attr)
        return None

    def resolve_call(self, call: ast.Call, module: ModuleInfo,
                     cls: ClassInfo | None,
                     local_types: dict[str, list[str]] | None = None
                     ) -> tuple[ClassInfo | None, ast.FunctionDef] | None:
        """(owning class or None, FunctionDef) for a call we can resolve
        statically; None otherwise."""
        f = call.func
        if isinstance(f, ast.Name):
            fn = module.functions.get(f.id)
            if fn is not None:
                return (None, fn)
            return None
        if isinstance(f, ast.Attribute):
            owner = self.resolve_receiver(f.value, cls, local_types)
            if owner is not None and f.attr in owner.methods:
                return (owner, owner.methods[f.attr])
        return None


def self_call_closure(cls: ClassInfo, roots) -> set[str]:
    """Method names reachable from ``roots`` through ``self.m()`` calls
    (transitively). The ONE implementation of the scheduler/stop-path
    closure walk shared by the host-sync, swallowed-exception and
    thread-lifecycle rules — closure semantics must not drift apart
    between them."""
    out: set[str] = set()
    stack = [r for r in roots if r in cls.methods]
    while stack:
        name = stack.pop()
        if name in out:
            continue
        out.add(name)
        for sub in ast.walk(cls.methods[name]):
            if (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and isinstance(sub.func.value, ast.Name)
                    and sub.func.value.id == "self"
                    and sub.func.attr in cls.methods
                    and sub.func.attr not in out):
                stack.append(sub.func.attr)
    return out


def local_alias_types(fn: ast.FunctionDef, project: Project,
                      cls: ClassInfo | None) -> dict[str, list[str]]:
    """Types of simple local aliases in one function body: parameters by
    annotation, plus ``x = self.a[.b]`` chains."""
    out: dict[str, list[str]] = {}
    for a in (fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs):
        names = _annotation_names(a.annotation)
        if names:
            out[a.arg] = names
    for sub in ast.walk(fn):
        if (isinstance(sub, ast.Assign) and len(sub.targets) == 1
                and isinstance(sub.targets[0], ast.Name)):
            target = sub.targets[0].id
            got = project.resolve_receiver(sub.value, cls, out)
            if got is not None:
                out.setdefault(target, []).append(got.name)
    return out


def _dotted_name(rel: str) -> str:
    """Repo-relative path -> importable dotted name
    (``lstm_tensorspark_tpu/serve/batcher.py`` ->
    ``lstm_tensorspark_tpu.serve.batcher``; ``__init__.py`` names the
    package)."""
    parts = rel[:-3].split("/") if rel.endswith(".py") else rel.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _imported_names(module: ModuleInfo) -> set[str]:
    """Dotted names this module imports, with relative imports resolved
    against its own package."""
    out: set[str] = set()
    # the package context level-1 relative imports resolve against: for
    # a plain module that is its CONTAINING package (pkg.sub.mod -> from
    # . import x means pkg.sub.x); for an __init__.py the dotted name
    # already IS the package
    parts = _dotted_name(module.rel).split(".")
    is_pkg = module.rel.endswith("__init__.py")
    ctx = parts if is_pkg else parts[:-1]
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Import):
            out.update(a.name for a in node.names)
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                # from ..x import y: level 1 = own package, each extra
                # level climbs one more
                climb = node.level - 1
                base_parts = ctx[: len(ctx) - climb] if climb <= len(
                    ctx) else []
                base = ".".join(base_parts)
                if node.module:
                    base = f"{base}.{node.module}" if base else node.module
            else:
                base = node.module or ""
            if base:
                out.add(base)
            for a in node.names:
                out.add(f"{base}.{a.name}" if base else a.name)
    return out


def changed_closure(project: Project, changed_rels: set[str]) -> set[str]:
    """``changed_rels`` plus every analyzed module that imports one of
    them, plus the modules the changed files themselves import (one hop
    each way). The --changed scoped mode lints this closure only: a
    signature/contract change shows up in the module or its importers,
    and the changed files' own imports must be IN the model or
    cross-module resolution degrades and invents findings the full-tree
    gate doesn't have (a scoped run may only under-report, never
    over-report). Full-tree coverage stays verify.sh phase 0's job."""
    targets: set[str] = set()
    for rel in changed_rels:
        name = _dotted_name(rel)
        if name:
            targets.add(name)
    out = set(changed_rels) & set(project.by_rel)
    by_name = {_dotted_name(m.rel): m.rel for m in project.modules}
    # imports OF the changed files (the resolution universe)
    for rel in list(out):
        for imported in _imported_names(project.by_rel[rel]):
            for name, mrel in by_name.items():
                if imported == name or imported.startswith(name + "."):
                    out.add(mrel)
    for module in project.modules:
        if module.rel in out:
            continue
        for imported in _imported_names(module):
            if any(imported == t or imported.startswith(t + ".")
                   or t.startswith(imported + ".")
                   for t in targets):
                out.add(module.rel)
                break
    return out


# ---- CFG-lite ----------------------------------------------------------
#
# A statement-granular control-flow graph per function: branch/loop
# edges, try/except/finally edges, return/raise exits. Built for the
# lifecycle rules (resource-pairing needs "is this resource released on
# EVERY path out of the function, including exception exits"), and
# deliberately small: nodes are statements, expression evaluation order
# inside one statement is not modeled, and `finally` re-entry is
# approximated (the finally body is built once; its last node gets extra
# edges to EXIT/RAISE for the abnormal-exit flows routed through it).
# The approximations all err toward EXTRA paths, which for a may-
# analysis ("exists a path where the resource is still held") means a
# rule can over-report only on code whose control flow is already too
# clever — and the fixture suite pins the shapes that must stay silent.

#: symbolic terminals (negative so they never collide with node ids)
CFG_EXIT = -1   # normal completion: return / fall off the end
CFG_RAISE = -2  # uncaught exception leaves the function


def _own_exprs(stmt: ast.stmt) -> list[ast.AST]:
    """The expressions evaluated BY this statement itself — excluding
    nested statement bodies (those are their own CFG nodes) and nested
    function definitions (separate execution contexts)."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, ast.For):
        return [stmt.iter, stmt.target]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        out: list[ast.AST] = []
        for item in stmt.items:
            out.append(item.context_expr)
            if item.optional_vars is not None:
                out.append(item.optional_vars)
        return out
    if isinstance(stmt, ast.Return):
        return [] if stmt.value is None else [stmt.value]
    if isinstance(stmt, ast.Raise):
        return [e for e in (stmt.exc, stmt.cause) if e is not None]
    if isinstance(stmt, (ast.Assign,)):
        return [stmt.value, *stmt.targets]
    if isinstance(stmt, ast.AugAssign):
        return [stmt.value, stmt.target]
    if isinstance(stmt, ast.AnnAssign):
        return [e for e in (stmt.value, stmt.target) if e is not None]
    if isinstance(stmt, ast.Expr):
        return [stmt.value]
    if isinstance(stmt, (ast.Assert,)):
        return [e for e in (stmt.test, stmt.msg) if e is not None]
    if isinstance(stmt, ast.Delete):
        return list(stmt.targets)
    if isinstance(stmt, (ast.Try, ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return []
    return []


def handler_catches_all(handler: ast.ExceptHandler) -> bool:
    """Bare ``except`` or ``except Exception``/``BaseException`` (also
    inside tuples/attribute forms) — the ONE catch-all definition shared
    by the CFG's try wiring and the swallowed-exception rule."""
    if handler.type is None:
        return True
    names = {n.attr if isinstance(n, ast.Attribute)
             else getattr(n, "id", "")
             for n in ast.walk(handler.type)}
    return bool(names & {"Exception", "BaseException"})


def stmt_may_raise(stmt: ast.stmt) -> bool:
    """Whether this statement's OWN expressions can raise: any call (or
    an explicit raise). Attribute/subscript errors are ignored — calls
    are where IO, device work and lock operations live."""
    if isinstance(stmt, ast.Raise):
        return True
    for expr in _own_exprs(stmt):
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call):
                return True
    return False


class CFG:
    """Per-function CFG (see the section comment above). Public surface:
    ``stmts`` (node id -> statement), ``succ`` (normal-flow successor
    ids / terminals), ``exc_succ`` (where control may go when the node's
    own expressions raise), ``entry``."""

    def __init__(self, fn: ast.FunctionDef):
        self.stmts: list[ast.stmt] = []
        self.succ: list[list[int]] = []
        self.exc_succ: list[list[int]] = []
        self.entry = self._build_body(fn.body, CFG_EXIT, (CFG_RAISE,),
                                      None, None, None)

    def _node(self, stmt: ast.stmt, exc: tuple[int, ...]) -> int:
        nid = len(self.stmts)
        self.stmts.append(stmt)
        self.succ.append([])
        self.exc_succ.append(list(exc) if stmt_may_raise(stmt) else [])
        return nid

    def _build_body(self, body: list[ast.stmt], follow: int,
                    exc: tuple[int, ...], brk: int | None,
                    cont: int | None, fin: int | None) -> int:
        """Wire ``body`` so it flows to ``follow``; returns its entry.
        ``fin`` is the innermost enclosing finally entry (within this
        function): abnormal exits (return/break/continue) route through
        it — the finally's tail carries the extra EXIT/RAISE edges."""
        entry = follow
        for stmt in reversed(body):
            entry = self._build_stmt(stmt, entry, exc, brk, cont, fin)
        return entry

    def _build_stmt(self, stmt: ast.stmt, follow: int,
                    exc: tuple[int, ...], brk: int | None,
                    cont: int | None, fin: int | None) -> int:
        if isinstance(stmt, ast.If):
            nid = self._node(stmt, exc)
            self.succ[nid].append(
                self._build_body(stmt.body, follow, exc, brk, cont, fin))
            self.succ[nid].append(
                self._build_body(stmt.orelse, follow, exc, brk, cont,
                                 fin)
                if stmt.orelse else follow)
            return nid
        if isinstance(stmt, (ast.While, ast.For)):
            nid = self._node(stmt, exc)
            body_entry = self._build_body(stmt.body, nid, exc,
                                          brk=follow, cont=nid, fin=fin)
            self.succ[nid].append(body_entry)
            self.succ[nid].append(
                self._build_body(stmt.orelse, follow, exc, brk, cont,
                                 fin)
                if stmt.orelse else follow)
            return nid
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            nid = self._node(stmt, exc)
            self.succ[nid].append(
                self._build_body(stmt.body, follow, exc, brk, cont, fin))
            return nid
        if isinstance(stmt, ast.Try):
            return self._build_try(stmt, follow, exc, brk, cont, fin)
        if isinstance(stmt, ast.Return):
            nid = self._node(stmt, exc)
            # a return inside try/finally runs the finally first (its
            # tail has the extra EXIT edge)
            self.succ[nid].append(CFG_EXIT if fin is None else fin)
            return nid
        if isinstance(stmt, ast.Raise):
            nid = self._node(stmt, exc)
            # normal flow ends here; the raise itself follows exc edges
            return nid
        if isinstance(stmt, ast.Break):
            nid = self._node(stmt, exc)
            self.succ[nid].append(follow if brk is None else brk)
            return nid
        if isinstance(stmt, ast.Continue):
            nid = self._node(stmt, exc)
            self.succ[nid].append(follow if cont is None else cont)
            return nid
        # nested defs/classes and simple statements: one node, straight
        # through (nested bodies are separate execution contexts)
        nid = self._node(stmt, exc)
        self.succ[nid].append(follow)
        return nid

    def _build_try(self, stmt: ast.Try, follow: int, exc: tuple[int, ...],
                   brk: int | None, cont: int | None,
                   fin: int | None) -> int:
        after = follow
        fin_entry = None
        if stmt.finalbody:
            lo = len(self.stmts)
            fin_entry = self._build_body(stmt.finalbody, after, exc,
                                         brk, cont, fin)
            # abnormal exits route through the finally too: give its
            # last-reachable flow extra edges to EXIT and RAISE (the
            # finally body was built once — this over-approximates by
            # letting every execution "exit abnormally", which only adds
            # paths, never hides one)
            for nid in range(lo, len(self.stmts)):
                succ = self.succ[nid]
                if after in succ:
                    succ.extend(t for t in (CFG_EXIT, CFG_RAISE)
                                if t not in succ)
            after = fin_entry
        # everything leaving the try region abnormally runs the finally
        # first: handler bodies' own exceptions (incl. a re-raise),
        # else-body exceptions, and return/break/continue out of the
        # body — without this, try/except-reraise/finally-release would
        # read as skipping the release
        inner_fin = fin_entry if fin_entry is not None else fin
        inner_exc = (fin_entry,) if fin_entry is not None else exc
        inner_brk = fin_entry if (fin_entry is not None
                                  and brk is not None) else brk
        inner_cont = fin_entry if (fin_entry is not None
                                   and cont is not None) else cont
        handler_entries = []
        catch_all = False
        for handler in stmt.handlers:
            handler_entries.append(
                self._build_body(handler.body, after, inner_exc,
                                 inner_brk, inner_cont, inner_fin))
            if handler_catches_all(handler):
                catch_all = True
        body_exc: tuple[int, ...] = tuple(handler_entries)
        if not catch_all:
            # unmatched exceptions escape the handlers: through the
            # finally when there is one, else out of the function
            body_exc += (fin_entry,) if fin_entry is not None else exc
        elif not handler_entries and fin_entry is not None:
            body_exc = (fin_entry,)
        body_follow = after
        if stmt.orelse:
            # else runs only when the body completed without raising:
            # wire body -> else -> after. Else-body exceptions are NOT
            # caught by this try's handlers — they route through the
            # finally (or out)
            body_follow = self._build_body(stmt.orelse, after, inner_exc,
                                           inner_brk, inner_cont,
                                           inner_fin)
        return self._build_body(stmt.body, body_follow, body_exc,
                                inner_brk, inner_cont, inner_fin)


# ---- resource registry --------------------------------------------------
#
# Acquire/release call shapes the lifecycle rules pair up. Each entry:
# acquire method names -> (release method names, leak-tracked?). Plain
# `acquire`/`release` is registered but NOT leak-tracked: StateCache's
# acquire transfers ownership to the cache's own LRU table (an unpinned
# slot is always reclaimable, so "not released" is routinely the correct
# ownership transfer, e.g. kept sessions). Pinned slots and in-flight
# counters are the leakable kinds — a pinned slot is unevictable and a
# wedged counter blocks flush() forever (the PR 7/PR 8 classes).

RESOURCE_PAIRS: dict[str, dict] = {
    "pin": {"acquire": {"pin", "acquire_pinned"},
            "release": {"unpin", "release"},
            "tracked": True},
    "slot": {"acquire": {"acquire"}, "release": {"release"},
             "tracked": False},
    "handle": {"acquire": {"open"}, "release": {"close"},
               "tracked": True},
    # thread start/stop pairing is structural (owner's stop()/close()
    # must reach a join or a signal the worker loop reads) and lives in
    # rules_threads rather than the per-function dataflow
    "thread": {"acquire": {"start"}, "release": {"join", "close"},
               "tracked": False},
}


def resource_kind_of_call(call: ast.Call) -> tuple[str, str] | None:
    """('kind', 'acquire'|'release') for a call matching a tracked
    resource shape, else None. ``open(...)`` matches as a Name call;
    the slot/pin shapes as attribute calls (``cache.pin(sid)``)."""
    f = call.func
    name = (f.attr if isinstance(f, ast.Attribute)
            else f.id if isinstance(f, ast.Name) else None)
    if name is None:
        return None
    for kind, spec in RESOURCE_PAIRS.items():
        if not spec["tracked"]:
            continue
        if name in spec["acquire"]:
            if kind == "handle" and not isinstance(f, ast.Name):
                continue  # only the builtin open(); obj.open() is opaque
            return kind, "acquire"
        if name in spec["release"]:
            return kind, "release"
    return None


def load_project(paths: list[str], repo_root: str) -> Project:
    """Parse every ``.py`` under ``paths`` (files or directories).
    Unparseable files are skipped — a syntax error is the interpreter's
    job to report, not the linter's."""
    files: list[str] = []
    for p in paths:
        if os.path.isfile(p):
            files.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__" and not d.startswith(".")]
            files.extend(os.path.join(dirpath, f)
                         for f in filenames if f.endswith(".py"))
    modules = []
    for f in sorted(set(files)):
        rel = os.path.relpath(os.path.abspath(f), repo_root).replace(
            os.sep, "/")
        try:
            with open(f, encoding="utf-8") as fh:
                source = fh.read()
            modules.append(ModuleInfo(f, rel, source))
        except (OSError, SyntaxError, ValueError):
            continue
    return Project(modules)
