"""graftlint's shared project model: one parse of the analyzed tree.

Every rule consumes the same :class:`Project` — modules parsed once,
classes/methods indexed, best-effort attribute types inferred from
constructor parameter annotations and constructor-call assignments — so
adding a rule never adds a parse pass, and cross-module resolution
(``self.engine.warmup`` → ``ServeEngine.warmup``) lives in ONE place.

The type inference here is deliberately shallow and under-approximate:
names it cannot resolve simply resolve to nothing, so rules built on it
miss, they do not false-positive. That is the right default for a gate
(tools/lint/core.py exits REGRESSION_RC on NEW findings): a silent miss
costs a review; a noisy false positive costs the gate's credibility.
"""

from __future__ import annotations

import ast
import os


class ModuleInfo:
    """One parsed source file."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel  # repo-relative, posix separators (finding identity)
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=rel)
        self.classes: dict[str, ClassInfo] = {}
        self.functions: dict[str, ast.FunctionDef] = {}
        for node in self.tree.body:
            if isinstance(node, ast.ClassDef):
                self.classes[node.name] = ClassInfo(node, self)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


def _annotation_names(node: ast.AST | None) -> list[str]:
    """Candidate class names in an annotation: ``Batcher | None`` →
    ["Batcher"], ``"ServeEngine"`` (string annotation) → ["ServeEngine"]."""
    if node is None:
        return []
    out: list[str] = []
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        try:
            node = ast.parse(node.value, mode="eval").body
        except SyntaxError:
            return []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id[:1].isupper():
            out.append(sub.id)
        elif isinstance(sub, ast.Attribute) and sub.attr[:1].isupper():
            out.append(sub.attr)
    return out


def _value_type_names(value: ast.AST, param_types: dict[str, list[str]]
                      ) -> list[str]:
    """Best-effort type candidates for an assigned value."""
    if isinstance(value, ast.Name):
        return param_types.get(value.id, [])
    if isinstance(value, ast.Call):
        f = value.func
        if isinstance(f, ast.Name) and f.id[:1].isupper():
            return [f.id]
        if isinstance(f, ast.Attribute) and f.attr[:1].isupper():
            return [f.attr]
        return []
    if isinstance(value, ast.IfExp):
        return (_value_type_names(value.body, param_types)
                or _value_type_names(value.orelse, param_types))
    if isinstance(value, ast.BoolOp):
        for v in value.values:
            got = _value_type_names(v, param_types)
            if got:
                return got
    return []


class ClassInfo:
    """A class, its directly-defined methods, and inferred attr types."""

    def __init__(self, node: ast.ClassDef, module: ModuleInfo):
        self.node = node
        self.name = node.name
        self.module = module
        self.methods: dict[str, ast.FunctionDef] = {}
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[item.name] = item
        #: attr name -> candidate class names ("self.engine" -> ["ServeEngine"])
        self.attr_types: dict[str, list[str]] = {}
        for meth in self.methods.values():
            param_types = {
                a.arg: _annotation_names(a.annotation)
                for a in (meth.args.posonlyargs + meth.args.args
                          + meth.args.kwonlyargs)
            }
            for sub in ast.walk(meth):
                if not isinstance(sub, ast.Assign):
                    continue
                for tgt in sub.targets:
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"
                            and tgt.attr not in self.attr_types):
                        names = _value_type_names(sub.value, param_types)
                        if names:
                            self.attr_types[tgt.attr] = names


class Project:
    """All analyzed modules + cross-module class index."""

    def __init__(self, modules: list[ModuleInfo]):
        self.modules = modules
        self.by_rel = {m.rel: m for m in modules}
        self.classes_by_name: dict[str, list[ClassInfo]] = {}
        for m in modules:
            for c in m.classes.values():
                self.classes_by_name.setdefault(c.name, []).append(c)

    def find_class(self, name: str) -> ClassInfo | None:
        hits = self.classes_by_name.get(name)
        return hits[0] if hits else None

    # ---- call / attribute resolution (shared by rules) ----------------

    def attr_class(self, cls: ClassInfo, attr: str) -> ClassInfo | None:
        for name in cls.attr_types.get(attr, []):
            hit = self.find_class(name)
            if hit is not None:
                return hit
        return None

    def resolve_receiver(self, expr: ast.AST, cls: ClassInfo | None,
                         local_types: dict[str, list[str]] | None = None
                         ) -> ClassInfo | None:
        """Class of the object an attribute access hangs off: ``self`` →
        cls; ``self.a`` / ``self.a.b`` → chased through attr_types; a
        local name → its recorded candidate types."""
        if isinstance(expr, ast.Name):
            if expr.id == "self":
                return cls
            for name in (local_types or {}).get(expr.id, []):
                hit = self.find_class(name)
                if hit is not None:
                    return hit
            return None
        if isinstance(expr, ast.Attribute):
            owner = self.resolve_receiver(expr.value, cls, local_types)
            if owner is not None:
                return self.attr_class(owner, expr.attr)
        return None

    def resolve_call(self, call: ast.Call, module: ModuleInfo,
                     cls: ClassInfo | None,
                     local_types: dict[str, list[str]] | None = None
                     ) -> tuple[ClassInfo | None, ast.FunctionDef] | None:
        """(owning class or None, FunctionDef) for a call we can resolve
        statically; None otherwise."""
        f = call.func
        if isinstance(f, ast.Name):
            fn = module.functions.get(f.id)
            if fn is not None:
                return (None, fn)
            return None
        if isinstance(f, ast.Attribute):
            owner = self.resolve_receiver(f.value, cls, local_types)
            if owner is not None and f.attr in owner.methods:
                return (owner, owner.methods[f.attr])
        return None


def local_alias_types(fn: ast.FunctionDef, project: Project,
                      cls: ClassInfo | None) -> dict[str, list[str]]:
    """Types of simple local aliases in one function body: parameters by
    annotation, plus ``x = self.a[.b]`` chains."""
    out: dict[str, list[str]] = {}
    for a in (fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs):
        names = _annotation_names(a.annotation)
        if names:
            out[a.arg] = names
    for sub in ast.walk(fn):
        if (isinstance(sub, ast.Assign) and len(sub.targets) == 1
                and isinstance(sub.targets[0], ast.Name)):
            target = sub.targets[0].id
            got = project.resolve_receiver(sub.value, cls, out)
            if got is not None:
                out.setdefault(target, []).append(got.name)
    return out


def load_project(paths: list[str], repo_root: str) -> Project:
    """Parse every ``.py`` under ``paths`` (files or directories).
    Unparseable files are skipped — a syntax error is the interpreter's
    job to report, not the linter's."""
    files: list[str] = []
    for p in paths:
        if os.path.isfile(p):
            files.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__" and not d.startswith(".")]
            files.extend(os.path.join(dirpath, f)
                         for f in filenames if f.endswith(".py"))
    modules = []
    for f in sorted(set(files)):
        rel = os.path.relpath(os.path.abspath(f), repo_root).replace(
            os.sep, "/")
        try:
            with open(f, encoding="utf-8") as fh:
                source = fh.read()
            modules.append(ModuleInfo(f, rel, source))
        except (OSError, SyntaxError, ValueError):
            continue
    return Project(modules)
