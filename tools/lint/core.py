"""graftlint core: findings, the rule registry, inline suppressions, and
the baseline diff gate.

The gate is modeled on tools/tier1_diff.py: a checked-in baseline
(tools/lint_baseline.txt) records accepted findings WITH a written
justification each, and the exit code is ``REGRESSION_RC`` (3, imported
from resilience/exit_codes.py — the one table) only on NEW findings.
Fixing a finding makes the run report it as retired (tighten with
``--update-baseline``); introducing one fails ``tools/verify.sh`` before
the timed tier-1 suite ever starts.

Finding identity is ``path:rule:fingerprint`` — no line number, so an
unrelated edit shifting lines never churns the baseline. The fingerprint
is the stable part of the message (rules keep names/identifiers in it,
not positions).

Suppression: append ``# graftlint: disable=<rule-id>[,<rule-id>...]`` to
the offending line. Suppressions are for findings the code is RIGHT to
trigger on generically but wrong here for a stated reason — put the
reason in a comment next to the pragma (docs/LINT.md has the policy).
"""

from __future__ import annotations

import ast
import dataclasses
import json
import re
import sys

from .model import ModuleInfo, Project

# the shared regression exit code — resilience/exit_codes.py is the one
# authority (tools/tier1_diff.py routes on the same constant)
from lstm_tensorspark_tpu.resilience.exit_codes import (  # noqa: E402
    REGRESSION_RC,
    USAGE_RC,
)

__all__ = [
    "Finding", "Rule", "RULES", "register", "run_rules",
    "load_baseline", "write_baseline", "suppressed",
    "REGRESSION_RC", "USAGE_RC",
]


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str          # rule id (kebab-case)
    rel: str           # repo-relative path
    line: int          # 1-based, for the human report only
    message: str       # one line, stable identifiers only

    def key(self) -> str:
        """Baseline identity — line-number free (see module docstring)."""
        return f"{self.rel}:{self.rule}:{self.message}"

    def render(self) -> str:
        return f"{self.rel}:{self.line}: {self.rule} {self.message}"


class Rule:
    """One invariant. Subclasses set ``id``/``doc`` and implement
    :meth:`run` returning findings over the whole project (rules are
    project-scoped, not file-scoped: lock graphs and warmup reachability
    span modules)."""

    id: str = ""
    doc: str = ""

    def run(self, project: Project) -> list[Finding]:  # pragma: no cover
        raise NotImplementedError


RULES: dict[str, Rule] = {}


def register(rule_cls: type[Rule]) -> type[Rule]:
    rule = rule_cls()
    if not rule.id:
        raise ValueError(f"{rule_cls.__name__} has no id")
    if rule.id in RULES:
        raise ValueError(f"duplicate rule id {rule.id!r}")
    RULES[rule.id] = rule
    return rule_cls


_PRAGMA_RE = re.compile(r"#\s*graftlint:\s*disable=([a-z0-9_,\- ]+)")


def _pragma_spans(module: ModuleInfo) -> list[tuple[int, int]]:
    """Header spans a suppression must cover as a unit: a def/class's
    decorator-to-signature block and a (possibly multi-line) ``with``
    header. A pragma anywhere in the span — or on the line above it —
    suppresses findings attributed to any line of the span, so
    ``# graftlint: disable=`` above a decorated ``def`` (whose physical
    line-above is the last decorator) and inside a wrapped ``with``
    header both work. Cached on the module (one AST pass)."""
    spans = getattr(module, "_graftlint_pragma_spans", None)
    if spans is not None:
        return spans
    spans = []
    for node in ast.walk(module.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            start = min([d.lineno for d in node.decorator_list]
                        + [node.lineno])
            end = node.body[0].lineno - 1 if node.body else node.lineno
            spans.append((start, max(start, end)))
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            end = node.body[0].lineno - 1 if node.body else node.lineno
            if end > node.lineno:  # multi-line header only
                spans.append((node.lineno, end))
    module._graftlint_pragma_spans = spans  # type: ignore[attr-defined]
    return spans


def _pragma_names(module: ModuleInfo, line: int) -> set[str]:
    m = _PRAGMA_RE.search(module.line(line))
    if not m:
        return set()
    return {r.strip() for r in m.group(1).split(",")}


def suppressed(module: ModuleInfo, line: int, rule_id: str) -> bool:
    """True when the finding's line, the line above it, or — for
    findings inside a decorated-def / multi-line-``with`` header span —
    any line of that span (or the line above the span) carries a
    disable pragma naming the rule."""
    candidates = {line, line - 1}
    for start, end in _pragma_spans(module):
        if start <= line <= end:
            candidates.update(range(start - 1, end + 1))
    return any(rule_id in _pragma_names(module, ln) for ln in candidates)


def run_rules(project: Project,
              only: set[str] | None = None) -> list[Finding]:
    findings: list[Finding] = []
    for rule_id in sorted(RULES):
        if only is not None and rule_id not in only:
            continue
        for f in RULES[rule_id].run(project):
            module = project.by_rel.get(f.rel)
            if module is not None and suppressed(module, f.line, f.rule):
                continue
            findings.append(f)
    findings.sort(key=lambda f: (f.rel, f.line, f.rule, f.message))
    return findings


# ---- baseline ----------------------------------------------------------

_BASELINE_HEADER = """\
# graftlint baseline (tools/lint/core.py) — accepted findings.
#
# Format: one `path:rule:fingerprint` per line; everything after ` # ` is
# the REQUIRED one-line justification for accepting instead of fixing.
# The gate (verify.sh) exits REGRESSION_RC only on findings NOT listed
# here. Tighten with `python -m tools.lint --update-baseline` after
# fixing entries; never add one without a justification.
"""


def load_baseline(path: str) -> dict[str, str]:
    """{finding key: justification}. Missing file = empty baseline."""
    out: dict[str, str] = {}
    try:
        with open(path, encoding="utf-8") as f:
            lines = f.read().splitlines()
    except OSError:
        return out
    for ln in lines:
        ln = ln.strip()
        if not ln or ln.startswith("#"):
            continue
        key, _, just = ln.partition(" # ")
        out[key.strip()] = just.strip()
    return out


def write_baseline(path: str, findings: list[Finding],
                   old: dict[str, str]) -> None:
    """Rewrite the baseline to the current finding set, keeping existing
    justifications and marking new entries for a human to justify."""
    with open(path, "w", encoding="utf-8") as f:
        f.write(_BASELINE_HEADER)
        for finding in findings:
            key = finding.key()
            just = old.get(key, "TODO: justify or fix")
            f.write(f"{key} # {just}\n")


# ---- report ------------------------------------------------------------

def report(findings: list[Finding], baseline: dict[str, str],
           *, json_path: str | None = None, scoped: bool = False,
           out=None) -> tuple[list[Finding], list[str]]:
    """Print the human report; return (new findings, retired keys)."""
    if out is None:
        out = sys.stdout  # resolved at call time (test capture works)
    new = [f for f in findings if f.key() not in baseline]
    current_keys = {f.key() for f in findings}
    retired = sorted(k for k in baseline if k not in current_keys)
    for f in findings:
        tag = "" if f.key() in baseline else " [NEW]"
        print(f.render() + tag, file=out)
    for k in retired:
        print(f"retired (fixed — tighten with --update-baseline): {k}",
              file=out)
    deltas = ""
    if json_path:
        # per-rule deltas vs the PREVIOUS report at this path, when one
        # exists (verify.sh writes LINT_report.json in place each run, so
        # the summary line trends finding movement next to BENCH_*.json).
        # Scoped (--changed) runs neither compute deltas nor count as a
        # trend point: partial counts vs full-tree counts would print
        # large spurious deltas either way — the scoped flag in the
        # payload tells the next full run to skip the comparison.
        prev = None
        try:
            with open(json_path, encoding="utf-8") as f:
                prev = json.load(f)
        except (OSError, ValueError):
            prev = None
        by_rule = _by_rule(findings)
        prev_by_rule = prev.get("by_rule") if isinstance(prev, dict) \
            else None
        if (not scoped and isinstance(prev_by_rule, dict)
                and not prev.get("scoped")):
            parts = []
            for rule in sorted(set(by_rule) | set(prev_by_rule)):
                d = by_rule.get(rule, 0) - int(prev_by_rule.get(rule, 0))
                if d:
                    parts.append(f"d({rule})={d:+d}")
            if parts:
                deltas = " " + " ".join(parts)
        payload = {
            "findings": [dataclasses.asdict(f) | {"key": f.key(),
                                                  "new": f.key() not in
                                                  baseline}
                         for f in findings],
            "new": len(new),
            "baseline": len(baseline),
            "retired": retired,
            "by_rule": by_rule,
            "scoped": scoped,
        }
        with open(json_path, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")
    # the one summary line tools/verify.sh surfaces for its GRAFTLINT phase
    print(f"GRAFTLINT new={len(new)} baseline={len(baseline)}" + deltas,
          file=out)
    return new, retired


def _by_rule(findings: list[Finding]) -> dict[str, int]:
    out: dict[str, int] = {}
    for f in findings:
        out[f.rule] = out.get(f.rule, 0) + 1
    return out
