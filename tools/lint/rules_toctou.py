"""``toctou-fs``: an ``exists()``/``stat()`` result guarding a
``remove``/``replace``/``rename``/``open`` on the SAME path expression.

The file can vanish (or appear) between the check and the use — another
process, another replica on a shared ``--session-dir``, or the keep-N
cleanup racing a restore. This repo has hit the class for real: PR 8
round 3 turned the training checkpoints' sidecar ``exists``+``remove``
into try/remove precisely because two writers racing one path could
interleave between the two calls. The honest pattern is to just do the
operation and handle ``FileNotFoundError`` (which the guarded code must
be prepared for anyway — the guard only narrows the window, it never
closes it).

Matched shape (lexical, deliberately narrow): an ``if`` whose test
contains a NON-negated ``os.path.exists(P)`` / ``os.path.isfile(P)`` /
``os.stat(P)`` / ``os.lstat(P)``, and whose body contains
``os.remove(P)`` / ``os.unlink(P)`` / ``os.replace(P, ...)`` /
``os.rename(P, ...)`` / ``open(P, ...)`` with a syntactically identical
``P``. Negated guards (``if not exists: ...``), guards feeding
different paths, and interprocedural uses stay silent — the rule
under-approximates, it does not guess.
"""

from __future__ import annotations

import ast

from .core import Finding, Rule, register
from .model import Project

_CHECKS = {"exists", "isfile", "stat", "lstat"}
#: verb -> which arg positions name the guarded path
_VERBS = {"remove": (0,), "unlink": (0,), "replace": (0,),
          "rename": (0,), "open": (0,)}


def _check_paths(test: ast.AST) -> list[str]:
    """Dumps of path args of non-negated exists/stat calls in a test."""
    out: list[str] = []

    def walk(node: ast.AST, negated: bool) -> None:
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
            walk(node.operand, not negated)
            return
        if (not negated and isinstance(node, ast.Call) and node.args
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _CHECKS):
            out.append(ast.dump(node.args[0]))
        for child in ast.iter_child_nodes(node):
            walk(child, negated)

    walk(test, False)
    return out


def _verb_of(call: ast.Call) -> str | None:
    f = call.func
    if isinstance(f, ast.Name) and f.id == "open":
        return "open"
    if isinstance(f, ast.Attribute) and f.attr in _VERBS \
            and f.attr != "open":
        return f.attr
    return None


@register
class ToctouFsRule(Rule):
    id = "toctou-fs"
    doc = ("exists()/stat() result guarding a remove/replace/rename/"
           "open on the same path expression — the file can vanish "
           "between check and use; do the operation and handle "
           "FileNotFoundError instead.")

    def run(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        seen: set[tuple] = set()  # nested ifs can guard one verb twice
        for module in project.modules:
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.If):
                    continue
                guarded = set(_check_paths(node.test))
                if not guarded:
                    continue
                for sub in ast.walk(ast.Module(body=node.body,
                                               type_ignores=[])):
                    if not isinstance(sub, ast.Call) or not sub.args:
                        continue
                    verb = _verb_of(sub)
                    if verb is None:
                        continue
                    for pos in _VERBS[verb]:
                        if pos < len(sub.args) \
                                and ast.dump(sub.args[pos]) in guarded:
                            ident = (module.rel, sub.lineno, verb)
                            if ident in seen:
                                break
                            seen.add(ident)
                            findings.append(Finding(
                                self.id, module.rel, sub.lineno,
                                f"exists()-guarded {verb}() on the same "
                                f"path ({ast.unparse(sub.args[pos])}) — "
                                "the file can vanish between check and "
                                "use; use try/except FileNotFoundError"))
                            break
        return findings
