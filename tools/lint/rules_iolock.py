"""``io-under-lock``: no blocking filesystem or device-sync call may
execute — directly or through any resolvable callee — inside a
``with``-region of the serve plane's designated hot locks.

The hot locks are the ones every request crosses: the shared state/
prefix/tier cache RLock, the batcher's scheduler lock, the router's
global admission lock, and the disk tier's index lock. PR 8 fixed this
class THREE TIMES in review (rounds 1–3): ``fill``'s disk read+verify
under the shared cache lock, the eviction listener's ``has`` stat under
the hot lock, and ``fill_ahead``'s potential file IO under the router's
global lock. One fsync under the shared lock stalls every admission,
health probe and scheduler iteration behind the filesystem.

Blocking shapes: ``open``/``os.replace``/``os.remove``/``os.rename``/
``os.unlink``/``os.listdir``/``os.scandir``/``os.makedirs``/
``os.fsync``/``shutil.*``, the durability core ``atomic_write``/
``read_verified``, ``time.sleep``, the device syncs
``jax.device_get`` / ``fetch_detached`` / ``fetch_detached_batch``, and
the network shapes ``urlopen`` / ``rpc_get`` / ``rpc_post`` (ISSUE 17:
the remote affinity probe once held the router's global lock across a
bounded HTTP GET per continuation — fixture pair
``viol/clean_remote_sync``).
Metadata probes (``os.path.exists``/``os.stat``) are deliberately NOT
in the set: the router's disk-residency probe does one deduped stat per
session directory under its global lock by design (PR 8 round 3), and a
stat is bounded in a way data IO is not.

Resolution is the project model's (under-approximate): a callee the
model cannot resolve is silent, so the rule misses rather than guesses.
Lock identity comes from the lock-order rule's union-found world, so
the shared-RLock alias (``PrefixCache._lock = cache._lock``) is one
identity — holding it through ANY alias counts.
"""

from __future__ import annotations

import ast

from .core import Finding, Rule, register
from .model import ClassInfo, ModuleInfo, Project, local_alias_types
from .rules_locks import _attr_chain_lock, analyze

#: classes whose locks are the serve plane's hot locks (fixtures use the
#: same names — matching mirrors rules_hostsync.SCHEDULER_CLASSES)
HOT_LOCK_CLASSES = {"StateCache", "PrefixCache", "SessionTiers",
                    "Batcher", "Router", "_DiskTier"}

_BLOCKING_NAME_CALLS = {"open", "atomic_write", "read_verified",
                        "urlopen"}
_BLOCKING_OS_CALLS = {"replace", "remove", "rename", "unlink", "listdir",
                      "scandir", "makedirs", "fsync"}
# network RPCs block like file IO does (ISSUE 17: the remote affinity
# probe once did a bounded HTTP GET under the router's global lock —
# one slow peer stalled every admission): urlopen plus the transport
# layer's deliberately distinctive rpc_get/rpc_post entry points
_BLOCKING_ATTR_CALLS = {"atomic_write", "read_verified",
                        "fetch_detached", "fetch_detached_batch",
                        "urlopen", "rpc_get", "rpc_post"}


def _blocking_desc(call: ast.Call) -> str | None:
    """Short description when ``call`` is a blocking shape, else None."""
    f = call.func
    if isinstance(f, ast.Name):
        if f.id in _BLOCKING_NAME_CALLS:
            return f"{f.id}()"
        return None
    if not isinstance(f, ast.Attribute):
        return None
    recv = f.value
    if isinstance(recv, ast.Name):
        if recv.id == "os" and f.attr in _BLOCKING_OS_CALLS:
            return f"os.{f.attr}()"
        if recv.id == "shutil":
            return f"shutil.{f.attr}()"
        if recv.id == "jax" and f.attr == "device_get":
            return "jax.device_get()"
        if recv.id == "time" and f.attr == "sleep":
            return "time.sleep()"
    if f.attr in _BLOCKING_ATTR_CALLS:
        return f".{f.attr}()"
    return None


class _IoIndex:
    """Per-function direct blocking shapes + transitive closure through
    resolvable calls, memoized across the whole project."""

    def __init__(self, project: Project):
        self.project = project
        self._memo: dict[tuple, str | None] = {}

    def blocks_via(self, fn: ast.FunctionDef, cls: ClassInfo | None,
                   module: ModuleInfo, _depth: int = 0) -> str | None:
        """Description of a blocking call reachable from ``fn``, or
        None. Depth-limited; cycles cut via the memo's in-progress
        None."""
        key = (module.rel, cls.name if cls else None, fn.name)
        if key in self._memo:
            return self._memo[key]
        self._memo[key] = None  # cut recursion
        found: str | None = None
        if _depth <= 6:
            ltypes = local_alias_types(fn, self.project, cls)
            for sub in ast.walk(fn):
                if not isinstance(sub, ast.Call):
                    continue
                desc = _blocking_desc(sub)
                if desc is not None:
                    found = desc
                    break
                resolved = self.project.resolve_call(sub, module, cls,
                                                     ltypes)
                if resolved is None:
                    continue
                owner, callee = resolved
                inner = self.blocks_via(
                    callee, owner, owner.module if owner else module,
                    _depth + 1)
                if inner is not None:
                    callee_disp = (f"{owner.name}.{callee.name}" if owner
                                   else callee.name)
                    found = f"{inner} via {callee_disp}"
                    break
        self._memo[key] = found
        return found


@register
class IoUnderLockRule(Rule):
    id = "io-under-lock"
    doc = ("Blocking filesystem/device-sync/network calls (open, "
           "os.replace/remove/listdir/fsync, atomic_write/read_verified, "
           "jax.device_get, fetch_detached*, urlopen, rpc_get/rpc_post) "
           "inside a with-region of a designated hot lock (StateCache/"
           "PrefixCache/SessionTiers/Batcher/Router/_DiskTier), directly "
           "or through any resolvable callee.")

    def run(self, project: Project) -> list[Finding]:
        analysis = analyze(project)
        world = analysis.world
        hot_roots: set[str] = set()
        for module in project.modules:
            for cls in module.classes.values():
                if cls.name not in HOT_LOCK_CLASSES:
                    continue
                for attr in world.class_lock_attrs(cls):
                    root = world.root(cls, attr)
                    if root is not None:
                        hot_roots.add(root)
        if not hot_roots:
            return []
        index = _IoIndex(project)
        findings: list[Finding] = []
        for module in project.modules:
            for cls in module.classes.values():
                for meth in cls.methods.values():
                    findings.extend(self._scan(
                        project, module, cls, meth, world, hot_roots,
                        index))
        return findings

    def _scan(self, project, module, cls, fn, world, hot_roots,
              index) -> list[Finding]:
        findings: list[Finding] = []
        local_types = local_alias_types(fn, project, cls)
        where = f"{cls.name}.{fn.name}"

        def walk(node: ast.AST, held: tuple[str, ...]) -> None:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                acquired = []
                for item in node.items:
                    root = _attr_chain_lock(item.context_expr, project,
                                            cls, local_types, world)
                    if root is not None and root in hot_roots:
                        acquired.append(root)
                    else:
                        walk(item.context_expr, held)
                inner = held + tuple(a for a in acquired
                                     if a not in held)
                for stmt in node.body:
                    walk(stmt, inner)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # nested def: separate execution context (it may run on
                # another thread without the lexical hold)
                for stmt in node.body:
                    walk(stmt, ())
                return
            if isinstance(node, ast.Call) and held:
                desc = _blocking_desc(node)
                if desc is not None:
                    findings.append(Finding(
                        self.id, module.rel, node.lineno,
                        f"blocking {desc} runs inside the "
                        f"{world.display(held[-1])} hot-lock region in "
                        f"{where}() — move the IO outside the lock"))
                else:
                    resolved = project.resolve_call(node, module, cls,
                                                    local_types)
                    if resolved is not None:
                        owner, callee = resolved
                        via = index.blocks_via(
                            callee, owner,
                            owner.module if owner else module)
                        if via is not None:
                            callee_disp = (
                                f"{owner.name}.{callee.name}"
                                if owner else callee.name)
                            findings.append(Finding(
                                self.id, module.rel, node.lineno,
                                f"{where}() calls {callee_disp} under "
                                f"the {world.display(held[-1])} hot "
                                f"lock, and it reaches blocking {via} — "
                                "move the IO outside the lock"))
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        for stmt in fn.body:
            walk(stmt, ())
        return findings
