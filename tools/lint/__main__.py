"""graftlint CLI: ``python -m tools.lint [paths...]``.

Default paths are the production tree (``lstm_tensorspark_tpu/`` +
``tools/``); tests pass fixture directories instead. Exit codes come
from the one table (resilience/exit_codes.py):

- 0  — no findings outside the baseline;
- 3  — REGRESSION_RC: new findings (the verify.sh gate);
- 2  — USAGE_RC: bad flags/paths.

``--update-baseline`` rewrites tools/lint_baseline.txt to the current
finding set (keeping existing justifications; new entries get a TODO a
human must replace). ``--json PATH`` writes the machine-readable report
(mirrors serve/loadgen.py --json) so finding counts can be trended next
to the BENCH_*.json baselines; when a previous report exists at the
same path the summary line grows per-rule ``d(rule)=±k`` deltas vs it.
``--changed GIT_REF`` is the sub-second pre-commit mode: only files
changed vs the ref plus their importers (from the project model) are
analyzed — verify.sh phase 0 keeps the full-tree run.
"""

from __future__ import annotations

import argparse
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(os.path.dirname(_HERE))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from tools.lint import RULES, core, model  # noqa: E402

DEFAULT_PATHS = ("lstm_tensorspark_tpu", "tools")
DEFAULT_BASELINE = os.path.join(_REPO, "tools", "lint_baseline.txt")


def _changed_files(ref: str, root: str) -> set[str] | None:
    """Repo-relative ``.py`` files changed vs ``ref``: the diff (incl.
    working-tree edits) PLUS untracked files — a brand-new module is
    exactly the one most likely to carry fresh violations, and a plain
    ``git diff`` would hide it until ``git add``. None (-> USAGE_RC)
    when git cannot answer."""
    import subprocess
    files: set[str] = set()
    for cmd in (["git", "-C", root, "diff", "--name-only", ref, "--",
                 "*.py"],
                ["git", "-C", root, "ls-files", "--others",
                 "--exclude-standard", "--", "*.py"]):
        try:
            out = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=30)
        except (OSError, subprocess.TimeoutExpired) as e:
            print(f"lint: --changed: {' '.join(cmd[3:5])} failed: {e}",
                  file=sys.stderr)
            return None
        if out.returncode != 0:
            print(f"lint: --changed: {' '.join(cmd[3:5])} vs {ref!r} "
                  f"failed: {out.stderr.strip()}", file=sys.stderr)
            return None
        files.update(ln.strip().replace(os.sep, "/")
                     for ln in out.stdout.splitlines() if ln.strip())
    return files


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="AST invariant analyzer (see docs/LINT.md)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to analyze (default: "
                         "lstm_tensorspark_tpu/ tools/)")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="accepted-findings file (default: "
                         "tools/lint_baseline.txt)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline: exit 3 on ANY finding "
                         "(fixture tests)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to the current finding set")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule ids to run (default: all)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalogue and exit")
    ap.add_argument("--json", default=None, metavar="PATH",
                    help="write a machine-readable findings report")
    ap.add_argument("--root", default=None,
                    help="repo root for relative finding paths (default: "
                         "inferred; fixture tests pass the fixture dir)")
    ap.add_argument("--changed", default=None, metavar="GIT_REF",
                    help="scoped pre-commit mode: lint only files "
                         "changed vs GIT_REF plus their importers from "
                         "the project model (verify.sh phase 0 keeps the "
                         "full-tree run)")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule_id in sorted(RULES):
            print(f"{rule_id}: {RULES[rule_id].doc}")
        return 0

    only = None
    if args.rules:
        only = {r.strip() for r in args.rules.split(",") if r.strip()}
        unknown = only - set(RULES)
        if unknown:
            print(f"lint: unknown rule(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return core.USAGE_RC

    paths = args.paths or [os.path.join(_REPO, p) for p in DEFAULT_PATHS]
    for p in paths:
        if not os.path.exists(p):
            print(f"lint: no such path: {p}", file=sys.stderr)
            return core.USAGE_RC
    root = os.path.abspath(args.root) if args.root else _REPO

    project = model.load_project(paths, root)
    baseline = {} if args.no_baseline else core.load_baseline(args.baseline)
    if args.changed is not None:
        if args.update_baseline:
            # write_baseline rewrites the WHOLE file from the current
            # finding set — under a scoped run that would silently drop
            # every out-of-scope entry and its hand-written
            # justification, then fail the next full-tree gate
            print("lint: --changed cannot be combined with "
                  "--update-baseline (the rewrite needs the full-tree "
                  "finding set)", file=sys.stderr)
            return core.USAGE_RC
        changed = _changed_files(args.changed, root)
        if changed is None:
            return core.USAGE_RC
        scope = model.changed_closure(project, changed)
        project = model.Project(
            [m for m in project.modules if m.rel in scope])
        # rules that need the full project universe (the metrics rule's
        # docs-runbook check) consult this to stay silent in scoped mode
        project.scoped = True
        # baseline entries for files outside the scope are neither
        # judged nor reported retired — this run never analyzed them
        baseline = {k: v for k, v in baseline.items()
                    if k.split(":", 1)[0] in scope}
        print(f"lint: --changed {args.changed}: {len(changed)} changed "
              f"file(s), {len(scope)} analyzed with importers",
              file=sys.stderr)
    findings = core.run_rules(project, only)

    if args.update_baseline:
        # ALWAYS read the file here, even under --no-baseline: the rewrite
        # must preserve existing hand-written justifications
        core.write_baseline(args.baseline, findings,
                            core.load_baseline(args.baseline))
        print(f"lint: baseline updated ({len(findings)} entries) — fill in "
              "any TODO justifications")
        # an intentional rewrite is not a regression (tier1_diff contract)
        core.report(findings, {f.key(): "" for f in findings},
                    json_path=args.json)
        return 0

    new, _retired = core.report(findings, baseline, json_path=args.json,
                                scoped=args.changed is not None)
    return core.REGRESSION_RC if new else 0


if __name__ == "__main__":
    raise SystemExit(main())
