"""``thread-lifecycle``: every daemon thread a class starts and keeps a
handle to must be stoppable from that class's ``stop()``/``close()``.

The PR 8 round-3 class: every retired serve stack leaked one
forever-polling spill-worker daemon pinning the engine's arrays, until
``SessionTiers.close()`` learned to park it. ``daemon=True`` means the
interpreter won't join the thread at exit — so if the OWNER doesn't
provide a stop path, nobody does, and long-lived processes (supervise
restarts, test suites, replica retirement) accumulate pollers.

Matched shape: inside a class method, a ``threading.Thread(...,
daemon=True)`` construction whose handle is stored on an attribute
(``self._thread = Thread(...)`` or ``t = Thread(...); obj.thread = t``)
and started. The OWNING class must have a method named ``stop`` /
``close`` / ``shutdown`` / ``__exit__`` whose transitive self-call
closure either:

- calls ``.join()`` on an attribute with the same name the handle was
  stored under, or
- writes (or ``.set()``s / ``notify*``s) an attribute that the thread's
  TARGET method reads — the ``self._closed = True`` + worker-loop-
  checks-it protocol (target resolvable as a method of the same class).

Threads held only in locals (loadgen workers joined in-function,
supervise's log pump) and non-daemon threads (the interpreter joins
them — checkpoint's async writer) are out of scope. An UNRESOLVABLE
target does not excuse the owner: the stored handle is the stop
affordance, so the join path is still required.
"""

from __future__ import annotations

import ast

from .core import Finding, Rule, register
from .model import ClassInfo, Project, self_call_closure

_STOP_NAMES = {"stop", "close", "shutdown", "__exit__"}


def _is_thread_ctor(call: ast.Call) -> bool:
    f = call.func
    name = (f.attr if isinstance(f, ast.Attribute)
            else f.id if isinstance(f, ast.Name) else None)
    return name == "Thread"


def _kw(call: ast.Call, name: str) -> ast.AST | None:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _is_daemon(call: ast.Call) -> bool:
    val = _kw(call, "daemon")
    return isinstance(val, ast.Constant) and val.value is True


class _Started:
    __slots__ = ("attr", "target", "line", "cls")

    def __init__(self, attr: str, target: ast.AST | None, line: int,
                 cls: ClassInfo):
        self.attr = attr      # attribute name the handle is stored under
        self.target = target  # the Thread(target=...) expression
        self.line = line
        self.cls = cls


def _collect_started(cls: ClassInfo) -> list[_Started]:
    """Daemon threads stored on an attribute and started, per class.
    Store and start accumulate CLASS-wide: the common idiom constructs
    the Thread in ``__init__`` and starts it from ``start()``, and the
    pairing must survive the method boundary."""
    stored: dict[int, _Started] = {}  # id(ctor call) -> record
    started_ids: set[int] = set()
    started_attrs: set[str] = set()  # obj.attr.start() receivers
    for meth in cls.methods.values():
        # local name -> Thread ctor call (for the t = Thread(); x.t = t;
        # t.start() split form) — locals do NOT cross methods
        local_ctors: dict[str, ast.Call] = {}
        for sub in ast.walk(meth):
            if isinstance(sub, ast.Assign) and isinstance(sub.value,
                                                          ast.Call) \
                    and _is_thread_ctor(sub.value) \
                    and _is_daemon(sub.value):
                for tgt in sub.targets:
                    if isinstance(tgt, ast.Name):
                        local_ctors[tgt.id] = sub.value
                    elif isinstance(tgt, ast.Attribute):
                        stored[id(sub.value)] = _Started(
                            tgt.attr, _kw(sub.value, "target"),
                            sub.lineno, cls)
            elif isinstance(sub, ast.Assign):
                # x.attr = t   (t previously bound to a Thread ctor)
                if isinstance(sub.value, ast.Name) \
                        and sub.value.id in local_ctors:
                    ctor = local_ctors[sub.value.id]
                    for tgt in sub.targets:
                        if isinstance(tgt, ast.Attribute):
                            stored[id(ctor)] = _Started(
                                tgt.attr, _kw(ctor, "target"),
                                sub.lineno, cls)
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr == "start":
                recv = sub.func.value
                if isinstance(recv, ast.Name) \
                        and recv.id in local_ctors:
                    started_ids.add(id(local_ctors[recv.id]))
                elif isinstance(recv, ast.Attribute):
                    started_attrs.add(recv.attr)
    return [rec for cid, rec in stored.items()
            if cid in started_ids or rec.attr in started_attrs]


def _stop_closure(cls: ClassInfo) -> list[ast.FunctionDef]:
    """stop/close/shutdown methods plus their transitive self-calls."""
    return [cls.methods[n]
            for n in sorted(self_call_closure(cls, _STOP_NAMES))]


def _joins_attr(stop_methods: list[ast.FunctionDef], attr: str) -> bool:
    for meth in stop_methods:
        for sub in ast.walk(meth):
            if (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "join"
                    and isinstance(sub.func.value, ast.Attribute)
                    and sub.func.value.attr == attr):
                return True
    return False


def _signalled_attrs(stop_methods: list[ast.FunctionDef]) -> set[str]:
    """Attributes a stop-closure method writes or signals (.set(),
    .notify(), .notify_all()) — candidate worker-loop stop flags."""
    out: set[str] = set()
    for meth in stop_methods:
        for sub in ast.walk(meth):
            if isinstance(sub, ast.Assign):
                for tgt in sub.targets:
                    if isinstance(tgt, ast.Attribute):
                        out.add(tgt.attr)
            if (isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in ("set", "notify", "notify_all")
                    and isinstance(sub.func.value, ast.Attribute)):
                out.add(sub.func.value.attr)
    return out


def _target_reads(cls: ClassInfo, target: ast.AST | None) -> set[str]:
    """self-attributes the resolved thread target method reads (its
    transitive self-call closure included)."""
    if not (isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
            and target.attr in cls.methods):
        return set()
    seen: set[str] = set()
    reads: set[str] = set()
    stack = [target.attr]
    while stack:
        name = stack.pop()
        if name in seen or name not in cls.methods:
            continue
        seen.add(name)
        for sub in ast.walk(cls.methods[name]):
            if (isinstance(sub, ast.Attribute)
                    and isinstance(sub.value, ast.Name)
                    and sub.value.id == "self"):
                reads.add(sub.attr)
                if sub.attr in cls.methods:
                    stack.append(sub.attr)
    return reads


@register
class ThreadLifecycleRule(Rule):
    id = "thread-lifecycle"
    doc = ("A daemon thread stored on an attribute and started must be "
           "stoppable: the owning class needs a stop/close/shutdown "
           "whose closure joins the handle or signals a flag/condition "
           "the thread's target loop reads. Daemon threads nobody can "
           "stop outlive every retire/restart (the PR 8 leaked-poller "
           "class).")

    def run(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        for module in project.modules:
            for cls in module.classes.values():
                started = _collect_started(cls)
                if not started:
                    continue
                stop_methods = _stop_closure(cls)
                signalled = _signalled_attrs(stop_methods)
                for rec in started:
                    if _joins_attr(stop_methods, rec.attr):
                        continue
                    if signalled & _target_reads(cls, rec.target):
                        continue
                    findings.append(Finding(
                        self.id, module.rel, rec.line,
                        f"{cls.name}.{rec.attr} holds a started daemon "
                        "thread but no stop()/close()/shutdown() path "
                        "joins it or signals a flag its loop reads — "
                        "the thread outlives every stop"))
        return findings
