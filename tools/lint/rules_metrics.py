"""``metrics-consistency``: one name, one meaning — statically.

The PR 5 registry enforces at runtime that a metric name maps to one
kind and one labelset; dashboards built on ``/metrics`` additionally
assume docs/OPERATIONS.md's runbook signatures exist. This rule moves
all three contracts to lint time:

1. every registration of a name (``reg.counter/gauge/histogram("name",
   ...)``) agrees on kind AND ``labelnames`` with every other
   registration (a mismatch is a guaranteed ``ValueError`` on whichever
   code path registers second — possibly a rarely-exercised one);
2. every ``.labels(...)`` call on a family resolved from a registration
   passes exactly the registered label keys (else a guaranteed
   runtime ``ValueError`` at the record site);
3. every metric the docs/OPERATIONS.md runbook names (backticked
   ``serve_*``/``supervise_*``/``train_*`` tokens, with optional
   ``{label=...}`` signatures) is actually registered, with those label
   keys — a renamed metric must not leave the runbook pointing at a
   series that no longer exists.

Help strings: the FIRST non-empty help is the definition; a second
registration with a DIFFERENT non-empty help is two meanings for one
name and flagged. Help-less re-fetches (``reg.gauge("name")``) are the
sanctioned idempotent-lookup idiom and never conflict.
"""

from __future__ import annotations

import ast
import os
import re

from .core import Finding, Rule, register
from .model import Project

_REG_METHODS = {"counter", "gauge", "histogram"}
_NAME_OK = re.compile(r"^[a-z][a-z0-9_]*$")
#: docs token: `serve_queue_depth` or `serve_requests_total{outcome="x"}`
_DOC_TOKEN = re.compile(
    r"`((?:serve|supervise|train)_[a-z][a-z0-9_]*)"
    r"(?:\{([^}`]*)\})?`")  # closing backtick required: `serve_error@N`
# (a fault name, not a metric) must not match as `serve_error`
_DOC_LABEL = re.compile(r"([a-zA-Z_][a-zA-Z0-9_]*)\s*=")
_DOC_RELPATH = os.path.join("docs", "OPERATIONS.md")


class _Registration:
    __slots__ = ("kind", "labelnames", "help", "rel", "line")

    def __init__(self, kind, labelnames, help_, rel, line):
        self.kind = kind
        self.labelnames = labelnames
        self.help = help_
        self.rel = rel
        self.line = line


def _labelnames_from_call(call: ast.Call) -> tuple[str, ...] | None:
    """Literal labelnames tuple, () when omitted, None when dynamic."""
    for kw in call.keywords:
        if kw.arg == "labelnames":
            if isinstance(kw.value, ast.Tuple) and all(
                    isinstance(e, ast.Constant) and isinstance(e.value, str)
                    for e in kw.value.elts):
                return tuple(e.value for e in kw.value.elts)
            return None
    return ()


def _help_from_call(call: ast.Call) -> str:
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant) \
            and isinstance(call.args[1].value, str):
        return call.args[1].value
    for kw in call.keywords:
        if kw.arg == "help" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    return ""


def _registration_of(call: ast.Call) -> tuple[str, str] | None:
    """(kind, metric name) when ``call`` is a registry registration."""
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr in _REG_METHODS):
        return None
    if not call.args or not isinstance(call.args[0], ast.Constant) \
            or not isinstance(call.args[0].value, str):
        return None
    name = call.args[0].value
    if not _NAME_OK.match(name):
        return None
    return f.attr, name


@register
class MetricsConsistencyRule(Rule):
    id = "metrics-consistency"
    doc = ("Metric registrations must agree on kind/labelnames/help "
           "across all sites; .labels() keyword sets must match the "
           "registered labelnames; every metric docs/OPERATIONS.md's "
           "runbook names must exist with those labels.")

    def run(self, project: Project) -> list[Finding]:
        registrations: dict[str, list[_Registration]] = {}
        # family-variable bindings: (scope id, var) -> metric name;
        # scope id keeps function-local `fam` bindings apart
        findings: list[Finding] = []

        label_sites: list[tuple[str, frozenset[str], str, int]] = []
        for module in project.modules:
            self._scan_module(module, registrations, label_sites)

        # 1. cross-site registration consistency
        for name, regs in sorted(registrations.items()):
            first = regs[0]
            for other in regs[1:]:
                if other.kind != first.kind:
                    findings.append(Finding(
                        self.id, other.rel, other.line,
                        f"metric {name!r} registered as {other.kind} here "
                        f"but as {first.kind} at {first.rel} — one name, "
                        "one kind"))
                if (other.labelnames is not None
                        and first.labelnames is not None
                        and other.labelnames != () and first.labelnames != ()
                        and other.labelnames != first.labelnames):
                    findings.append(Finding(
                        self.id, other.rel, other.line,
                        f"metric {name!r} registered with labelnames "
                        f"{other.labelnames} here but {first.labelnames} "
                        f"at {first.rel}"))
                if (other.help and first.help and other.help != first.help):
                    findings.append(Finding(
                        self.id, other.rel, other.line,
                        f"metric {name!r} registered with a different "
                        "help string than the defining site — two "
                        "meanings for one name"))

        # 2. .labels(...) keyword sets
        defined_labels: dict[str, tuple[str, ...]] = {}
        for name, regs in registrations.items():
            for reg in regs:
                if reg.labelnames:
                    defined_labels[name] = reg.labelnames
                    break
        for name, keys, rel, line in label_sites:
            expected = defined_labels.get(name)
            if expected is None:
                if name in registrations:
                    findings.append(Finding(
                        self.id, rel, line,
                        f".labels() called on label-less metric {name!r}"))
                continue
            if keys != frozenset(expected):
                findings.append(Finding(
                    self.id, rel, line,
                    f".labels({sorted(keys)}) on {name!r} does not match "
                    f"registered labelnames {expected}"))

        # 3. runbook references
        findings.extend(self._doc_findings(project, registrations,
                                           defined_labels))
        return findings

    # ---- scanning ------------------------------------------------------

    def _scan_module(self, module, registrations, label_sites) -> None:
        # walk per top-level scope so `fam` bindings don't leak between
        # functions; class-level: track self._attr bindings per class.
        # Local bindings are position-aware: `fam = reg.counter(A); ...
        # fam = reg.counter(B)` is the registry's documented idiom, and a
        # labels() call must resolve against the assignment ABOVE it.
        for scope, attr_binds, local_assigns, global_binds in self._scopes(
                module):
            for node in ast.walk(scope):
                if not isinstance(node, ast.Call):
                    continue
                reg = _registration_of(node)
                if reg is not None:
                    kind, name = reg
                    registrations.setdefault(name, []).append(_Registration(
                        kind, _labelnames_from_call(node),
                        _help_from_call(node), module.rel, node.lineno))
                    continue
                f = node.func
                if (isinstance(f, ast.Attribute) and f.attr == "labels"):
                    name = self._family_name(
                        f.value, attr_binds, local_assigns, node.lineno,
                        global_binds)
                    if name is not None:
                        keys = frozenset(kw.arg for kw in node.keywords
                                         if kw.arg is not None)
                        label_sites.append(
                            (name, keys, module.rel, node.lineno))

    @staticmethod
    def _scopes(module):
        """Yield (scope node, self-attr bindings, positional local
        assigns). Local assigns are ``(line, var, metric)`` sorted by
        line, so a ``labels()`` call binds to the nearest assignment
        above it (the `fam = ...; fam = ...` re-binding idiom)."""
        class_attr_bindings: dict[str, dict[str, str]] = {}
        # pre-pass: self._x = reg.counter("name", ...) per class
        for cls in module.classes.values():
            binds: dict[str, str] = {}
            for meth in cls.methods.values():
                for node in ast.walk(meth):
                    if not (isinstance(node, ast.Assign)
                            and isinstance(node.value, ast.Call)):
                        continue
                    reg = _registration_of(node.value)
                    if reg is None:
                        continue
                    for tgt in node.targets:
                        if (isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"):
                            binds[f"self.{tgt.attr}"] = reg[1]
            class_attr_bindings[cls.name] = binds
        # module-level scope: registrations at import time (`M = reg.
        # counter(...)` between defs) must be visible too, or the runbook
        # check calls them unregistered. Nested defs/classes are excluded
        # — they have their own scopes below.
        top = ast.Module(
            body=[s for s in module.tree.body
                  if not isinstance(s, (ast.FunctionDef,
                                        ast.AsyncFunctionDef,
                                        ast.ClassDef))],
            type_ignores=[])
        top_assigns = MetricsConsistencyRule._local_assigns(top)
        # read-only globals fallback for function scopes (last bind wins)
        global_binds = {var: metric for _, var, metric in top_assigns}
        yield top, {}, top_assigns, {}
        # per-function scopes (methods AND module functions)
        for cls in module.classes.values():
            for meth in cls.methods.values():
                yield (meth, class_attr_bindings[cls.name],
                       MetricsConsistencyRule._local_assigns(meth),
                       global_binds)
        for fn in module.functions.values():
            yield (fn, {}, MetricsConsistencyRule._local_assigns(fn),
                   global_binds)

    @staticmethod
    def _local_assigns(fn) -> list[tuple[int, str, str]]:
        out: list[tuple[int, str, str]] = []
        for node in ast.walk(fn):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            reg = _registration_of(node.value)
            if reg is None:
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out.append((node.lineno, tgt.id, reg[1]))
        out.sort()
        return out

    @staticmethod
    def _family_name(expr: ast.AST, attr_binds: dict[str, str],
                     local_assigns: list[tuple[int, str, str]],
                     at_line: int,
                     global_binds: dict[str, str] | None = None
                     ) -> str | None:
        if isinstance(expr, ast.Name):
            best = None
            for line, var, metric in local_assigns:
                if var == expr.id and line <= at_line:
                    best = metric
            if best is None and global_binds:
                best = global_binds.get(expr.id)
            return best
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"):
            return attr_binds.get(f"self.{expr.attr}")
        if isinstance(expr, ast.Call):
            reg = _registration_of(expr)
            if reg is not None:  # reg.gauge("name", ...).labels(...)
                return reg[1]
        return None

    # ---- docs ----------------------------------------------------------

    def _doc_findings(self, project: Project, registrations,
                      defined_labels) -> list[Finding]:
        if getattr(project, "scoped", False):
            # --changed sub-project: the runbook check needs the FULL
            # registration universe — a metric registered in an
            # unanalyzed file would read as "not registered anywhere"
            # (false positive, the one thing the gate must never do).
            # The full-tree verify.sh phase 0 keeps the docs honest.
            return []
        # locate the repo root from any analyzed module path
        doc_path = None
        for module in project.modules:
            root = module.path[: -len(module.rel)] if module.path.endswith(
                module.rel.replace("/", os.sep)) else None
            if root:
                cand = os.path.join(root, _DOC_RELPATH)
                if os.path.exists(cand):
                    doc_path = cand
                    break
        if doc_path is None:
            return []
        findings: list[Finding] = []
        with open(doc_path, encoding="utf-8") as f:
            doc_lines = f.read().splitlines()
        rel = _DOC_RELPATH.replace(os.sep, "/")
        for lineno, line in enumerate(doc_lines, 1):
            for m in _DOC_TOKEN.finditer(line):
                name, labelpart = m.group(1), m.group(2)
                if name not in registrations:
                    findings.append(Finding(
                        self.id, rel, lineno,
                        f"runbook references metric {name!r} which is not "
                        "registered anywhere in the analyzed tree"))
                    continue
                if labelpart:
                    expected = defined_labels.get(name, ())
                    for lm in _DOC_LABEL.finditer(labelpart):
                        if lm.group(1) not in expected:
                            findings.append(Finding(
                                self.id, rel, lineno,
                                f"runbook names label "
                                f"{lm.group(1)!r} on {name!r} but its "
                                f"registered labelnames are {expected}"))
        return findings
