"""``swallowed-exception``: catch-all ``except: pass`` in scheduler /
worker closures.

The serve plane's scheduler and spill-worker threads are the ONLY
execution context for their work — an exception swallowed there doesn't
bubble to a client or a log, it just silently drops a request, a spill,
or a checkpoint. The honest patterns this repo uses everywhere are: a
metric/counter (``disk_errors += 1`` + ``serve_tier_lost_total``), a
``print(..., flush=True)`` breadcrumb, a re-raise, or a NARROW
exception type documenting the expected absence (``except ValueError``
around a list remove). What must not land is ``except Exception:
pass`` in the hot loop — the shape every review round has to hunt by
hand.

Scope (under-approximate): methods in the ``run``/``step``/``drain``
closure of the designated scheduler classes (rules_hostsync
``SCHEDULER_CLASSES`` — the same scope the host-sync rule polices),
including nested worker closures defined inside them. A handler counts
as swallowing when it catches everything (bare ``except``, ``except
Exception``/``BaseException``) and its body is only ``pass`` /
``continue``. Narrow types stay legal anywhere.
"""

from __future__ import annotations

import ast

from .core import Finding, Rule, register
from .model import Project, handler_catches_all, self_call_closure
from .rules_hostsync import _SCHEDULER_ENTRIES, SCHEDULER_CLASSES


def _body_swallows(handler: ast.ExceptHandler) -> bool:
    return all(isinstance(s, (ast.Pass, ast.Continue))
               for s in handler.body)


@register
class SwallowedExceptionRule(Rule):
    id = "swallowed-exception"
    doc = ("Catch-all except with a pass/continue body inside the "
           "scheduler hot loop (Batcher/SessionTiers run/step/drain "
           "closures) — failures there have no other surface; count a "
           "metric, log, or re-raise. Narrow exception types stay "
           "legal.")

    def run(self, project: Project) -> list[Finding]:
        findings: list[Finding] = []
        for module in project.modules:
            for cls in module.classes.values():
                if cls.name not in SCHEDULER_CLASSES:
                    continue
                for meth_name in sorted(self._closure(cls)):
                    meth = cls.methods.get(meth_name)
                    if meth is None:
                        continue
                    for sub in ast.walk(meth):
                        if not isinstance(sub, ast.ExceptHandler):
                            continue
                        if handler_catches_all(sub) and _body_swallows(sub):
                            what = ("bare except" if sub.type is None
                                    else "except "
                                    + ast.unparse(sub.type))
                            findings.append(Finding(
                                self.id, module.rel, sub.lineno,
                                f"{what}: pass in scheduler hot path "
                                f"{cls.name}.{meth_name}() swallows "
                                "failures — count a metric, log, or "
                                "re-raise"))
        return findings

    @staticmethod
    def _closure(cls) -> set[str]:
        return self_call_closure(cls, _SCHEDULER_ENTRIES)
