#!/usr/bin/env bash
# One-command repo verify: graftlint gate + tier-1 + regression gate +
# serve smoke, in that order.
#
# Phase 0 — GRAFTLINT: `python -m tools.lint` (AST invariant analyzer,
# docs/LINT.md) over lstm_tensorspark_tpu/ + tools/, gated on
# tools/lint_baseline.txt. Prints its own `GRAFTLINT new=N baseline=M`
# summary line — with per-rule `d(rule)=±k` deltas vs the previous
# LINT_report.json when one exists (the report is rewritten in place
# each run, trendable next to BENCH_*.json) — and exits REGRESSION_RC
# (3) on NEW findings — the run aborts HERE, before the ~30 min suite,
# because a lint regression is a deterministic fail and the feedback
# should be seconds, not minutes (phase-0 budget: 10 s; see
# docs/OPERATIONS.md). Pure CPU/AST, sequenced BEFORE the timed suite
# so it cannot perturb it.
#
# Phase 1 — tier-1: the ROADMAP.md "Tier-1 verify" line exactly (same
# timeout, same pytest flags, same DOTS_PASSED accounting), then gated
# on tools/tier1_diff.py — which diffs the failing-test SET against
# tools/tier1_baseline.txt and exits 3 (REGRESSION_RC) only on NEW
# failures. The raw pytest rc is reported but NOT the verdict: the seed
# tree carries ~75 known-environmental failures.
#
# Phase 2 — serve smoke: tools/serve_smoke.py boots the real
# `cli serve --http --replicas 2` subprocess and validates the /healthz
# replica fan-in, routed /v1/generate replies, /stats router+replica
# sections, and the replica-labelled /metrics Prometheus exposition;
# then the restart drill — kept session, disk-tier checkpoint awaited,
# SIGKILL, fresh boot on the same --session-dir, continuation served
# from the disk tier (runs AFTER the timed suite on purpose — never
# concurrently with it).
#
# Phase 3 — serve chaos drill: tools/chaos_serve.py machine-checks the
# robustness invariants under INJECTED faults (replica death loses zero
# kept sessions token-identically; disk errors lose durability but
# never correctness; corrupt session files quarantine + fail honestly;
# priority p99 TTFT holds its SLO under a 4x burst while best-effort
# sheds with honest Retry-After 429s; a blackholed remote host opens
# its circuit, is routed around losing nothing, and REJOINS on heal
# with replay-deduped exactly-once generates) and rewrites
# BENCH_serve_r04.json + BENCH_serve_r09.json — sequenced after the
# smoke, never concurrent with the timed suite; ~60 s budget, 900 s
# hard cap.
#
# Usage: tools/verify.sh        (from anywhere; cd's to the repo root)
# Exit:  graftlint's code on lint regressions (3), else tier1_diff's on
#        gate failure (3 regression, 2 usage, 76 liveness), else the
#        serve smoke's, else the chaos drill's (0 ok, 1 fail).
#
# Run it with nothing else executing: CPU contention flakes the
# convergence-threshold tests (ROADMAP.md).
set -o pipefail
cd "$(dirname "$0")/.." || exit 2

python -m tools.lint --json LINT_report.json
lint_rc=$?
if [ "$lint_rc" -ne 0 ]; then
  echo "verify: graftlint gate failed (rc=$lint_rc) — fix or baseline" \
       "with a justification (docs/LINT.md) before running the suite"
  exit "$lint_rc"
fi

rm -f /tmp/_t1.log
timeout -k 10 1800 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
  2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
echo "pytest raw rc=$rc (informational; the baseline diff below is the gate)"

python tools/tier1_diff.py --log /tmp/_t1.log
gate=$?
if [ "$gate" -ne 0 ]; then
  exit "$gate"
fi

# 900 s > the smoke's own worst-case internal budget (4x 180 s boot
# waits — main + restart + pallas + mesh boots — + generates + GETs +
# 30 s checkpoint wait) so its failure diagnostics always print before
# the outer kill fires
JAX_PLATFORMS=cpu timeout -k 10 900 python tools/serve_smoke.py
smoke=$?
if [ "$smoke" -ne 0 ]; then
  exit "$smoke"
fi

# serve chaos drill (sequenced after the smoke — never concurrent with
# the timed suite): ~60 s measured. The 900 s cap covers the host_die
# AND partition phases' worst-case internal budgets on a loaded box
# (each boots a 180 s replica-host subprocess + 30 s checkpoint wait,
# plus host_die's 15 s retirement wait and partition's 25 s circuit-
# open + 20 s rejoin waits on top of the ~30 s fault phases) so the
# drill's failure diagnostics always print before the outer kill
# fires. Rewrites BENCH_serve_r04.json (burst-shedding + host-death
# trajectory) and BENCH_serve_r09.json (partition/heal zero-lost /
# zero-duplicate / routed-around accounting) in place.
JAX_PLATFORMS=cpu timeout -k 10 900 python tools/chaos_serve.py \
  --json BENCH_serve_r04.json --json-partition BENCH_serve_r09.json
exit $?
