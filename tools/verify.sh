#!/usr/bin/env bash
# One-command tier-1 verify + regression gate + serve smoke.
#
# Runs the ROADMAP.md "Tier-1 verify" line exactly (same timeout, same
# pytest flags, same DOTS_PASSED accounting), then gates on
# tools/tier1_diff.py — which diffs the failing-test SET against
# tools/tier1_baseline.txt and exits 3 (REGRESSION_RC) only on NEW
# failures. The raw pytest rc is reported but NOT the verdict: the seed
# tree carries ~75 known-environmental failures.
#
# After the gate passes, tools/serve_smoke.py boots the real
# `cli serve --http` subprocess and validates /healthz, /v1/generate,
# /stats, and the /metrics Prometheus exposition (runs AFTER the timed
# suite on purpose — never concurrently with it).
#
# Usage: tools/verify.sh        (from anywhere; cd's to the repo root)
# Exit:  tier1_diff's code on gate failure (3 regression, 2 usage,
#        76 liveness), else the serve smoke's (0 ok, 1 fail).
#
# Run it with nothing else executing: CPU contention flakes the
# convergence-threshold tests (ROADMAP.md).
set -o pipefail
cd "$(dirname "$0")/.." || exit 2

rm -f /tmp/_t1.log
timeout -k 10 1080 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
  2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
echo "pytest raw rc=$rc (informational; the baseline diff below is the gate)"

python tools/tier1_diff.py --log /tmp/_t1.log
gate=$?
if [ "$gate" -ne 0 ]; then
  exit "$gate"
fi

# 420 s > the smoke's own worst-case internal budget (180 s boot wait +
# 60 s generate + 3x30 s GETs) so its failure diagnostics always print
# before the outer kill fires
JAX_PLATFORMS=cpu timeout -k 10 420 python tools/serve_smoke.py
exit $?
