#!/usr/bin/env python
"""Per-kernel device-time breakdown of any BENCH_TABLE config's train step
on the real chip: trace a few K-step dispatches of EXACTLY the program
`bench.py`'s measure_config times (make_multi_train_step over a staged
synthetic batch at real model dims), parse the xplane with
jax.profiler.ProfileData, and aggregate kernel durations per optimizer
step.

Usage: python tools/profile_step.py [config] [K]
  config: ptb_char (default) | imdb_bilstm | wikitext2 | uci_seq2seq
          | wikitext103
  K:      steps per traced dispatch (default 32)

This is the diagnostic that found the vocabulary-indexing bottleneck
(ops/embedding.py): at ptb_char it showed 43 us/step in the target-logit
gather and 28 us/step in the embedding-grad scatter vs 29 us/step for the
fused Pallas recurrence pair — 48% of the step in indexing; after the fix
the same trace reads ~78 us/step with both kernels gone. Rerun it whenever
a config's measured step time drifts from its roofline bound
(BENCH_TABLE.json:roofline) to see where the slack actually is.
"""

import collections
import glob
import os
import shutil
import sys

import jax

PROF_DIR = "/tmp/prof_step"


def build_step(name: str, k: int):
    """Mirror bench.measure_config's program construction."""
    import jax.numpy as jnp

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
    import bench

    from lstm_tensorspark_tpu.train import make_multi_train_step, make_optimizer
    from lstm_tensorspark_tpu.train.loop import init_train_state

    c = bench.CONFIGS[name]
    kind = c["kind"]
    if kind == "lm":
        from lstm_tensorspark_tpu.models import LMConfig, init_lm, lm_loss
        cfg = LMConfig(vocab_size=c["V"], hidden_size=c["H"],
                       num_layers=c["L"], compute_dtype="bfloat16",
                       logits_dtype=c.get("logits_dtype", "float32"),
                       use_pallas=True)
        params = init_lm(jax.random.PRNGKey(0), cfg)
        loss_fn = lambda p, b, r: lm_loss(p, b, cfg)  # noqa: E731
    elif kind == "classifier":
        from lstm_tensorspark_tpu.models import (
            ClassifierConfig, classifier_loss, init_classifier,
        )
        cfg = ClassifierConfig(vocab_size=c["V"], hidden_size=c["H"],
                               num_layers=c["L"], compute_dtype="bfloat16",
                               use_pallas=True)
        params = init_classifier(jax.random.PRNGKey(0), cfg)
        loss_fn = lambda p, b, r: classifier_loss(p, b, cfg)  # noqa: E731
    else:
        from lstm_tensorspark_tpu.models import (
            Seq2SeqConfig, init_seq2seq, seq2seq_loss,
        )
        cfg = Seq2SeqConfig(num_features=c["F"], hidden_size=c["H"],
                            num_layers=c["L"], horizon=c["horizon"],
                            compute_dtype="bfloat16", use_pallas=True)
        params = init_seq2seq(jax.random.PRNGKey(0), cfg)
        loss_fn = lambda p, b, r: seq2seq_loss(p, b, cfg)  # noqa: E731

    opt = make_optimizer("sgd", 0.1)
    state = init_train_state(params, opt, jax.random.PRNGKey(1))
    step = make_multi_train_step(loss_fn, opt)
    batch = bench._rand_batch(kind, c, jax.random.PRNGKey(2))
    stacked = jax.tree.map(
        lambda a: jnp.broadcast_to(a[None], (k, *a.shape)), batch
    )
    stacked = jax.device_put(stacked)
    return step, state, stacked


def main():
    name = sys.argv[1] if len(sys.argv) > 1 else "ptb_char"
    k = int(sys.argv[2]) if len(sys.argv) > 2 else 32
    step, state, stacked = build_step(name, k)
    for _ in range(3):
        state, m = step(state, stacked)
    float(m["loss"])

    shutil.rmtree(PROF_DIR, ignore_errors=True)
    calls = max(1, 256 // k)
    with jax.profiler.trace(PROF_DIR):
        for _ in range(calls):
            state, m = step(state, stacked)
        float(m["loss"])

    paths = glob.glob(os.path.join(PROF_DIR, "**", "*.xplane.pb"),
                      recursive=True)
    if not paths:
        print("no xplane written", file=sys.stderr)
        return 1
    pd = jax.profiler.ProfileData.from_file(paths[0])
    for pl in pd.planes:
        if "TPU" not in pl.name and "Device" not in pl.name:
            continue
        agg = collections.defaultdict(lambda: [0.0, 0])
        t_min, t_max = float("inf"), 0.0
        for line in pl.lines:
            for ev in line.events:
                dur = (ev.duration_ns or 0) / 1e3  # us
                agg[ev.name][0] += dur
                agg[ev.name][1] += 1
                if ev.start_ns:
                    t_min = min(t_min, ev.start_ns)
                    t_max = max(t_max, ev.start_ns + (ev.duration_ns or 0))
        steps_total = calls * k
        span_us = (t_max - t_min) / 1e3 if t_max > t_min else 0.0
        print(f"\n=== {name} plane {pl.name}: {steps_total} optimizer "
              f"steps, trace span {span_us:.0f} us "
              f"({span_us / steps_total:.2f} us/step) ===")
        rows = sorted(agg.items(), key=lambda kv: -kv[1][0])
        total = sum(v[0] for _, v in rows)
        print(f"{'us/step':>9} {'count/step':>11} {'pct':>5}  kernel")
        for kname, (dur, cnt) in rows[:40]:
            print(f"{dur / steps_total:9.3f} {cnt / steps_total:11.2f} "
                  f"{100 * dur / total:5.1f}  {kname[:100]}")
        print(f"{total / steps_total:9.3f} {'':>11} 100.0  TOTAL device time")
    return 0


if __name__ == "__main__":
    sys.exit(main())
