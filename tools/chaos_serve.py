#!/usr/bin/env python
"""Serve-plane chaos drill: machine-check the robustness invariants the
replicated/tiered serve stack promises, under INJECTED faults
(resilience/faults.py serve kinds), deterministically on CPU.

Four phases, each building a fresh in-process stack from one fixed seed:

1. **replica death** — a 2-replica ``--session-dir`` stack serves kept
   conversations; the replica owning them is killed mid-run
   (``replica_die@RxK``); the router's retirement (detach/restore
   migration + shared-disk persistence) must lose ZERO kept sessions and
   every continuation must be token-identical to an uninterrupted run.
2. **disk errors** — an injected ``disk_write_err`` on the write-behind
   checkpoint must surface as
   ``serve_tier_lost_total{reason="disk_error"}`` with correct tokens
   still served (durability lost, correctness kept); an injected
   ``session_corrupt`` must be QUARANTINED at fill time on a fresh boot
   and fail the continuation honestly — never wrong tokens.
3. **latency faults** — ``slow_readback`` + ``spill_stall`` inject
   delays into the decode-window fetch and the spill worker; outputs
   stay token-identical and ``flush()`` stays a real durability barrier.
4. **burst shed** — a 4x open-loop burst with mixed admission classes:
   the priority class p99 TTFT must hold the configured SLO while
   best-effort sheds with honest ``Retry-After`` 429s; the same burst is
   replayed with the old indiscriminate-FIFO settings for contrast, and
   both land in BENCH_serve_r04.json (``--json``).
5. **host death** (``host_die`` fault kind) — a REMOTE replica (a real
   ``cli serve --http`` subprocess behind the front router via the RPC
   transport, serve/remote.py) is SIGKILLed mid-conversation; the
   shared ``--session-dir`` disk tier must hand every kept session to
   the surviving local replica, token-identical to an uninterrupted
   run — PR 7's replica-death invariant generalized to a dead HOST.
6. **partition/heal** (``net_blackhole`` + ``net_drop``, ISSUE 17) — a
   remote replica host is BLACKHOLED (alive, unreachable)
   mid-conversation: the per-peer circuit must open within a few failed
   probes, continuations must route around it fast (never waiting out
   the generate timeout, zero kept sessions lost via the shared
   ``--session-dir``), a burst must shed with honest ``Retry-After``;
   on heal the peer must REJOIN without restart (probe hysteresis
   closes the circuit, fresh traffic routes there again) and the full
   conversation stays token-identical. A dropped-response generate then
   proves exactly-once: the transport retries under the request_id and
   the peer replays its settled reply — ZERO duplicate decodes
   (``--json-partition`` → BENCH_serve_r09.json).

Wired into tools/verify.sh after the serve smoke (sequenced, never
concurrent with the timed suite). Exit 0 on PASS, 1 on any violated
invariant, with the failing invariant + the fault spec that reproduces
it printed (see docs/OPERATIONS.md "Chaos drill failed").

Usage::

    JAX_PLATFORMS=cpu python tools/chaos_serve.py [--json OUT] \
        [--json-partition OUT2] [--slo-ms 1000] [--seed 0]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import shutil
import sys
import tempfile
import threading
import time
import urllib.request

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

from lstm_tensorspark_tpu.models import LMConfig, init_lm  # noqa: E402
from lstm_tensorspark_tpu.obs import MetricsRegistry  # noqa: E402
from lstm_tensorspark_tpu.resilience import faults  # noqa: E402
from lstm_tensorspark_tpu.serve import (  # noqa: E402
    ServeEngine,
    ServeServer,
    run_loadgen,
)
from lstm_tensorspark_tpu.serve.state_cache import (  # noqa: E402
    session_file_path as _session_file,
)
from tools.serve_proc import boot_serve_http_or_raise  # noqa: E402

_CFG = LMConfig(vocab_size=41, hidden_size=16, num_layers=1)
_SEED = 3  # params seed — every stack (chaos + reference) shares params


def _build(params, n, *, session_dir=None, num_slots=8, max_active=4,
           queue_size=16, **server_kw):
    reg = MetricsRegistry()
    engines = [
        ServeEngine(params, _CFG, num_slots=num_slots,
                    prefill_buckets=(4, 8), batch_buckets=(1, 2, 4),
                    rng_seed=i, registry=reg, session_dir=session_dir,
                    replica=i)
        for i in range(n)
    ]
    return ServeServer(engines if n > 1 else engines[0],
                       max_active=max_active, queue_size=queue_size,
                       **server_kw)


def _create_kept(server, i):
    """One kept session with a per-index prompt; returns (sid, tokens,
    home replica)."""
    r = server.generate([i + 1, i + 2, 3], max_new_tokens=4,
                        keep_session=True)
    return r.session_id, list(r.tokens), r.replica


def _continue_kept(server, sid, last_tok):
    r = server.generate([last_tok], max_new_tokens=4, session_id=sid,
                        keep_session=True)
    return list(r.tokens)


def _reference_tokens(params, n_sessions, turns):
    """The uninterrupted single-replica run of the same conversation
    schedule — the token-identity oracle for every fault phase."""
    ref = _build(params, 1)
    out = []
    with ref:
        sids = []
        for i in range(n_sessions):
            sid, toks, _ = _create_kept(ref, i)
            sids.append(sid)
            out.append(toks)
        for _ in range(turns):
            for i, sid in enumerate(sids):
                out[i].extend(_continue_kept(ref, sid, out[i][-1]))
    return out


# ---- phase 1: replica death --------------------------------------------


def _phase_replica_death(params, seed, failures):
    work = tempfile.mkdtemp(prefix="chaos_serve_death_")
    n_sessions = 4
    res = {"sessions": n_sessions}
    try:
        srv = _build(params, 2, session_dir=work)
        with srv:
            sids, toks, homes = [], [], []
            for i in range(n_sessions):
                sid, t, home = _create_kept(srv, i)
                sids.append(sid)
                toks.append(t)
                homes.append(home)
            for i, sid in enumerate(sids):  # one pre-death turn
                toks[i].extend(_continue_kept(srv, sid, toks[i][-1]))
            victim = homes[0]
            spec = f"replica_die@{victim}x1;seed@{seed}"
            res["fault_spec"] = spec
            res["victim"] = victim
            res["victim_sessions"] = sum(1 for h in homes if h == victim)
            faults.arm(spec)
            t = srv.replicas[victim].thread
            t.join(timeout=15.0)
            faults.disarm()
            if t.is_alive():
                failures.append(
                    f"replica_death: {spec} never killed the scheduler")
                return res
            srv.health()  # piggybacked sweep retires + migrates
            lost = 0
            for i, sid in enumerate(sids):  # post-death continuations
                try:
                    toks[i].extend(_continue_kept(srv, sid, toks[i][-1]))
                except Exception as e:
                    lost += 1
                    failures.append(
                        f"replica_death: kept session {sid!r} lost after "
                        f"{spec}: {type(e).__name__}: {e}")
            res["lost_sessions"] = lost
            res["router"] = {
                k: srv.router.stats()[k]
                for k in ("retired", "migrated_sessions", "lost_sessions",
                          "requeued", "failed_on_death")}
        ref = _reference_tokens(params, n_sessions, turns=2)
        res["token_identical"] = toks == ref
        if toks != ref:
            failures.append(
                f"replica_death: continuations diverged from the "
                f"uninterrupted run (spec {res['fault_spec']})")
    finally:
        faults.disarm()
        shutil.rmtree(work, ignore_errors=True)
    return res


# ---- phase 2: disk-tier faults -----------------------------------------


def _phase_disk_faults(params, seed, failures):
    res = {}
    # ---- write error: durability lost, correctness kept ----------------
    work = tempfile.mkdtemp(prefix="chaos_serve_disk_")
    try:
        srv = _build(params, 1, session_dir=work)
        with srv:
            sid, toks, _ = _create_kept(srv, 0)
            srv.engine.tiers.flush(timeout=15.0)
            spec = f"disk_write_err@1;seed@{seed}"
            res["write_fault_spec"] = spec
            faults.arm(spec)
            toks.extend(_continue_kept(srv, sid, toks[-1]))
            srv.engine.tiers.flush(timeout=15.0)
            faults.disarm()
            ts = srv.engine.tiers.stats()
            res["disk_errors"] = ts["disk_errors"]
            key = 'serve_tier_lost_total{reason="disk_error",replica="0"}'
            res["disk_error_metric"] = srv.engine.metrics.summaries().get(
                key, 0)
            if ts["disk_errors"] < 1 or res["disk_error_metric"] < 1:
                failures.append(
                    f"disk_faults: {spec} did not surface as "
                    f"serve_tier_lost_total{{reason=\"disk_error\"}} "
                    f"(stats {ts['disk_errors']}, metric "
                    f"{res['disk_error_metric']})")
            # correctness kept: the state never left RAM/device
            toks.extend(_continue_kept(srv, sid, toks[-1]))
        ref = _reference_tokens(params, 1, turns=2)
        res["write_token_identical"] = [toks] == ref
        if [toks] != ref:
            failures.append(
                f"disk_faults: tokens diverged after a failed disk write "
                f"(spec {spec}) — durability trouble must never cost "
                "correctness")
    finally:
        faults.disarm()
        shutil.rmtree(work, ignore_errors=True)
    # ---- corrupt session file: quarantine + honest loss ----------------
    work = tempfile.mkdtemp(prefix="chaos_serve_corrupt_")
    try:
        spec = f"session_corrupt@1;seed@{seed}"
        res["corrupt_fault_spec"] = spec
        faults.arm(spec)
        srv = _build(params, 1, session_dir=work)
        with srv:
            sid, toks, _ = _create_kept(srv, 0)
            srv.engine.tiers.flush(timeout=15.0)
        faults.disarm()
        # fresh boot on the same dir — the restart that must detect it
        srv2 = _build(params, 1, session_dir=work)
        with srv2:
            honest = False
            try:
                _continue_kept(srv2, sid, toks[-1])
                failures.append(
                    f"disk_faults: corrupt session file served a "
                    f"continuation (spec {spec}) — wrong tokens risk")
            except RuntimeError as e:
                honest = "unknown session" in str(e)
                if not honest:
                    failures.append(
                        f"disk_faults: corrupt-file continuation failed "
                        f"with the wrong error: {e}")
            res["honest_failure"] = honest
            ts = srv2.engine.tiers.stats()
            # the corruption is detected at whichever layer reads it
            # first: a damaged HEADER is quarantined by the fresh boot's
            # startup scan (the continuation then counts a miss), a
            # damaged BODY passes the scan and is quarantined at fill
            # time (counted corrupt). Both are the honest path.
            res["corrupt_counted"] = ts["corrupt"]
            res["miss_counted"] = ts["misses"]
        quarantined = glob.glob(os.path.join(work, "*.quarantined"))
        res["quarantined"] = len(quarantined)
        if not quarantined:
            failures.append(
                f"disk_faults: no *.quarantined file after {spec}")
        if res["corrupt_counted"] + res["miss_counted"] < 1:
            failures.append(
                "disk_faults: the corrupt file's continuation was "
                "counted neither corrupt nor miss")
    finally:
        faults.disarm()
        shutil.rmtree(work, ignore_errors=True)
    return res


# ---- phase 3: latency faults (slow readback, spill stall) ---------------


def _phase_latency_faults(params, seed, failures):
    res = {}
    work = tempfile.mkdtemp(prefix="chaos_serve_latency_")
    try:
        spec = f"slow_readback@1x200;spill_stall@1x1;seed@{seed}"
        res["fault_spec"] = spec
        # 2 slots + 3 kept sessions forces evictions (spills) and fills
        srv = _build(params, 1, session_dir=work, num_slots=2,
                     max_active=2)
        faults.arm(spec)
        toks = []
        with srv:
            sids = []
            for i in range(3):
                sid, t, _ = _create_kept(srv, i)
                sids.append(sid)
                toks.append(t)
            for _ in range(2):
                for i, sid in enumerate(sids):
                    toks[i].extend(_continue_kept(srv, sid, toks[i][-1]))
            flushed = srv.engine.tiers.flush(timeout=30.0)
            res["flush_ok"] = bool(flushed)
            if not flushed:
                failures.append(
                    f"latency_faults: flush() wedged under {spec} — the "
                    "durability barrier must survive a stalled worker")
        faults.disarm()
        # reference needs the same slot pressure (3 sessions over 2
        # slots re-prefill nothing — tiers restore exactly), so the
        # plain 1-replica reference with ample slots is still the oracle
        ref = _reference_tokens(params, 3, turns=2)
        res["token_identical"] = toks == ref
        if toks != ref:
            failures.append(
                f"latency_faults: tokens diverged under {spec} — "
                "injected latency must never change output")
    finally:
        faults.disarm()
        shutil.rmtree(work, ignore_errors=True)
    return res


# ---- phase 5: host death (remote replica killed mid-conversation) -------


_HOST_ARGS = [
    "serve", "--http", "--port", "0", "--vocab-size", str(_CFG.vocab_size),
    "--hidden-units", str(_CFG.hidden_size),
    "--num-layers", str(_CFG.num_layers), "--seed", str(_SEED),
    "--prefill-buckets", "4,8", "--batch-buckets", "1,2",
    "--decode-window", "1", "--prefix-cache", "off",
    "--num-slots", "8", "--max-active", "4",
]


def _boot_remote_host(session_dir: str, timeout: float = 180.0):
    """Boot a replica-host subprocess (same params as the in-process
    reference: the CLI re-derives them from --seed/--vocab-size/...)
    and wait for its address line (tools/serve_proc.py)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [sys.executable, "-m", "lstm_tensorspark_tpu.cli",
           *_HOST_ARGS, "--session-dir", session_dir]
    return boot_serve_http_or_raise(cmd, env, timeout)




def _phase_host_death(params, seed, failures):
    work = tempfile.mkdtemp(prefix="chaos_serve_hostdie_")
    n_sessions = 4
    res = {"sessions": n_sessions, "fault_spec": "host_die@remote"}
    proc = None
    try:
        proc, base = _boot_remote_host(work)
        res["remote_url"] = base
        from lstm_tensorspark_tpu.serve import ServeServer

        reg = MetricsRegistry()
        eng = ServeEngine(params, _CFG, num_slots=8,
                          prefill_buckets=(4, 8), batch_buckets=(1, 2),
                          rng_seed=0, registry=reg, session_dir=work,
                          replica=0)
        srv = ServeServer(eng, max_active=4, queue_size=16,
                          window_ladder=(1,), remote_replicas=(base,))
        with srv:
            sids, toks, homes = [], [], []
            for i in range(n_sessions):
                sid, t, home = _create_kept(srv, i)
                sids.append(sid)
                toks.append(t)
                homes.append(home)
            res["remote_sessions"] = sum(1 for h in homes if h == 1)
            if res["remote_sessions"] < 1:
                failures.append(
                    "host_death: no kept session landed on the remote "
                    f"replica (homes {homes}) — the kill would test "
                    "nothing")
                return res
            t_turn = time.monotonic()
            # wall clock on purpose: compared against file MTIMES below
            # (the checkpoint-flushed probe) — monotonic has no epoch
            t_turn_wall = time.time()  # graftlint: disable=wallclock-timing
            for i, sid in enumerate(sids):  # one pre-death turn
                toks[i].extend(_continue_kept(srv, sid, toks[i][-1]))
            # durability boundary: the drill tests host DEATH, not an
            # unflushed write-behind — await every session's checkpoint
            # (file mtime at/after the turn) before pulling the trigger
            deadline = time.monotonic() + 30

            def flushed():
                # every file strictly after the turn started (a file
                # from a PREVIOUS boundary would resume the
                # conversation without tokens the client already saw)
                # AND quiescent for 1 s: the write-behind worker merges
                # a superseded capture and rewrites within ~100 ms, so
                # a lagging creation-boundary write landing after
                # t_turn_wall cannot masquerade as the turn's
                # checkpoint past the quiet window
                mtimes = []
                for sid in sids:
                    p = _session_file(work, sid)
                    if not os.path.exists(p):
                        return False
                    mtimes.append(os.path.getmtime(p))
                return (min(mtimes) >= t_turn_wall
                        and time.time()  # graftlint: disable=wallclock-timing
                        - max(mtimes) > 1.0)

            while not flushed() and time.monotonic() < deadline:
                time.sleep(0.1)
            res["checkpoints_flushed"] = flushed()
            if not flushed():
                failures.append(
                    "host_death: write-behind session checkpoints never "
                    "landed on the shared --session-dir")
                return res
            proc.kill()  # SIGKILL mid-conversation: host death
            proc.wait()
            res["kill_after_s"] = round(time.monotonic() - t_turn, 2)
            lost = 0
            for i, sid in enumerate(sids):  # post-death continuations
                try:
                    toks[i].extend(_continue_kept(srv, sid, toks[i][-1]))
                except Exception as e:
                    lost += 1
                    failures.append(
                        f"host_death: kept session {sid!r} lost after "
                        f"the host kill: {type(e).__name__}: {e}")
            res["lost_sessions"] = lost
            # the heartbeat poller exits → the sweep retires the host
            deadline = time.monotonic() + 15
            while (1 not in srv.router.stats()["retired"]
                   and time.monotonic() < deadline):
                srv.router.sweep()
                time.sleep(0.2)
            rt = srv.router.stats()
            res["retired"] = rt["retired"]
            res["router"] = {k: rt[k] for k in
                             ("retired", "failed_on_death", "requeued")}
            if 1 not in rt["retired"]:
                failures.append(
                    "host_death: the dead host was never retired (the "
                    "heartbeat poller must exit and the sweep must "
                    "claim it)")
        ref = _reference_tokens(params, n_sessions, turns=2)
        res["token_identical"] = toks == ref
        if toks != ref:
            failures.append(
                "host_death: continuations diverged from the "
                "uninterrupted run (host_die@remote)")
    except Exception as e:
        failures.append(f"host_death: drill error: {type(e).__name__}: {e}")
    finally:
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait()
        shutil.rmtree(work, ignore_errors=True)
    return res


# ---- phase 6: partition / heal (blackholed remote host, ISSUE 17) -------


def _peer_heartbeat(base: str) -> dict:
    with urllib.request.urlopen(base + "/replica/heartbeat",
                                timeout=10.0) as resp:
        return json.loads(resp.read().decode("utf-8"))


def _peer_metric(base: str, token: str) -> float:
    """Scrape one sample from the peer's /metrics exposition."""
    with urllib.request.urlopen(base + "/metrics", timeout=10.0) as resp:
        text = resp.read().decode("utf-8")
    for line in text.splitlines():
        if line.startswith(token):
            return float(line.rsplit(None, 1)[-1])
    return 0.0


def _await_flushed(work, sids, t_turn_wall, timeout=30.0) -> bool:
    """Every kept session's checkpoint at/after the turn AND quiescent
    for 1 s (same durability boundary the host-death phase awaits)."""

    def flushed():
        mtimes = []
        for sid in sids:
            p = _session_file(work, sid)
            if not os.path.exists(p):
                return False
            mtimes.append(os.path.getmtime(p))
        return (min(mtimes) >= t_turn_wall
                and time.time()  # graftlint: disable=wallclock-timing
                - max(mtimes) > 1.0)

    deadline = time.monotonic() + timeout
    while not flushed() and time.monotonic() < deadline:
        time.sleep(0.1)
    return flushed()


def _phase_partition(params, seed, failures):
    """Blackhole a live remote host mid-conversation, prove the circuit
    opens and the router routes around it (fast, honestly, losing
    nothing), heal, prove it rejoins WITHOUT restart, then prove the
    request_id replay path decodes a dropped-response generate exactly
    once."""
    work = tempfile.mkdtemp(prefix="chaos_serve_partition_")
    n_sessions = 4
    res = {"sessions": n_sessions,
           "fault_spec": f"net_blackhole@1 then net_drop@1;seed@{seed}"}
    proc = None
    try:
        proc, base = _boot_remote_host(work)
        res["remote_url"] = base
        reg = MetricsRegistry()
        eng = ServeEngine(params, _CFG, num_slots=8,
                          prefill_buckets=(4, 8), batch_buckets=(1, 2),
                          rng_seed=0, registry=reg, session_dir=work,
                          replica=0)
        srv = ServeServer(eng, max_active=4, queue_size=16,
                          window_ladder=(1,), remote_replicas=(base,),
                          remote_poll_interval_s=0.1,
                          remote_rpc_timeout_s=1.0,
                          remote_timeout_s=30.0)
        with srv:
            shim = srv.replicas[1].batcher
            sids, toks, homes = [], [], []
            for i in range(n_sessions):
                sid, t, home = _create_kept(srv, i)
                sids.append(sid)
                toks.append(t)
                homes.append(home)
            res["remote_sessions"] = sum(1 for h in homes if h == 1)
            if res["remote_sessions"] < 1:
                failures.append(
                    "partition: no kept session landed on the remote "
                    f"replica (homes {homes}) — the blackhole would "
                    "test nothing")
                return res
            # wall clock on purpose: compared against file MTIMES (the
            # checkpoint-flushed probe) — monotonic has no epoch
            t_turn_wall = time.time()  # graftlint: disable=wallclock-timing
            for i, sid in enumerate(sids):  # one pre-partition turn
                toks[i].extend(_continue_kept(srv, sid, toks[i][-1]))
            res["checkpoints_flushed"] = _await_flushed(
                work, sids, t_turn_wall)
            if not res["checkpoints_flushed"]:
                failures.append(
                    "partition: write-behind session checkpoints never "
                    "landed on the shared --session-dir")
                return res
            routed_before = srv.router.stats()["routed"].get("1", 0)
            # ---- partition: blackhole the peer (until the heal) -------
            t_cut = time.monotonic()
            faults.arm(f"net_blackhole@1;seed@{seed}")
            deadline = time.monotonic() + 25
            while (shim.circuit.state() != "open"
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            res["seconds_to_open"] = round(time.monotonic() - t_cut, 2)
            res["circuit_opened"] = shim.circuit.state() == "open"
            if not res["circuit_opened"]:
                failures.append(
                    f"partition: the circuit never opened within "
                    f"{res['seconds_to_open']}s of the blackhole "
                    f"(open_after={shim.circuit.open_after} failed "
                    "probes expected)")
                return res
            # the partition is a route-around state, never a death:
            if not srv.replicas[1].thread.is_alive():
                failures.append(
                    "partition: the heartbeat poller exited on "
                    "partition-shaped failures (retirement must be "
                    "refused-only)")
            # continuations during the partition: every kept session —
            # including the peer's — must complete on the local replica
            # from the shared disk tier, fast (nobody waits out the 30s
            # generate timeout or queues behind the blackhole)
            lost = 0
            slow = 0.0
            for i, sid in enumerate(sids):
                t0 = time.monotonic()
                try:
                    toks[i].extend(_continue_kept(srv, sid, toks[i][-1]))
                except Exception as e:
                    lost += 1
                    failures.append(
                        f"partition: kept session {sid!r} lost during "
                        f"the partition: {type(e).__name__}: {e}")
                slow = max(slow, time.monotonic() - t0)
            res["lost_sessions"] = lost
            res["partition_continue_max_s"] = round(slow, 2)
            if slow >= 10.0:
                failures.append(
                    f"partition: a continuation took {slow:.1f}s during "
                    "the partition — routing around an open circuit "
                    "must not wait on the dead link")
            routed_mid = srv.router.stats()["routed"].get("1", 0)
            res["routed_remote_during_partition"] = (
                routed_mid - routed_before)
            if res["routed_remote_during_partition"] > 0:
                failures.append(
                    "partition: the router sent requests to the "
                    "blackholed peer while its circuit was open")
            # burst shed during the partition: capacity honestly halved,
            # overload answered with 429 + measured Retry-After
            shed_retry_after = []
            done = []

            def _burst_one(k):
                try:
                    srv.generate([k + 2, 5, 3], max_new_tokens=8,
                                 klass="best_effort", timeout=30.0)
                    done.append(k)
                except Exception as e:
                    ra = getattr(e, "retry_after_s", None)
                    if ra is not None:
                        shed_retry_after.append(float(ra))

            threads = [threading.Thread(target=_burst_one, args=(k,))
                       for k in range(32)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=60.0)
            res["burst_completed"] = len(done)
            res["burst_shed"] = len(shed_retry_after)
            res["burst_retry_after_s_max"] = (
                round(max(shed_retry_after), 3) if shed_retry_after
                else None)
            if not shed_retry_after:
                failures.append(
                    "partition: a 32-request burst against the halved "
                    "fleet shed nothing — the admission bound must "
                    "exclude the partitioned peer's queue")
            elif min(shed_retry_after) <= 0:
                failures.append(
                    "partition: a shed carried a non-positive "
                    "Retry-After — the drain estimate must stay honest")
            # ---- heal: probes close the circuit, the peer rejoins -----
            t_heal = time.monotonic()
            faults.disarm()
            deadline = time.monotonic() + 20
            while (shim.circuit.state() != "closed"
                   and time.monotonic() < deadline):
                time.sleep(0.05)
            res["seconds_to_close"] = round(time.monotonic() - t_heal, 2)
            res["circuit_closed"] = shim.circuit.state() == "closed"
            res["circuit_opened_total"] = shim.circuit.opened_total
            res["circuit_closed_total"] = shim.circuit.closed_total
            res["rejoined_without_restart"] = (
                res["circuit_closed"] and proc.poll() is None)
            if not res["rejoined_without_restart"]:
                failures.append(
                    "partition: the peer never rejoined after the heal "
                    f"(circuit {shim.circuit.state()!r}, process "
                    f"{'alive' if proc.poll() is None else 'dead'}) — "
                    "rejoin must need no restart")
                return res
            # fresh traffic routes to the healed peer again
            res["fresh_routed_to_peer"] = False
            for k in range(20):
                r = srv.generate([k + 3, 7, 3], max_new_tokens=2)
                if r.replica == 1:
                    res["fresh_routed_to_peer"] = True
                    break
            if not res["fresh_routed_to_peer"]:
                failures.append(
                    "partition: no fresh session routed to the healed "
                    "peer — rejoin is incomplete")
            for i, sid in enumerate(sids):  # post-heal turn
                toks[i].extend(_continue_kept(srv, sid, toks[i][-1]))
            # ---- exactly-once: drop a generate response, replay it ----
            hb0 = _peer_heartbeat(base)
            completed0 = int(hb0["batcher"]["completed"])
            hits0 = _peer_metric(
                base, 'serve_replay_dedup_total{result="hit"}')
            retries0 = shim.stats()["rpc_retries"]
            faults.arm(f"net_drop@1;seed@{seed}")
            try:
                dropped = None
                for k in range(12):
                    r = srv.generate([k + 4, 6, 3], max_new_tokens=3)
                    if r.replica == 1:
                        dropped = r
                        break
                if dropped is None:
                    failures.append(
                        "partition: no generate routed to the peer for "
                        "the drop — dedup untested")
                    return res
            finally:
                faults.disarm()
            retries = shim.stats()["rpc_retries"] - retries0
            hb1 = _peer_heartbeat(base)
            completed1 = int(hb1["batcher"]["completed"])
            hits1 = _peer_metric(
                base, 'serve_replay_dedup_total{result="hit"}')
            res["dedup"] = {
                "tokens_delivered": len(dropped.tokens),
                "transport_retries": retries,
                "peer_completed_delta": completed1 - completed0,
                "replay_hits": hits1 - hits0,
                "duplicate_decodes": max(0, completed1 - completed0 - 1),
            }
            if len(dropped.tokens) != 3:
                failures.append(
                    "partition: the dropped-then-replayed generate "
                    f"delivered {len(dropped.tokens)} tokens, wanted 3")
            if retries < 1:
                failures.append(
                    "partition: the transport never retried the "
                    "dropped response — the replay path is untested")
            if res["dedup"]["duplicate_decodes"] != 0:
                failures.append(
                    f"partition: the peer decoded the same request_id "
                    f"{completed1 - completed0} times — replay dedup "
                    "must make delivery exactly-once")
            if hits1 - hits0 < 1:
                failures.append(
                    "partition: the peer's settled cache counted no "
                    "replay hit for the retried request_id")
        ref = _reference_tokens(params, n_sessions, turns=3)
        res["token_identical"] = toks == ref
        if toks != ref:
            failures.append(
                "partition: continuations diverged from the "
                "uninterrupted run across partition + heal")
    except Exception as e:
        failures.append(f"partition: drill error: {type(e).__name__}: {e}")
    finally:
        faults.disarm()
        if proc is not None and proc.poll() is None:
            proc.kill()
            proc.wait()
        shutil.rmtree(work, ignore_errors=True)
    return res


# ---- phase 4: burst shed (SLO-aware vs indiscriminate FIFO) -------------


def _burst(params, *, rate, seed, slo_aware: bool):
    """One open-loop burst at ``rate`` req/s, 25% priority traffic.
    ``slo_aware=False`` replays it with the pre-PR settings (even
    dequeue weights, one shared bound) for the BENCH contrast."""
    kw = (dict(class_weights=(4, 1), best_effort_queue_frac=0.5)
          if slo_aware else
          dict(class_weights=(1, 1), best_effort_queue_frac=1.0))
    srv = _build(params, 2, queue_size=16, **kw)
    with srv:
        srv.warmup(prompt_lens=(4,))
        report = run_loadgen(
            srv, vocab_size=_CFG.vocab_size, sessions=8,
            requests_per_session=8, prompt_len=4, max_new_tokens=8,
            mode="open", rate=rate, seed=seed, priority_frac=0.25,
            retry_max=1, retry_base_s=0.02, retry_cap_s=0.25,
        )
    return {
        "mode": "slo_aware" if slo_aware else "fifo",
        "offered_rate_rps": rate,
        "completed": report["completed"],
        "rejected": report["rejected"],
        "classes": report["classes"],
        "router": report["router"],
    }


def _phase_burst_shed(params, seed, slo_ms, failures):
    res = {"slo_ms": slo_ms}
    # calibrate sustainable throughput on the same stack shape
    cal_srv = _build(params, 2, queue_size=16)
    with cal_srv:
        cal_srv.warmup(prompt_lens=(4,))
        cal = run_loadgen(cal_srv, vocab_size=_CFG.vocab_size, sessions=4,
                          requests_per_session=4, prompt_len=4,
                          max_new_tokens=8, seed=seed)
    capacity = max(cal["requests_per_sec"], 1.0)
    rate = 4.0 * capacity
    res["capacity_rps"] = capacity
    res["burst_rate_rps"] = rate
    res["slo_aware"] = _burst(params, rate=rate, seed=seed, slo_aware=True)
    res["fifo"] = _burst(params, rate=rate, seed=seed + 1, slo_aware=False)
    pr = res["slo_aware"]["classes"]["priority"]
    be = res["slo_aware"]["classes"]["best_effort"]
    # "policy engaged" / "bound not inverted" read the ROUTER's per-class
    # shed counts (requests 429'd at admission), not the loadgen's gave-up
    # counter: whether a shed request's retries eventually land depends on
    # how fast the burst drains — a drain race on the calibrated rate —
    # while the admission bound rejecting best-effort (and only
    # best-effort) under a 4x burst is structural.
    ra = res["slo_aware"]["router"].get("shed_by_class", {})
    if ra.get("best_effort", 0) < 1:
        failures.append(
            "burst_shed: a 4x burst shed ZERO best-effort requests — "
            "the SLO-aware policy never engaged")
    if ra.get("priority", 0) > 0:
        failures.append(
            f"burst_shed: {ra.get('priority')} PRIORITY requests shed "
            "while best-effort headroom existed — the class bound is "
            "inverted")
    p99 = pr["p99_ttft_ms"]
    res["priority_p99_ttft_ms"] = p99
    res["best_effort_p99_ttft_ms"] = be["p99_ttft_ms"]
    if p99 is None or not p99 == p99 or p99 > slo_ms:
        failures.append(
            f"burst_shed: priority p99 TTFT {p99} ms missed the "
            f"{slo_ms} ms SLO under the 4x burst")
    res["retry_after_honored"] = (
        be["retried"] >= 1 and ra.get("best_effort", 0) >= 1)
    if be["retried"] < 1:
        failures.append(
            "burst_shed: the loadgen client never retried a shed — "
            "Retry-After honoring is untested by this run")
    return res


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", type=str, default=None,
                    help="write the machine-readable drill report here "
                         "(BENCH_serve_r04.json in CI)")
    ap.add_argument("--json-partition", type=str, default=None,
                    help="write the partition/heal phase's zero-lost / "
                         "zero-duplicate / routed-around accounting here "
                         "(BENCH_serve_r09.json in CI)")
    ap.add_argument("--slo-ms", type=float, default=1000.0,
                    help="priority-class p99 TTFT SLO under the 4x burst "
                         "(CPU-noise-tolerant default)")
    ap.add_argument("--seed", type=int, default=0,
                    help="fault seed (reproduces the corruption bytes and "
                         "the workload)")
    args = ap.parse_args(argv)

    t_start = time.monotonic()
    params = init_lm(jax.random.PRNGKey(_SEED), _CFG)
    failures: list[str] = []
    summary = {"note": "chaos_serve", "seed": args.seed}
    summary["replica_death"] = _phase_replica_death(params, args.seed,
                                                    failures)
    summary["disk_faults"] = _phase_disk_faults(params, args.seed, failures)
    summary["latency_faults"] = _phase_latency_faults(params, args.seed,
                                                      failures)
    summary["burst_shed"] = _phase_burst_shed(params, args.seed,
                                              args.slo_ms, failures)
    summary["host_death"] = _phase_host_death(params, args.seed, failures)
    summary["partition"] = _phase_partition(params, args.seed, failures)
    summary["wall_s"] = round(time.monotonic() - t_start, 1)
    summary["result"] = "PASS" if not failures else "FAIL"
    summary["failures"] = failures
    print(json.dumps(summary))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(summary, f, indent=1, sort_keys=True)
        print(f"chaos_serve: report written to {args.json}",
              file=sys.stderr)
    if args.json_partition:
        part = dict(summary["partition"])
        part["note"] = "chaos_serve partition/heal (ISSUE 17)"
        part["result"] = ("PASS" if not any(
            f.startswith("partition:") for f in failures) else "FAIL")
        with open(args.json_partition, "w") as f:
            json.dump(part, f, indent=1, sort_keys=True)
        print("chaos_serve: partition report written to "
              f"{args.json_partition}", file=sys.stderr)
    print(f"chaos_serve: {summary['result']} in {summary['wall_s']}s"
          + (f" — {len(failures)} violated invariant(s)" if failures
             else ""),
          file=sys.stderr)
    for f in failures:
        print(f"chaos_serve: FAIL {f}", file=sys.stderr)
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
