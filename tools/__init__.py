"""Repo tooling. This package marker exists so ``python -m tools.lint``
resolves; the standalone scripts here (tier1_diff.py, serve_smoke.py,
bench_serve.py, ...) keep their own ``sys.path`` bootstraps and still
run file-direct."""
