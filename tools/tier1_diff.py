#!/usr/bin/env python
"""Tier-1 regression gate: diff the failing-test SET against the seed
baseline instead of gating on the raw exit code.

The seed tree ships with ~75 environmental failures (container jax too old
for `shard_map(check_vma=...)`, Gloo multiprocess init) that no PR is
expected to fix — so ``pytest`` returning non-zero tells a perf PR
nothing. What a PR must guarantee is NO NEW FAILURES: this tool runs the
ROADMAP's tier-1 command (or ingests an existing ``pytest -q`` log via
``--log``), extracts every ``FAILED``/``ERROR`` test id, and compares the
set against ``tools/tier1_baseline.txt``:

- new failures     → listed, exit ``REGRESSION_RC`` (3, the exit-code
  table's regression code — supervisors/CI route on it);
- fixed failures   → listed as informational (tighten the baseline with
  ``--update-baseline`` when a PR legitimately fixes seed failures);
- identical/better → exit 0.

Usage::

    python tools/tier1_diff.py                  # run tier-1, then diff
    python tools/tier1_diff.py --log /tmp/_t1.log   # diff an existing log
    python tools/tier1_diff.py --log /tmp/_t1.log --update-baseline

The tier-1 command itself comes from ROADMAP.md; keep the two in sync.
"""

from __future__ import annotations

import argparse
import os
import re
import subprocess
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)
sys.path.insert(0, _REPO)

from lstm_tensorspark_tpu.resilience.exit_codes import (  # noqa: E402
    LIVENESS_RC,
    REGRESSION_RC,
    USAGE_RC,
)

DEFAULT_BASELINE = os.path.join(_HERE, "tier1_baseline.txt")
DEFAULT_LOG = "/tmp/_t1.log"

# ROADMAP.md "Tier-1 verify" — minus the shell plumbing (tee/pipefail/dots)
TIER1_CMD = [
    sys.executable, "-m", "pytest", "tests/", "-q", "-m", "not slow",
    "--continue-on-collection-errors", "-p", "no:cacheprovider",
    "-p", "no:xdist", "-p", "no:randomly",
]
TIER1_TIMEOUT_S = 1080  # matches ROADMAP's `timeout -k 10 1080`

# pytest -q short-summary lines: "FAILED tests/test_x.py::test_y[param] - ..."
# and collection errors: "ERROR tests/test_x.py - ...". Anchored on the
# tests/ prefix: failing tests also print captured-log sections whose
# "ERROR   <logger>:<file>:<line> msg" lines must NOT be ingested as
# (line-number-varying) phantom test ids.
_FAIL_RE = re.compile(r"^(FAILED|ERROR)\s+(tests/\S+)")


def parse_failures(log_text: str) -> set[str]:
    out = set()
    for line in log_text.splitlines():
        m = _FAIL_RE.match(line.strip())
        if m:
            out.add(m.group(2))
    return out


def load_baseline(path: str) -> set[str]:
    if not os.path.exists(path):
        return set()
    with open(path) as f:
        return {
            ln.strip() for ln in f
            if ln.strip() and not ln.strip().startswith("#")
        }


def write_baseline(path: str, failures: set[str]) -> None:
    with open(path, "w") as f:
        f.write("# tier-1 baseline failing-test set (tools/tier1_diff.py)\n"
                "# these are known-environmental seed failures, NOT bugs a\n"
                "# PR must fix; the gate fires only on NEW failures\n")
        for t in sorted(failures):
            f.write(t + "\n")


def run_tier1(log_path: str) -> str:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    try:
        proc = subprocess.run(
            TIER1_CMD, cwd=_REPO, env=env, timeout=TIER1_TIMEOUT_S + 60,
            capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        # an incomplete run cannot be diffed — this is a liveness
        # failure (the probe window exhausted), not a regression verdict
        print(f"tier1_diff: tier-1 suite exceeded {TIER1_TIMEOUT_S + 60}s")
        raise SystemExit(LIVENESS_RC)
    text = proc.stdout + proc.stderr
    try:
        with open(log_path, "w") as f:
            f.write(text)
    except OSError as e:
        print(f"tier1_diff: warning: could not write {log_path}: {e}")
    return text


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="recorded failing-test set (default: "
                         "tools/tier1_baseline.txt)")
    ap.add_argument("--log", default=None,
                    help="parse an existing pytest -q log instead of "
                         "running the ~13 min tier-1 suite")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to the current failure set "
                         "(after a PR that legitimately fixes failures)")
    args = ap.parse_args(argv)

    if args.log:
        try:
            with open(args.log) as f:
                text = f.read()
        except OSError as e:
            print(f"tier1_diff: cannot read --log: {e}")
            return USAGE_RC
        if not any(w in text for w in ("passed", "failed", "error")):
            print(f"tier1_diff: {args.log} does not look like a pytest log")
            return USAGE_RC
    else:
        text = run_tier1(DEFAULT_LOG)

    current = parse_failures(text)
    baseline = load_baseline(args.baseline)
    new = sorted(current - baseline)
    fixed = sorted(baseline - current)

    print(f"tier1_diff: {len(current)} failing now, "
          f"{len(baseline)} in baseline ({args.baseline})")
    if fixed:
        print(f"tier1_diff: {len(fixed)} baseline failure(s) no longer "
              "fail (consider --update-baseline):")
        for t in fixed:
            print(f"  fixed: {t}")
    if new:
        print(f"tier1_diff: {len(new)} NEW failure(s) — REGRESSION:")
        for t in new:
            print(f"  NEW: {t}")

    if args.update_baseline:
        write_baseline(args.baseline, current)
        print(f"tier1_diff: baseline updated ({len(current)} entries)")
        return 0  # an intentional rewrite is not a regression

    return REGRESSION_RC if new else 0


if __name__ == "__main__":
    raise SystemExit(main())
