#!/bin/bash
# Chip-recovery watcher: probe the tunneled TPU until it answers, then run
# the full measurement queue (tools/chip_recovery.py) immediately — so a
# recovery window that opens while nobody is looking is never wasted.
#
#   nohup setsid tools/chip_watch.sh [logfile] >/dev/null 2>&1 &
#
# The probe REUSES bench.py's _probe_once: the child is managed with
# Popen + poll + kill-without-wait (the documented wedge can leave a probe
# child unreapable in a driver call — a shell `timeout` would block on it
# forever, wedging the watcher itself), and the probe requires the TPU
# platform (a cleanly-failing TPU init that silently falls back to CPU
# must NOT count as recovery — docs/OPERATIONS.md pathology 1).
#
# Single-instance + STOP discipline (ADVICE.md r5 finding 4): a flock on
# $LOG.lock refuses a second concurrent watcher (two queues would contend
# for the chip and skew the banked measurements), and an existing
# $LOG.STOP marker refuses to start at all — a restart must not re-burn
# recovery windows on an already-diagnosed persistent failure. Remove the
# marker after investigating to re-arm.
#
# Exit policy after a recovery attempt (chip_recovery.py's contract):
#   rc=0   queue complete — exit.
#   rc=75  wedge sentinel (a queue step timed out or bench's liveness
#          contract fired: the chip re-wedged) — resume probing so a
#          later window isn't lost. Dedicated code: child failures can
#          no longer collide with it (ADVICE.md r5 findings 1+2).
#   other  PERSISTENT failure (70 = a step failed on its own, 3 = the
#          throughput regression gate): re-running the heavy queue would
#          burn every future window on the same failure — stop loudly
#          (STOP marker next to the log).
LOG="${1:-/tmp/chip_recovery.log}"
WEDGE_RC=75  # keep in sync with tools/chip_recovery.py WEDGE_RC
cd "$(dirname "$0")/.."
if [ -e "$LOG.STOP" ]; then
  echo "refusing to start: $LOG.STOP exists (investigate, then remove it)" >&2
  exit 1
fi
# open APPEND: a refused second watcher's `exec` must not truncate the
# running watcher's recorded pid out of the lock file
exec 9>>"$LOG.lock"
if ! flock -n 9; then
  echo "refusing to start: another watcher holds $LOG.lock" >&2
  exit 1
fi
truncate -s 0 "$LOG.lock" 2>/dev/null || true  # we hold it: fresh record
echo "$$" >&9  # forensic: which pid holds the lock
while true; do
  python3 -c "
import bench
err = bench._probe_once(75.0)
raise SystemExit(0 if err is None else 1)" >/dev/null 2>&1
  rc=$?
  echo "$(date -u +%F' '%H:%M:%S) probe rc=$rc" >> "$LOG"
  if [ "$rc" -eq 0 ]; then
    echo "$(date -u +%F' '%H:%M:%S) CHIP ALIVE — starting chip_recovery" >> "$LOG"
    python3 tools/chip_recovery.py >> "$LOG" 2>&1
    qrc=$?
    echo "$(date -u +%F' '%H:%M:%S) chip_recovery exited rc=$qrc" >> "$LOG"
    if [ "$qrc" -eq 0 ]; then exit 0; fi
    if [ "$qrc" -ne "$WEDGE_RC" ]; then
      echo "persistent chip_recovery failure rc=$qrc at $(date -u +%F' '%H:%M:%S) — investigate ($LOG)" > "$LOG.STOP"
      exit "$qrc"
    fi
  fi
  sleep 480
done
