#!/usr/bin/env python
"""Serve-bench trajectory: the prefix-cache / chunked-prefill comparison.

One command, CPU-runnable, writes a machine-readable report (the checked-in
baseline is BENCH_serve_r01.json). Two probes, matching the ISSUE-4
acceptance criteria:

1. **TTFT, shared-prefix workload** (8 closed-loop sessions, prompts
   >= 50% shared): p50 time-to-first-token with the prefix cache ON
   (measured hot — a priming pass populates the cache, as a shared system
   prompt would be after the first request) vs OFF. The cache skips the
   shared tokens' prefill entirely, so TTFT should improve >= 1.5x.

2. **ITL, head-of-line-blocking probe**: one cold max-bucket prompt is
   injected mid-run into steady-state decode. With chunked prefill the
   stall any running session sees is bounded by ONE chunk program's
   latency; the report compares running sessions' p99 inter-token latency
   {chunked baseline (no injection), chunked + injection, unchunked +
   injection} — each the MEDIAN of ``ITL_REPEATS`` runs, because
   thread-timed token arrivals on a shared CPU carry tens of ms of
   scheduler jitter — and directly measures both the chunk program's and
   the monolithic max-bucket prefill program's device latency (the
   structural stall bound chunking enforces vs the stall it replaces).
   PASS: p99_itl(chunked+inject) - p99_itl(chunked baseline) <= chunk
   latency (+ a 2x scheduling-noise allowance on CPU).

With ``--replicas 1,2`` the tool instead runs the **data-parallel
replica scaling probe** (ISSUE-8 acceptance; writes BENCH_serve_r02.json):
the same closed-loop decode workload — sessions at 2x one engine's
largest decode bucket, where a single scheduler must serialise sub-bucket
chunks — at each replica count, gating on aggregate tokens/s >= 1.7x at
2 replicas and greedy outputs token-identical across levels.

With ``--tiered-cache on,off`` it runs the **tiered session-state probe**
(ISSUE-9 acceptance; writes BENCH_serve_r03.json): the long-tail
idle-churn workload (10x more live kept sessions than device slots,
Zipf-popularity continuations — serve/loadgen.py ``run_longtail``) under
three configs: tiers ON (small slot count, host tier + disk tier), tiers
OFF at the same slot count (evicted continuations re-prefill their full
history — the cost the tiers delete), and ALL-ON-DEVICE (slots >=
sessions — the upper bound). Gates: hot-set tokens/s with tiers on
within ~10% of all-on-device, and an in-process server restart resuming
a kept session token-identically from the disk tier. Spill/fill p99
latencies come from the per-config private registry.

Usage::

    JAX_PLATFORMS=cpu python tools/bench_serve.py [--out BENCH_serve_r01.json]
    JAX_PLATFORMS=cpu python tools/bench_serve.py --replicas 1,2
    JAX_PLATFORMS=cpu python tools/bench_serve.py --tiered-cache on,off

Run it with nothing else executing (same discipline as the tier-1 suite:
CPU contention corrupts latency percentiles).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)
sys.path.insert(0, _REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# the mesh probe (--mesh-shards) needs virtual devices to shard over
# (must be set BEFORE jax imports); the other probes keep the host's
# default so their numbers stay comparable with earlier trajectory runs
if (any(a.startswith("--mesh-shards") for a in sys.argv)
        and "xla_force_host_platform_device_count"
        not in os.environ.get("XLA_FLAGS", "")):
    os.environ.update(XLA_FLAGS=(
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8").strip())

import jax  # noqa: E402
import numpy as np  # noqa: E402

from flax import serialization  # noqa: E402

from lstm_tensorspark_tpu.models import LMConfig, init_lm  # noqa: E402
from lstm_tensorspark_tpu.obs import MetricsRegistry  # noqa: E402
from lstm_tensorspark_tpu.serve import (  # noqa: E402
    ModelRegistry,
    ServeEngine,
    ServeServer,
)
from lstm_tensorspark_tpu.serve.loadgen import (  # noqa: E402
    kernel_sweep,
    mesh_sweep,
    replica_sweep,
    run_loadgen,
    run_longtail,
)

CFG = dict(vocab_size=89, hidden_size=128, num_layers=2)
SESSIONS = 8
PROMPT_LEN = 120          # shared-prefix workload prompt
SHARED_LEN = 112          # >= 50% shared (93%), stride-aligned
STRIDE = 8
CHUNK = 16                # chunked-prefill probe chunk size
INJECT_LEN = 128          # the max prefill bucket: worst-case cold prompt
INJECT_DELAY_S = 0.1      # must land while sessions are mid-decode
DECODE_PROMPT_LEN = 8     # ITL probe: short prompts, long decode
MAX_NEW = 64
REQS = 3
REQS_ITL = 6
ITL_SESSIONS = 4          # fewer client threads = less scheduler jitter
ITL_REPEATS = 3           # median over repeats (CPU thread-timing noise)


def build_server(*, prefix_cache: bool, prefill_chunk: int | None,
                 window_ladder=(1, 4, 8), seed: int = 0):
    cfg = LMConfig(**CFG)
    params = init_lm(jax.random.PRNGKey(seed), cfg)
    engine = ServeEngine(
        params, cfg, num_slots=64,
        prefill_buckets=(8, 16, 32, 64, 128), batch_buckets=(1, 2, 4, 8, 16),
        prefix_cache=prefix_cache, prefix_stride=STRIDE, prefix_entries=16,
        # a PRIVATE registry per probe server: each report's embedded
        # "server_histograms" (run_loadgen) then covers only that server's
        # traffic — the server-side TTFT/ITL summaries land in the bench
        # JSON next to loadgen's percentiles, diffable run over run. The
        # probes measure WITH telemetry on, so the bench gates also price
        # its (near-zero) recording overhead.
        registry=MetricsRegistry(),
    )
    server = ServeServer(engine, max_active=16, queue_size=64,
                         prefill_chunk=prefill_chunk,
                         window_ladder=window_ladder)
    return cfg, server


def ttft_run(prefix_cache: bool) -> dict:
    """Hot-cache shared-prefix TTFT: prime one round, then measure."""
    cfg, server = build_server(prefix_cache=prefix_cache, prefill_chunk=None)
    with server:
        server.warmup(prompt_lens=(PROMPT_LEN, PROMPT_LEN - SHARED_LEN))
        kw = dict(vocab_size=cfg.vocab_size, sessions=SESSIONS,
                  prompt_len=PROMPT_LEN, shared_prefix_len=SHARED_LEN,
                  max_new_tokens=4, seed=1)
        run_loadgen(server, requests_per_session=1, **kw)  # prime
        report = run_loadgen(server, requests_per_session=REQS, **kw)
    return report


def itl_run(prefill_chunk: int | None, inject: bool) -> dict:
    """Median-of-repeats ITL probe on ONE warm server. Returns the run
    whose p99 ITL is the median (so all its fields stay consistent)."""
    # window ladder pinned to 1: the per-token path is where a prefill
    # stall is visible per-gap (window bursts would drown it in their own
    # boundary gaps — docs/OPERATIONS.md "when to pin --decode-window 1")
    cfg, server = build_server(prefix_cache=False, prefill_chunk=prefill_chunk,
                               window_ladder=(1,))
    runs = []
    with server:
        server.warmup(prompt_lens=(DECODE_PROMPT_LEN, INJECT_LEN))
        for rep in range(ITL_REPEATS):
            runs.append(run_loadgen(
                server, vocab_size=cfg.vocab_size, sessions=ITL_SESSIONS,
                requests_per_session=REQS_ITL, prompt_len=DECODE_PROMPT_LEN,
                max_new_tokens=MAX_NEW, seed=2 + rep,
                inject_prompt_len=INJECT_LEN if inject else 0,
                inject_delay_s=INJECT_DELAY_S,
            ))
    runs.sort(key=lambda r: r["p99_itl_ms"])
    median = dict(runs[len(runs) // 2])
    median["repeats"] = ITL_REPEATS
    median["p99_itl_ms_all"] = [r["p99_itl_ms"] for r in runs]
    return median


def _program_latency_ms(fn, sync, samples: int = 20) -> float:
    fn()  # compile
    sync()
    times = []
    for _ in range(samples):
        t0 = time.perf_counter()
        fn()
        sync()
        times.append(time.perf_counter() - t0)
    return round(sorted(times)[len(times) // 2] * 1e3, 3)


def stall_latencies_ms() -> tuple[float, float]:
    """Median device latency of (one prefill_chunk program, one monolithic
    max-bucket prefill program): the per-iteration stall chunking enforces
    vs the stall it replaces — measured directly, immune to loadgen thread
    jitter."""
    cfg, server = build_server(prefix_cache=False, prefill_chunk=CHUNK)
    engine = server.engine
    scratch = engine.cache.scratch_slot
    sync = lambda: jax.block_until_ready(engine.cache.h)  # noqa: E731
    chunk_tokens = np.zeros((CHUNK,), np.int32)
    full_tokens = np.zeros((INJECT_LEN,), np.int32)
    chunk_ms = _program_latency_ms(
        lambda: engine.prefill_chunk([(scratch, scratch, True, chunk_tokens)]),
        sync)
    full_ms = _program_latency_ms(
        lambda: engine.prefill([(scratch, True, full_tokens)]), sync)
    return chunk_ms, full_ms


# ---- data-parallel replica scaling probe (--replicas; BENCH_serve_r02) --
#
# The single-scheduler stack hard-caps aggregate decode at one engine's
# batch bucket: with 2x the sessions of the largest decode bucket, ONE
# replica must split every iteration into sequential sub-bucket chunks
# (and loses the windowed fast path, which requires the whole active set
# to fit one bucket), while N replicas run their buckets concurrently —
# exactly the capacity wall data-parallel serving removes. The probe runs
# the SAME workload at --replicas 1 and 2 and gates on aggregate
# tokens/s >= 1.7x plus greedy parity (token-identical outputs).

R_CFG = dict(vocab_size=89, hidden_size=128, num_layers=2)
R_SESSIONS = 16           # 2x the decode bucket: one scheduler saturates
R_BATCH_BUCKETS = (1, 2, 4, 8)   # largest bucket = one replica's capacity
R_PROMPT_LEN = 8
R_MAX_NEW = 64
R_REQS = 4


def replica_probe(levels: tuple[int, ...]) -> dict:
    cfg = LMConfig(**R_CFG)
    params = init_lm(jax.random.PRNGKey(0), cfg)

    def make_server(n: int) -> ServeServer:
        # ONE private registry per level, shared by every replica of that
        # level: the router/server aggregate engines[0].metrics, so
        # per-engine registries would silently drop replica >= 1's
        # server-side histograms from the embedded report
        reg = MetricsRegistry()
        engines = [
            ServeEngine(
                params, cfg, num_slots=32,
                prefill_buckets=(8, 16), batch_buckets=R_BATCH_BUCKETS,
                rng_seed=i, registry=reg,
            )
            for i in range(n)
        ]
        return ServeServer(engines if n > 1 else engines[0],
                           max_active=R_SESSIONS, queue_size=64,
                           window_ladder=(1, 4, 8))

    return replica_sweep(
        make_server, vocab_size=cfg.vocab_size, levels=levels,
        sessions=R_SESSIONS, requests_per_session=R_REQS,
        prompt_len=R_PROMPT_LEN, max_new_tokens=R_MAX_NEW, seed=5,
    )


def run_replica_bench(levels: tuple[int, ...], out_path: str) -> int:
    print(f"bench_serve: replica scaling probe (levels {levels})...",
          flush=True)
    sweep = replica_probe(levels)
    sc = sweep["scaling"]
    speedup = sc["speedup_top_vs_base"]
    out = {
        "note": "serve_bench_r02 replica scaling (tools/bench_serve.py "
                "--replicas)",
        "config": {
            **R_CFG, "sessions": R_SESSIONS,
            "batch_buckets": list(R_BATCH_BUCKETS),
            "prompt_len": R_PROMPT_LEN, "max_new_tokens": R_MAX_NEW,
            "requests_per_session": R_REQS, "levels": list(levels),
            "platform": jax.devices()[0].platform,
        },
        "replica_scaling": sweep,
        "pass_1p7x": bool(speedup >= 1.7),
        "pass_parity": bool(sweep.get("parity_ok", False)),
    }
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps({
        "tokens_per_sec": sc["tokens_per_sec"],
        "speedup_top_vs_base": speedup,
        "parity_ok": sweep.get("parity_ok"),
        "pass_1p7x": out["pass_1p7x"],
    }))
    print(f"bench_serve: report written to {out_path}")
    return 0 if (out["pass_1p7x"] and out["pass_parity"]) else 1


# ---- tiered session-state probe (--tiered-cache; BENCH_serve_r03) ------
#
# The fixed-slot cache hard-fails the long-tail multi-tenant workload:
# with 10x more live kept sessions than slots, LRU eviction expires the
# idle tail, and every evicted continuation re-pays a FULL prefill of its
# accumulated history. The tiers make eviction a spill (host RAM, disk
# below) and a continuation a one-state-copy fill. The probe runs the
# same Zipf idle-churn workload (run_longtail) under tiers-on /
# tiers-off / all-on-device and gates on hot-set throughput: tiered
# serving must stay within ~10% of keeping everything resident.
#
# Protocol notes (honesty):
# - the all-on-device baseline keeps the TIERS (and write-behind session
#   checkpointing) ON with slots >= sessions — both gate configs pay the
#   same durability cost, so the ratio isolates exactly the spill/fill
#   plane the 10x-slot-compression adds (production would run the
#   durability SLO either way);
# - tokens/s ratios on a shared CPU host carry ~±5-10% ambient noise, so
#   the gate is the MEDIAN of back-to-back (device, tiered) pair ratios
#   — pairing cancels load drift the way the ITL probe's
#   median-of-repeats does;
# - tiers-off runs once for the re-prefill contrast (what eviction costs
#   without the tiers), not for the gate.

T_CFG = dict(vocab_size=89, hidden_size=64, num_layers=2)
T_SLOTS = 16              # device slots — the scarce resource
T_SESSIONS = 160          # 10x the slots: the ROADMAP-gate ratio
T_HOST_ENTRIES = 256      # host tier sized for the tail (RAM is cheap —
#                           the disk tier is durability + restart, and is
#                           exercised by the checkpoints + restart check)
T_PROMPT_LEN = 8
T_MAX_NEW = 32            # decode-dominated requests: serving time is
#                           decode, not admission bookkeeping — the
#                           regime the hot-set gate is about (per-event
#                           tier costs are fixed and amortize)
T_REQS = 3                # Zipf-weighted turns per live session
T_ZIPF_S = 1.5            # a real hot set: top-10% draw ~3/4 of traffic
T_MAX_ACTIVE = 8
T_PAIRS = 5               # (device, tiered) pairs; gate = median ratio


def _tier_server(mode: str, session_dir: str | None):
    """One probe server: 'on' (tiers over few slots), 'off' (few slots,
    no tiers), 'device' (slots >= sessions, tiers + durability still on
    — see protocol notes)."""
    cfg = LMConfig(**T_CFG)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    slots = T_SESSIONS + 16 if mode == "device" else T_SLOTS
    engine = ServeEngine(
        params, cfg, num_slots=slots,
        prefill_buckets=(8, 16, 32, 64, 128), batch_buckets=(1, 2, 4, 8),
        prefix_cache=False,
        tiered_cache=mode in ("on", "device"),
        host_tier_entries=T_HOST_ENTRIES,
        session_dir=session_dir if mode in ("on", "device") else None,
        registry=MetricsRegistry(),
    )
    return cfg, ServeServer(engine, max_active=T_MAX_ACTIVE, queue_size=256)


def _longtail_run(mode: str, session_dir: str | None, seed: int = 13) -> dict:
    cfg, server = _tier_server(mode, session_dir)
    with server:
        server.warmup(prompt_lens=tuple(
            set(server.engine.prefill_buckets) | {T_PROMPT_LEN}))
        # prime: touch the spill/fill program shapes so the measured run
        # is not charged their one-time compiles
        run_longtail(server, vocab_size=cfg.vocab_size,
                     sessions=3 * T_SLOTS, requests_per_session=2,
                     prompt_len=T_PROMPT_LEN, max_new_tokens=2,
                     zipf_s=T_ZIPF_S, seed=97)
        report = run_longtail(
            server, vocab_size=cfg.vocab_size, sessions=T_SESSIONS,
            requests_per_session=T_REQS, prompt_len=T_PROMPT_LEN,
            max_new_tokens=T_MAX_NEW, zipf_s=T_ZIPF_S, seed=seed)
        summary = server.metrics_summary()
        for fam in ("serve_tier_spill_seconds", "serve_tier_fill_seconds"):
            if isinstance(summary.get(fam), dict):
                report[fam] = summary[fam]
    return report


def _restart_resume_check(session_dir: str) -> bool:
    """Kept session on server A (disk-tier checkpoint flushed by stop),
    continuation on a FRESH server B over the same session dir — the
    concatenation must be token-identical to one uninterrupted
    models/generate.py run."""
    from lstm_tensorspark_tpu.models import make_generate_fn

    cfg, server_a = _tier_server("on", session_dir)
    prompt = np.arange(1, T_PROMPT_LEN + 1, dtype=np.int32)
    with server_a:
        server_a.warmup(prompt_lens=(T_PROMPT_LEN,))
        first = server_a.generate(prompt, max_new_tokens=4,
                                  keep_session=True)
        sid = first.session_id
    _, server_b = _tier_server("on", session_dir)
    with server_b:
        server_b.warmup(prompt_lens=(T_PROMPT_LEN,))
        cont = server_b.generate([first.tokens[-1]], max_new_tokens=6,
                                 session_id=sid, keep_session=True)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    ref = np.asarray(make_generate_fn(cfg, max_new_tokens=10, greedy=True)(
        params, prompt[None, :], jax.random.PRNGKey(0)))[0, prompt.size:]
    got = np.asarray(list(first.tokens) + list(cont.tokens), np.int32)
    return bool(np.array_equal(got, ref))


def _tiered_pairs(label: str) -> tuple[dict, list[float]]:
    """``T_PAIRS`` back-to-back (all-on-device, tiered) longtail pairs —
    pairing cancels ambient CPU drift; the reported runs are the MEDIAN
    pair's (all fields consistent). Shared by the r03 probe and the r05
    re-gate."""
    import tempfile

    pair_ratios: list[float] = []
    pairs: list[tuple[dict, dict]] = []
    for rep in range(T_PAIRS):
        print(f"bench_serve: {label} pair {rep + 1}/{T_PAIRS} "
              "(all-on-device, then tiered)...", flush=True)
        dev = _longtail_run(
            "device", tempfile.mkdtemp(prefix=f"bench_{label}_dev_"),
            seed=13 + rep)
        on = _longtail_run(
            "on", tempfile.mkdtemp(prefix=f"bench_{label}_on_"),
            seed=13 + rep)
        pairs.append((dev, on))
        base = dev["hot_set"]["tokens_per_sec"]
        pair_ratios.append(
            round(on["hot_set"]["tokens_per_sec"] / base, 3)
            if base else 0.0)
    order = sorted(range(T_PAIRS), key=lambda i: pair_ratios[i])
    med = order[T_PAIRS // 2]
    return {"all_on_device": pairs[med][0],
            "tiered_on": pairs[med][1]}, pair_ratios


def run_tiered_bench(modes: tuple[str, ...], out_path: str) -> int:
    import tempfile

    runs: dict[str, dict] = {}
    pair_ratios: list[float] = []
    if "on" in modes:
        runs, pair_ratios = _tiered_pairs("r03")
    if "off" in modes:
        print("bench_serve: tiered probe (tiered-cache off — re-prefill "
              "contrast)...", flush=True)
        runs["tiered_off"] = _longtail_run("off", None)
    print("bench_serve: restart-resume check (disk tier)...", flush=True)
    restart_ok = _restart_resume_check(
        tempfile.mkdtemp(prefix="bench_serve_restart_"))

    ratio = (sorted(pair_ratios)[T_PAIRS // 2] if pair_ratios else None)
    # the 10%-gate only exists when the (device, tiered-on) pairs ran —
    # an off-only invocation reports the re-prefill contrast and the
    # restart check, and must not record (or exit on) a gate that never
    # executed
    gate = None if ratio is None else bool(ratio >= 0.9)
    out = {
        "note": "serve_bench_r03 tiered session-state cache "
                "(tools/bench_serve.py --tiered-cache)",
        "config": {
            **T_CFG, "num_slots": T_SLOTS, "sessions": T_SESSIONS,
            "host_tier_entries": T_HOST_ENTRIES,
            "prompt_len": T_PROMPT_LEN, "max_new_tokens": T_MAX_NEW,
            "requests_per_session": T_REQS, "zipf_s": T_ZIPF_S,
            "max_active": T_MAX_ACTIVE, "pairs": T_PAIRS,
            "platform": jax.devices()[0].platform,
        },
        "runs": runs,
        "hot_set_pair_ratios": pair_ratios,
        "hot_set_ratio_on_vs_device": ratio,
        "pass_within_10pct": gate,
        "restart_resume_token_identical": restart_ok,
    }
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
        f.write("\n")
    brief = {
        name: {
            "tokens_per_sec": r["tokens_per_sec"],
            "hot_set_tokens_per_sec": r["hot_set"]["tokens_per_sec"],
            "re_prefills": r["re_prefills"],
            "re_prefill_tokens": r["re_prefill_tokens"],
            "tier_hit_rates": (r.get("tiers") or {}).get("hit_rates"),
        }
        for name, r in runs.items()
    }
    print(json.dumps({
        **brief,
        "hot_set_ratio_on_vs_device": ratio,
        "pass_within_10pct": out["pass_within_10pct"],
        "restart_resume_token_identical": restart_ok,
    }))
    print(f"bench_serve: report written to {out_path}")
    return 0 if ((gate is None or gate) and restart_ok) else 1


# ---- decode-kernel comparison + tier re-gate (--decode-kernel; r05) -----
#
# Two probes in one report (ISSUE-12 acceptance; writes
# BENCH_serve_r05.json):
#
# 1. **Decode-kernel comparison**: the same closed-loop decode-heavy
#    workload through `--decode-kernel scan` and `pallas`, tokens/s +
#    TTFT/ITL deltas + greedy token parity. On CPU the pallas kernel
#    runs in INTERPRETER mode — a correctness path that is expected to
#    be slower than the scan window; the ratio is recorded honestly
#    (the speed claim belongs to real TPUs: tests_tpu/
#    test_pallas_decode_tpu.py is the hardware gate).
# 2. **Tier-overhead re-gate**: the PR 8 hot-set probe re-run on the
#    BATCHED admission fill path (SessionTiers.fill_batch — one scatter
#    program per admission batch, tier-dict bookkeeping in one lock
#    hold): median of T_PAIRS paired (all-on-device, tiered) runs at
#    10x sessions/slots, gated at >= 0.9x — the ratio PR 8 marginally
#    missed at 0.87x with per-session fills.

K_SESSIONS = 8
K_PROMPT_LEN = 8
K_MAX_NEW = 64
K_REQS = 3


def _kernel_server(kern: str) -> ServeServer:
    cfg = LMConfig(**CFG)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    engine = ServeEngine(
        params, cfg, num_slots=64,
        prefill_buckets=(8, 16, 32, 64, 128), batch_buckets=(1, 2, 4, 8, 16),
        prefix_cache=False, decode_kernel=kern,
        registry=MetricsRegistry(),
    )
    return ServeServer(engine, max_active=16, queue_size=64,
                       window_ladder=(1, 4, 8))


def run_decode_kernel_bench(kernels: tuple[str, ...], out_path: str) -> int:
    print(f"bench_serve: decode-kernel comparison ({kernels})...",
          flush=True)
    sweep = kernel_sweep(
        _kernel_server, vocab_size=CFG["vocab_size"], kernels=kernels,
        sessions=K_SESSIONS, requests_per_session=K_REQS,
        prompt_len=K_PROMPT_LEN, max_new_tokens=K_MAX_NEW, seed=5)
    print("bench_serve: tier-overhead re-gate (batched admission "
          "fills)...", flush=True)
    runs, pair_ratios = _tiered_pairs("r05")
    ratio = sorted(pair_ratios)[T_PAIRS // 2]
    gate = bool(ratio >= 0.9)
    platform = jax.devices()[0].platform
    out = {
        "note": "serve_bench_r05 decode-kernel comparison + tier-overhead "
                "re-gate (tools/bench_serve.py --decode-kernel)",
        "config": {
            "kernel_probe": {
                **CFG, "sessions": K_SESSIONS, "prompt_len": K_PROMPT_LEN,
                "max_new_tokens": K_MAX_NEW,
                "requests_per_session": K_REQS, "kernels": list(kernels),
            },
            "tier_regate": {
                **T_CFG, "num_slots": T_SLOTS, "sessions": T_SESSIONS,
                "host_tier_entries": T_HOST_ENTRIES,
                "prompt_len": T_PROMPT_LEN, "max_new_tokens": T_MAX_NEW,
                "requests_per_session": T_REQS, "zipf_s": T_ZIPF_S,
                "max_active": T_MAX_ACTIVE, "pairs": T_PAIRS,
            },
            "platform": platform,
        },
        "decode_kernel_comparison": sweep,
        # honesty marker: off-TPU the pallas path is interpreter-mode —
        # slower by construction; the comparison still proves parity +
        # plumbing, the speedup claim is the tests_tpu hardware gate
        "pallas_interpreted": platform != "tpu",
        "tier_regate": {
            "runs": runs,
            "hot_set_pair_ratios": pair_ratios,
            "hot_set_ratio_on_vs_device": ratio,
            "pass_0p9x": gate,
        },
    }
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
        f.write("\n")
    vs = sweep.get("pallas_vs_scan", {})
    print(json.dumps({
        "tokens_per_sec": {k: r["tokens_per_sec"]
                           for k, r in sweep["kernels"].items()},
        "pallas_vs_scan": vs,
        "parity_ok": sweep.get("parity_ok"),
        "hot_set_ratio_on_vs_device": ratio,
        "pass_0p9x": gate,
    }))
    print(f"bench_serve: report written to {out_path}")
    return 0 if (sweep.get("parity_ok", True) and gate) else 1


# ---- tensor-parallel mesh probe (--mesh-shards; BENCH_serve_r06) --------
#
# The mesh-serving trendline's SEED datapoint (ISSUE-14): the same
# closed-loop decode workload through a 1-shard engine and an N-shard
# GSPMD engine on virtual CPU devices. On CPU the shards are threads of
# one host, so the ratio prices partition/collective overhead WITHOUT
# the memory-capacity win sharding exists for — recorded honestly, no
# >= gate (the capacity/speed claims belong to real multi-chip hosts).
# What IS gated: greedy token parity across shard counts, and the
# warmup-asserted zero-mid-traffic-compile invariant on the sharded
# ("decode_window", bucket, K, sampling, shards) family.

M_CFG = dict(vocab_size=89, hidden_size=128, num_layers=2)
M_SESSIONS = 8
M_PROMPT_LEN = 8
M_MAX_NEW = 64
M_REQS = 3


def _mesh_server(shards: int) -> ServeServer:
    cfg = LMConfig(**M_CFG)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    engine = ServeEngine(
        params, cfg, num_slots=32,
        prefill_buckets=(8, 16), batch_buckets=(1, 2, 4, 8),
        prefix_cache=False, mesh_shards=shards,
        registry=MetricsRegistry(),
    )
    return ServeServer(engine, max_active=M_SESSIONS, queue_size=64,
                       window_ladder=(1, 4, 8))


def run_mesh_bench(levels: tuple[int, ...], out_path: str) -> int:
    print(f"bench_serve: tensor-parallel mesh probe (shards {levels})...",
          flush=True)
    sweep = mesh_sweep(
        _mesh_server, vocab_size=M_CFG["vocab_size"], levels=levels,
        sessions=M_SESSIONS, requests_per_session=M_REQS,
        prompt_len=M_PROMPT_LEN, max_new_tokens=M_MAX_NEW, seed=5)
    sc = sweep["scaling"]
    out = {
        "note": "serve_bench_r06 tensor-parallel mesh serving "
                "(tools/bench_serve.py --mesh-shards)",
        "config": {
            **M_CFG, "sessions": M_SESSIONS, "prompt_len": M_PROMPT_LEN,
            "max_new_tokens": M_MAX_NEW, "requests_per_session": M_REQS,
            "levels": list(levels),
            "platform": jax.devices()[0].platform,
            "devices": jax.device_count(),
        },
        "mesh_scaling": sweep,
        # honesty marker: CPU virtual-device shards share one host's
        # cores — the ratio prices GSPMD overhead, not the capacity win
        "cpu_virtual_devices": jax.devices()[0].platform != "tpu",
        "pass_parity": bool(sweep.get("parity_ok", False)),
        "pass_warmup_covered": bool(sweep.get("warmup_covered", False)),
    }
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps({
        "tokens_per_sec": sc["tokens_per_sec"],
        "shard_ratio_top_vs_base": sc["shard_ratio_top_vs_base"],
        "p50_ttft_ms": sc["p50_ttft_ms"],
        "p99_itl_ms": sc["p99_itl_ms"],
        "mid_traffic_compiles": sweep["mid_traffic_compiles"],
        "parity_ok": sweep.get("parity_ok"),
    }))
    print(f"bench_serve: report written to {out_path}")
    return 0 if (out["pass_parity"] and out["pass_warmup_covered"]) else 1


# ---- online-autotuner probe (--autotune; BENCH_serve_r07) ---------------
#
# The closed-loop control gate (ISSUE-15): a TWO-PHASE workload on one
# live server — phase A is ITL/throughput-bound (long decodes, short
# prompts, empty queues), phase B is TTFT-bound (bursts of short chunked
# prompts arriving over standing background decoders, so every burst
# lands behind whatever decode window is in flight). The FROZEN arm
# pins the mid-ladder operating point (window cap 4 of (1,4,8), chunk 16
# of (8,16,32)) for both phases; the TUNED arm runs the same workload
# with the controller live, which should raise the window cap in phase A
# (amortize dispatches) and pull it down + grow the chunk in phase B
# (protect TTFT). Gates: the controller moves >= 2 distinct knobs across
# the phases (window_k in BOTH directions), the phase-B TTFT p99
# improves >= 5% vs frozen (median of paired runs — pairing cancels CPU
# drift), ZERO mid-traffic compiles with the controller live, and the
# PR 10 4x-burst gate still passes with the controller enabled.

AT_CFG = dict(vocab_size=89, hidden_size=256, num_layers=2)
AT_LADDER = (1, 4, 8)
AT_CHUNK = 16
AT_CHUNKS = (8, 16, 32)
AT_MID_CAP = 4            # the frozen mid-ladder operating point
AT_INTERVAL_S = 0.05      # control window (fast enough to adapt inside
#                           one phase-B shakedown segment)
AT_MIN_EVENTS = 6         # a burst contributes AT_B_BURST ttft samples
AT_PATIENCE_UP = 5        # > the clean windows inside one burst gap, so
#                           phase B cannot oscillate K back up between
#                           bursts (0.2 s gap / 0.05 s interval = 4)
AT_A_SESSIONS = 6
AT_A_REQS = 1             # one long decode per session: phase A is pure
AT_A_PROMPT = 8           # steady-state decode after the first window
AT_A_MAX_NEW = 1536       # sized so phase A OUTLASTS the grow hysteresis
#                           by a wide margin on a fast host (~10k+ tok/s
#                           CPU → >= 0.5 s of steady ITL-bound decode vs
#                           patience_up * interval = 0.25 s): the K-up
#                           move needs 5 consecutive headroom windows,
#                           and a phase shorter than that proves nothing
AT_B_PROBES = 32          # measured TTFT probes, bursts of AT_B_BURST
AT_B_SHAKE = 16           # unmeasured shakedown probes first: the
#                           controller converges, THEN the segment both
#                           arms are judged on runs at steady state
AT_B_BURST = 8
AT_B_GAP_S = 0.2
AT_B_PROMPT = 48          # 3 chunks at the base chunk size
AT_B_MAX_NEW = 4
AT_BG_DECODERS = 6        # standing long-decode sessions during phase B
#                           (their in-flight windows are what a probe
#                           waits behind — the K-cap cost made visible)
AT_PAIRS = 3              # (frozen, tuned) pairs; gate = median ratio
AT_SLO_GATE_MS = 1000.0   # burst-gate p99 TTFT bound (CPU-noise-tolerant)


def _autotune_server(tuned: bool, slo_s: float, queue_size: int = 64):
    from lstm_tensorspark_tpu.serve import AutoTuneConfig

    cfg = LMConfig(**AT_CFG)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    engine = ServeEngine(
        params, cfg, num_slots=32,
        prefill_buckets=(8, 16, 32, 64), batch_buckets=(1, 2, 4, 8),
        prefix_cache=False, registry=MetricsRegistry(),
    )
    server = ServeServer(
        engine, max_active=8, queue_size=queue_size,
        window_ladder=AT_LADDER,
        prefill_chunk=AT_CHUNK, prefill_chunk_choices=AT_CHUNKS,
        autotune=(AutoTuneConfig(interval_s=AT_INTERVAL_S, slo_s=slo_s,
                                 min_events=AT_MIN_EVENTS,
                                 patience_up=AT_PATIENCE_UP)
                  if tuned else None),
    )
    # BOTH arms start at the frozen mid-ladder operating point: the
    # tuned arm's improvement must come from MOVING, not from a better
    # starting point
    server.batcher.set_window_cap(AT_MID_CAP)
    return cfg, server


def _autotune_phases(server, cfg, seed: int) -> tuple[dict, dict]:
    """The two-phase workload on one live server: (phase A report,
    MEASURED phase B probe report). Phase B runs an unmeasured
    shakedown segment first — the controller converges on the phase's
    operating point, then both arms are judged on steady state (the
    frozen arm runs the identical segments, so the comparison stays
    paired)."""
    a = run_loadgen(
        server, vocab_size=cfg.vocab_size, sessions=AT_A_SESSIONS,
        requests_per_session=AT_A_REQS, prompt_len=AT_A_PROMPT,
        max_new_tokens=AT_A_MAX_NEW, seed=seed)
    # phase B: standing decoders keep windows in flight while bursts of
    # short chunked prompts probe TTFT — the knob-down scenario
    import threading

    stop = threading.Event()
    rng = np.random.RandomState(seed + 31)
    bg_prompt = rng.randint(0, cfg.vocab_size,
                            size=AT_A_PROMPT).astype(np.int32)

    def background():
        while not stop.is_set():
            try:
                server.generate(bg_prompt, max_new_tokens=64,
                                timeout=120.0)
            except Exception:
                break  # shed/timeout under churn: the decoders are load

    threads = [threading.Thread(target=background, daemon=True)
               for _ in range(AT_BG_DECODERS)]
    for t in threads:
        t.start()
    try:
        burst_kw = dict(
            vocab_size=cfg.vocab_size, sessions=AT_B_BURST,
            prompt_len=AT_B_PROMPT, max_new_tokens=AT_B_MAX_NEW,
            mode="open", arrival="burst", burst_n=AT_B_BURST,
            burst_gap_s=AT_B_GAP_S)
        run_loadgen(server,
                    requests_per_session=AT_B_SHAKE // AT_B_BURST,
                    seed=seed + 1, **burst_kw)  # shakedown (unmeasured)
        b = run_loadgen(server,
                        requests_per_session=AT_B_PROBES // AT_B_BURST,
                        seed=seed + 2, **burst_kw)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=120.0)
    return a, b


def _autotune_arm(tuned: bool, slo_s: float, seed: int) -> dict:
    cfg, server = _autotune_server(tuned, slo_s)
    with server:
        server.warmup(prompt_lens=(AT_A_PROMPT, AT_B_PROMPT))
        warm = server.engine.num_compiles()
        a, b = _autotune_phases(server, cfg, seed)
        out = {
            "phase_a": a,
            "phase_b": b,
            "mid_traffic_compiles": server.engine.num_compiles() - warm,
            "window_cap_final": server.batcher.window_cap,
            "prefill_chunk_final": server.batcher.prefill_chunk,
        }
        if tuned:
            st = server.autotuner.stats()
            out["autotune"] = {"moves": st["moves"], "ticks": st["ticks"],
                               "errors": st["errors"],
                               "history": st["history"]}
    return out


def _autotune_burst_gate(slo_s: float, seed: int) -> dict:
    """The PR 10 4x-burst gate WITH the controller live: calibrate
    sustainable throughput, replay a 4x open-loop burst (25% priority),
    and require zero priority sheds + priority p99 TTFT within the
    (generous, CPU-noise-tolerant) SLO while best-effort sheds."""
    cfg, cal = _autotune_server(True, slo_s)
    with cal:
        cal.warmup(prompt_lens=(AT_A_PROMPT,))
        base = run_loadgen(cal, vocab_size=cfg.vocab_size, sessions=4,
                           requests_per_session=4, prompt_len=AT_A_PROMPT,
                           max_new_tokens=8, seed=seed)
    capacity = max(base["requests_per_sec"], 1.0)
    rate = 4.0 * capacity
    # the tight PR 10 burst-gate queue shape (chaos_serve uses 16 too)
    cfg, srv = _autotune_server(True, slo_s, queue_size=16)
    with srv:
        srv.warmup(prompt_lens=(AT_A_PROMPT,))
        report = run_loadgen(
            srv, vocab_size=cfg.vocab_size, sessions=8,
            requests_per_session=8, prompt_len=AT_A_PROMPT,
            max_new_tokens=8, mode="open", rate=rate, seed=seed + 1,
            priority_frac=0.25, retry_max=1, retry_base_s=0.02,
            retry_cap_s=0.25)
    pr = report["classes"]["priority"]
    be = report["classes"]["best_effort"]
    return {
        "capacity_rps": capacity,
        "burst_rate_rps": rate,
        "priority": pr,
        "best_effort": be,
        "priority_p99_ttft_ms": pr["p99_ttft_ms"],
        # the PR 10 contract: priority never sees a 429 (shed NOR a
        # retried one) while best-effort absorbs them — "retried"
        # counts 429s the client recovered from, which on a fast host
        # is where most of the burst's sheds end up
        "pass_priority_no_shed": pr["shed"] == 0 and pr["retried"] == 0,
        "pass_best_effort_sheds": be["shed"] + be["retried"] >= 1,
        "pass_priority_slo": (pr["p99_ttft_ms"] is not None
                              and pr["p99_ttft_ms"] <= AT_SLO_GATE_MS),
    }


def run_autotune_bench(out_path: str) -> int:
    # SLO calibration: the FIRST frozen arm anchors --slo-ms to THIS
    # machine's contested phase-B TTFT scale, so the controller's
    # fractional thresholds land on the right side of both phases on
    # any host (the frozen arm never reads the SLO, so using it as the
    # calibration run costs nothing)
    pairs: list[tuple[dict, dict]] = []
    ratios: list[float] = []
    slo_s = None
    for rep in range(AT_PAIRS):
        print(f"bench_serve: autotune pair {rep + 1}/{AT_PAIRS} "
              "(frozen, then tuned)...", flush=True)
        frozen = _autotune_arm(False, 1.0, seed=13 + rep)
        if slo_s is None:
            slo_s = max(frozen["phase_b"]["p99_ttft_ms"] / 1e3, 0.04)
            print(f"bench_serve: autotune probe — slo calibrated to "
                  f"{slo_s * 1e3:.1f} ms", flush=True)
        tuned = _autotune_arm(True, slo_s, seed=13 + rep)
        pairs.append((frozen, tuned))
        fz = frozen["phase_b"]["p99_ttft_ms"]
        td = tuned["phase_b"]["p99_ttft_ms"]
        ratios.append(round(fz / td, 3) if td else 0.0)
    order = sorted(range(AT_PAIRS), key=lambda i: ratios[i])
    med = order[AT_PAIRS // 2]
    frozen, tuned = pairs[med]
    moves = tuned["autotune"]["moves"]
    knobs_moved = sorted(k for k, v in moves.items()
                         if v["up"] + v["down"] > 0)
    ratio = ratios[med]
    print("bench_serve: autotune probe — PR 10 burst gate with the "
          "controller live...", flush=True)
    burst = _autotune_burst_gate(slo_s, seed=41)
    compiles = max(t["mid_traffic_compiles"] for _, t in pairs)
    gates = {
        "pass_two_knobs_moved": len(knobs_moved) >= 2,
        "pass_window_k_both_directions": (
            moves["window_k"]["up"] >= 1
            and moves["window_k"]["down"] >= 1),
        "pass_p99_improves_5pct": ratio >= 1.05,
        "pass_zero_mid_traffic_compiles": compiles == 0,
        "pass_burst_gate": (burst["pass_priority_no_shed"]
                            and burst["pass_best_effort_sheds"]
                            and burst["pass_priority_slo"]),
    }
    out = {
        "note": "serve_bench_r07 online autotuner two-phase gate "
                "(tools/bench_serve.py --autotune)",
        "config": {
            **AT_CFG, "ladder": list(AT_LADDER),
            "mid_cap": AT_MID_CAP, "chunk": AT_CHUNK,
            "chunk_choices": list(AT_CHUNKS),
            "phase_a": {"sessions": AT_A_SESSIONS, "reqs": AT_A_REQS,
                        "prompt_len": AT_A_PROMPT,
                        "max_new": AT_A_MAX_NEW},
            "phase_b": {"probes": AT_B_PROBES, "burst_n": AT_B_BURST,
                        "burst_gap_s": AT_B_GAP_S,
                        "prompt_len": AT_B_PROMPT,
                        "max_new": AT_B_MAX_NEW,
                        "background_decoders": AT_BG_DECODERS},
            "pairs": AT_PAIRS, "slo_ms": round(slo_s * 1e3, 3),
            "platform": jax.devices()[0].platform,
        },
        "runs": {"frozen": frozen, "tuned": tuned},
        "watched_histogram": "serve_ttft_seconds (phase B p99)",
        "phase_b_p99_ttft_ms": {
            "frozen": frozen["phase_b"]["p99_ttft_ms"],
            "tuned": tuned["phase_b"]["p99_ttft_ms"],
        },
        "phase_a_tokens_per_sec": {
            "frozen": frozen["phase_a"]["tokens_per_sec"],
            "tuned": tuned["phase_a"]["tokens_per_sec"],
        },
        "pair_ratios_frozen_over_tuned": ratios,
        "p99_ratio_frozen_over_tuned": ratio,
        "knobs_moved": knobs_moved,
        "moves": moves,
        "mid_traffic_compiles_max": compiles,
        "burst_gate": burst,
        **gates,
    }
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps({
        "phase_b_p99_ttft_ms": out["phase_b_p99_ttft_ms"],
        "p99_ratio_frozen_over_tuned": ratio,
        "pair_ratios": ratios,
        "knobs_moved": knobs_moved,
        "window_k_moves": moves["window_k"],
        "mid_traffic_compiles_max": compiles,
        **{k: v for k, v in gates.items()},
    }))
    print(f"bench_serve: report written to {out_path}")
    return 0 if all(gates.values()) else 1


# ---- rolling-reload gate (--rollout; BENCH_serve_r08) -------------------
#
# The zero-downtime rollout drill (ISSUE-16 acceptance): a 2-replica
# fleet boots on v1 with a registry holding v1, v2 (genuinely different
# weights) and v3 (the SAME bytes as v2 — the deterministic
# canary-match arm). Under continuous closed-loop traffic the
# controller rolls v1 -> v2 and then v2 -> v3 with the canary shadow
# compare live. Gates: ZERO failed requests across both rolling swaps
# (drain requeues, migration preserves kept sessions, capacity stays
# >= N-1), ZERO mid-traffic compiles (params are traced ARGUMENTS —
# same-shape swaps reuse every compiled program), a kept session
# started on v1 continuing TOKEN-IDENTICALLY to a single-replica
# in-place-swap reference, fresh post-rollout requests matching the new
# version's reference tokens, and the canary report comparing >= the
# configured pair floor with 0 diffs on identical weights.

R_CFG = dict(vocab_size=89, hidden_size=128, num_layers=2)
R_REPLICAS = 2
R_PUMPS = 3
R_MAX_NEW = 4
R_CANARY_PAIRS = 4


def _rollout_server(params, cfg, n):
    engines = [
        ServeEngine(params, cfg, num_slots=8,
                    prefill_buckets=(8, 16), batch_buckets=(1, 2, 4),
                    rng_seed=i, replica=i)
        for i in range(n)
    ]
    return ServeServer(engines if n > 1 else engines[0],
                       max_active=4, queue_size=64)


def run_rollout_bench(out_path: str) -> int:
    print(f"bench_serve: rolling-reload gate ({R_REPLICAS} replicas, "
          "v1 -> v2 under load, then the v3 canary-match arm)...",
          flush=True)
    cfg = LMConfig(**R_CFG)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    params_v2 = init_lm(jax.random.PRNGKey(7), cfg)
    reg = ModelRegistry(tempfile.mkdtemp(prefix="bench_rollout_reg_"))
    v2_bytes = serialization.to_bytes(jax.device_get(params_v2))
    reg.publish("default", serialization.to_bytes(jax.device_get(params)))
    reg.publish("default", v2_bytes)  # v2: the new weights
    reg.publish("default", v2_bytes)  # v3 == v2 bytes: canary must match

    engines = [
        ServeEngine(params, cfg, num_slots=8,
                    prefill_buckets=(8, 16), batch_buckets=(1, 2, 4),
                    rng_seed=i, replica=i)
        for i in range(R_REPLICAS)
    ]
    server = ServeServer(
        engines, max_active=4, queue_size=64, model_registry=reg,
        rollout_kw={"drain_timeout_s": 60.0,
                    "canary_min_pairs": R_CANARY_PAIRS,
                    "canary_timeout_s": 120.0,
                    "require_canary_match": True})
    failures: list = []
    done = threading.Event()
    pumped = [0] * R_PUMPS

    def pump(worker):
        while not done.is_set():
            try:
                r = server.generate([1 + worker, 2, 3],
                                    max_new_tokens=R_MAX_NEW)
                if r.error is not None:
                    failures.append((worker, r.error))
            except Exception as e:  # queue-full is a failure too:
                # capacity must stay >= N-1 replicas throughout
                failures.append((worker, repr(e)))
            pumped[worker] += 1

    with server:
        server.warmup()
        r1 = server.generate([1, 2, 3], max_new_tokens=R_MAX_NEW,
                             keep_session=True)
        sid, v1_toks = r1.session_id, list(r1.tokens)
        compiles_before = sum(sum(r.engine.compile_counts.values())
                              for r in server.replicas)
        pumps = [threading.Thread(target=pump, args=(w,), daemon=True)
                 for w in range(R_PUMPS)]
        t0 = time.monotonic()
        for t in pumps:
            t.start()
        try:
            record = server.rollout.run_rollout("default", 2)
            canary_record = server.rollout.run_rollout("default", 3,
                                                       canary_every=1)
        finally:
            done.set()
            for t in pumps:
                t.join(timeout=60)
        traffic_wall_s = round(time.monotonic() - t0, 3)
        compiles_after = sum(sum(r.engine.compile_counts.values())
                             for r in server.replicas)
        cont = server.generate([v1_toks[-1]], max_new_tokens=R_MAX_NEW,
                               session_id=sid, keep_session=True)
        post = server.generate([1, 2, 3], max_new_tokens=R_MAX_NEW)
        versions = [r.engine.model_version for r in server.replicas]

    # the reference: the same conversation on ONE replica with an
    # in-place weight swap (no drain, no migration, no rollout) — the
    # rolling path must be indistinguishable token-for-token
    ref = _rollout_server(params, cfg, 1)
    with ref:
        ref.warmup()
        a = ref.generate([1, 2, 3], max_new_tokens=R_MAX_NEW,
                         keep_session=True)
        ref.engine.swap_model(jax.device_get(params_v2), version=2)
        b = ref.generate([a.tokens[-1]], max_new_tokens=R_MAX_NEW,
                         session_id=a.session_id, keep_session=True)
        c = ref.generate([1, 2, 3], max_new_tokens=R_MAX_NEW)

    canary = canary_record["canary"] or {}
    counts = canary.get("counts", {})
    phases_ok = all(
        p["outcome"] == "ok"
        for rec in (record, canary_record)
        for e in rec["replicas"] for p in e["phases"])
    gates = {
        "pass_zero_failed_requests": not failures,
        "pass_zero_mid_traffic_compiles":
            compiles_after == compiles_before,
        "pass_all_phases_ok": bool(
            phases_ok and record["outcome"] == "ok"
            and canary_record["outcome"] == "ok"),
        "pass_kept_session_token_identical":
            list(a.tokens) == v1_toks
            and list(cont.tokens) == list(b.tokens),
        "pass_post_rollout_new_version_tokens":
            list(post.tokens) == list(c.tokens),
        "pass_fleet_converged": all(v == 3 for v in versions),
        "pass_canary_match": bool(
            counts.get("compared", 0) >= R_CANARY_PAIRS
            and counts.get("diff", 1) == 0),
    }
    out = {
        "note": "serve_bench_r08 zero-downtime rolling reload gate "
                "(tools/bench_serve.py --rollout)",
        "config": {
            **R_CFG, "replicas": R_REPLICAS, "pump_threads": R_PUMPS,
            "max_new_tokens": R_MAX_NEW,
            "canary_min_pairs": R_CANARY_PAIRS,
            "platform": jax.devices()[0].platform,
        },
        "traffic": {
            "requests": sum(pumped), "failed": len(failures),
            "failures_sample": failures[:5],
            "wall_s": traffic_wall_s,
        },
        "mid_traffic_compiles": compiles_after - compiles_before,
        "rollout_v2": record,
        "rollout_v3_canary": canary_record,
        "canary_report": canary,
        "fleet_versions": versions,
        **gates,
    }
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps({
        "requests_during_rollouts": sum(pumped),
        "failed": len(failures),
        "mid_traffic_compiles": compiles_after - compiles_before,
        "canary_counts": counts,
        "fleet_versions": versions,
        **gates,
    }))
    print(f"bench_serve: report written to {out_path}")
    return 0 if all(gates.values()) else 1


# ---- speculative-vs-plain paired probe (--speculative; r10) -------------
#
# The ISSUE-18 acceptance arm: distill a draft from the probe teacher,
# then run the SAME closed-loop greedy decode workload through a
# speculative server (draft attached, spec ladder live) and a plain one,
# paired back-to-back so ambient CPU load drift cancels in the ratio
# (the tiered probe's pairing discipline). Reported per run:
#
# - aggregate tokens/s both arms + the median pair ratio (HONEST on CPU:
#   like r05's interpreted-pallas ratio, the >= 1.0x speedup claim
#   belongs to tests_tpu/ where draft-vs-target step cost is real);
# - mean accepted draft tokens per live verify row (the
#   serve_spec_accept_len histogram the autotuner steers on);
# - draft-overhead fraction: 1 - plain_window_ms / spec_window_ms at the
#   top rung, both measured as device program latencies on the scratch
#   slot — the spec program runs the same K+1 teacher-forced target
#   steps as a (K+1)-token plain window, so the surplus is exactly the
#   draft propose + accept-latch work speculation adds.
#
# Gates: greedy outputs token-identical between arms (per prompt), zero
# mid-traffic compiles on the speculative server, spec windows actually
# dispatched, and the conditional throughput claim — whenever the
# measured per-emitted-token program cost predicts a speculative win
# (spec_ms / (mean_accept + 1) < plain_ms / (K + 1), with a 1.2x margin
# for loadgen host overhead), the measured ratio must be >= 1.0.

S_CFG = dict(vocab_size=89, hidden_size=128, num_layers=2)
S_SESSIONS = 4
S_PROMPT_LEN = 8
S_MAX_NEW = 64
S_REQS = 3
S_SPEC_LADDER = (2, 4)
S_DISTILL_STEPS = 600
S_DISTILL_BATCH = 16
S_DISTILL_SEQ = 32
S_PAIRS = 3               # (plain, spec) loadgen pairs; ratio = median
S_PARITY_PROMPTS = 4
S_ACCEPT_BUCKETS = (0.0, 1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0)


def _rollout_batches(params, cfg, batch: int, seq: int, seed: int = 0):
    """On-policy distillation stream: greedy TEACHER rollouts from
    uniform-random prompts. Decode-time contexts are the teacher's own
    continuations after the first few tokens, so training the draft on
    rollouts (not on uniform windows, where acceptance stays ~0) fits
    it exactly where the verify window will query it — the on-policy
    half of standard speculative-draft distillation."""
    from lstm_tensorspark_tpu.models import make_generate_fn

    gen = jax.jit(lambda p: make_generate_fn(
        cfg, max_new_tokens=seq, greedy=True)(params, p,
                                              jax.random.PRNGKey(0)))
    rng = np.random.default_rng(seed)
    while True:
        prompts = rng.integers(0, cfg.vocab_size,
                               size=(batch, S_PROMPT_LEN), dtype=np.int32)
        toks = np.asarray(gen(prompts))
        yield {"inputs": toks[:, :-1].astype(np.int32),
               "targets": toks[:, 1:].astype(np.int32)}


def _spec_server(params, cfg, draft):
    """One probe server; ``draft=(params, cfg)`` attaches the draft and
    turns speculation on, ``None`` builds the plain pair arm."""
    reg = MetricsRegistry()
    engine = ServeEngine(
        params, cfg, num_slots=16,
        prefill_buckets=(8, 16), batch_buckets=(1, 2, 4),
        prefix_cache=False, registry=reg,
    )
    kw = {}
    if draft is not None:
        engine.attach_draft(draft[0], draft[1], version=1)
        kw = {"speculative": True, "spec_ladder": S_SPEC_LADDER}
    server = ServeServer(engine, max_active=S_SESSIONS, queue_size=64,
                         window_ladder=(1, 4, 8), **kw)
    return server, reg


def _spec_program_ms(engine, k: int) -> tuple[float, float]:
    """Median device latency of (plain (K+1)-window, spec K-window) at
    the top batch bucket, scratch-slot rows — the apples-to-apples
    program pair behind the draft-overhead fraction."""
    scratch = engine.cache.scratch_slot
    bb = engine.batch_buckets[-1]
    sync = lambda: jax.block_until_ready(engine.cache.h)  # noqa: E731
    plain_ms = _program_latency_ms(
        lambda: engine.fetch_window(engine.decode_window(
            [scratch] * bb, [0] * bb, [k + 1] * bb, window=k + 1)),
        sync)
    spec_ms = _program_latency_ms(
        lambda: engine.fetch_window(engine.spec_window(
            [scratch] * bb, [0] * bb, [k + 1] * bb, k_draft=k)),
        sync)
    return plain_ms, spec_ms


def run_spec_bench(out_path: str) -> int:
    from lstm_tensorspark_tpu.train.distill import distill

    cfg = LMConfig(**S_CFG)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    print(f"bench_serve: distilling draft ({S_DISTILL_STEPS} steps, "
          "KL+CE)...", flush=True)
    dparams, dcfg = distill(
        params, cfg,
        _rollout_batches(params, cfg, S_DISTILL_BATCH, S_DISTILL_SEQ),
        num_steps=S_DISTILL_STEPS, log_every=0)

    spec_server, spec_reg = _spec_server(params, cfg, (dparams, dcfg))
    plain_server, _ = _spec_server(params, cfg, None)
    top_k = max(S_SPEC_LADDER)
    kw = dict(vocab_size=cfg.vocab_size, sessions=S_SESSIONS,
              requests_per_session=S_REQS, prompt_len=S_PROMPT_LEN,
              max_new_tokens=S_MAX_NEW)
    pairs, parity = [], []
    with spec_server, plain_server:
        spec_server.warmup(prompt_lens=(S_PROMPT_LEN,))
        plain_server.warmup(prompt_lens=(S_PROMPT_LEN,))

        print("bench_serve: greedy parity check "
              f"({S_PARITY_PROMPTS} prompts)...", flush=True)
        rng = np.random.default_rng(9)
        for _ in range(S_PARITY_PROMPTS):
            prompt = rng.integers(0, cfg.vocab_size,
                                  size=S_PROMPT_LEN).tolist()
            a = spec_server.generate(prompt, max_new_tokens=S_MAX_NEW)
            b = plain_server.generate(prompt, max_new_tokens=S_MAX_NEW)
            parity.append(a.error is None and b.error is None
                          and list(a.tokens) == list(b.tokens))

        compiles_before = dict(spec_server.engine.compile_counts)
        runs = []
        for n in range(S_PAIRS):
            print(f"bench_serve: paired run {n + 1}/{S_PAIRS} "
                  "(plain, then speculative)...", flush=True)
            p = run_loadgen(plain_server, seed=20 + n, **kw)
            s = run_loadgen(spec_server, seed=20 + n, **kw)
            runs.append({"plain": p, "spec": s})
            pairs.append(round(s["tokens_per_sec"]
                               / p["tokens_per_sec"], 4))
        mid_compiles = {
            k: v for k, v in spec_server.engine.compile_counts.items()
            if v != compiles_before.get(k, 0)}

        print("bench_serve: program-latency probe (plain vs spec "
              f"window, K={top_k})...", flush=True)
        plain_ms, spec_ms = _spec_program_ms(spec_server.engine, top_k)
        spec_stats = spec_server.batcher.stats()

    fam = spec_reg.histogram(
        "serve_spec_accept_len", "", labelnames=("replica",),
        buckets=S_ACCEPT_BUCKETS)
    accept, _ = fam.snapshot_delta(None)
    mean_accept = (round(accept["sum"] / accept["count"], 4)
                   if accept["count"] else None)
    ratio = sorted(pairs)[len(pairs) // 2]
    overhead_frac = (round(max(0.0, spec_ms - plain_ms) / spec_ms, 4)
                     if spec_ms else None)
    # the conditional claim: per-emitted-token program cost predicts a
    # win only when the spec window's cost amortizes over its accepted
    # run; 1.2x margin absorbs loadgen's host-side (non-program) share
    predicted_win = bool(
        mean_accept is not None
        and spec_ms * 1.2 / (mean_accept + 1) < plain_ms / (top_k + 1))
    gates = {
        "pass_token_identical": bool(parity and all(parity)),
        "pass_zero_mid_traffic_compiles": not mid_compiles,
        "pass_spec_windows_dispatched":
            sum(spec_stats["spec_windows_dispatched"].values()) > 0,
        "pass_ratio_when_predicted":
            (not predicted_win) or ratio >= 1.0,
    }
    platform = jax.devices()[0].platform
    out = {
        "note": "serve_bench_r10 speculative-vs-plain paired greedy "
                "decode (tools/bench_serve.py --speculative)",
        "config": {
            **S_CFG, "sessions": S_SESSIONS, "prompt_len": S_PROMPT_LEN,
            "max_new_tokens": S_MAX_NEW, "requests_per_session": S_REQS,
            "spec_ladder": list(S_SPEC_LADDER), "pairs": S_PAIRS,
            "distill_steps": S_DISTILL_STEPS,
            "draft": {"hidden_size": dcfg.hidden_size,
                      "num_layers": dcfg.num_layers},
            "platform": platform,
        },
        "runs": runs,
        "tokens_per_sec_plain": runs[-1]["plain"]["tokens_per_sec"],
        "tokens_per_sec_spec": runs[-1]["spec"]["tokens_per_sec"],
        "pair_ratios_spec_over_plain": pairs,
        "spec_over_plain_ratio": ratio,
        "mean_accepted_len": mean_accept,
        "accept_observations": accept["count"],
        "spec_windows_dispatched": spec_stats["spec_windows_dispatched"],
        "spec_accepted_tokens": spec_stats["spec_accepted_tokens"],
        "program_latency_ms": {"plain_window": plain_ms,
                               "spec_window": spec_ms,
                               "window_k": top_k},
        "draft_overhead_fraction": overhead_frac,
        "predicted_win": predicted_win,
        "mid_traffic_compiles": {str(k): v
                                 for k, v in mid_compiles.items()},
        # honesty marker, same protocol as r05/r06: CPU ratios price the
        # draft at interpreter-speed parity with the target — the
        # >= 1.0x claim is the tests_tpu/ hardware gate
        "cpu_ratio_honest": platform != "tpu",
        **gates,
    }
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps({
        "spec_over_plain_ratio": ratio,
        "mean_accepted_len": mean_accept,
        "draft_overhead_fraction": overhead_frac,
        "predicted_win": predicted_win,
        **gates,
    }))
    print(f"bench_serve: report written to {out_path}")
    return 0 if all(gates.values()) else 1


# ---- prefix-state fabric probe (--prefix-trie; BENCH_serve_r11) ---------
#
# The template-mix gate (ISSUE-19 / ROADMAP item 4): tenant preamble x
# few-shot template x unique suffix at fleet scale. Exact-match prefix
# caching needs a byte-identical stride-aligned re-prompt, so across 100
# distinct (tenant, template) pairs its 16-entry LRU thrashes and nearly
# every admission recomputes the shared 160 tokens; the radix trie keys
# nodes by token PATH — the first session of a pair warms its preamble+
# template prefix for every later sibling (and the preamble alone for
# every later template of that tenant). Paired arms, same workload, same
# seed: gate on >= 10x fewer prefill tokens actually computed, greedy
# token parity per session, zero mid-traffic compiles, and the spilled-
# node footprint within the configured host-tier byte bound.

T_CFG = dict(vocab_size=89, hidden_size=64, num_layers=2)
T_SESSIONS = 10_000
T_TENANTS = 4
T_TEMPLATES = 25          # 4 x 25 = 100 (tenant, template) pairs
T_PREAMBLE = 128
T_TEMPLATE = 32
T_SUFFIX = 8              # prompt = 168; boundary(168) = 160 = shared
T_STRIDE = 8
T_CHUNK = 32              # chunk stops = insert points at both depths
T_MAX_NEW = 4
T_WORKERS = 32
T_NODES = 160             # >= 100 pairs + per-tenant interior nodes
T_HOST_MB = 1.0           # state_bytes = 2*2*64*4 = 1 KiB; 160 KiB max
T_SLOTS = 96              # < stateful nodes: the spill plane must work


def _trie_arm(mode: str, sessions: int) -> dict:
    from lstm_tensorspark_tpu.serve.loadgen import run_template_mix

    cfg = LMConfig(**T_CFG)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    engine = ServeEngine(
        params, cfg, num_slots=T_SLOTS,
        prefill_buckets=(8, 16, 32, 64, 128, 256),
        batch_buckets=(1, 2, 4, 8, 16),
        prefix_cache=mode == "exact", prefix_fabric=mode == "trie",
        prefix_stride=T_STRIDE, prefix_entries=16,
        prefix_nodes=T_NODES, prefix_host_mb=T_HOST_MB,
        tiered_cache=True, host_tier_entries=512,
        registry=MetricsRegistry(),
    )
    server = ServeServer(engine, max_active=16, queue_size=64,
                         prefill_chunk=T_CHUNK)
    prompt_len = T_PREAMBLE + T_TEMPLATE + T_SUFFIX
    with server:
        server.warmup(prompt_lens=(prompt_len,))
        compiles_before = engine.num_compiles()
        report = run_template_mix(
            server, vocab_size=cfg.vocab_size, sessions=sessions,
            tenants=T_TENANTS, templates=T_TEMPLATES,
            preamble_len=T_PREAMBLE, template_len=T_TEMPLATE,
            suffix_len=T_SUFFIX, max_new_tokens=T_MAX_NEW,
            workers=T_WORKERS, seed=11, collect_tokens=True,
        )
        report["compiles_during_run"] = (engine.num_compiles()
                                         - compiles_before)
        report["prefix_stats_final"] = engine.prefix.stats()
    return report


def run_prefix_trie_bench(out_path: str, sessions: int = T_SESSIONS) -> int:
    print(f"bench_serve: template-mix arm (radix trie, {sessions} "
          "sessions)...", flush=True)
    trie = _trie_arm("trie", sessions)
    print(f"bench_serve: template-mix arm (exact-match, {sessions} "
          "sessions)...", flush=True)
    exact = _trie_arm("exact", sessions)

    # per-session greedy parity: identical prompts (same seed) must
    # decode identical tokens whether the prefill was trie-resumed,
    # exact-resumed, or cold
    t_tok = trie.pop("tokens_by_session")
    e_tok = exact.pop("tokens_by_session")
    compared = [i for i in t_tok if i in e_tok]
    mismatches = [i for i in compared if t_tok[i] != e_tok[i]]

    t_computed = trie["prefill"]["tokens_computed"]
    e_computed = exact["prefill"]["tokens_computed"]
    ratio = round(e_computed / t_computed, 3) if t_computed else None
    ts = trie["prefix_stats_final"]
    gates = {
        "pass_compute_drop_10x": bool(ratio is not None and ratio >= 10.0),
        "pass_token_identical": (not mismatches
                                 and len(compared) == len(t_tok) > 0),
        "pass_zero_mid_traffic_compiles":
            trie["compiles_during_run"] == 0
            and exact["compiles_during_run"] == 0,
        "pass_host_bound_held":
            ts["spilled_bytes"] <= ts["host_bytes"]
            and ts["entries"] <= T_NODES,
    }
    out = {
        "note": "serve_bench_r11 prefix-state fabric: radix-trie vs "
                "exact-match prefix store on the template-mix workload "
                "(tools/bench_serve.py --prefix-trie)",
        "config": {
            **T_CFG, "sessions": sessions, "tenants": T_TENANTS,
            "templates_per_tenant": T_TEMPLATES,
            "preamble_len": T_PREAMBLE, "template_len": T_TEMPLATE,
            "suffix_len": T_SUFFIX, "stride": T_STRIDE,
            "prefill_chunk": T_CHUNK, "max_new_tokens": T_MAX_NEW,
            "workers": T_WORKERS, "num_slots": T_SLOTS,
            "prefix_nodes": T_NODES, "prefix_host_mb": T_HOST_MB,
            "platform": jax.devices()[0].platform,
        },
        "trie": trie,
        "exact": exact,
        "prefill_tokens_computed": {"trie": t_computed,
                                    "exact": e_computed},
        "compute_drop_ratio": ratio,
        "parity_sessions_compared": len(compared),
        "parity_mismatches": len(mismatches),
        **gates,
    }
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps({
        "compute_drop_ratio": ratio,
        "tokens_computed_trie": t_computed,
        "tokens_computed_exact": e_computed,
        "trie_hit_rate": trie["prefix_cache"]["hit_rate"],
        "exact_hit_rate": exact["prefix_cache"]["hit_rate"],
        **gates,
    }))
    print(f"bench_serve: report written to {out_path}")
    return 0 if all(gates.values()) else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=None,
                    help="report path (default BENCH_serve_r01.json, "
                         "BENCH_serve_r02.json with --replicas, or "
                         "BENCH_serve_r03.json with --tiered-cache)")
    ap.add_argument("--replicas", default=None,
                    help="comma list (e.g. 1,2): run the data-parallel "
                         "replica scaling probe instead of the r01 "
                         "prefix/ITL probes")
    ap.add_argument("--tiered-cache", default=None,
                    help="comma list of modes (e.g. on,off): run the "
                         "long-tail tiered session-state probe instead "
                         "('on' runs the paired all-on-device-vs-tiered "
                         "gate; 'off' adds the re-prefill contrast; "
                         "writes BENCH_serve_r03.json)")
    ap.add_argument("--mesh-shards", default=None,
                    help="comma list of shard counts (e.g. 1,2): run the "
                         "tensor-parallel mesh probe on virtual devices "
                         "— aggregate tokens/s + TTFT/ITL per shard "
                         "count, honest CPU ratio, greedy cross-config "
                         "parity + warmup-asserted zero mid-traffic "
                         "compiles; writes BENCH_serve_r06.json")
    ap.add_argument("--autotune", action="store_true",
                    help="run the online-autotuner two-phase probe: an "
                         "ITL-bound long-decode phase then a TTFT-bound "
                         "burst phase on one live server, frozen "
                         "mid-ladder vs controller-live arms (paired "
                         "runs), gating on >= 2 knobs moved, phase-B "
                         "TTFT p99 >= 5% better, zero mid-traffic "
                         "compiles, and the PR 10 4x-burst gate with "
                         "the controller on; writes BENCH_serve_r07.json")
    ap.add_argument("--rollout", action="store_true",
                    help="run the zero-downtime rolling-reload gate: a "
                         "2-replica fleet rolls registry v1 -> v2 under "
                         "continuous load, then v2 -> v3 (identical "
                         "bytes) with the canary shadow compare live — "
                         "zero failed requests, zero mid-traffic "
                         "compiles, kept-session continuations token-"
                         "identical to an in-place-swap reference, "
                         "canary reports 0 diffs; writes "
                         "BENCH_serve_r08.json")
    ap.add_argument("--speculative", action="store_true",
                    help="run the speculative-vs-plain paired probe: "
                         "distill a draft from the probe teacher, then "
                         "the same closed-loop greedy workload through a "
                         "speculative and a plain server back-to-back — "
                         "tokens/s ratio (honest on CPU), mean accepted "
                         "draft tokens per verify row, draft-overhead "
                         "fraction from paired program latencies, greedy "
                         "parity, zero mid-traffic compiles; writes "
                         "BENCH_serve_r10.json")
    ap.add_argument("--prefix-trie", action="store_true",
                    help="run the prefix-state fabric probe: the paired "
                         "template-mix workload (tenant preamble x few-"
                         "shot template x unique suffix, 10k sessions "
                         "over 100 pairs) through a radix-trie and an "
                         "exact-match prefix store — gating on >= 10x "
                         "fewer prefill tokens computed, greedy token "
                         "parity, zero mid-traffic compiles, and the "
                         "spilled-node footprint within the host-tier "
                         "byte bound; writes BENCH_serve_r11.json")
    ap.add_argument("--trie-sessions", type=int, default=T_SESSIONS,
                    help="--prefix-trie: session count (the gate's "
                         "population; smaller for a quick sanity run)")
    ap.add_argument("--decode-kernel", default=None,
                    help="comma list of kernels (e.g. pallas,scan): run "
                         "the decode-kernel comparison (tokens/s + ITL "
                         "deltas + greedy parity; pallas is interpreter-"
                         "mode on CPU, recorded honestly) PLUS the "
                         "tier-overhead re-gate on the batched admission "
                         "fill path; writes BENCH_serve_r05.json")
    args = ap.parse_args(argv)

    if args.replicas:
        levels = tuple(int(x) for x in args.replicas.split(",") if x.strip())
        out_path = args.out or os.path.join(_REPO, "BENCH_serve_r02.json")
        return run_replica_bench(levels, out_path)
    if args.tiered_cache:
        modes = tuple(m.strip() for m in args.tiered_cache.split(",")
                      if m.strip())
        bad = [m for m in modes if m not in ("on", "off")]
        if bad:
            ap.error(f"--tiered-cache modes must be on/off, got {bad}")
        out_path = args.out or os.path.join(_REPO, "BENCH_serve_r03.json")
        return run_tiered_bench(modes, out_path)
    if args.mesh_shards:
        try:
            levels = tuple(int(x) for x in args.mesh_shards.split(",")
                           if x.strip())
        except ValueError:
            ap.error(f"--mesh-shards must be ints, got {args.mesh_shards!r}")
        out_path = args.out or os.path.join(_REPO, "BENCH_serve_r06.json")
        return run_mesh_bench(levels, out_path)
    if args.autotune:
        out_path = args.out or os.path.join(_REPO, "BENCH_serve_r07.json")
        return run_autotune_bench(out_path)
    if args.rollout:
        out_path = args.out or os.path.join(_REPO, "BENCH_serve_r08.json")
        return run_rollout_bench(out_path)
    if args.speculative:
        out_path = args.out or os.path.join(_REPO, "BENCH_serve_r10.json")
        return run_spec_bench(out_path)
    if args.prefix_trie:
        out_path = args.out or os.path.join(_REPO, "BENCH_serve_r11.json")
        return run_prefix_trie_bench(out_path, sessions=args.trie_sessions)
    if args.decode_kernel:
        kernels = tuple(k.strip() for k in args.decode_kernel.split(",")
                        if k.strip())
        bad = [k for k in kernels if k not in ("pallas", "scan")]
        if bad:
            ap.error(f"--decode-kernel kernels must be pallas/scan, "
                     f"got {bad}")
        out_path = args.out or os.path.join(_REPO, "BENCH_serve_r05.json")
        return run_decode_kernel_bench(kernels, out_path)
    args.out = args.out or os.path.join(_REPO, "BENCH_serve_r01.json")

    print("bench_serve: TTFT probe (prefix cache on, hot)...", flush=True)
    on = ttft_run(prefix_cache=True)
    print("bench_serve: TTFT probe (prefix cache off)...", flush=True)
    off = ttft_run(prefix_cache=False)
    speedup = round(off["p50_ttft_ms"] / on["p50_ttft_ms"], 3) \
        if on["p50_ttft_ms"] else float("nan")

    print("bench_serve: prefill-stall latency probe...", flush=True)
    chunk_ms, full_ms = stall_latencies_ms()
    print("bench_serve: ITL probe (chunked, no injection)...", flush=True)
    base = itl_run(CHUNK, inject=False)
    print("bench_serve: ITL probe (chunked + max-bucket injection)...",
          flush=True)
    inj = itl_run(CHUNK, inject=True)
    print("bench_serve: ITL probe (unchunked + injection, for contrast)...",
          flush=True)
    inj_mono = itl_run(None, inject=True)

    regression_ms = round(inj["p99_itl_ms"] - base["p99_itl_ms"], 3)
    max_regression_ms = round(inj["max_itl_ms"] - base["max_itl_ms"], 3)
    # one chunk's latency is the design bound; 2x allows CPU scheduling
    # noise on a shared host (the GIL-threaded loadgen is not an RTOS)
    bound_ms = round(2 * chunk_ms, 3)
    out = {
        "note": "serve_bench_r01 (tools/bench_serve.py)",
        "config": {
            **CFG, "sessions": SESSIONS, "prompt_len": PROMPT_LEN,
            "shared_prefix_len": SHARED_LEN, "prefix_stride": STRIDE,
            "prefill_chunk": CHUNK, "inject_prompt_len": INJECT_LEN,
            "decode_prompt_len": DECODE_PROMPT_LEN, "max_new_tokens": MAX_NEW,
            "requests_per_session": REQS, "itl_sessions": ITL_SESSIONS,
            "itl_repeats": ITL_REPEATS, "itl_requests_per_session": REQS_ITL,
            "platform": jax.devices()[0].platform,
        },
        "ttft_shared_prefix": {
            "cache_on_hot": on,
            "cache_off": off,
            "p50_speedup": speedup,
            "pass_1p5x": bool(speedup >= 1.5),
        },
        "itl_injection": {
            "chunk_latency_ms": chunk_ms,
            "monolithic_prefill_latency_ms": full_ms,
            "stall_reduction": round(full_ms / chunk_ms, 3) if chunk_ms else None,
            "chunked_baseline": base,
            "chunked_injected": inj,
            "unchunked_injected": inj_mono,
            "p99_itl_regression_ms": regression_ms,
            "max_itl_regression_ms": max_regression_ms,
            "bound_ms": bound_ms,
            "pass_bounded": bool(regression_ms <= bound_ms),
        },
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
        f.write("\n")
    print(json.dumps({
        "ttft_p50_on_ms": on["p50_ttft_ms"], "ttft_p50_off_ms": off["p50_ttft_ms"],
        "ttft_speedup": speedup,
        "itl_p99_base_ms": base["p99_itl_ms"], "itl_p99_inject_ms": inj["p99_itl_ms"],
        "itl_p99_inject_unchunked_ms": inj_mono["p99_itl_ms"],
        "chunk_latency_ms": chunk_ms, "monolithic_prefill_ms": full_ms,
        "pass_ttft": speedup >= 1.5,
        "pass_itl": regression_ms <= bound_ms,
    }))
    print(f"bench_serve: report written to {args.out}")
    return 0 if (speedup >= 1.5 and regression_ms <= bound_ms) else 1


if __name__ == "__main__":
    raise SystemExit(main())
