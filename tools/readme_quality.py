#!/usr/bin/env python3
"""Regenerate README.md's wall-clock-to-quality table from
BASELINE_MEASURED.json — the quality-section counterpart of
tools/readme_table.py (the perf-prose staleness the r3/r4 verdicts
flagged twice). Mechanical from here on:

    python3 tools/readme_quality.py          # rewrite README.md in place
    python3 tools/readme_quality.py --check  # exit 1 if README is stale

The generator owns ONLY the table block between the quality-table header
and the first non-table line (surrounding prose stays hand-written). A
config whose entry carries the r5 ``invalidated`` marker (task changed,
TPU leg not yet re-measured) renders an honest pending row built from
its banked CPU curve instead of a cross-task speedup.
"""

import argparse
import json
import os
import re
import sys

_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
README = os.path.join(_DIR, "README.md")
CACHE = os.path.join(_DIR, "BASELINE_MEASURED.json")

_LABELS = {
    "config1_ptb_char": "1 — PTB char 1×128",
    "config2_imdb": "2 — IMDB bi-LSTM 256",
    "config3_wikitext2": "3 — WikiText-2 2×650",
    "config4_uci": "4 — UCI seq2seq 2×256",
    "config5_wikitext103": "5 — WT-103 4×1024",
}

_METRICS = {
    "eval_ppl": ("ppl", "≤"),
    "eval_accuracy": ("accuracy", "≥"),
    "eval_mse": ("free-run MSE", "≤"),
}


def _fmt_target(metric: str, target: float) -> str:
    name, cmp = _METRICS.get(metric, (metric, "@"))
    t = f"{target:g}"
    return f"{name} {cmp} {t}"


def _cpu_reached(entry: dict):
    """(target, seconds) at the tightest target the banked CPU leg
    reached, for pending rows. Target keys preserve insertion order =
    loosest → tightest (bench_quality CONFIGS orders them that way)."""
    targets = (entry.get("cpu") or {}).get("targets") or {}
    if not targets:
        return None
    tight = list(targets)[-1]
    return tight, targets[tight]["t"]


def _vintage(entry: dict) -> str:
    """Both legs' measurement dates when they differ — a row combining a
    fresh TPU leg with an older banked CPU leg must say so."""
    tv = entry.get("tpu_measured_at")
    cv = entry.get("cpu_measured_at")
    if tv and cv and tv != cv:
        return f" (tpu {tv}, cpu {cv})"
    if tv or cv:
        return f" ({tv or cv})"
    return ""


def render(results: dict) -> str:
    rows = [
        "| Config | Metric @ target | TPU | CPU "
        "| Speedup (incl. compile / post-compile / warm) |",
        "|---|---|---|---|---|",
    ]
    for name, label in _LABELS.items():
        entry = results.get(name) or {}
        metric = entry.get("metric", "?")
        summary = entry.get("summary")
        invalidated = "invalidated" in entry
        # the marker is authoritative: a stale cross-task summary must
        # never render as a measured row just because the key survived
        if invalidated or not isinstance(summary, dict):
            reached = _cpu_reached(entry)
            cpu_s = "—"
            if reached:
                tight, secs = reached
                cpu_s = f"{secs:.1f} s to {_fmt_target(metric, float(tight))}"
                when = entry.get("cpu_measured_at")
                if when:
                    cpu_s += f" (banked {when})"
            state = ("*TPU leg pending chip recovery*" if invalidated
                     else "*no common target reached*")
            task = "(new task)" if invalidated else "—"
            rows.append(f"| {label} | {task} | {state} | {cpu_s} | — |")
            continue
        # measured row: cold and warm halves are EACH optional (a
        # warm-only summary is legal — bench_quality's _summarize builds
        # it when only the warm legs share a common target)
        target = summary.get("target", summary.get("warm_target"))
        target_s = (_fmt_target(metric, target) if target is not None
                    else "—")
        cold = "target" in summary
        tpu_s = f"{summary['tpu_seconds']:.1f} s" if cold else "—"
        cpu_s = f"{summary['cpu_seconds']:.1f} s" if cold else "—"
        if cold:
            speed = (f"{summary['speedup']:.1f}× / "
                     f"**{summary['speedup_train']:.1f}×**")
        else:
            speed = "— / —"
        warm = summary.get("speedup_warm")
        speed += (f" / {warm:.1f}×" if isinstance(warm, (int, float))
                  else " / —")
        speed += _vintage(entry)
        rows.append(f"| {label} | {target_s} | {tpu_s} | {cpu_s} "
                    f"| {speed} |")
    return "\n".join(rows)


_BLOCK = re.compile(
    r"(\| Config \| Metric @ target \| TPU \| CPU \|[^\n]*\|\n)(?:\|.*\n)+"
)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--check", action="store_true",
                    help="exit 1 if README's quality table is stale")
    args = ap.parse_args()

    with open(CACHE) as f:
        results = json.load(f)["quality"]["results"]
    with open(README) as f:
        readme = f.read()
    m = _BLOCK.search(readme)
    if not m:
        print("README quality-table block not found (markers changed?)",
              file=sys.stderr)
        return 2
    new_block = render(results) + "\n"
    if readme[m.start():m.end()] == new_block:
        print("README quality table is in sync with BASELINE_MEASURED.json")
        return 0
    if args.check:
        print("README quality table is STALE vs BASELINE_MEASURED.json "
              "(run tools/readme_quality.py)", file=sys.stderr)
        return 1
    with open(README, "w") as f:
        f.write(readme[:m.start()] + new_block + readme[m.end():])
    print("README quality table regenerated from BASELINE_MEASURED.json")
    return 0


if __name__ == "__main__":
    sys.exit(main())
