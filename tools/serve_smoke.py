#!/usr/bin/env python
"""Serve smoke: boot the real `cli serve --http --replicas 2` subprocess,
hit /healthz (per-replica fan-in) + /v1/generate (router-stamped replica)
+ /stats (router + per-replica sections) + /metrics, and validate the
Prometheus exposition parses (obs.parse_exposition — the same validator
the tests use, so the wire contract is checked by the exact code that
defines it) including the `replica` label on the serve families.

Run by tools/verify.sh after the tier-1 gate. CPU, tiny model, pinned
--decode-window 1 and two prefill buckets to keep the warmup lattice
(compiled once PER replica) to a few seconds. Exit 0 on PASS, 1 on any
failure, with the child's output replayed on failure for diagnosis.

Usage::

    JAX_PLATFORMS=cpu python tools/serve_smoke.py [--timeout 180]
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
import threading
import time
import urllib.request

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)
sys.path.insert(0, _REPO)

from lstm_tensorspark_tpu.obs import parse_exposition  # noqa: E402

_REPLICAS = 2
_SERVE_ARGS = [
    "serve", "--http", "--port", "0", "--vocab-size", "31",
    "--hidden-units", "12", "--num-layers", "1",
    "--prefill-buckets", "4,8", "--batch-buckets", "1,2",
    "--decode-window", "1", "--prefix-cache", "off",
    "--replicas", str(_REPLICAS),
]


def _fail(proc: subprocess.Popen, lines: list[str], why: str) -> int:
    print(f"serve_smoke: FAIL — {why}", file=sys.stderr)
    print("---- child output ----", file=sys.stderr)
    print("".join(lines), file=sys.stderr)
    proc.terminate()
    return 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--timeout", type=float, default=180.0,
                    help="seconds to wait for the server to come up "
                         "(covers the CPU warmup compiles)")
    args = ap.parse_args(argv)

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [sys.executable, "-m", "lstm_tensorspark_tpu.cli", *_SERVE_ARGS]
    proc = subprocess.Popen(cmd, cwd=_REPO, env=env, text=True,
                            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    lines: list[str] = []
    url: list[str] = []
    ready = threading.Event()

    def pump():
        for line in proc.stdout:
            lines.append(line)
            m = re.search(r"serving on (http://[\w.]+:\d+)", line)
            if m:
                url.append(m.group(1))
                ready.set()
        ready.set()  # EOF: unblock the waiter to report the death

    threading.Thread(target=pump, daemon=True).start()
    try:
        if not ready.wait(args.timeout) or not url:
            return _fail(proc, lines, "server never reported its address")
        base = url[0]

        with urllib.request.urlopen(base + "/healthz", timeout=30) as r:
            health = json.loads(r.read())
        if not health.get("ok") or health.get("status") != "ok":
            return _fail(proc, lines, f"unhealthy at boot: {health}")
        reps = health.get("replicas", [])
        if len(reps) != _REPLICAS or not all(x.get("ok") for x in reps):
            return _fail(proc, lines,
                         f"/healthz replica fan-in wrong: {reps}")

        body = json.dumps({"prompt": [1, 2, 3], "max_new_tokens": 4,
                           "greedy": True}).encode()
        req = urllib.request.Request(
            base + "/v1/generate", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as r:
            reply = json.loads(r.read())
        if len(reply.get("tokens", [])) != 4 or "phases_ms" not in reply:
            return _fail(proc, lines, f"bad generate reply: {reply}")
        if reply.get("replica") not in range(_REPLICAS):
            return _fail(proc, lines,
                         f"generate reply missing routed replica: {reply}")

        with urllib.request.urlopen(base + "/stats", timeout=30) as r:
            stats = json.loads(r.read())
        summ = stats.get("metrics", {})
        if summ.get("serve_ttft_seconds", {}).get("count", 0) < 1:
            return _fail(proc, lines,
                         f"/stats metrics missing TTFT summary: {summ}")
        router = stats.get("router", {})
        if (router.get("live") != _REPLICAS
                or sum(router.get("routed", {}).values()) < 1):
            return _fail(proc, lines, f"/stats router section wrong: {router}")
        if len(stats.get("replicas", [])) != _REPLICAS:
            return _fail(proc, lines,
                         "/stats missing per-replica sections: "
                         f"{list(stats)}")

        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            ctype = r.headers.get("Content-Type", "")
            text = r.read().decode()
        if not ctype.startswith("text/plain"):
            return _fail(proc, lines, f"bad /metrics content type {ctype!r}")
        try:
            fams = parse_exposition(text)
        except ValueError as e:
            return _fail(proc, lines, f"exposition invalid: {e}")
        for name in ("serve_ttft_seconds", "serve_itl_seconds",
                     "serve_queue_wait_seconds", "serve_compiles_total",
                     "serve_router_routed_total", "serve_replicas"):
            if name not in fams:
                return _fail(proc, lines, f"/metrics missing {name}")
        # every replica's scheduler exports its own labelled children
        seen = {labels.get("replica")
                for _, labels, _ in fams["serve_queue_depth"]["samples"]}
        want = {str(i) for i in range(_REPLICAS)}
        if not want <= seen:
            return _fail(proc, lines,
                         f"/metrics replica labels wrong: {seen} != {want}")

        print(f"serve_smoke: PASS ({base}: healthz fan-in ({len(reps)} "
              f"replicas) + routed generate + stats + {len(fams)} metric "
              "families validated)")
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
        return 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


if __name__ == "__main__":
    start = time.monotonic()
    rc = main()
    print(f"serve_smoke: done in {time.monotonic() - start:.1f}s rc={rc}",
          file=sys.stderr)
    raise SystemExit(rc)
