#!/usr/bin/env python
"""Serve smoke: boot the real `cli serve --http --replicas 2` subprocess,
hit /healthz (per-replica fan-in) + /v1/generate (router-stamped replica)
+ /stats (router + per-replica sections) + /metrics, and validate the
Prometheus exposition parses (obs.parse_exposition — the same validator
the tests use, so the wire contract is checked by the exact code that
defines it) including the `replica` label on the serve families.

Then the RESTART-WITH-SESSION-RESTORE drill (tiered cache, PR 8): a
kept session is created, its write-behind disk-tier checkpoint
(--session-dir) is awaited, the server is SIGKILLed (a real crash — no
graceful flush), a fresh server is booted on the same session dir, and
the pre-restart session's continuation must succeed from the disk tier
(without it, the continuation fails "unknown session").

Then the ROLLING-RELOAD drill (model registry + rollout controller,
PR 16): fresh weights are published into the restarted server's
--registry-dir from this process (exactly what `supervise
--registry-dir` does — a different process than the server), twice with
identical bytes (v1 and v2). The live 2-replica fleet is rolled v0 → v1
→ v2 over POST /rollout: both rollouts must converge with every phase
"ok", the identical-bytes versions must serve identical greedy tokens
(the parity oracle), /stats must report the fleet converged on v2, and
the disk-restored kept session must survive BOTH rolling swaps.

Then two single-replica kernel/topology boots, each required to serve
the SAME greedy tokens as the main boot: `--decode-kernel pallas`
(interpreter-mode fused window, PR 11) — which also runs with
`--autotune on` (PR 15: the controller thread must boot, tick without
errors, export its `/stats` section, and hold every knob still on a
quiet workload) — and `--mesh-shards 2` (the
tensor-parallel mesh engine on 2 VIRTUAL cpu devices via
XLA_FLAGS=--xla_force_host_platform_device_count — sharding must not
change a single token, and /metrics keeps its replica-labelled
families).

Then the SPECULATIVE boot (PR 18): a tiny random-init draft is
published as the verified (config-hash + parent-fingerprint) pair into
a fresh registry, the server boots `--speculative`, and the greedy
reply must be token-identical to the main boot — lossless by
construction even with an undistilled draft — with at least one spec
window actually dispatched (so parity can't pass with speculation
inert).

Run by tools/verify.sh after the tier-1 gate. CPU, tiny model, pinned
--decode-window 1 and two prefill buckets to keep the warmup lattice
(compiled once PER replica) to a few seconds. Exit 0 on PASS, 1 on any
failure, with the child's output replayed on failure for diagnosis.

Usage::

    JAX_PLATFORMS=cpu python tools/serve_smoke.py [--timeout 180]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.error
import urllib.request

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)
sys.path.insert(0, _REPO)

from lstm_tensorspark_tpu.obs import parse_exposition  # noqa: E402
from tools.serve_proc import boot_serve_http  # noqa: E402

_REPLICAS = 2
_SERVE_ARGS = [
    "serve", "--http", "--port", "0", "--vocab-size", "31",
    "--hidden-units", "12", "--num-layers", "1",
    "--prefill-buckets", "4,8", "--batch-buckets", "1,2",
    "--decode-window", "1", "--prefix-cache", "off",
    "--tiered-cache", "on",
    "--replicas", str(_REPLICAS),
]
# the pallas fallback boot: one replica, windowed ladder (so decode
# actually dispatches the fused window kernel), interpreter mode on CPU;
# tiers off to keep the extra boot to a couple of seconds. This boot
# also carries --autotune on (ISSUE-15): the controller thread must
# boot, tick, export its /stats section, and change NOTHING about a
# quiet workload — the token-parity assertion below doubles as the
# controller-live no-op guarantee (hysteresis: min_events gates every
# vote, so a smoke-sized trickle never moves a knob)
_PALLAS_ARGS = [
    "serve", "--http", "--port", "0", "--vocab-size", "31",
    "--hidden-units", "12", "--num-layers", "1",
    "--prefill-buckets", "4,8", "--batch-buckets", "1,2",
    "--decode-window", "4", "--prefix-cache", "off",
    "--tiered-cache", "off", "--decode-kernel", "pallas",
    "--replicas", "1", "--autotune", "on", "--slo-ms", "250",
]
# the mesh (tensor-parallel) boot: one replica whose engine shards H
# over 2 VIRTUAL cpu devices (XLA_FLAGS in _boot's env below) — the
# sharded engine must serve routed traffic token-identically to the
# single-device boots and export the same replica-labelled families
_MESH_SHARDS = 2
_MESH_ARGS = [
    "serve", "--http", "--port", "0", "--vocab-size", "31",
    "--hidden-units", "12", "--num-layers", "1",
    "--prefill-buckets", "4,8", "--batch-buckets", "1,2",
    "--decode-window", "4", "--prefix-cache", "off",
    "--tiered-cache", "off", "--mesh-shards", str(_MESH_SHARDS),
    "--replicas", "1",
]
# the speculative boot (ISSUE-18): one replica with a tiny RANDOM-init
# draft published as the verified pair (config_hash + parent teacher
# fingerprint) into a fresh registry — greedy speculative output is
# token-identical to plain decode BY CONSTRUCTION regardless of draft
# weights (the target verifies every token; draft quality only moves
# acceptance), so an undistilled fixture draft is exactly the right
# smoke: it exercises the propose/verify/rollback plane while the
# token-parity assertion below carries the whole correctness claim
_SPEC_ARGS = [
    "serve", "--http", "--port", "0", "--vocab-size", "31",
    "--hidden-units", "12", "--num-layers", "1",
    "--prefill-buckets", "4,8", "--batch-buckets", "1,2",
    "--decode-window", "4", "--prefix-cache", "off",
    "--tiered-cache", "off", "--replicas", "1",
    "--speculative", "--spec-ladder", "2",
]
# the prefix-fabric pair boot (ISSUE-19): host A runs the trie alone;
# host B boots with --remote-replica A, so B's propagator pushes every
# inserted trie node to A over POST /replica/prefix. One local replica
# and tiers off keep the two extra boots to a few seconds each.
_FABRIC_ARGS = [
    "serve", "--http", "--port", "0", "--vocab-size", "31",
    "--hidden-units", "12", "--num-layers", "1",
    # bucket 16 admits the 9-token preamble+suffix prompts below (the
    # 8-token preamble node inserts at the stride-8 split point)
    "--prefill-buckets", "4,8,16", "--batch-buckets", "1,2",
    "--decode-window", "1", "--prefix-fabric", "on",
    "--tiered-cache", "off", "--replicas", "1",
]


def _fail(proc: subprocess.Popen, lines: list[str], why: str) -> int:
    print(f"serve_smoke: FAIL — {why}", file=sys.stderr)
    print("---- child output ----", file=sys.stderr)
    print("".join(lines), file=sys.stderr)
    proc.terminate()
    return 1


def _boot(cmd, env, timeout):
    """Start a serve subprocess and wait for its address line
    (tools/serve_proc.py — the shared boot protocol). Returns
    (proc, lines, base-url-or-None)."""
    return boot_serve_http(cmd, env, timeout)


def _generate(base, body: dict, timeout=60):
    req = urllib.request.Request(
        base + "/v1/generate", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read())
    except urllib.error.HTTPError as e:
        # non-200 replies carry a JSON error body — return it so the
        # caller can report WHY instead of dying on the HTTPError
        try:
            return json.loads(e.read())
        except Exception:
            return {"error": f"HTTP {e.code}"}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--timeout", type=float, default=180.0,
                    help="seconds to wait for the server to come up "
                         "(covers the CPU warmup compiles)")
    args = ap.parse_args(argv)

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    session_dir = tempfile.mkdtemp(prefix="serve_smoke_sessions_")
    registry_dir = tempfile.mkdtemp(prefix="serve_smoke_registry_")
    cmd = [sys.executable, "-m", "lstm_tensorspark_tpu.cli", *_SERVE_ARGS,
           "--session-dir", session_dir, "--registry-dir", registry_dir]
    proc, lines, base = _boot(cmd, env, args.timeout)
    try:
        if base is None:
            return _fail(proc, lines, "server never reported its address")

        with urllib.request.urlopen(base + "/healthz", timeout=30) as r:
            health = json.loads(r.read())
        if not health.get("ok") or health.get("status") != "ok":
            return _fail(proc, lines, f"unhealthy at boot: {health}")
        reps = health.get("replicas", [])
        if len(reps) != _REPLICAS or not all(x.get("ok") for x in reps):
            return _fail(proc, lines,
                         f"/healthz replica fan-in wrong: {reps}")

        reply = _generate(base, {"prompt": [1, 2, 3], "max_new_tokens": 4,
                                 "greedy": True})
        if len(reply.get("tokens", [])) != 4 or "phases_ms" not in reply:
            return _fail(proc, lines, f"bad generate reply: {reply}")
        if reply.get("replica") not in range(_REPLICAS):
            return _fail(proc, lines,
                         f"generate reply missing routed replica: {reply}")

        with urllib.request.urlopen(base + "/stats", timeout=30) as r:
            stats = json.loads(r.read())
        summ = stats.get("metrics", {})
        if summ.get("serve_ttft_seconds", {}).get("count", 0) < 1:
            return _fail(proc, lines,
                         f"/stats metrics missing TTFT summary: {summ}")
        router = stats.get("router", {})
        if (router.get("live") != _REPLICAS
                or sum(router.get("routed", {}).values()) < 1):
            return _fail(proc, lines, f"/stats router section wrong: {router}")
        if len(stats.get("replicas", [])) != _REPLICAS:
            return _fail(proc, lines,
                         "/stats missing per-replica sections: "
                         f"{list(stats)}")

        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            ctype = r.headers.get("Content-Type", "")
            text = r.read().decode()
        if not ctype.startswith("text/plain"):
            return _fail(proc, lines, f"bad /metrics content type {ctype!r}")
        try:
            fams = parse_exposition(text)
        except ValueError as e:
            return _fail(proc, lines, f"exposition invalid: {e}")
        for name in ("serve_ttft_seconds", "serve_itl_seconds",
                     "serve_queue_wait_seconds", "serve_compiles_total",
                     "serve_router_routed_total", "serve_replicas"):
            if name not in fams:
                return _fail(proc, lines, f"/metrics missing {name}")
        # every replica's scheduler exports its own labelled children
        seen = {labels.get("replica")
                for _, labels, _ in fams["serve_queue_depth"]["samples"]}
        want = {str(i) for i in range(_REPLICAS)}
        if not want <= seen:
            return _fail(proc, lines,
                         f"/metrics replica labels wrong: {seen} != {want}")

        # ---- restart-with-session-restore drill (tiered cache) --------
        # a kept session, its disk-tier checkpoint awaited, then a REAL
        # crash (SIGKILL — no graceful flush) and a fresh server on the
        # same --session-dir: the continuation must succeed from disk
        kept = _generate(base, {"prompt": [1, 2, 3], "max_new_tokens": 4,
                                "greedy": True, "keep_session": True})
        sid = kept.get("session_id")
        if not sid or len(kept.get("tokens", [])) != 4:
            return _fail(proc, lines, f"bad keep_session reply: {kept}")
        deadline = time.monotonic() + 30
        while (not glob.glob(os.path.join(session_dir, "sess-*.state"))
               and time.monotonic() < deadline):
            time.sleep(0.2)  # write-behind checkpoint landing
        if not glob.glob(os.path.join(session_dir, "sess-*.state")):
            return _fail(proc, lines,
                         "no disk-tier session checkpoint appeared in "
                         f"{session_dir}")
        proc.kill()  # SIGKILL: a crash, not a shutdown
        proc.wait()

        proc, lines, base = _boot(cmd, env, args.timeout)
        if base is None:
            return _fail(proc, lines,
                         "restarted server never reported its address")
        cont = _generate(base, {"prompt": [kept["tokens"][-1]],
                                "max_new_tokens": 4, "greedy": True,
                                "session_id": sid, "keep_session": True})
        if "error" in cont or len(cont.get("tokens", [])) != 4:
            return _fail(proc, lines,
                         f"post-restart continuation of {sid!r} failed "
                         f"(disk tier restore): {cont}")

        # ---- rolling-reload drill (registry + rollout controller) -----
        # publish fresh weights into the live server's --registry-dir
        # from THIS process (the supervise publication path), as v1 and
        # again with IDENTICAL bytes as v2, then roll the fleet over
        # HTTP: v0 -> v1 proves convergence, v1 -> v2 proves token
        # parity (same bytes must serve the same tokens), and the
        # disk-restored kept session must survive both rolling swaps
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        import jax  # noqa: E402 — lazy: only this drill inits weights
        from flax import serialization  # noqa: E402

        from lstm_tensorspark_tpu.models import (  # noqa: E402
            LMConfig,
            init_lm,
        )
        from lstm_tensorspark_tpu.serve.registry import (  # noqa: E402
            ModelRegistry,
        )

        blob = serialization.to_bytes(jax.device_get(init_lm(
            jax.random.PRNGKey(9),
            LMConfig(vocab_size=31, hidden_size=12, num_layers=1))))
        reg = ModelRegistry(registry_dir)
        reg.publish("default", blob)  # v1: the new weights
        reg.publish("default", blob)  # v2: same bytes — parity oracle

        def _roll_to(version: int) -> dict | None:
            req = urllib.request.Request(
                base + "/rollout",
                data=json.dumps({"model": "default",
                                 "version": version}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as r:
                if r.status != 202:
                    return None
            deadline = time.monotonic() + 120
            while time.monotonic() < deadline:
                with urllib.request.urlopen(base + "/rollout",
                                            timeout=30) as r:
                    rs = json.loads(r.read())
                hist = [h for h in rs.get("history", [])
                        if h.get("kind") == "rollout"
                        and h.get("version") == version]
                if hist:
                    return hist[-1]
                if rs.get("last_error"):
                    # a move that died before its record was opened
                    # (e.g. the registry refused the version) never
                    # reaches history — fail fast instead of timing out
                    return {"outcome": f"error: {rs['last_error']}"}
                time.sleep(0.25)
            return None

        rec1 = _roll_to(1)
        if not rec1 or rec1.get("outcome") != "ok":
            return _fail(proc, lines,
                         f"rolling reload v0 -> v1 did not converge: "
                         f"{rec1}")
        v1_reply = _generate(base, {"prompt": [1, 2, 3],
                                    "max_new_tokens": 4, "greedy": True})
        rec2 = _roll_to(2)
        if not rec2 or rec2.get("outcome") != "ok":
            return _fail(proc, lines,
                         f"rolling reload v1 -> v2 did not converge: "
                         f"{rec2}")
        v2_reply = _generate(base, {"prompt": [1, 2, 3],
                                    "max_new_tokens": 4, "greedy": True})
        if (len(v2_reply.get("tokens", [])) != 4
                or v2_reply.get("tokens") != v1_reply.get("tokens")):
            return _fail(proc, lines,
                         "identical-bytes registry versions served "
                         f"different tokens: {v1_reply.get('tokens')} "
                         f"!= {v2_reply.get('tokens')}")
        with urllib.request.urlopen(base + "/stats", timeout=30) as r:
            rstats = json.loads(r.read())
        if rstats.get("models", {}).get("default") != {"2": _REPLICAS}:
            return _fail(proc, lines,
                         "/stats models not converged on v2: "
                         f"{rstats.get('models')}")
        cont2 = _generate(base, {"prompt": [cont["tokens"][-1]],
                                 "max_new_tokens": 4, "greedy": True,
                                 "session_id": sid})
        if "error" in cont2 or len(cont2.get("tokens", [])) != 4:
            return _fail(proc, lines,
                         f"kept session {sid!r} lost across the rolling "
                         f"reload: {cont2}")
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()

        # ---- pallas decode-kernel boot (interpreter-mode fallback) ----
        # one boot with --decode-kernel pallas: off-TPU the fused window
        # kernel runs interpreted (ops/pallas_decode.py) — this keeps
        # the fallback path from rotting in CI, and the greedy tokens
        # must be IDENTICAL to the scan-window reply above (same model
        # flags/seed — the kernel must not change a single token)
        scan_base = base  # the (now-killed) 2-replica scan server's URL
        pallas_cmd = [sys.executable, "-m", "lstm_tensorspark_tpu.cli",
                      *_PALLAS_ARGS]
        proc, lines, base = _boot(pallas_cmd, env, args.timeout)
        if base is None:
            return _fail(proc, lines,
                         "--decode-kernel pallas server never reported "
                         "its address")
        preply = _generate(base, {"prompt": [1, 2, 3], "max_new_tokens": 4,
                                  "greedy": True})
        if preply.get("tokens") != reply.get("tokens"):
            return _fail(proc, lines,
                         "pallas decode-window tokens diverge from the "
                         f"scan window: {preply.get('tokens')} != "
                         f"{reply.get('tokens')}")
        # the controller is LIVE on this boot: its thread must be
        # running and error-free, its /stats section exported, and the
        # knobs still at their boot positions (a quiet smoke workload
        # must never trip the hysteresis — the parity check above
        # already proved it changed no tokens)
        with urllib.request.urlopen(base + "/stats", timeout=30) as r:
            pstats = json.loads(r.read())
        at = pstats.get("autotune")
        if not at or not at.get("running"):
            return _fail(proc, lines,
                         f"--autotune on but /stats autotune section "
                         f"missing or controller not running: {at}")
        if at.get("errors"):
            return _fail(proc, lines,
                         f"autotuner ticked with errors: "
                         f"{at.get('last_error')}")
        at_moves = sum(d for v in at["moves"].values() for d in v.values())
        if at_moves:
            return _fail(proc, lines,
                         f"autotuner moved knobs on a quiet smoke "
                         f"workload (hysteresis broken): {at['moves']}")
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()

        # ---- mesh (tensor-parallel) boot on 2 virtual devices ---------
        # the sharded engine behind the router: routed generate must be
        # token-identical to the single-device boots, and /metrics must
        # keep the replica-labelled serve families
        mesh_env = dict(env)
        mesh_env["XLA_FLAGS"] = (
            mesh_env.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={_MESH_SHARDS}"
        ).strip()
        mesh_cmd = [sys.executable, "-m", "lstm_tensorspark_tpu.cli",
                    *_MESH_ARGS]
        proc, lines, base = _boot(mesh_cmd, mesh_env, args.timeout)
        if base is None:
            return _fail(proc, lines,
                         "--mesh-shards server never reported its address")
        mreply = _generate(base, {"prompt": [1, 2, 3], "max_new_tokens": 4,
                                  "greedy": True})
        if mreply.get("tokens") != reply.get("tokens"):
            return _fail(proc, lines,
                         f"{_MESH_SHARDS}-shard mesh engine tokens "
                         f"diverge from the single-device engine: "
                         f"{mreply.get('tokens')} != {reply.get('tokens')}")
        if mreply.get("replica") != 0:
            return _fail(proc, lines,
                         f"mesh generate reply missing routed replica: "
                         f"{mreply}")
        with urllib.request.urlopen(base + "/stats", timeout=30) as r:
            mstats = json.loads(r.read())
        if mstats.get("mesh_shards") != _MESH_SHARDS:
            return _fail(proc, lines,
                         f"/stats mesh_shards wrong: "
                         f"{mstats.get('mesh_shards')}")
        with urllib.request.urlopen(base + "/metrics", timeout=30) as r:
            mtext = r.read().decode()
        try:
            mfams = parse_exposition(mtext)
        except ValueError as e:
            return _fail(proc, lines, f"mesh exposition invalid: {e}")
        mseen = {labels.get("replica")
                 for _, labels, _ in mfams["serve_queue_depth"]["samples"]}
        if "0" not in mseen:
            return _fail(proc, lines,
                         f"mesh /metrics replica labels wrong: {mseen}")
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()

        # ---- speculative boot (draft fixture pair) --------------------
        # publish the fixture draft into a FRESH registry as the
        # verified pair for this smoke's teacher config, boot
        # --speculative, and require token-identical greedy output to
        # the main boot plus at least one spec window actually
        # dispatched (parity alone could pass with speculation inert)
        from lstm_tensorspark_tpu.train.distill import (  # noqa: E402
            draft_config,
            publish_draft,
        )

        tcfg = LMConfig(vocab_size=31, hidden_size=12, num_layers=1)
        dcfg = draft_config(tcfg)
        spec_registry = tempfile.mkdtemp(prefix="serve_smoke_specreg_")
        publish_draft(spec_registry,
                      jax.device_get(init_lm(jax.random.PRNGKey(5), dcfg)),
                      dcfg, tcfg, teacher_id="default")
        spec_cmd = [sys.executable, "-m", "lstm_tensorspark_tpu.cli",
                    *_SPEC_ARGS, "--registry-dir", spec_registry]
        proc, lines, base = _boot(spec_cmd, env, args.timeout)
        if base is None:
            return _fail(proc, lines,
                         "--speculative server never reported its address")
        sreply = _generate(base, {"prompt": [1, 2, 3], "max_new_tokens": 4,
                                  "greedy": True})
        if sreply.get("tokens") != reply.get("tokens"):
            return _fail(proc, lines,
                         "speculative greedy tokens diverge from plain "
                         f"decode: {sreply.get('tokens')} != "
                         f"{reply.get('tokens')}")
        with urllib.request.urlopen(base + "/stats", timeout=30) as r:
            sstats = json.loads(r.read())
        sb = (sstats.get("replicas") or [{}])[0].get("batcher", {})
        if not sb.get("speculative"):
            return _fail(proc, lines,
                         f"--speculative boot but batcher not "
                         f"speculative: {sb}")
        if sum(sb.get("spec_windows_dispatched", {}).values()) < 1:
            return _fail(proc, lines,
                         "speculative boot dispatched no spec windows "
                         f"(speculation inert): {sb}")
        spec_base = base
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()

        # ---- prefix-fabric replica pair (cross-host propagation) ------
        # host A boots the fabric alone; host B boots with
        # --remote-replica A. A cold long-prompt generate on B's LOCAL
        # replica inserts the 8-token preamble trie node and B's
        # propagator pushes it to A; A must then report the adoption
        # (propagated_in >= 1), serve a same-preamble prompt WARM (a
        # trie hit), token-identically to B's cold reply — and the
        # fabric boot must match the main boot on the parity prompt
        fabric_cmd = [sys.executable, "-m", "lstm_tensorspark_tpu.cli",
                      *_FABRIC_ARGS]
        proc_a, lines_a, base_a = _boot(fabric_cmd, env, args.timeout)
        try:
            if base_a is None:
                return _fail(proc_a, lines_a,
                             "--prefix-fabric host A never reported its "
                             "address")
            proc, lines, base = _boot(
                fabric_cmd + ["--remote-replica", base_a], env,
                args.timeout)
            if base is None:
                return _fail(proc, lines,
                             "--prefix-fabric host B never reported its "
                             "address")
            freply = _generate(base_a, {"prompt": [1, 2, 3],
                                        "max_new_tokens": 4,
                                        "greedy": True})
            if freply.get("tokens") != reply.get("tokens"):
                return _fail(proc, lines,
                             "--prefix-fabric tokens diverge from the "
                             f"main boot: {freply.get('tokens')} != "
                             f"{reply.get('tokens')}")
            # land the cold insert on B's LOCAL replica: with the remote
            # peer in B's router a request may route to A, which would
            # insert the preamble on A directly — so each attempt uses a
            # FRESH preamble, and only a locally-served one counts (its
            # node is then unknown to A and must arrive by propagation)
            cold = None
            for i in range(1, 7):
                pre = list(range(i, i + 8))
                r2 = _generate(base, {"prompt": pre + [29],
                                      "max_new_tokens": 4,
                                      "greedy": True})
                if r2.get("replica") == 0 and len(r2.get("tokens", [])) == 4:
                    cold = (pre, r2)
                    break
            if cold is None:
                return _fail(proc, lines,
                             "no fabric generate landed on host B's "
                             "local replica")
            pre, breply = cold

            def _a_prefix() -> dict:
                with urllib.request.urlopen(base_a + "/stats",
                                            timeout=30) as r:
                    a_stats = json.loads(r.read())
                return ((a_stats.get("replicas") or [a_stats])[0]
                        .get("prefix_cache") or {})

            deadline = time.monotonic() + 30
            a_px = _a_prefix()
            while (a_px.get("propagated_in", 0) < 1
                   and time.monotonic() < deadline):
                time.sleep(0.25)
                a_px = _a_prefix()
            if a_px.get("propagated_in", 0) < 1:
                return _fail(proc, lines,
                             "host A never adopted a propagated trie "
                             f"node: {a_px}")
            wreply = _generate(base_a, {"prompt": pre + [29],
                                        "max_new_tokens": 4,
                                        "greedy": True})
            if wreply.get("tokens") != breply.get("tokens"):
                return _fail(proc, lines,
                             "cross-replica warm generate diverges from "
                             f"the cold one: {wreply.get('tokens')} != "
                             f"{breply.get('tokens')}")
            a_px = _a_prefix()
            if a_px.get("hits", 0) < 1:
                return _fail(proc, lines,
                             "host A served the propagated preamble "
                             f"COLD (no trie hit): {a_px}")
        finally:
            proc_a.terminate()
            try:
                proc_a.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc_a.kill()

        print(f"serve_smoke: PASS ({scan_base}: healthz fan-in "
              f"({len(reps)} replicas) + routed generate + stats + "
              f"{len(fams)} metric families validated; kill -9 → restart "
              f"→ session {sid!r} continued from the disk tier; "
              "registry publish → v0→v1→v2 rolling reload converged "
              "token-identically with the kept session intact; "
              "--decode-kernel pallas + --autotune on boot "
              "token-identical with a quiet error-free controller; "
              f"{_MESH_SHARDS}-shard mesh boot token-identical "
              "with replica-labelled metrics; "
              f"{spec_base}: --speculative boot with a fixture draft "
              "pair token-identical with "
              f"{sum(sb['spec_windows_dispatched'].values())} spec "
              "windows dispatched; "
              f"--prefix-fabric pair {base} -> {base_a}: propagated "
              "trie node adopted cross-host with a warm token-identical "
              "hit)")
        proc.terminate()
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
        return 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()


if __name__ == "__main__":
    start = time.monotonic()
    rc = main()
    print(f"serve_smoke: done in {time.monotonic() - start:.1f}s rc={rc}",
          file=sys.stderr)
    raise SystemExit(rc)
