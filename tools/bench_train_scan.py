#!/usr/bin/env python
"""Training-perf trajectory, first datapoint: parallel-scan BPTT vs the
sequential chain on the T=400 bucket (ISSUE-13 acceptance; writes
BENCH_train_scan_r01.json).

One command, CPU-runnable, same discipline as tools/bench_serve.py:

- **paired runs** — each of ``--pairs`` trials times BOTH bptt modes
  back to back on the same jitted steps and data, so slow machine drift
  cancels inside a pair; the reported ratio is the median of the
  per-pair ratios;
- **warmup before any timing** — both (bucket, bptt_mode) programs go
  through `TrainStepCompileCache.warmup` (train/device_step.py), so no
  timed sample ever pays an XLA compile (the compile-key lattice is
  asserted warm afterwards);
- **grad-parity checksum** — one batch's gradients computed under both
  modes must be allclose at the fp64-validated tolerances from
  tests/test_parallel_scan.py; the report carries max-abs-diff and a
  grad-sum checksum so two bench runs can be diffed for numerical drift,
  and parity failure fails the tool (exit 1);
- **peak-memory estimate from the plan model** — `parallel_scan.
  plan_bytes` for the assoc working set (the number `bptt="auto"` gates
  on), next to the measured numbers.

The CPU ratio is an HONEST datapoint, not the gate: the assoc backward
trades O(H) extra dense-compose FLOPs for O(T/log T) less dependency
depth, which pays on a latency-bound accelerator chain and usually does
NOT on a throughput-bound CPU. The >= 1.0x gate lives in
tests_tpu/test_parallel_scan_tpu.py (real hardware).

Usage::

    JAX_PLATFORMS=cpu python tools/bench_train_scan.py \
        [--out BENCH_train_scan_r01.json] [--bptt-mode assoc,sequential]

Run it with nothing else executing (same discipline as the tier-1
suite: CPU contention corrupts latency percentiles).
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)
sys.path.insert(0, _REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

from lstm_tensorspark_tpu.models import LMConfig, init_lm  # noqa: E402
from lstm_tensorspark_tpu.models.lstm_lm import lm_loss  # noqa: E402
from lstm_tensorspark_tpu.ops import parallel_scan  # noqa: E402
from lstm_tensorspark_tpu.train import TrainStepCompileCache  # noqa: E402
from lstm_tensorspark_tpu.train.loop import (  # noqa: E402
    init_train_state,
    make_train_step,
)

# the T=400 IMDB bucket (ROADMAP open item 2(b)); H/B sized so the assoc
# plan fits the default budget AND a CPU pair finishes in seconds — the
# TPU gate (tests_tpu/) runs the H=128 shape
DEFAULTS = dict(vocab=89, hidden=64, layers=1, batch=16, seq=400)
STEPS_PER_RUN = 3
# grad-parity tolerances: fp64-validated in tests/test_parallel_scan.py
PARITY_TOL = dict(rtol=5e-4, atol=5e-5)


def _build_cache(dims):
    def builder(bucket, bptt_mode):
        _B, T, _H = bucket
        cfg = LMConfig(vocab_size=dims["vocab"], hidden_size=dims["hidden"],
                       num_layers=dims["layers"], bptt=bptt_mode)

        def loss_fn(params, batch, rng):
            return lm_loss(params, batch, cfg)

        return make_train_step(loss_fn, _OPT, jit=False)

    return TrainStepCompileCache(builder)


_OPT = optax.sgd(0.1)


def _batch(rng, dims):
    toks = rng.randint(0, dims["vocab"],
                       size=(dims["batch"], dims["seq"] + 1)).astype(np.int32)
    return {"inputs": jnp.asarray(toks[:, :-1]),
            "targets": jnp.asarray(toks[:, 1:])}


def _grad_parity(dims, batch):
    """One batch's grads under both modes: allclose + checksums."""
    out = {}
    for mode in ("sequential", "assoc"):
        cfg = LMConfig(vocab_size=dims["vocab"], hidden_size=dims["hidden"],
                       num_layers=dims["layers"], bptt=mode)
        grads = jax.grad(
            lambda p: lm_loss(p, batch, cfg)[0])(
                init_lm(jax.random.PRNGKey(0), cfg))
        out[mode] = [np.asarray(g, np.float64) for g in jax.tree.leaves(grads)]
    max_abs = max(
        float(np.max(np.abs(a - b)))
        for a, b in zip(out["assoc"], out["sequential"]))
    ok = all(
        np.allclose(a, b, **PARITY_TOL)
        for a, b in zip(out["assoc"], out["sequential"]))
    checksum = float(sum(np.sum(np.abs(g)) for g in out["assoc"]))
    return {"parity_ok": bool(ok), "max_abs_diff": max_abs,
            "grad_abs_checksum": round(checksum, 6),
            "tolerances": PARITY_TOL}


def run_bench(dims, modes, pairs, out_path):
    rng = np.random.RandomState(0)
    bucket = (dims["batch"], dims["seq"], dims["hidden"])
    cache = _build_cache(dims)
    cfg0 = LMConfig(vocab_size=dims["vocab"], hidden_size=dims["hidden"],
                    num_layers=dims["layers"])
    batch = _batch(rng, dims)
    states = {m: init_train_state(init_lm(jax.random.PRNGKey(1), cfg0), _OPT,
                                  jax.random.PRNGKey(2)) for m in modes}
    print(f"warmup: {len(modes)} train-step programs at bucket {bucket}",
          file=sys.stderr)
    cache.warmup([(bucket, m, states[m], batch) for m in modes])
    for m in modes:
        assert cache.compile_counts.get(("train_step", bucket, m)) == 1, (
            "warmup must have traced each program exactly once",
            cache.compile_counts)

    tokens = dims["batch"] * dims["seq"] * STEPS_PER_RUN
    per_mode = {m: {"tokens_per_sec": [], "step_seconds": []} for m in modes}
    pair_ratios = []
    for p in range(pairs):
        pair_tps = {}
        for m in modes:
            step = cache.step_fn(bucket, m)
            state = states[m]
            t0 = time.perf_counter()
            for _ in range(STEPS_PER_RUN):
                state, metrics = step(state, batch)
            jax.block_until_ready(metrics["loss"])
            dt = time.perf_counter() - t0
            per_mode[m]["tokens_per_sec"].append(tokens / dt)
            per_mode[m]["step_seconds"].append(dt / STEPS_PER_RUN)
            pair_tps[m] = tokens / dt
        if "assoc" in pair_tps and "sequential" in pair_tps:
            pair_ratios.append(pair_tps["assoc"] / pair_tps["sequential"])
        print(f"pair {p}: " + " ".join(
            f"{m}={pair_tps[m]:,.0f} tok/s" for m in modes), file=sys.stderr)

    # no mid-timing compiles: the counts warmup asserted must be unchanged
    for m in modes:
        assert cache.compile_counts.get(("train_step", bucket, m)) == 1, (
            "a program re-traced mid-timing", cache.compile_counts)

    parity = _grad_parity(dims, batch)
    tile = parallel_scan.pick_tile(dims["seq"])
    report = {
        "bench": "train_scan",
        "revision": "r01",
        "backend": jax.default_backend(),
        "config": {**dims, "steps_per_run": STEPS_PER_RUN, "pairs": pairs,
                   "compute_dtype": "float32"},
        "modes": {
            m: {
                "tokens_per_sec_median": statistics.median(
                    per_mode[m]["tokens_per_sec"]),
                "step_seconds_p50": statistics.median(
                    per_mode[m]["step_seconds"]),
            } for m in modes
        },
        "ratio_assoc_vs_sequential": (
            statistics.median(pair_ratios) if pair_ratios else None),
        "pair_ratios": pair_ratios,
        "plan": {
            "tile": tile,
            "n_chunks": dims["seq"] // tile,
            "assoc_plan_bytes": parallel_scan.plan_bytes(
                dims["batch"], dims["seq"], dims["hidden"]),
            "budget_bytes": parallel_scan._budget_bytes(),
            "fits": parallel_scan.plan_fits(
                dims["batch"], dims["seq"], dims["hidden"]),
        },
        "grad_parity": parity,
        "gate": {
            # the speed claim is the TPU gate's
            # (tests_tpu/test_parallel_scan_tpu.py >= 1.0x); the CPU
            # ratio is the honest trajectory datapoint — the assoc
            # backward spends O(H) extra FLOPs to cut dependency depth,
            # which a throughput-bound CPU does not reward
            "tpu_gate": "tests_tpu/test_parallel_scan_tpu.py (>= 1.0x)",
            "cpu_ratio_is_honest_datapoint": True,
            "parity_required": True,
        },
    }
    with open(out_path, "w") as fh:
        json.dump(report, fh, indent=1, sort_keys=True)
        fh.write("\n")
    ratio = report["ratio_assoc_vs_sequential"]
    ratio_s = "n/a (single mode)" if ratio is None else f"{ratio:.3f}x"
    print(f"wrote {out_path}: ratio assoc/sequential = "
          f"{ratio_s}, parity_ok={parity['parity_ok']} "
          f"(max_abs_diff={parity['max_abs_diff']:.2e})", file=sys.stderr)
    return 0 if parity["parity_ok"] else 1


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default=os.path.join(
        _REPO, "BENCH_train_scan_r01.json"))
    ap.add_argument("--bptt-mode", default="assoc,sequential",
                    help="comma list of modes to pair (default both)")
    ap.add_argument("--pairs", type=int, default=5)
    ap.add_argument("--hidden", type=int, default=DEFAULTS["hidden"])
    ap.add_argument("--batch", type=int, default=DEFAULTS["batch"])
    ap.add_argument("--seq", type=int, default=DEFAULTS["seq"])
    args = ap.parse_args(argv)
    modes = [m.strip() for m in args.bptt_mode.split(",") if m.strip()]
    for m in modes:
        if m not in ("assoc", "sequential"):
            ap.error(f"--bptt-mode entries must be assoc|sequential, got {m}")
    dims = dict(DEFAULTS, hidden=args.hidden, batch=args.batch, seq=args.seq)
    return run_bench(dims, modes, args.pairs, args.out)


if __name__ == "__main__":
    sys.exit(main())
