#!/usr/bin/env python3
"""Regenerate README.md's five-config performance table from
BENCH_TABLE.json — the manual tail of the chip-recovery queue that went
stale in round 3 (the README carried a pre-refresh 756k row against the
table's 796k headline). Mechanical from here on:

    python3 tools/readme_table.py          # rewrite README.md in place
    python3 tools/readme_table.py --check  # exit 1 if README is stale

The generator owns ONLY the table block between the markers below (the
surrounding prose stays hand-written); it emits the r4 bound column
(`fraction_of_impl_bound2` against max(serial-chain, bandwidth) when
present, else the r3 `fraction_of_bound`).
"""

import argparse
import json
import os
import re
import sys

_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
README = os.path.join(_DIR, "README.md")
TABLE = os.path.join(_DIR, "BENCH_TABLE.json")

_LABELS = {
    "ptb_char": ("1 — PTB char", lambda d: f"1×{d['H']}, V={d['V']}"),
    "imdb_bilstm": ("2 — IMDB bi-LSTM",
                    lambda d: f"1×2×{d['H']}, V={d['V'] // 1000}k"),
    "wikitext2": ("3 — WikiText-2 word",
                  lambda d: f"{d['L']}×{d['H']}, V={d['V']:,}"),
    "uci_seq2seq": ("4 — UCI seq2seq",
                    lambda d: f"{d['L']}×{d['H']}, F={d['F']}"),
    "wikitext103": ("5 — WikiText-103 word",
                    lambda d: f"{d['L']}×{d['H']}, V={d['V']:,}"),
}


def _fmt_rate(x: float) -> str:
    if x >= 1e6:
        return f"{x / 1e6:.2f}M"
    if x >= 10_000:
        return f"{x / 1e3:.1f}k"
    if x >= 1_000:
        return f"{x / 1e3:.2f}k"
    return f"{x:.0f}"


def _batch(d: dict, kind: str) -> str:
    if kind == "seq2seq":
        return f"{d['B']}×{d['T']}→{d['horizon']}"
    return f"{d['B']}×{d['T']}"


def _vintage(table: dict) -> str:
    """Measurement-provenance line (VERDICT r4 #8): when+where the table's
    numbers were captured, so every number in the block carries its
    vintage. Prefers the table's OWN captured_at/measured_at_commit stamp
    (bench.py writes it at measurement time — git history would attribute
    a fresh uncommitted table to the PREVIOUS measurement's commit); falls
    back to git history for pre-r5 tables without the stamp."""
    when = (table.get("captured_at") or "")[:10]
    commit = table.get("measured_at_commit")
    if not when:
        import subprocess

        try:
            rec = subprocess.run(
                ["git", "log", "-1", "--format=%h %cs", "--",
                 os.path.basename(TABLE)],
                capture_output=True, text=True, cwd=_DIR, timeout=30,
            ).stdout.split()
        except Exception:
            rec = []
        if len(rec) != 2:
            return ""
        commit, when = rec
    line = f"*Measured on one TPU v5 lite chip, {when}"
    if commit:
        line += f" (tree `{commit}`)"
    return line + ".*\n\n"


def render(table: dict) -> str:
    rows = [
        "| Config | Model | Batch | Throughput | Model FLOPs | MFU "
        "| of bound |",
        "|---|---|---|---|---|---|---|",
    ]
    best_mfu = max(
        (r.get("mfu_vs_bf16_peak", 0.0)
         for r in table["configs"].values() if "error" not in r),
        default=0.0,
    )
    for name, (label, model_fmt) in _LABELS.items():
        rec = table["configs"].get(name)
        if rec is None or "error" in rec:
            rows.append(f"| {label} | — | — | (not measured: "
                        f"{(rec or {}).get('error', 'missing')}) | — | — "
                        f"| — |")
            continue
        d = rec["dims"]
        rl = rec.get("roofline", {})
        frac = rl.get("fraction_of_impl_bound2",
                      rl.get("fraction_of_bound"))
        frac_s = f"{frac:.0%}" if isinstance(frac, (int, float)) else "—"
        binding = rl.get("bound_binding")
        if binding == "bandwidth":
            frac_s += " (bw)"
        mfu = rec["mfu_vs_bf16_peak"]
        mfu_s = f"**{mfu:.1%}**" if mfu == best_mfu else f"{mfu:.1%}"
        seq = _fmt_rate(rec["seq_per_sec"])
        tok = rec["tokens_per_sec"] / 1e6
        thr = f"{seq} seq/s · {tok:.2f} M tok/s"
        if name == "ptb_char":
            thr = f"**{thr}**"
        rows.append(
            f"| {label} | {model_fmt(d)} | {_batch(d, rec['kind'])} "
            f"| {thr} | {rec['model_tflops_per_sec']:.1f} TF/s "
            f"| {mfu_s} | {frac_s} |"
        )
    return "\n".join(rows)


_BLOCK = re.compile(
    r"(?:\*Measured on one TPU[^\n]*\n\n)?"
    r"(\| Config \| Model \| Batch \| Throughput \| Model FLOPs \| MFU "
    r"\| of bound \|\n)(?:\|.*\n)+"
)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--check", action="store_true",
                    help="exit 1 if README's table is stale, change nothing")
    args = ap.parse_args()

    with open(TABLE) as f:
        table = json.load(f)
    with open(README) as f:
        readme = f.read()
    m = _BLOCK.search(readme)
    if not m:
        print("README table block not found (markers changed?)",
              file=sys.stderr)
        return 2
    new_block = _vintage(table) + render(table) + "\n"
    if readme[m.start():m.end()] == new_block:
        print("README table is in sync with BENCH_TABLE.json")
        return 0
    if args.check:
        print("README table is STALE vs BENCH_TABLE.json "
              "(run tools/readme_table.py)", file=sys.stderr)
        return 1
    with open(README, "w") as f:
        f.write(readme[:m.start()] + new_block + readme[m.end():])
    print("README table regenerated from BENCH_TABLE.json")
    return 0


if __name__ == "__main__":
    sys.exit(main())
