"""Shared serve-subprocess boot protocol for the drills.

THE one copy of the boot-and-wait-for-address dance (spawn
`cli serve --http`, pump its output on a thread, match the
"serving on http://..." line): tools/serve_smoke.py,
tools/chaos_serve.py's host_die phase, and the 2-process kill drill in
tests/test_serve_mesh.py all boot real serve processes, and three
private copies of the same regex/pump/ready-event logic would drift
apart the first time the CLI's address line changes — the same reason
state_cache.session_file_path is module-level instead of re-derived."""

from __future__ import annotations

import os
import re
import subprocess
import threading

#: the CLI's address announcement (cli._serve_http) — the boot barrier
_ADDR_RE = re.compile(r"serving on (http://[\w.]+:\d+)")

#: children run `-m lstm_tensorspark_tpu.cli`, which resolves from the
#: repo root regardless of where the drill itself was invoked
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def boot_serve_http(cmd, env, timeout: float):
    """Spawn a serve subprocess and wait for its address line.

    Returns ``(proc, lines, url-or-None)`` — ``lines`` accumulates the
    child's combined output (keeps filling on the pump thread; the
    smoke replays it on failure), ``url`` is None when the child died
    or never announced within ``timeout`` (callers fail/raise with the
    captured output)."""
    proc = subprocess.Popen(cmd, cwd=_REPO, env=env, text=True,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT)
    lines: list[str] = []
    url: list[str] = []
    ready = threading.Event()

    def pump():
        for line in proc.stdout:
            lines.append(line)
            m = _ADDR_RE.search(line)
            if m:
                url.append(m.group(1))
                ready.set()
        ready.set()  # EOF: unblock the waiter to report the death

    threading.Thread(target=pump, daemon=True).start()
    if not ready.wait(timeout) or not url:
        return proc, lines, None
    return proc, lines, url[0]


def boot_serve_http_or_raise(cmd, env, timeout: float = 180.0):
    """:func:`boot_serve_http` that kills the child and raises (with
    its output) when the address never appears — the drill/test form."""
    proc, lines, url = boot_serve_http(cmd, env, timeout)
    if url is None:
        proc.kill()
        raise RuntimeError(
            "serve subprocess never reported its address:\n"
            + "".join(lines))
    return proc, url
