#!/usr/bin/env python3
"""One-command chaos drill: prove the crash→restart→resume path end to end.

Runs the SAME tiny CPU training job twice —

1. **baseline**: unsupervised, no faults;
2. **chaos**: under the supervisor with a scripted fault schedule
   (hard crash at ~2/3 of the budget + checkpoint corruption at the
   preceding save + a 2-step NaN-gradient burst + a data-batch exception)
   and the anomaly watchdog armed —

then asserts the chaos run (a) exits 0 despite every injected fault,
(b) reaches EXACTLY the full step budget, and (c) lands within a loss
tolerance of the baseline (the NaN-burst steps skip their updates, so
bit-identity is not expected; divergence is).

This is the ops acceptance drill from ISSUE 2 / docs/OPERATIONS.md's
failure-modes runbook — run it after touching the train loop, the
checkpointer or the supervisor:

    python tools/chaos_smoke.py [--steps 12] [--rtol 0.2] [--keep DIR]

Exit 0 on PASS, 1 on any violated assertion. Wired as a `-m slow` test
(tests/test_chaos_smoke.py) so it stays runnable but off the tier-1 hot
path; tests/test_chaos.py covers the individual fault classes fast.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _DIR not in sys.path:
    sys.path.insert(0, _DIR)


def _base_cli(steps: int, ckpt: str, jsonl: str) -> list[str]:
    return [
        "--dataset", "ptb_char", "--hidden-units", "16", "--num-layers", "1",
        "--batch-size", "8", "--seq-len", "16", "--backend", "single",
        "--num-steps", str(steps), "--log-every", "1",
        "--checkpoint-dir", ckpt, "--checkpoint-every", "2",
        "--jsonl", jsonl,
    ]


def _run(cmd: list[str], timeout: float) -> int:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, *cmd], cwd=_DIR, env=env,
                          timeout=timeout)
    return proc.returncode


def _final_record(jsonl: str) -> dict:
    with open(jsonl) as f:
        records = [json.loads(line) for line in f]
    finals = [r for r in records if r.get("note") == "final"]
    if not finals:
        raise AssertionError(f"no final record in {jsonl}")
    return finals[-1]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--steps", type=int, default=12,
                   help="total step budget per run (default 12)")
    p.add_argument("--rtol", type=float, default=0.2,
                   help="relative final-eval-loss tolerance chaos vs "
                        "baseline (default 0.2 — the NaN-burst steps skip "
                        "updates, so the runs are close, not identical)")
    p.add_argument("--timeout", type=float, default=600.0,
                   help="per-run wall-clock bound in seconds")
    p.add_argument("--keep", type=str, default=None,
                   help="keep the work dir at this path (default: tmp, "
                        "deleted on exit)")
    args = p.parse_args(argv)
    steps = args.steps
    if steps < 6:
        raise SystemExit("--steps must be >= 6 (the schedule needs room "
                         "for a crash after a completed checkpoint)")

    work = args.keep or tempfile.mkdtemp(prefix="chaos_smoke_")
    os.makedirs(work, exist_ok=True)
    failures = []
    try:
        # ---- baseline ------------------------------------------------
        base_jsonl = os.path.join(work, "baseline.jsonl")
        rc = _run(["-m", "lstm_tensorspark_tpu.cli",
                   *_base_cli(steps, os.path.join(work, "ckpt_base"),
                              base_jsonl)], args.timeout)
        if rc != 0:
            print(f"FAIL: baseline run exited {rc}")
            return 1
        base = _final_record(base_jsonl)

        # ---- chaos ---------------------------------------------------
        # crash after the checkpoint at 2/3 budget; corrupt THAT
        # checkpoint (restore must fall back one interval); NaN burst in
        # the first third; a data-batch exception in the final third.
        crash_at = 2 * steps // 3 + 1              # after the save below
        corrupt_at = (crash_at - 1) // 2 * 2       # latest ckpt before crash
        nan_at = max(steps // 4, 1)
        data_at = min(crash_at + 1, steps)
        schedule = (f"crash@{crash_at};ckpt_corrupt@{corrupt_at};"
                    f"nan_grads@{nan_at}x2;data_error@{data_at}")
        chaos_jsonl = os.path.join(work, "chaos.jsonl")
        print(f"chaos schedule: {schedule}", flush=True)
        rc = _run(["-m", "lstm_tensorspark_tpu.supervise",
                   "--max-restarts", "4", "--restart-delay", "0.1",
                   "--max-delay", "1", "--",
                   *_base_cli(steps, os.path.join(work, "ckpt_chaos"),
                              chaos_jsonl),
                   "--faults", schedule, "--anomaly-limit", "50"],
                  args.timeout)
        if rc != 0:
            print(f"FAIL: supervised chaos run exited {rc} (expected 0)")
            return 1
        chaos = _final_record(chaos_jsonl)

        # ---- parity --------------------------------------------------
        if chaos["step"] != steps:
            failures.append(f"chaos run final step {chaos['step']} != "
                            f"budget {steps}")
        if base["step"] != steps:
            failures.append(f"baseline final step {base['step']} != {steps}")
        bl, cl = base.get("eval_loss"), chaos.get("eval_loss")
        if bl is None or cl is None or not (bl == bl and cl == cl):
            failures.append(f"non-finite/missing eval losses: "
                            f"baseline={bl} chaos={cl}")
        elif abs(cl - bl) > args.rtol * abs(bl):
            failures.append(f"final eval loss diverged: baseline={bl:.4f} "
                            f"chaos={cl:.4f} (rtol {args.rtol})")
        # every fault class must actually have fired (one-shot markers)
        fired = set(os.listdir(os.path.join(work, "ckpt_chaos", ".faults")))
        for fid in (f"crash@{crash_at}", f"ckpt_corrupt@{corrupt_at}",
                    f"data_error@{data_at}"):
            if fid + ".fired" not in fired:
                failures.append(f"fault {fid} never fired")
        quarantined = [n for n in os.listdir(os.path.join(work, "ckpt_chaos"))
                       if n.endswith(".quarantined")]
        if not quarantined:
            failures.append("corrupt checkpoint was never quarantined")

        summary = {
            "note": "chaos_smoke",
            "steps": steps,
            "schedule": schedule,
            "baseline_eval_loss": bl,
            "chaos_eval_loss": cl,
            "quarantined": quarantined,
            "result": "PASS" if not failures else "FAIL",
            "failures": failures,
        }
        print(json.dumps(summary))
        print(f"chaos smoke: {summary['result']}")
        return 0 if not failures else 1
    finally:
        if args.keep is None:
            shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
