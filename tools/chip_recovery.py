#!/usr/bin/env python3
"""One-shot runner for DESIGN.md's CHIP-RECOVERY QUEUE (wedge #3, plus the
round-4 additions queued while the wedge outlasted the session).

Run after the tunneled chip comes back:

    python3 tools/chip_recovery.py

Exit codes: 0 queue complete; 75 (WEDGE_RC) the chip re-wedged — the
watcher resumes probing; 70 (CHILD_FAIL_RC) a step failed persistently;
3 the throughput-regression gate. See the constants below.

Steps, in order (each prints its result; the script stops on the first
failure so a regression is investigated before the table is refreshed):

1. liveness probe (subprocess, 90 s — a wedged chip exits here fast);
2. tests_tpu/ on hardware — re-validates the dU-hoist kernels AND the
   round-4 Mosaic surfaces (stacked-direction bi-LSTM kernel, SP x
   Pallas all-manual shard_map, bf16 residual streams);
3. configs 2/4 throughput vs the pre-hoist r3 baselines (19,661 /
   65,165 seq/s, same-day quiet chip) — NOTE config 2 now also carries
   the stacked-direction kernel and bf16 streams, so a big positive
   delta is expected, not suspicious;
4. A/B levers on their target configs:
   - stacked-direction kernel (config 2): LSTM_TSP_NO_BIDIR_FUSE=1 off
     vs on;
   - bf16 residual streams (configs 1/4): LSTM_TSP_RESIDUAL_F32=1 off
     vs on (the r4 bandwidth analysis predicts the biggest relative win
     on config 1);
5. full bench.py (K=512 headline, impl_bound + r4 bandwidth-floor
   fields) -> fresh BENCH_TABLE.json;
6. bench_quality.py TPU legs — the r4 discriminating tasks invalidated
   the committed curves for configs 2/3/5; their CPU halves were
   re-banked during round 5's wedge window, so only the TPU legs run
   here (OPTIONAL: ~20-30 min; skip with --skip-quality and run
   separately).

The README's five-config table is regenerated automatically
(tools/readme_table.py); only the surrounding perf PROSE still needs a
manual re-check against the new numbers.
"""

import json
import os
import subprocess
import sys

_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _DIR not in sys.path:  # runnable from any cwd (the queue children get
    sys.path.insert(0, _DIR)  # cwd=_DIR; this script itself may not)

# Exit-code contract with chip_watch.sh — constants now live in the ONE
# shared table, lstm_tensorspark_tpu/resilience/exit_codes.py:
#   WEDGE_RC (75, EX_TEMPFAIL) — the chip re-wedged mid-queue (a step
#     timed out, or bench's liveness contract fired): the watcher resumes
#     probing so a later recovery window isn't lost.
#   CHILD_FAIL_RC (70, EX_SOFTWARE) — a child step failed for a
#     non-wedge reason (its own rc is printed in the log): persistent,
#     the watcher STOP-marks and exits.
#   REGRESSION_RC (3) — this script's own throughput-regression gate:
#     also persistent.
#   LIVENESS_RC (76) — bench.py's liveness contract. Dedicated since the
#     resilience PR (it used to reuse 3, colliding with the regression
#     gate): the rc alone now routes a wedge-shaped bench failure back to
#     the watcher. The marker-string scan below survives only as a
#     fallback for bench builds predating the dedicated code.
from lstm_tensorspark_tpu.resilience.exit_codes import (  # noqa: E402
    CHILD_FAIL_RC,
    LIVENESS_RC,
    REGRESSION_RC,
    WEDGE_RC,
)

_WEDGE_MARKER = "unreachable/wedged"


def _reemit_timeout_output(e) -> None:
    """Re-emit whatever a TimeoutExpired captured: capture mode buffers the
    child's output, and a wedged 45-min bench would otherwise leave no
    forensics in the watcher log at all. Shared by _run and _measure."""
    for chunk in (e.stdout, e.stderr):
        if chunk:
            sys.stdout.write(chunk if isinstance(chunk, str)
                             else chunk.decode(errors="replace"))
    sys.stdout.flush()

# pre-hoist same-day r3 baselines (quiet chip); regression = materially below
_BASELINES = {"imdb_bilstm": 19661.0, "uci_seq2seq": 65165.0}
# r4 A/B levers: {env_var: (configs, label)}
_AB_LEVERS = {
    "LSTM_TSP_NO_BIDIR_FUSE": (["imdb_bilstm"], "stacked-direction kernel"),
    "LSTM_TSP_RESIDUAL_F32": (["ptb_char", "uci_seq2seq"],
                              "bf16 residual streams"),
}


def _run(argv, timeout, label, scan_wedge=False):
    """Run one queue step. Timeouts exit WEDGE_RC; child failures exit
    CHILD_FAIL_RC (the child's own rc goes to the log only — propagating
    it raw let a child's rc collide with the watcher's sentinel space).
    With ``scan_wedge`` a liveness-shaped bench failure maps to WEDGE_RC,
    not to a persistent failure: the DEDICATED rc (LIVENESS_RC) is the
    primary route; the captured-output marker scan remains as a fallback
    for bench builds that still exit 3 (closes ADVICE r5 finding 1
    properly — the rc no longer collides with the regression gate)."""
    print(f"== {label}", flush=True)
    try:
        if scan_wedge:
            out = subprocess.run(argv, cwd=_DIR, timeout=timeout,
                                 capture_output=True, text=True)
            # re-emit for the watcher log (capture is for the scan only)
            sys.stdout.write(out.stdout)
            sys.stderr.write(out.stderr)
            sys.stdout.flush()
            rc = out.returncode
            if rc == LIVENESS_RC or (
                rc != 0 and _WEDGE_MARKER in out.stdout + out.stderr
            ):
                print(f"FAIL: {label} rc={rc} liveness contract fired "
                      "(chip wedged again?)")
                sys.exit(WEDGE_RC)
        else:
            rc = subprocess.run(argv, cwd=_DIR, timeout=timeout).returncode
    except subprocess.TimeoutExpired as e:
        _reemit_timeout_output(e)
        print(f"FAIL: {label} exceeded {timeout}s (chip wedged again?)")
        sys.exit(WEDGE_RC)
    if rc != 0:
        print(f"FAIL: {label} rc={rc}")
        sys.exit(CHILD_FAIL_RC)


def _measure(name, env=None, timeout=900):
    """measure_config in a subprocess (a chip that passes the probe can
    STILL wedge mid-measurement; bench's watchdog only arms in main()).
    Returns the record dict, or exits on failure."""
    run_env = dict(os.environ)
    if env:
        run_env.update(env)
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import json, bench; "
             f"r = bench.measure_config({name!r}); "
             "print(json.dumps(r))"],
            cwd=_DIR, timeout=timeout, capture_output=True, text=True,
            env=run_env,
        )
    except subprocess.TimeoutExpired as e:
        _reemit_timeout_output(e)
        print(f"FAIL: measure_config({name}) exceeded {timeout}s "
              "(chip wedged again?)")
        sys.exit(WEDGE_RC)
    if out.returncode != 0:
        print(f"FAIL: measure_config({name}) rc={out.returncode}:\n"
              f"{out.stderr[-1000:]}")
        wedged = (out.returncode == LIVENESS_RC
                  or _WEDGE_MARKER in out.stdout + out.stderr)
        sys.exit(WEDGE_RC if wedged else CHILD_FAIL_RC)
    return json.loads(out.stdout.strip().splitlines()[-1])


def main() -> int:
    skip_quality = "--skip-quality" in sys.argv[1:]
    _run([sys.executable, "-c",
          "import jax, jax.numpy as jnp; "
          "x = jnp.ones((128, 128)); print(float((x @ x).sum()))"],
         timeout=90, label="liveness probe")
    _run([sys.executable, "-m", "pytest", "tests_tpu/", "-q"],
         timeout=1200, label="tests_tpu on hardware")

    print("== configs 2/4 throughput vs pre-hoist r3 baselines", flush=True)
    regressed = []
    for name, base in _BASELINES.items():
        rec = _measure(name)
        got = rec["seq_per_sec"]
        delta = (got / base - 1.0) * 100.0
        print(f"{name}: {got:,.0f} seq/s vs pre-hoist {base:,.0f} "
              f"({delta:+.1f}%), MFU {rec['mfu_vs_bf16_peak']:.1%}")
        if got < 0.97 * base:  # >3% below: not chip noise — investigate
            regressed.append(name)
    if regressed:
        print(f"FAIL: regression vs pre-hoist baselines on {regressed}; "
              "investigate before refreshing the table (DESIGN.md queue)")
        return REGRESSION_RC

    print("== r4 A/B levers", flush=True)
    for var, (names, label) in _AB_LEVERS.items():
        for name in names:
            on = _measure(name)  # lever off = the new default path
            off = _measure(name, env={var: "1"})  # lever on = old behavior
            speedup = on["seq_per_sec"] / max(off["seq_per_sec"], 1e-9)
            print(f"{label} on {name}: {off['seq_per_sec']:,.0f} -> "
                  f"{on['seq_per_sec']:,.0f} seq/s ({speedup:.2f}x; "
                  f"{var}=1 is the old path)")
            if speedup < 0.97:
                print(f"WARN: {label} REGRESSES {name} — consider gating "
                      "it off for this config and record the negative "
                      "result in DESIGN.md")

    # scan_wedge: bench's liveness contract exits LIVENESS_RC (76) — the
    # rc routes a mid-queue re-wedge back to the watcher's resume path
    # (marker scan kept as a legacy fallback)
    _run([sys.executable, "bench.py"], timeout=2700, label="full bench.py",
         scan_wedge=True)
    table = json.load(open(os.path.join(_DIR, "BENCH_TABLE.json")))
    print(f"fresh table: headline {table['headline_seq_per_sec']:,.0f} "
          f"seq/s, {table['vs_cpu_baseline']:.0f}x CPU")
    hbm = table.get("hbm_bandwidth", {})
    if "gb_per_sec" in hbm:
        print(f"measured HBM bandwidth: {hbm['gb_per_sec']:,.0f} GB/s")
    for name, rec in table.get("configs", {}).items():
        rl = rec.get("roofline", {}) if isinstance(rec, dict) else {}
        if "bound_binding" in rl:
            print(f"  {name}: binding={rl['bound_binding']}, "
                  f"fraction_of_impl_bound2={rl['fraction_of_impl_bound2']}")

    _run([sys.executable, "tools/readme_table.py"], timeout=60,
         label="README table regen from fresh BENCH_TABLE.json")

    if not skip_quality:
        # TPU legs only: the CPU halves for the r4 discriminating tasks
        # (configs 2/3/5) were re-measured and banked during round 5's
        # wedge window on a quiet machine (configs 1/4 CPU curves were
        # never invalidated); running them again here would just burn an
        # hour of the recovery window re-proving the slow leg
        _run([sys.executable, "bench_quality.py", "--platform", "tpu"],
             timeout=7200,
             label="bench_quality.py TPU legs (r4 discriminating tasks; "
                   "CPU legs banked r5)")
        _run([sys.executable, "tools/readme_quality.py"], timeout=60,
             label="README quality-table regen from BASELINE_MEASURED.json")
    else:
        print("skipped bench_quality.py (--skip-quality); run it before "
              "committing BASELINE_MEASURED.json")
    print("NOW: re-check the README perf PROSE against the new table "
          "(the table itself is regenerated) and commit the refreshed "
          "artifacts.")
    return 0


if __name__ == "__main__":
    sys.exit(main())
