#!/usr/bin/env python3
"""One-shot runner for DESIGN.md's CHIP-RECOVERY QUEUE (round-3 wedge #3).

Run after the tunneled chip comes back:

    python3 tools/chip_recovery.py

Steps, in order (each prints its result; the script stops on the first
failure so a regression is investigated before the table is refreshed):

1. liveness probe (subprocess, 90 s — a wedged chip exits here fast);
2. tests_tpu/ on hardware (re-validates the dU-hoist kernels on-chip);
3. configs 2/4 throughput vs the pre-hoist baselines measured same-day on
   the quiet chip (19,661 / 65,165 seq/s) — the dU-hoist before/after;
4. full bench.py (K=512 headline, impl_bound roofline fields, post-hoist
   rows) -> fresh BENCH_TABLE.json.

Then regenerate the README performance table from the new BENCH_TABLE.json
by hand (rows + K-note), per the queue's step 3.
"""

import json
import os
import subprocess
import sys

_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# pre-hoist same-day baselines (quiet chip); regression = materially below
_BASELINES = {"imdb_bilstm": 19661.0, "uci_seq2seq": 65165.0}


def _run(argv, timeout, label):
    print(f"== {label}", flush=True)
    try:
        rc = subprocess.run(argv, cwd=_DIR, timeout=timeout).returncode
    except subprocess.TimeoutExpired:
        print(f"FAIL: {label} exceeded {timeout}s (chip wedged again?)")
        sys.exit(2)
    if rc != 0:
        print(f"FAIL: {label} rc={rc}")
        sys.exit(rc)


def main() -> int:
    _run([sys.executable, "-c",
          "import jax, jax.numpy as jnp; "
          "x = jnp.ones((128, 128)); print(float((x @ x).sum()))"],
         timeout=90, label="liveness probe")
    _run([sys.executable, "-m", "pytest", "tests_tpu/", "-q"],
         timeout=900, label="tests_tpu on hardware")

    print("== configs 2/4 throughput (dU-hoist before/after)", flush=True)
    regressed = []
    for name, base in _BASELINES.items():
        # subprocess + timeout like every other step: a chip that passes
        # the probe can STILL wedge mid-measurement (a jit dispatch that
        # never returns), and bench's watchdog only arms in bench.main()
        try:
            out = subprocess.run(
                [sys.executable, "-c",
                 "import json, bench; "
                 f"r = bench.measure_config({name!r}); "
                 "print(json.dumps(r))"],
                cwd=_DIR, timeout=900, capture_output=True, text=True,
            )
        except subprocess.TimeoutExpired:
            print(f"FAIL: measure_config({name}) exceeded 900s "
                  "(chip wedged again?)")
            return 2
        if out.returncode != 0:
            print(f"FAIL: measure_config({name}) rc={out.returncode}:\n"
                  f"{out.stderr[-1000:]}")
            return out.returncode
        rec = json.loads(out.stdout.strip().splitlines()[-1])
        got = rec["seq_per_sec"]
        delta = (got / base - 1.0) * 100.0
        print(f"{name}: {got:,.0f} seq/s vs pre-hoist {base:,.0f} "
              f"({delta:+.1f}%), MFU {rec['mfu_vs_bf16_peak']:.1%}")
        if got < 0.97 * base:  # >3% below: not chip noise — investigate
            regressed.append(name)
    if regressed:
        print(f"FAIL: regression vs pre-hoist baselines on {regressed}; "
              "investigate before refreshing the table (DESIGN.md queue "
              "step 4)")
        return 3

    _run([sys.executable, "bench.py"], timeout=2700, label="full bench.py")
    table = json.load(open(os.path.join(_DIR, "BENCH_TABLE.json")))
    print(f"fresh table: headline {table['headline_seq_per_sec']:,.0f} "
          f"seq/s, {table['vs_cpu_baseline']:.0f}x CPU")
    print("NOW: regenerate the README performance table from "
          "BENCH_TABLE.json and commit both (queue step 3).")
    return 0


if __name__ == "__main__":
    sys.exit(main())
