"""Structured metrics: stdout + JSONL file.

Reference parity: SURVEY.md §5 "Metrics / logging" — the reference prints
per-epoch loss to driver stdout and leans on the Spark UI; structured metrics
are new capability (jsonl lines consumable by any downstream tooling).
"""

from __future__ import annotations

import json
import sys
import time


class MetricsLogger:
    def __init__(self, jsonl_path: str | None = None, stream=None, quiet: bool = False):
        self.jsonl_path = jsonl_path
        self.stream = stream or sys.stdout
        self.quiet = quiet
        self._fh = open(jsonl_path, "a") if jsonl_path else None
        self._t0 = time.time()

    def log(self, record: dict) -> None:
        record = {"t": round(time.time() - self._t0, 3), **record}
        if self._fh:
            self._fh.write(json.dumps(record) + "\n")
            self._fh.flush()
        if not self.quiet:
            parts = " ".join(
                f"{k}={v:.4g}" if isinstance(v, float) else f"{k}={v}"
                for k, v in record.items()
            )
            print(parts, file=self.stream, flush=True)

    def close(self) -> None:
        if self._fh:
            self._fh.close()
