"""Structured metrics: stdout + JSONL file.

Reference parity: SURVEY.md §5 "Metrics / logging" — the reference prints
per-epoch loss to driver stdout and leans on the Spark UI; structured metrics
are new capability (jsonl lines consumable by any downstream tooling).

``MetricsLogger`` is a context manager (``with MetricsLogger(path) as
logger``) so the JSONL handle closes on exception paths too — cli.py runs
every task under it. :meth:`log_registry` writes one flat snapshot record
of a telemetry registry (obs/) — histogram count/sum/p50/p99 plus
counter/gauge values — so a training run's JSONL ends with the same
numbers a live ``/metrics`` scrape would have shown.
"""

from __future__ import annotations

import json
import sys
import time


class MetricsLogger:
    def __init__(self, jsonl_path: str | None = None, stream=None, quiet: bool = False):
        self.jsonl_path = jsonl_path
        self.stream = stream or sys.stdout
        self.quiet = quiet
        self._fh = open(jsonl_path, "a") if jsonl_path else None
        # elapsed-time origin: monotonic — the "t" field is a duration
        # since logger construction, and wall clock slews under NTP
        self._t0 = time.monotonic()

    def log(self, record: dict) -> None:
        record = {"t": round(time.monotonic() - self._t0, 3), **record}
        if self._fh:
            self._fh.write(json.dumps(record) + "\n")
            self._fh.flush()
        if not self.quiet:
            parts = " ".join(
                f"{k}={v:.4g}" if isinstance(v, float) else f"{k}={v}"
                for k, v in record.items()
            )
            print(parts, file=self.stream, flush=True)

    def log_registry(self, registry, note: str = "metrics_snapshot",
                     extra: dict | None = None) -> None:
        """One flat record of the registry's current state (histograms as
        ``name_count``/``name_sum``/``name_p50``/``name_p99`` keys).
        ``extra`` merges run-level context the registry cannot carry —
        e.g. the requested ``bptt_mode`` string next to the numeric
        assoc-trace/fallback counters, so supervised restarts can diff
        the record across resume legs."""
        snap = registry.snapshot()
        if snap or extra:
            self.log({"note": note, **snap, **(extra or {})})

    def close(self) -> None:
        if self._fh:
            self._fh.close()
            self._fh = None

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        # close on success AND on exception/SystemExit paths — the JSONL
        # handle must never leak past the run that opened it
        self.close()
