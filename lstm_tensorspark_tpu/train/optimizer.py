"""Optimizers via optax.

Reference parity: SURVEY.md §2 "Optimizer / update rule" [D][I] — the
reference applies plain SGD on the driver after gradient averaging
(``params -= lr * avg_grad``). SGD is therefore the default; momentum/adam
and gradient clipping are capability extensions (BASELINE.md configs 2–5
train poorly without them).
"""

from __future__ import annotations

import optax


def make_optimizer(
    name: str = "sgd",
    learning_rate: float = 1.0,
    *,
    momentum: float = 0.0,
    clip_norm: float | None = None,
    weight_decay: float = 0.0,
    warmup_steps: int = 0,
    decay_steps: int | None = None,
) -> optax.GradientTransformation:
    """Build an optax chain: [clip] -> optimizer [-> wd] with optional
    linear-warmup cosine-decay schedule."""
    if decay_steps is not None:
        schedule = optax.warmup_cosine_decay_schedule(
            init_value=0.0 if warmup_steps > 0 else learning_rate,
            peak_value=learning_rate,
            warmup_steps=max(warmup_steps, 1),
            decay_steps=max(decay_steps, warmup_steps + 1),
            end_value=learning_rate * 0.1,
        )
    elif warmup_steps > 0:
        # Warmup with no decay horizon: ramp to peak, then HOLD at peak.
        schedule = optax.join_schedules(
            [
                optax.linear_schedule(0.0, learning_rate, warmup_steps),
                optax.constant_schedule(learning_rate),
            ],
            [warmup_steps],
        )
    else:
        schedule = learning_rate

    name = name.lower()
    if name == "sgd":
        opt = optax.sgd(schedule, momentum=momentum if momentum > 0 else None)
    elif name == "momentum":
        opt = optax.sgd(schedule, momentum=momentum or 0.9)
    elif name == "adam":
        opt = optax.adam(schedule)
    elif name == "adamw":
        opt = optax.adamw(schedule, weight_decay=weight_decay)
    elif name == "rmsprop":
        opt = optax.rmsprop(schedule)
    else:
        raise ValueError(f"unknown optimizer {name!r}")

    chain = []
    if clip_norm is not None:
        chain.append(optax.clip_by_global_norm(clip_norm))
    chain.append(opt)
    return optax.chain(*chain)
