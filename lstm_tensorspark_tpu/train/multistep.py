"""Multi-step training: K optimizer steps per host dispatch via `lax.scan`.

The single-step path (train/loop.py) already collapses the reference's whole
round — broadcast, mapPartitions, treeAggregate, update (SURVEY.md §3.1) —
into one XLA program, leaving host→device dispatch as the only per-step host
cost. For small models that dispatch dominates: the PTB config's step is
~25µs of TPU compute but ~150µs of dispatch over this environment's tunneled
chip. This module removes it the TPU-native way: stage K batches on device
([K, ...] leading axis) and `lax.scan` the SAME step body K times inside one
jitted call, so the host pays one dispatch per K steps.

This is the moral opposite of the reference's design point: Spark pays
per-round *network serialization*; single-step jit pays per-step *dispatch*;
multi-step amortises even that. The step body is shared verbatim with the
single-step and DP paths (step_body), so the K-step program is provably K
iterations of the same update — tests/test_multistep.py asserts bit-level
parity against K sequential single steps.

Metrics: ``loss`` is the mean over the K steps (the natural logging quantity
for a K-step window), ``loss_last``/``grad_norm`` are the final step's.

Fused eval composes with the HOST-FED feed too: only the EVAL data must be
device-resident for the in-executable eval pass (device_step.py), so
``eval_data`` (LM valid stream) or ``metric_fn`` (stacked task eval
batches) turn these builders into fused train+eval steps — the case where
the train set exceeds HBM but the valid split fits.
"""

from __future__ import annotations

from typing import Callable

import jax
import optax
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.4.35
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from ..data.device_dataset import DeviceLMData
from .device_step import _gated_eval_batches, _gated_lm_eval, _jit_step
from .loop import (
    TrainState,
    dp_reduce_fn,
    dp_rng_transform,
    step_body,
    summarize_scan_metrics,
)


def _scan_steps(loss_fn, optimizer, state, batches, *, stateful, rng_transform=None,
                reduce_fn=None, grad_accum=1):
    """scan step_body over the leading [K] axis of ``batches``."""

    def body(s, b):
        s2, m = step_body(
            loss_fn, optimizer, s, b, stateful=stateful,
            rng_transform=rng_transform, reduce_fn=reduce_fn,
            grad_accum=grad_accum,
        )
        return s2, m

    state, ms = jax.lax.scan(body, state, batches)
    return state, summarize_scan_metrics(ms)


def _fused_tail(loss_fn, eval_data, eval_windows, metric_fn, metric_keys,
                stateful, psum_axis=None):
    """Resolve which fused-eval tail (if any) the builder should append:
    returns None (plain step) or a closure (state, ms, *eval_args) -> ms."""
    if eval_data is not None:
        n_ev = min(eval_data.n_windows, eval_windows or eval_data.n_windows)
        ev_T = eval_data.seq_len

        def tail(state, ms, eval_arrays, do_eval, eval_carries=None):
            return _gated_lm_eval(
                loss_fn, state, eval_arrays, do_eval, ms, n_windows=n_ev,
                seq_len=ev_T, stateful=stateful, eval_carries=eval_carries,
                psum_axis=psum_axis,
            )

        return tail
    if metric_fn is not None:
        keys = tuple(metric_keys)

        def tail(state, ms, eval_batches, do_eval):
            return _gated_eval_batches(
                metric_fn, state, eval_batches, do_eval, ms, keys
            )

        return tail
    return None


def make_multi_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    *,
    eval_data: DeviceLMData | None = None,
    eval_windows: int | None = None,
    metric_fn: Callable | None = None,
    metric_keys=(),
    jit: bool = True,
    donate: bool | None = None,
    stateful: bool = False,
    grad_accum: int = 1,
):
    """Single-chip K-steps-per-call train step.

    ``multi_step(state, batches)`` where ``batches`` is the usual batch pytree
    with an extra leading K axis (see data.batching.stacked_batches). K is
    read from the array shapes — one compilation per distinct K.

    With ``eval_data`` (LM valid stream) or ``metric_fn`` (stacked task
    eval batches), returns the FUSED step
    ``multi_step(state, batches, <eval args>, do_eval[, eval_carries])`` —
    identical semantics to device_step.py's fused builders but with a
    host-fed train feed.
    """
    tail = _fused_tail(loss_fn, eval_data, eval_windows, metric_fn,
                       metric_keys, stateful)

    def core(state: TrainState, batches):
        return _scan_steps(
            loss_fn, optimizer, state, batches,
            stateful=stateful, grad_accum=grad_accum,
        )

    if tail is None:
        multi_step = core
    else:

        def multi_step(state: TrainState, batches, *eval_args):
            state, ms = core(state, batches)
            return state, tail(state, ms, *eval_args)

    return _jit_step(multi_step, jit, donate)


def make_dp_multi_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    eval_data: DeviceLMData | None = None,
    eval_windows: int | None = None,
    metric_fn: Callable | None = None,
    metric_keys=(),
    axis: str = "data",
    jit: bool = True,
    donate: bool | None = None,
    stateful: bool = False,
    grad_accum: int = 1,
):
    """Data-parallel K-steps-per-call: the DP per-shard body (rng fold-in +
    pmean grad all-reduce — parallel/data_parallel.py) scanned K times inside
    the shard_map, so the ICI all-reduce happens every step but the host
    dispatch only once per K. ``batches`` leading axes are [K, B, ...] with B
    sharded over the data axis (spec ``P(None, axis)``).

    ``eval_data``/``metric_fn`` append the fused eval tail (device_step.py
    sharding contracts: LM valid stream shards batch rows + psums the
    token-weighted sums; task eval batches replicate)."""
    tail = _fused_tail(loss_fn, eval_data, eval_windows, metric_fn,
                       metric_keys, stateful,
                       psum_axis=axis if eval_data is not None else None)

    def core(state: TrainState, batches):
        return _scan_steps(
            loss_fn, optimizer, state, batches, stateful=stateful,
            grad_accum=grad_accum,
            rng_transform=dp_rng_transform(axis),
            reduce_fn=dp_reduce_fn(axis),
        )

    state_spec = TrainState(
        step=P(), params=P(), opt_state=P(), rng=P(),
        carries=P(axis) if stateful else P(),
    )
    if tail is None:
        per_shard = core
        in_specs = (state_spec, P(None, axis))
    elif eval_data is not None:
        stream_spec = {"streams": P(axis, None), "shifted": P(axis, None)}

        def per_shard(state, batches, eval_arrays, do_eval, eval_carries):
            state, ms = core(state, batches)
            return state, tail(state, ms, eval_arrays, do_eval, eval_carries)

        in_specs = (state_spec, P(None, axis), stream_spec, P(),
                    P(axis) if stateful else P())
    else:

        def per_shard(state, batches, eval_batches, do_eval):
            state, ms = core(state, batches)
            return state, tail(state, ms, eval_batches, do_eval)

        in_specs = (state_spec, P(None, axis), P(), P())

    sharded = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(state_spec, P()),
        check_vma=False,
    )
    return _jit_step(sharded, jit, donate)
