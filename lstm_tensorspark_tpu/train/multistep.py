"""Multi-step training: K optimizer steps per host dispatch via `lax.scan`.

The single-step path (train/loop.py) already collapses the reference's whole
round — broadcast, mapPartitions, treeAggregate, update (SURVEY.md §3.1) —
into one XLA program, leaving host→device dispatch as the only per-step host
cost. For small models that dispatch dominates: the PTB config's step is
~25µs of TPU compute but ~150µs of dispatch over this environment's tunneled
chip. This module removes it the TPU-native way: stage K batches on device
([K, ...] leading axis) and `lax.scan` the SAME step body K times inside one
jitted call, so the host pays one dispatch per K steps.

This is the moral opposite of the reference's design point: Spark pays
per-round *network serialization*; single-step jit pays per-step *dispatch*;
multi-step amortises even that. The step body is shared verbatim with the
single-step and DP paths (step_body), so the K-step program is provably K
iterations of the same update — tests/test_multistep.py asserts bit-level
parity against K sequential single steps.

Metrics: ``loss`` is the mean over the K steps (the natural logging quantity
for a K-step window), ``loss_last``/``grad_norm`` are the final step's.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.4.35
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from .loop import (
    TrainState,
    _donation_supported,
    dp_reduce_fn,
    dp_rng_transform,
    step_body,
    summarize_scan_metrics,
)


def _scan_steps(loss_fn, optimizer, state, batches, *, stateful, rng_transform=None,
                reduce_fn=None, grad_accum=1):
    """scan step_body over the leading [K] axis of ``batches``."""

    def body(s, b):
        s2, m = step_body(
            loss_fn, optimizer, s, b, stateful=stateful,
            rng_transform=rng_transform, reduce_fn=reduce_fn,
            grad_accum=grad_accum,
        )
        return s2, m

    state, ms = jax.lax.scan(body, state, batches)
    return state, summarize_scan_metrics(ms)


def make_multi_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    *,
    jit: bool = True,
    donate: bool | None = None,
    stateful: bool = False,
    grad_accum: int = 1,
):
    """Single-chip K-steps-per-call train step.

    ``multi_step(state, batches)`` where ``batches`` is the usual batch pytree
    with an extra leading K axis (see data.batching.stacked_batches). K is
    read from the array shapes — one compilation per distinct K.
    """

    def multi_step(state: TrainState, batches):
        return _scan_steps(
            loss_fn, optimizer, state, batches,
            stateful=stateful, grad_accum=grad_accum,
        )

    if jit:
        if donate is None:
            donate = _donation_supported()
        multi_step = jax.jit(multi_step, donate_argnums=(0,) if donate else ())
    return multi_step


def make_dp_multi_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    axis: str = "data",
    jit: bool = True,
    donate: bool | None = None,
    stateful: bool = False,
    grad_accum: int = 1,
):
    """Data-parallel K-steps-per-call: the DP per-shard body (rng fold-in +
    pmean grad all-reduce — parallel/data_parallel.py) scanned K times inside
    the shard_map, so the ICI all-reduce happens every step but the host
    dispatch only once per K. ``batches`` leading axes are [K, B, ...] with B
    sharded over the data axis (spec ``P(None, axis)``)."""

    def per_shard_multi(state: TrainState, batches):
        return _scan_steps(
            loss_fn, optimizer, state, batches, stateful=stateful,
            grad_accum=grad_accum,
            rng_transform=dp_rng_transform(axis),
            reduce_fn=dp_reduce_fn(axis),
        )

    state_spec = TrainState(
        step=P(), params=P(), opt_state=P(), rng=P(),
        carries=P(axis) if stateful else P(),
    )
    sharded = shard_map(
        per_shard_multi,
        mesh=mesh,
        in_specs=(state_spec, P(None, axis)),
        out_specs=(state_spec, P()),
        check_vma=False,
    )
    if jit:
        if donate is None:
            donate = _donation_supported()
        sharded = jax.jit(sharded, donate_argnums=(0,) if donate else ())
    return sharded
