"""Train steps over device-resident datasets (data/device_dataset.py).

The step takes (state, staged arrays, per-step index array) and runs K
optimizer steps, materialising each batch out of HBM inside the scan — the
per-dispatch host traffic is the tiny index array (one scalar for the LM's
contiguous windows, [K, B] row ids for examples/series). Combines the
K-steps-per-call dispatch amortisation (train/multistep.py) with the
reference's cached-RDD data locality (SURVEY.md §3.1: executors iterate
their *resident* shard).

Three dataset shapes share ONE generic core (`make_device_train_step` /
`make_device_dp_train_step`, parameterised by a traced ``window_fn``):
  - LM contiguous windows (`slice_window`) — wrappers below keep the
    scalar-w0 API used by the CLI and bench;
  - per-example gather (`take_batch`) — classification;
  - series windows (`slice_forecast_batch`) — forecasting.

The scan body is the shared `step_body`, so semantics are identical to the
host-fed paths — tests/test_device_data.py asserts bit-level parity.

Fused train+eval — the eval pass lives INSIDE the train executable.
On dispatch-expensive backends (the tunneled chip here) switching between
the train and eval executables costs ~3 s per swap — far more than either
program's compute at small dims, and it DOMINATED the wall-clock-to-quality
runs. The reference never had this problem only because it never had
executables: eval was one more Spark job. The TPU-native answer is ONE
program: the K-step train scan followed by a lax.cond-gated forward-only
eval pass, requested by passing ``metric_fn``/``metric_keys`` (generic,
over stacked eval batches) or ``eval_data`` (LM, over a staged valid
stream) to the builders below. The ``do_eval`` flag is a traced scalar —
both cadences run the SAME executable, and XLA's cond skips the eval
branch entirely on non-eval calls (tests/test_fused_eval.py).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.4.35
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from ..data.device_dataset import DeviceLMData, slice_window
from .loop import (
    TrainState,
    _donation_supported,
    call_loss,
    dp_reduce_fn,
    dp_rng_transform,
    step_body,
    summarize_scan_metrics,
)


def _scan_indexed(loss_fn, optimizer, state, arrays, idxs, *, window_fn,
                  stateful, grad_accum, rng_transform=None, reduce_fn=None):
    """lax.scan over the leading [K] axis of ``idxs``; each step builds its
    batch with ``window_fn(arrays, idx)`` and runs the shared step_body."""

    def body(s, idx):
        return step_body(
            loss_fn, optimizer, s, window_fn(arrays, idx), stateful=stateful,
            grad_accum=grad_accum, rng_transform=rng_transform,
            reduce_fn=reduce_fn,
        )

    state, ms = lax.scan(body, state, idxs)
    return state, summarize_scan_metrics(ms)


def _jit_step(step, jit: bool, donate: bool | None):
    """The ONE jit/donation wrapper shared by every builder here."""
    if not jit:
        return step
    if donate is None:
        donate = _donation_supported()
    return jax.jit(step, donate_argnums=(0,) if donate else ())


# ---- traced eval bodies (the on-device forms of the host eval loops) ----


def _device_eval_batches(metric_fn, params, eval_batches, keys):
    """Traced weighted-mean eval over a stacked [n_ev, ...] batch pytree:
    ``metric_fn(params, batch) -> (metrics dict, weight)``; returns
    ``{k: sum(m_k * w) / sum(w)}`` — the on-device body of the task
    runners' host eval loops."""

    def body(acc, batch):
        tot, wt = acc
        m, w = metric_fn(params, batch)
        w = w.astype(jnp.float32)
        tot = {k: tot[k] + m[k].astype(jnp.float32) * w for k in keys}
        return (tot, wt + w), None

    zeros = {k: jnp.zeros((), jnp.float32) for k in keys}
    (tot, wt), _ = lax.scan(
        body, (zeros, jnp.zeros((), jnp.float32)), eval_batches
    )
    wt = jnp.maximum(wt, 1.0)
    return {k: tot[k] / wt for k in keys}


def _gated_eval_batches(metric_fn, state, eval_batches, do_eval, ms, keys):
    ms.update(lax.cond(
        do_eval,
        lambda _: _device_eval_batches(metric_fn, state.params, eval_batches,
                                       keys),
        lambda _: {k: jnp.float32(jnp.nan) for k in keys},
        operand=None,
    ))
    return ms


def _device_lm_eval(loss_fn, params, eval_arrays, n_windows, seq_len, *,
                    stateful, eval_carries, psum_axis=None):
    """Traced token-weighted eval over the staged valid stream — the
    on-device body of `evaluate()` (train/loop.py): sum(loss*tokens) /
    sum(tokens) over the epoch's windows, carries threaded when stateful."""

    def body(acc, w):
        carries, tot, wt = acc
        batch = slice_window(eval_arrays, w, seq_len)
        loss, aux = call_loss(loss_fn, params, batch, None, carries,
                              stateful=stateful)
        tok = (aux["tokens"] if isinstance(aux, dict) and "tokens" in aux
               else jnp.float32(1.0))
        carries = aux["carries"] if stateful else carries
        return (carries, tot + loss * tok, wt + tok), None

    zero = jnp.zeros((), jnp.float32)
    (_, tot, wt), _ = lax.scan(
        body, (eval_carries, zero, zero),
        jnp.arange(n_windows, dtype=jnp.int32),
    )
    if psum_axis is not None:
        # per-shard sums → exact global token-weighted mean (equal-shape
        # shards make this identical to make_dp_eval_step + evaluate())
        tot = lax.psum(tot, psum_axis)
        wt = lax.psum(wt, psum_axis)
    return tot / jnp.maximum(wt, 1.0)


def _gated_lm_eval(loss_fn, state, eval_arrays, do_eval, ms, *, n_windows,
                   seq_len, stateful, eval_carries, psum_axis=None):
    ms["eval_loss"] = lax.cond(
        do_eval,
        lambda _: _device_lm_eval(
            loss_fn, state.params, eval_arrays, n_windows, seq_len,
            stateful=stateful, eval_carries=eval_carries,
            psum_axis=psum_axis,
        ),
        lambda _: jnp.float32(jnp.nan),
        operand=None,
    )
    return ms


# ---- generic builders (classification / forecasting / any window_fn) ----


def make_device_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    window_fn: Callable,
    *,
    metric_fn: Callable | None = None,
    metric_keys=(),
    stateful: bool = False,
    grad_accum: int = 1,
    jit: bool = True,
    donate: bool | None = None,
):
    """Generic single-chip device-data step: ``step(state, arrays, idxs)``
    with ``idxs`` carrying a leading K axis (one entry per optimizer step).

    With ``metric_fn`` set, returns the FUSED train+eval step
    ``step(state, arrays, idxs, eval_batches, do_eval)``: a lax.cond-gated
    weighted eval over the HBM-staged ``eval_batches`` follows the train
    scan in the SAME executable; its metrics appear under ``metric_keys``
    (NaN on non-eval calls)."""
    def core(state: TrainState, arrays, idxs):
        return _scan_indexed(
            loss_fn, optimizer, state, arrays, idxs, window_fn=window_fn,
            stateful=stateful, grad_accum=grad_accum,
        )

    if metric_fn is None:
        step = core
    else:
        keys = tuple(metric_keys)

        def step(state: TrainState, arrays, idxs, eval_batches, do_eval):
            state, ms = core(state, arrays, idxs)
            return state, _gated_eval_batches(
                metric_fn, state, eval_batches, do_eval, ms, keys
            )

    return _jit_step(step, jit, donate)


def make_device_dp_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    window_fn: Callable,
    mesh: Mesh,
    arrays_spec,
    *,
    metric_fn: Callable | None = None,
    metric_keys=(),
    idx_spec=P(),
    axis: str = "data",
    stateful: bool = False,
    grad_accum: int = 1,
    jit: bool = True,
    donate: bool | None = None,
):
    """Generic data-parallel device-data step. ``arrays_spec`` gives the
    staged arrays' shardings (LM streams shard their batch rows; example/
    series arrays replicate); ``idx_spec`` the index array's (P() when every
    shard uses the same indices, P(None, axis) to split a [K, B] batch of
    row ids). Grads pmean over the ICI mesh as always.

    With ``metric_fn`` set, the fused step's eval batches are REPLICATED
    (``P()``): every shard runs the identical eval concurrently — same
    wall-clock as one shard, exact same value on all, no collective."""
    kw = dict(stateful=stateful, grad_accum=grad_accum,
              rng_transform=dp_rng_transform(axis), reduce_fn=dp_reduce_fn(axis))
    state_spec = TrainState(
        step=P(), params=P(), opt_state=P(), rng=P(),
        carries=P(axis) if stateful else P(),
    )
    def core(state: TrainState, arrays, idxs):
        return _scan_indexed(
            loss_fn, optimizer, state, arrays, idxs, window_fn=window_fn,
            **kw,
        )

    if metric_fn is None:
        per_shard = core
        in_specs = (state_spec, arrays_spec, idx_spec)
    else:
        keys = tuple(metric_keys)

        def per_shard(state: TrainState, arrays, idxs, eval_batches, do_eval):
            state, ms = core(state, arrays, idxs)
            return state, _gated_eval_batches(
                metric_fn, state, eval_batches, do_eval, ms, keys
            )

        in_specs = (state_spec, arrays_spec, idx_spec, P(), P())

    sharded = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(state_spec, P()),
        check_vma=False,
    )
    return _jit_step(sharded, jit, donate)


# ---- LM wrappers: scalar-w0 per-dispatch API (window indices computed
# ON-DEVICE from the traced scalar — per-dispatch host traffic really is
# one int32) ----


def _lm_window_idxs(w0, data: DeviceLMData, steps_per_call: int):
    return lax.rem(
        w0 + jnp.arange(steps_per_call, dtype=jnp.int32),
        jnp.int32(data.n_windows),
    )


def make_device_lm_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    data: DeviceLMData,
    *,
    eval_data: DeviceLMData | None = None,
    eval_windows: int | None = None,
    steps_per_call: int = 1,
    stateful: bool = False,
    grad_accum: int = 1,
    jit: bool = True,
    donate: bool | None = None,
):
    """Single-chip LM device-data step: ``step(state, data.arrays, w0)``.

    With ``eval_data`` (a staged valid stream) set, returns the FUSED
    train+eval step ``step(state, arrays, w0, eval_arrays, do_eval
    [, eval_carries])`` whose ``metrics["eval_loss"]`` is the token-weighted
    valid loss when ``do_eval`` is true, NaN otherwise. ``eval_windows``
    caps the eval pass (the --eval-batches bound)."""
    window_fn = lambda arrays, w: slice_window(arrays, w, data.seq_len)  # noqa: E731

    def core(state: TrainState, arrays, w0):
        return _scan_indexed(
            loss_fn, optimizer, state, arrays,
            _lm_window_idxs(w0, data, steps_per_call),
            window_fn=window_fn, stateful=stateful, grad_accum=grad_accum,
        )

    if eval_data is None:
        step = core
    else:
        n_ev = min(eval_data.n_windows, eval_windows or eval_data.n_windows)
        ev_T = eval_data.seq_len

        def step(state: TrainState, arrays, w0, eval_arrays, do_eval,
                 eval_carries=None):
            state, ms = core(state, arrays, w0)
            return state, _gated_lm_eval(
                loss_fn, state, eval_arrays, do_eval, ms, n_windows=n_ev,
                seq_len=ev_T, stateful=stateful, eval_carries=eval_carries,
            )

    return _jit_step(step, jit, donate)


class TrainStepCompileCache:
    """Keyed train-step executables with trace-time compile counting and
    a warmup path — the serve engine's compile-key discipline applied to
    the training side. A (bucket, bptt_mode) step program that first
    traces mid-measurement charges one timed sample a full XLA compile
    (the exact failure class `tools/bench_train_scan.py` pairs runs to
    avoid); the ``("train_step", bucket, bptt_mode)`` family is gated by
    graftlint's warmup-coverage rule like the serve families, so an
    unwarmed consumer cannot land.

    ``builder(bucket, bptt_mode)`` must return an UNJITTED step
    ``(state, batch) -> (state', metrics)`` (e.g. `make_train_step`
    with ``jit=False``); this cache owns the jit so the trace-time
    counter sits inside the traced callable.
    """

    def __init__(self, builder):
        self._builder = builder
        self._fns: dict = {}
        self.compile_counts: dict = {}

    def step_fn(self, bucket, bptt_mode: str):
        key = (bucket, bptt_mode)
        if key not in self._fns:
            raw = self._builder(bucket, bptt_mode)

            def counted(state, batch, _raw=raw, _key=key):
                # bumped at TRACE time (python side effect inside the
                # jitted callable) — one count per compiled program
                count_key = ("train_step", _key[0], _key[1])
                self.compile_counts[count_key] = (
                    self.compile_counts.get(count_key, 0) + 1)
                return _raw(state, batch)

            self._fns[key] = jax.jit(counted)
        return self._fns[key]

    def warmup(self, cases):
        """Dispatch each ``(bucket, bptt_mode, state, batch)`` once so
        every program in the lattice compiles before timed traffic."""
        for bucket, mode, state, batch in cases:
            out = self.step_fn(bucket, mode)(state, batch)
            jax.block_until_ready(jax.tree.leaves(out)[0])


def make_device_dp_lm_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    data: DeviceLMData,
    mesh: Mesh,
    *,
    eval_data: DeviceLMData | None = None,
    eval_windows: int | None = None,
    axis: str = "data",
    steps_per_call: int = 1,
    stateful: bool = False,
    grad_accum: int = 1,
    jit: bool = True,
    donate: bool | None = None,
):
    """Data-parallel LM device-data step: streams live sharded
    ``P(axis, None)`` (each chip's HBM holds only its batch rows — a cached
    RDD partition); the window slice is along time, so the feed needs no
    collective.

    With ``eval_data`` set (FUSED step), the valid stream shards its batch
    rows the same way and the per-shard eval sums psum into the exact
    global token-weighted mean (same value as make_dp_eval_step +
    evaluate())."""
    window_fn = lambda arrays, w: slice_window(arrays, w, data.seq_len)  # noqa: E731
    kw = dict(stateful=stateful, grad_accum=grad_accum,
              rng_transform=dp_rng_transform(axis), reduce_fn=dp_reduce_fn(axis))
    state_spec = TrainState(
        step=P(), params=P(), opt_state=P(), rng=P(),
        carries=P(axis) if stateful else P(),
    )
    stream_spec = {"streams": P(axis, None), "shifted": P(axis, None)}

    def core(state: TrainState, arrays, w0):
        return _scan_indexed(
            loss_fn, optimizer, state, arrays,
            _lm_window_idxs(w0, data, steps_per_call),
            window_fn=window_fn, **kw,
        )

    if eval_data is None:
        per_shard = core
        in_specs = (state_spec, stream_spec, P())
    else:
        n_ev = min(eval_data.n_windows, eval_windows or eval_data.n_windows)
        ev_T = eval_data.seq_len

        def per_shard(state: TrainState, arrays, w0, eval_arrays, do_eval,
                      eval_carries):
            state, ms = core(state, arrays, w0)
            return state, _gated_lm_eval(
                loss_fn, state, eval_arrays, do_eval, ms, n_windows=n_ev,
                seq_len=ev_T, stateful=stateful, eval_carries=eval_carries,
                psum_axis=axis,
            )

        in_specs = (state_spec, stream_spec, P(), stream_spec, P(),
                    P(axis) if stateful else P())

    sharded = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(state_spec, P()),
        check_vma=False,
    )
    return _jit_step(sharded, jit, donate)
