"""Train steps over device-resident datasets (data/device_dataset.py).

The step takes (state, staged arrays, per-step index array) and runs K
optimizer steps, materialising each batch out of HBM inside the scan — the
per-dispatch host traffic is the tiny index array (one scalar for the LM's
contiguous windows, [K, B] row ids for examples/series). Combines the
K-steps-per-call dispatch amortisation (train/multistep.py) with the
reference's cached-RDD data locality (SURVEY.md §3.1: executors iterate
their *resident* shard).

Three dataset shapes share ONE generic core (`make_device_train_step` /
`make_device_dp_train_step`, parameterised by a traced ``window_fn``):
  - LM contiguous windows (`slice_window`) — wrappers below keep the
    scalar-w0 API used by the CLI and bench;
  - per-example gather (`take_batch`) — classification;
  - series windows (`slice_forecast_batch`) — forecasting.

The scan body is the shared `step_body`, so semantics are identical to the
host-fed paths — tests/test_device_data.py asserts bit-level parity.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.4.35
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from ..data.device_dataset import DeviceLMData, slice_window
from .loop import (
    TrainState,
    _donation_supported,
    dp_reduce_fn,
    dp_rng_transform,
    step_body,
    summarize_scan_metrics,
)


def _scan_indexed(loss_fn, optimizer, state, arrays, idxs, *, window_fn,
                  stateful, grad_accum, rng_transform=None, reduce_fn=None):
    """lax.scan over the leading [K] axis of ``idxs``; each step builds its
    batch with ``window_fn(arrays, idx)`` and runs the shared step_body."""

    def body(s, idx):
        return step_body(
            loss_fn, optimizer, s, window_fn(arrays, idx), stateful=stateful,
            grad_accum=grad_accum, rng_transform=rng_transform,
            reduce_fn=reduce_fn,
        )

    state, ms = lax.scan(body, state, idxs)
    return state, summarize_scan_metrics(ms)


def make_device_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    window_fn: Callable,
    *,
    stateful: bool = False,
    grad_accum: int = 1,
    jit: bool = True,
    donate: bool | None = None,
):
    """Generic single-chip device-data step: ``step(state, arrays, idxs)``
    with ``idxs`` carrying a leading K axis (one entry per optimizer step)."""

    def step(state: TrainState, arrays, idxs):
        return _scan_indexed(
            loss_fn, optimizer, state, arrays, idxs, window_fn=window_fn,
            stateful=stateful, grad_accum=grad_accum,
        )

    if jit:
        if donate is None:
            donate = _donation_supported()
        step = jax.jit(step, donate_argnums=(0,) if donate else ())
    return step


def make_device_dp_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    window_fn: Callable,
    mesh: Mesh,
    arrays_spec,
    *,
    idx_spec=P(),
    axis: str = "data",
    stateful: bool = False,
    grad_accum: int = 1,
    jit: bool = True,
    donate: bool | None = None,
):
    """Generic data-parallel device-data step. ``arrays_spec`` gives the
    staged arrays' shardings (LM streams shard their batch rows; example/
    series arrays replicate); ``idx_spec`` the index array's (P() when every
    shard uses the same indices, P(None, axis) to split a [K, B] batch of
    row ids). Grads pmean over the ICI mesh as always."""

    def per_shard(state: TrainState, arrays, idxs):
        return _scan_indexed(
            loss_fn, optimizer, state, arrays, idxs, window_fn=window_fn,
            stateful=stateful, grad_accum=grad_accum,
            rng_transform=dp_rng_transform(axis),
            reduce_fn=dp_reduce_fn(axis),
        )

    state_spec = TrainState(
        step=P(), params=P(), opt_state=P(), rng=P(),
        carries=P(axis) if stateful else P(),
    )
    sharded = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(state_spec, arrays_spec, idx_spec),
        out_specs=(state_spec, P()),
        check_vma=False,
    )
    if jit:
        if donate is None:
            donate = _donation_supported()
        sharded = jax.jit(sharded, donate_argnums=(0,) if donate else ())
    return sharded


# ---- LM wrappers: scalar-w0 per-dispatch API (window indices computed
# ON-DEVICE from the traced scalar — per-dispatch host traffic really is
# one int32) ----


def _lm_window_idxs(w0, data: DeviceLMData, steps_per_call: int):
    return lax.rem(
        w0 + jnp.arange(steps_per_call, dtype=jnp.int32),
        jnp.int32(data.n_windows),
    )


def make_device_lm_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    data: DeviceLMData,
    *,
    steps_per_call: int = 1,
    stateful: bool = False,
    grad_accum: int = 1,
    jit: bool = True,
    donate: bool | None = None,
):
    """Single-chip LM device-data step: ``step(state, data.arrays, w0)``."""
    window_fn = lambda arrays, w: slice_window(arrays, w, data.seq_len)  # noqa: E731

    def step(state: TrainState, arrays, w0):
        return _scan_indexed(
            loss_fn, optimizer, state, arrays,
            _lm_window_idxs(w0, data, steps_per_call),
            window_fn=window_fn, stateful=stateful, grad_accum=grad_accum,
        )

    if jit:
        if donate is None:
            donate = _donation_supported()
        step = jax.jit(step, donate_argnums=(0,) if donate else ())
    return step


def make_device_dp_lm_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    data: DeviceLMData,
    mesh: Mesh,
    *,
    axis: str = "data",
    steps_per_call: int = 1,
    stateful: bool = False,
    grad_accum: int = 1,
    jit: bool = True,
    donate: bool | None = None,
):
    """Data-parallel LM device-data step: streams live sharded
    ``P(axis, None)`` (each chip's HBM holds only its batch rows — a cached
    RDD partition); the window slice is along time, so the feed needs no
    collective."""
    window_fn = lambda arrays, w: slice_window(arrays, w, data.seq_len)  # noqa: E731

    def per_shard(state: TrainState, arrays, w0):
        return _scan_indexed(
            loss_fn, optimizer, state, arrays,
            _lm_window_idxs(w0, data, steps_per_call),
            window_fn=window_fn, stateful=stateful, grad_accum=grad_accum,
            rng_transform=dp_rng_transform(axis),
            reduce_fn=dp_reduce_fn(axis),
        )

    state_spec = TrainState(
        step=P(), params=P(), opt_state=P(), rng=P(),
        carries=P(axis) if stateful else P(),
    )
    sharded = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(state_spec,
                  {"streams": P(axis, None), "shifted": P(axis, None)}, P()),
        out_specs=(state_spec, P()),
        check_vma=False,
    )
    if jit:
        if donate is None:
            donate = _donation_supported()
        sharded = jax.jit(sharded, donate_argnums=(0,) if donate else ())
    return sharded
