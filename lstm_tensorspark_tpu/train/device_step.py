"""Train steps over the device-resident LM dataset (data/device_dataset.py).

The step takes (state, staged arrays, scalar window index) and runs K
optimizer steps, slicing each [B, T] window out of HBM inside the scan —
host→device traffic per dispatch is ONE int32. Combines the K-steps-per-call
dispatch amortisation (train/multistep.py) with the reference's cached-RDD
data locality (SURVEY.md §3.1: executors iterate their *resident* shard).

The scan body is the shared `step_body`, so semantics are identical to the
host-fed paths — tests/test_device_data.py asserts bit-level parity.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.4.35
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from ..data.device_dataset import DeviceLMData, slice_window
from .loop import (
    TrainState,
    _donation_supported,
    dp_reduce_fn,
    dp_rng_transform,
    step_body,
    summarize_scan_metrics,
)


def _scan_windows(loss_fn, optimizer, state, arrays, w0, *, seq_len, n_windows,
                  steps_per_call, stateful, grad_accum, rng_transform=None,
                  reduce_fn=None):
    def body(s, j):
        batch = slice_window(arrays, lax.rem(w0 + j, n_windows), seq_len)
        return step_body(
            loss_fn, optimizer, s, batch, stateful=stateful,
            grad_accum=grad_accum, rng_transform=rng_transform,
            reduce_fn=reduce_fn,
        )

    state, ms = lax.scan(
        body, state, jnp.arange(steps_per_call, dtype=jnp.int32)
    )
    return state, summarize_scan_metrics(ms)


def make_device_lm_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    data: DeviceLMData,
    *,
    steps_per_call: int = 1,
    stateful: bool = False,
    grad_accum: int = 1,
    jit: bool = True,
    donate: bool | None = None,
):
    """Single-chip device-data step: ``step(state, data.arrays, w0)``."""

    def step(state: TrainState, arrays, w0):
        return _scan_windows(
            loss_fn, optimizer, state, arrays, w0,
            seq_len=data.seq_len, n_windows=data.n_windows,
            steps_per_call=steps_per_call, stateful=stateful,
            grad_accum=grad_accum,
        )

    if jit:
        if donate is None:
            donate = _donation_supported()
        step = jax.jit(step, donate_argnums=(0,) if donate else ())
    return step


def make_device_dp_lm_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    data: DeviceLMData,
    mesh: Mesh,
    *,
    axis: str = "data",
    steps_per_call: int = 1,
    stateful: bool = False,
    grad_accum: int = 1,
    jit: bool = True,
    donate: bool | None = None,
):
    """Data-parallel device-data step: streams live sharded ``P(axis, None)``
    (each chip's HBM holds only its batch rows — a cached RDD partition);
    the window slice is along time, so the feed needs no collective; grads
    pmean over the ICI mesh as always."""

    def per_shard(state: TrainState, arrays, w0):
        return _scan_windows(
            loss_fn, optimizer, state, arrays, w0,
            seq_len=data.seq_len, n_windows=data.n_windows,
            steps_per_call=steps_per_call, stateful=stateful,
            grad_accum=grad_accum,
            rng_transform=dp_rng_transform(axis),
            reduce_fn=dp_reduce_fn(axis),
        )

    state_spec = TrainState(
        step=P(), params=P(), opt_state=P(), rng=P(),
        carries=P(axis) if stateful else P(),
    )
    arrays_spec = {"streams": P(axis, None), "shifted": P(axis, None)}
    sharded = shard_map(
        per_shard,
        mesh=mesh,
        in_specs=(state_spec, arrays_spec, P()),
        out_specs=(state_spec, P()),
        check_vma=False,
    )
    if jit:
        if donate is None:
            donate = _donation_supported()
        sharded = jax.jit(sharded, donate_argnums=(0,) if donate else ())
    return sharded
