"""Draft-model distillation for speculative decoding (serve/ PR 18).

The serve plane's speculative decoder (serve/engine.py ``spec_window``)
needs a DRAFT LM that imitates the target's next-token behaviour at a
fraction of its step cost. This module trains one: a small LM (default
H/4, 1 layer, shared vocab — ``draft_config``) fit to the TARGET's
logits over a corpus with a KL+CE mixed loss, driven through the
existing train plane (``train/loop.py`` step/loop + ``make_optimizer``
— nothing speculative about the optimization itself).

The teacher's logits come from a batched SCORING pass
(``make_teacher_fn``): one jitted forward over each [B, T] window,
re-used across epochs is deliberately NOT done — the stream is
contiguous and the logits array is B*T*V floats, so holding an epoch of
them would dwarf the draft's own footprint. The map/reduce framing is
the paper's Spark lineage: score a partition, learn from it, move on.

Artifacts publish through the PR 16 model registry as a VERIFIED PAIR:
the draft's record carries ``config_hash`` = fingerprint of the draft's
own config and ``parent`` = ``"<teacher_id>:<teacher config hash>"``.
``load_draft`` re-derives the draft config from the teacher's
(``draft_config`` is deterministic) and refuses artifacts whose hashes
disagree — serve never pairs a draft with a teacher it was not
distilled from (the "version skew" runbook row, speculative edition).

Greedy speculative decode is token-identical to plain decode REGARDLESS
of draft quality (the target verifies every token); distillation only
buys acceptance length. So a bad draft is a PERFORMANCE bug, and this
module's only correctness duty is the pairing check above.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from ..models import LMConfig, init_lm, lm_forward
from ..serve.registry import ModelRegistry, config_fingerprint
from .loop import init_train_state, make_train_step, train_loop
from .optimizer import make_optimizer

#: the default draft shape relative to the teacher — ONE definition,
#: shared by `cli distill` and serve's `load_draft` derivation
DRAFT_HIDDEN_DIV = 4
DRAFT_NUM_LAYERS = 1


def draft_config(teacher_cfg: LMConfig, *,
                 hidden_div: int = DRAFT_HIDDEN_DIV,
                 num_layers: int = DRAFT_NUM_LAYERS) -> LMConfig:
    """The draft LM's config, derived DETERMINISTICALLY from the
    teacher's: shared vocab (proposals must be teacher tokens), hidden
    size divided by ``hidden_div`` (floored at 8 — below that the LSTM
    cannot even capture bigram structure), ``num_layers`` layers. The
    derivation is the pairing contract: serve re-derives this config
    from its resident teacher and verifies the published draft's
    ``config_hash`` against it, so the two sides agree on the
    architecture without shipping a config blob."""
    if hidden_div < 1:
        raise ValueError(f"hidden_div must be >= 1, got {hidden_div}")
    return LMConfig(
        vocab_size=teacher_cfg.vocab_size,
        hidden_size=max(8, teacher_cfg.hidden_size // hidden_div),
        num_layers=num_layers,
        tie_embeddings=teacher_cfg.tie_embeddings,
        compute_dtype=teacher_cfg.compute_dtype,
    )


def make_teacher_fn(teacher_params, teacher_cfg: LMConfig):
    """Jitted batched scoring pass: inputs [B, T] int32 → the teacher's
    logits [B, T, V] float32 (stop-gradient by construction — the
    teacher is data here, not a trainable)."""
    # strip training-only knobs: scoring is a plain forward, and e.g. a
    # teacher remat_chunk would just slow it down
    cfg = dataclasses.replace(teacher_cfg, dropout=0.0, remat_chunk=None)

    @jax.jit
    def score(inputs):
        logits, _ = lm_forward(teacher_params, inputs, cfg,
                               deterministic=True)
        return logits.astype(jnp.float32)

    return score


def make_distill_loss(cfg: LMConfig, *, alpha: float = 0.5,
                      temperature: float = 2.0):
    """KL+CE mixed distillation loss for ``make_train_step``:
    ``loss_fn(params, batch, rng) -> (loss, aux)`` over batches with
    ``inputs``/``targets`` [B, T] and ``teacher_logits`` [B, T, V].

    ``alpha`` weights the KL(teacher ‖ student) term at softmax
    temperature ``temperature`` (scaled by temperature² so the gradient
    magnitude is temperature-invariant — the standard Hinton scaling);
    ``1 - alpha`` weights the hard-label cross-entropy. ``alpha=1`` is
    pure imitation, ``alpha=0`` plain LM training on the same stream."""
    if not 0.0 <= alpha <= 1.0:
        raise ValueError(f"alpha must be in [0, 1], got {alpha}")
    if temperature <= 0.0:
        raise ValueError(f"temperature must be > 0, got {temperature}")
    tau = float(temperature)

    def loss_fn(params, batch, dropout_rng):
        logits, _ = lm_forward(
            params, batch["inputs"], cfg, dropout_rng=dropout_rng,
            deterministic=dropout_rng is None,
        )
        logits = logits.astype(jnp.float32)
        # soft target: KL(teacher ‖ student) at temperature tau
        t_logp = jax.nn.log_softmax(batch["teacher_logits"] / tau, axis=-1)
        s_logp = jax.nn.log_softmax(logits / tau, axis=-1)
        kl = jnp.mean(jnp.sum(jnp.exp(t_logp) * (t_logp - s_logp),
                              axis=-1)) * tau * tau
        # hard target: next-token NLL on the corpus labels
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(
            logp, batch["targets"][..., None].astype(jnp.int32), axis=-1,
        )[..., 0]
        ce = jnp.mean(nll)
        loss = alpha * kl + (1.0 - alpha) * ce
        aux = {"loss": loss, "kl": kl, "ce": ce,
               "tokens": batch["targets"].size}
        return loss, aux

    return loss_fn


def distill_batches(batches, teacher_fn):
    """Wrap an ``{"inputs", "targets"}`` batch stream with the
    teacher's logits, computed per window by the jitted scoring pass —
    the batched logit-harvest leg of the distillation loop (epoch-sized
    logit caches would be B*T*V floats per window; see module
    docstring)."""
    for batch in batches:
        out = dict(batch)
        out["teacher_logits"] = teacher_fn(batch["inputs"])
        yield out


def distill(teacher_params, teacher_cfg: LMConfig, batches, *,
            num_steps: int, hidden_div: int = DRAFT_HIDDEN_DIV,
            num_layers: int = DRAFT_NUM_LAYERS, alpha: float = 0.5,
            temperature: float = 2.0, optimizer: str = "adam",
            learning_rate: float = 1e-3, seed: int = 0,
            log_every: int = 50, logger=None):
    """Train a draft against ``teacher_params`` over an
    ``{"inputs", "targets"}`` batch stream. Returns
    ``(draft_params, draft_cfg)`` with params on host (ready to publish
    or attach)."""
    dcfg = draft_config(teacher_cfg, hidden_div=hidden_div,
                        num_layers=num_layers)
    params = init_lm(jax.random.PRNGKey(seed), dcfg)
    opt = make_optimizer(optimizer, learning_rate)
    state = init_train_state(params, opt, jax.random.PRNGKey(seed + 1))
    step = make_train_step(
        make_distill_loss(dcfg, alpha=alpha, temperature=temperature), opt)
    teacher_fn = make_teacher_fn(teacher_params, teacher_cfg)
    state = train_loop(
        state, step, distill_batches(batches, teacher_fn),
        num_steps=num_steps, log_every=log_every, logger=logger,
    )
    return jax.device_get(state.params), dcfg


# ---- registry pairing ----------------------------------------------------


def draft_model_id(teacher_id: str) -> str:
    """The registry id a teacher's draft publishes under by default."""
    return f"{teacher_id}-draft"


def publish_draft(registry, draft_params, draft_cfg: LMConfig,
                  teacher_cfg: LMConfig, *, teacher_id: str = "default",
                  draft_id: str | None = None,
                  version: int | None = None) -> dict:
    """Publish a distilled draft as the VERIFIED PAIR record (module
    docstring): ``config_hash`` fingerprints the draft's own config,
    ``parent`` names the teacher id and its config fingerprint.
    ``registry`` is a :class:`ModelRegistry` or a directory path."""
    from flax import serialization

    if isinstance(registry, str):
        registry = ModelRegistry(registry)
    return registry.publish(
        draft_id or draft_model_id(teacher_id),
        serialization.to_bytes(draft_params),
        version=version,
        config_hash=config_fingerprint(draft_cfg),
        parent=f"{teacher_id}:{config_fingerprint(teacher_cfg)}",
    )


def load_draft(registry, teacher_cfg: LMConfig, *,
               teacher_id: str = "default", draft_id: str | None = None,
               version: int | None = None):
    """Load a published draft for serving, verifying the pair: the
    draft config is RE-DERIVED from ``teacher_cfg`` (``draft_config``)
    and the artifact's ``config_hash`` must match it; the record's
    ``parent`` teacher fingerprint must match ``teacher_cfg``. Returns
    ``(meta, draft_params, draft_cfg)``; raises ``ValueError`` on any
    mismatch (serving an unpaired draft only costs acceptance, but a
    silent pairing bug would make every acceptance histogram a lie)."""
    if isinstance(registry, str):
        registry = ModelRegistry(registry)
    mid = draft_id or draft_model_id(teacher_id)
    dcfg = draft_config(teacher_cfg)
    want_hash = config_fingerprint(dcfg)
    meta = registry.meta(mid, version)
    if meta.get("config_hash") != want_hash:
        raise ValueError(
            f"draft {mid} v{meta['version']}: config_hash "
            f"{meta.get('config_hash')!r} does not match the derived "
            f"draft config {want_hash!r} (distilled for a different "
            "teacher shape, or with non-default draft dimensions)")
    want_parent = f"{teacher_id}:{config_fingerprint(teacher_cfg)}"
    if meta.get("parent") != want_parent:
        raise ValueError(
            f"draft {mid} v{meta['version']}: parent "
            f"{meta.get('parent')!r} does not match the serving teacher "
            f"{want_parent!r} — refusing the unverified pair")
    template = init_lm(jax.random.PRNGKey(0), dcfg)
    meta, params = registry.load_params(mid, template, meta["version"])
    return meta, jax.device_get(params), dcfg
