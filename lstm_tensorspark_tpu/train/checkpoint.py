"""Checkpoint/resume: msgpack-serialized TrainState pytree + step counter.

Reference parity: SURVEY.md §5 "Checkpoint / resume" — believed ABSENT in the
reference (a driver crash loses the run); this is deliberate new capability,
and the fault-tolerance story for the rebuild: Spark's lineage-based task
retry has no XLA equivalent and is subsumed by checkpoint-restart
(SURVEY.md §7 step 6).
"""

from __future__ import annotations

import os
import re

import jax
from flax import serialization


class Checkpointer:
    """Atomic msgpack checkpoints: ``step_<N>.msgpack`` under ``directory``."""

    _PAT = re.compile(r"step_(\d+)\.msgpack$")

    def __init__(self, directory: str, *, keep: int = 3):
        self.directory = directory
        self.keep = keep
        os.makedirs(directory, exist_ok=True)

    def _paths(self) -> list[tuple[int, str]]:
        out = []
        for name in os.listdir(self.directory):
            m = self._PAT.match(name)
            if m:
                out.append((int(m.group(1)), os.path.join(self.directory, name)))
        return sorted(out)

    def save(self, state) -> str:
        from ..utils import span

        with span("checkpoint_save"):
            state = jax.device_get(state)
            step = int(state.step)
            path = os.path.join(self.directory, f"step_{step}.msgpack")
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(serialization.to_bytes(state))
            os.replace(tmp, path)  # atomic: partial writes never count as a checkpoint
            for _, old in self._paths()[: -self.keep]:
                os.remove(old)
        return path

    def has_checkpoint(self) -> bool:
        return bool(self._paths())

    def restore_latest(self, template):
        """Restore newest checkpoint into the structure of ``template``
        (same model/optimizer config); None if no checkpoint exists."""
        paths = self._paths()
        if not paths:
            return None
        _, path = paths[-1]
        with open(path, "rb") as f:
            return serialization.from_bytes(template, f.read())
