"""Checkpoint/resume: msgpack-serialized TrainState pytree + step counter.

Reference parity: SURVEY.md §5 "Checkpoint / resume" — believed ABSENT in the
reference (a driver crash loses the run); this is deliberate new capability,
and the fault-tolerance story for the rebuild: Spark's lineage-based task
retry has no XLA equivalent and is subsumed by checkpoint-restart
(SURVEY.md §7 step 6).

Multi-host safety (VERDICT r1 weak #6): on a multi-process run the params
can be sharded so no process holds the full arrays — ``jax.device_get``
would fail, and every process racing to write one file would corrupt it.
The multi-process path therefore writes ONE FILE PER PROCESS containing
only that process's addressable shards, deduplicated by ``replica_id == 0``
so each global index is written exactly once across the job; process 0
then writes a ``step_<N>.complete`` marker (only marked steps are
restorable — a crash mid-save never yields a half checkpoint). Restore
merges every process file, reassembles full host arrays, and reshards them
onto the template's shardings via ``make_array_from_callback``.

Durability + corruption story (the resilience plane's checkpoint half):
every state-bearing file is fsync'd before the rename that makes it
visible, carries a ``.sha256`` sidecar, and ``restore_latest`` verifies
before trusting — a checkpoint that fails its checksum (or cannot be
deserialized at all) is QUARANTINED (renamed ``*.quarantined``, kept for
forensics) and restore falls back to the newest valid step instead of
crashing the run. Provoked deterministically by the ``ckpt_corrupt`` fault
(resilience/faults.py) in tests/test_chaos*.py.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import threading

import jax
import numpy as np
from flax import serialization

from ..resilience import ckpt_layout as _ckpt_layout
from ..resilience import faults as _faults


class CorruptCheckpointError(RuntimeError):
    """A state-bearing file failed its sha256 sidecar check (torn write,
    bit rot, or an injected ``ckpt_corrupt`` drill). ``restore_latest``
    quarantines the offending step and falls back to the newest valid
    one; the serve disk tier (serve/state_cache.py) quarantines the
    session file and reports the state honestly lost; this type only
    escapes from paths with nothing to fall back to."""


def atomic_write(path: str, data: bytes, *, checksum: bool = False) -> None:
    """fsync + tmp-write + rename: partial writes never count, and the
    data is durable BEFORE the rename makes it visible (rename-first
    ordering can leave a zero-length "complete" file after power loss
    — exactly the torn state the restore path would then trust).
    ``checksum=True`` additionally writes a ``<path>.sha256`` sidecar
    (state-bearing files only) that :func:`read_verified` checks; any
    PREVIOUS sidecar is removed before the payload rename and the new one
    lands after it, so a crash anywhere in the sequence leaves a payload
    (old or new) without a sidecar — verified as legacy/unchecked, never
    as a false mismatch. This matters for OVERWRITTEN paths (best.msgpack,
    serve session files): without the pre-remove, a crash between the two
    renames would pair the new payload with the old file's hash and the
    reader would quarantine a perfectly valid file.

    The reusable durability core shared by training checkpoints and the
    serve session disk tier (serve/state_cache.py)."""
    # writer-unique tmp names: two processes/threads writing the SAME
    # path concurrently (e.g. serve replicas checkpointing one session
    # into a shared --session-dir during a retirement race) must not
    # interleave inside one tmp file — each writes its own and the
    # os.replace ordering decides, atomically, which full payload wins
    uniq = f".{os.getpid()}.{threading.get_ident()}.tmp"
    tmp = path + uniq
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    if checksum:
        try:  # never pair new bytes w/ old hash (no exists/remove
            os.remove(path + ".sha256")  # TOCTOU: a concurrent writer
        except FileNotFoundError:  # may have removed it first)
            pass
    os.replace(tmp, path)
    if checksum:
        side_tmp = path + ".sha256" + uniq
        with open(side_tmp, "wb") as f:
            f.write(hashlib.sha256(data).hexdigest().encode())
            f.flush()
            os.fsync(f.fileno())
        os.replace(side_tmp, path + ".sha256")


def read_verified(path: str) -> bytes:
    """Read a state-bearing file, checking its sha256 sidecar when one
    exists (pre-checksum files have none and are accepted). Raises
    :class:`CorruptCheckpointError` on mismatch."""
    with open(path, "rb") as f:
        data = f.read()
    side = path + ".sha256"
    try:
        with open(side) as f:
            expect = f.read().strip()
    except FileNotFoundError:
        # pre-checksum files have no sidecar — accepted as legacy. The
        # exists()-then-open TOCTOU this replaces could race a writer's
        # sidecar swap (atomic_write removes the old sidecar before the
        # payload rename) into a spurious "corrupt" verdict.
        return data
    got = hashlib.sha256(data).hexdigest()
    if got != expect:
        raise CorruptCheckpointError(
            f"{path}: sha256 mismatch (expected {expect[:12]}…, "
            f"got {got[:12]}…) — truncated or corrupted write"
        )
    return data


def _sync(name: str) -> None:
    """Cross-process barrier (no-op single-process)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


class Checkpointer:
    """Atomic msgpack checkpoints under ``directory``.

    Single-process: ``step_<N>.msgpack`` (whole state, unchanged format).
    Multi-process: ``step_<N>.proc<k>.msgpack`` per process + a
    ``step_<N>.complete`` marker from process 0.
    """

    # filename patterns live in resilience/ckpt_layout.py (the ONE naming
    # authority, jax-free so the supervisor can share it)
    _PAT = _ckpt_layout.STEP_PAT
    _PROC_PAT = _ckpt_layout.PROC_PAT
    _DONE_PAT = _ckpt_layout.DONE_PAT

    def __init__(self, directory: str, *, keep: int = 3,
                 async_save: bool = False):
        """``async_save``: overlap serialization + file IO with training.
        ``save()`` then blocks only for the device→host snapshot
        (`jax.device_get`) and hands the write to a background thread — at
        most one in flight (a second save waits for the first). Write
        errors surface at the next ``save()``/``wait()``; the interpreter
        joins the non-daemon writer at exit, so the last checkpoint is
        durable even without an explicit ``wait()``. Multi-process saves
        always run synchronously (their cross-process barriers belong on
        the main thread), whatever this flag says."""
        self.directory = directory
        self.keep = keep
        self.async_save = async_save
        self._thread: threading.Thread | None = None
        self._error: BaseException | None = None
        self._best_meta_cache: dict | None = None
        os.makedirs(directory, exist_ok=True)

    # -- discovery ---------------------------------------------------------

    def _steps(self) -> list[int]:
        """Restorable steps, ascending: single-file steps plus marked
        multi-process steps."""
        single, marked = set(), set()
        for name in os.listdir(self.directory):
            m = self._PAT.match(name)
            if m:
                single.add(int(m.group(1)))
            m = self._DONE_PAT.match(name)
            if m:
                marked.add(int(m.group(1)))
        return sorted(single | marked)

    def _files_for_step(self, step: int) -> list[str]:
        out = []
        for name in os.listdir(self.directory):
            for pat in (self._PAT, self._PROC_PAT, self._DONE_PAT):
                m = self._match_state_file(pat, name)
                if m and int(m.group(1)) == step:
                    out.append(os.path.join(self.directory, name))
        return out

    def has_checkpoint(self) -> bool:
        return bool(self._steps())

    def has_quarantined(self) -> bool:
        """True when the directory holds ``*.quarantined`` files — evidence
        that checkpoints EXISTED and were set aside as corrupt. A --resume
        that finds no valid checkpoint but sees this must refuse to fresh-
        start (cli._wire_checkpoint), or the supervisor's relaunch would
        silently defeat the corruption refusal one restart later."""
        try:
            return any(n.endswith(".quarantined")
                       for n in os.listdir(self.directory))
        except OSError:
            return False

    def latest_step(self):
        """Newest restorable step, or None."""
        steps = self._steps()
        return steps[-1] if steps else None

    def fence_after(self, step: int) -> None:
        """Delete every step_N checkpoint NEWER than ``step`` — the
        --resume-best rewind: the abandoned lineage's later checkpoints
        must not be restorable, or a subsequent --resume would silently
        continue the diverged weights the user rewound away from.

        Multi-process: process 0 deletes, everyone barriers on both
        edges — concurrent unlinks of the same shared-fs files would
        race, and no process may proceed to re-save until the fence is
        fully down."""
        _sync(f"ckpt_fence_enter_{step}")
        if jax.process_index() == 0:
            for s in self._steps():
                if s > step:
                    for f in self._files_for_step(s):
                        os.remove(f)
        _sync(f"ckpt_fence_done_{step}")

    # -- save --------------------------------------------------------------

    def save(self, state) -> str:
        from ..utils import span

        with span("checkpoint_save"):
            if jax.process_count() > 1:
                path = self._save_sharded(state)
                self._cleanup()
            elif self.async_save:
                self.wait()  # one write in flight; surface prior errors
                host = jax.device_get(state)  # snapshot BEFORE training moves on
                path = self._path_for(int(host.step))
                self._thread = threading.Thread(
                    target=self._write_and_clean, args=(host,),
                    name="checkpoint-writer",
                )
                self._thread.start()
            else:
                path = self._save_single(jax.device_get(state))
                self._cleanup()
        return path

    def wait(self) -> None:
        """Join any in-flight async write; re-raise its error if it failed."""
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def _write_and_clean(self, host_state) -> None:
        try:
            self._save_single(host_state)
            self._cleanup()
        except BaseException as e:  # surfaced by the next save()/wait()
            self._error = e

    def _cleanup(self) -> None:
        # keep-N, oldest first (process 0 only — the others' files are
        # deleted by step, after the save barrier)
        if jax.process_index() == 0:
            for step in self._steps()[: -self.keep]:
                for f in self._files_for_step(step):
                    os.remove(f)

    def _path_for(self, step: int) -> str:
        """Single-process checkpoint filename — the ONE naming authority
        (must stay in sync with ``_PAT``)."""
        return os.path.join(self.directory, f"step_{step}.msgpack")

    # -- best-metric checkpoint -------------------------------------------

    @property
    def _best_path(self) -> str:
        return os.path.join(self.directory, "best.msgpack")

    def save_best(self, state, value: float) -> str:
        """Write/overwrite the best-eval checkpoint.

        Single-process: ONE atomic artifact (``best.msgpack``: {step,
        value, state-bytes}) so the metadata can never describe different
        weights than the file holds. Multi-process (VERDICT r3 item 7):
        the SAME sharded-writer machinery as ``save()`` — one
        ``best_<step>.proc<k>.msgpack`` per process, then a
        ``best.complete`` marker from process 0 carrying {writers, step,
        value}; the step-stamped filenames mean a crash mid-save can
        never mix old and new shard files under one marker (the old
        marker keeps pointing at the old, complete set). ``best.json``
        is a derived convenience view written after (advisory only).
        Called by the train loop only on metric improvement, so it stays
        synchronous (rare) and independent of the step_N rotation —
        keep-N cleanup never deletes it."""
        if jax.process_count() > 1:
            return self._save_best_sharded(state, value)
        self.wait()  # never interleave with an in-flight async write
        host = jax.device_get(state)
        payload = {
            "step": int(host.step),
            "value": float(value),
            "state": serialization.to_bytes(host),
        }
        self._atomic_write(self._best_path,
                           serialization.msgpack_serialize(payload),
                           checksum=True)
        meta = os.path.join(self.directory, "best.json")
        self._atomic_write(
            meta,
            json.dumps({"step": payload["step"],
                        "value": payload["value"]}).encode(),
        )
        # a single-process best supersedes any earlier SHARDED best: drop
        # its marker + shard files so the two artifact kinds never coexist
        # past a save (see _best_artifact for the crash-window tiebreak)
        try:
            os.remove(self._best_marker)
        except FileNotFoundError:
            pass  # no sharded best to supersede
        for name in os.listdir(self.directory):
            if self._match_state_file(self._BEST_PROC_PAT, name):
                os.remove(os.path.join(self.directory, name))
        self._best_meta_cache = {"step": payload["step"],
                                 "value": payload["value"]}
        return self._best_path

    @staticmethod
    def _match_state_file(pat, name: str):
        """Pattern-match a state filename OR its ``.sha256`` sidecar (the
        sidecar shares every lifecycle event — cleanup, fencing,
        quarantine — with its payload)."""
        base = name[: -len(".sha256")] if name.endswith(".sha256") else name
        return pat.match(base)

    _BEST_PROC_PAT = re.compile(r"best_(\d+)\.proc(\d+)\.msgpack$")

    @property
    def _best_marker(self) -> str:
        return os.path.join(self.directory, "best.complete")

    def _save_best_sharded(self, state, value: float) -> str:
        # defensive fence for save-path symmetry with save_best (ADVICE
        # r4): today async step saves only start on the single-process
        # branch, so this is a no-op under current routing — it exists so
        # the "never interleave with an in-flight async write" contract
        # survives if a sharded async path is ever added (wait() also
        # re-raises a failed writer's exception, same as save_best)
        self.wait()
        step = int(jax.device_get(state.step))
        pid = jax.process_index()
        # clear leftovers of a crashed attempt AT THIS STEP (other steps'
        # files may be the live best — only the marker says which)
        if pid == 0:
            for name in os.listdir(self.directory):
                m = self._match_state_file(self._BEST_PROC_PAT, name)
                if m and int(m.group(1)) == step:
                    os.remove(os.path.join(self.directory, name))
        _sync(f"best_clean_{step}")
        payload = self._local_shards_payload(state, step)
        payload["value"] = float(value)
        path = os.path.join(self.directory,
                            f"best_{step}.proc{pid}.msgpack")
        self._atomic_write(path, serialization.msgpack_serialize(payload),
                           checksum=True)
        # every process must finish before the marker flips the live best
        _sync(f"best_save_{step}")
        if pid == 0:
            meta = {"writers": jax.process_count(), "step": step,
                    "value": float(value)}
            self._atomic_write(self._best_marker,
                               json.dumps(meta).encode())
            self._atomic_write(
                os.path.join(self.directory, "best.json"),
                json.dumps({"step": step, "value": float(value)}).encode(),
            )
            # the marker now points at this step's set: older sets AND any
            # single-process best.msgpack from an earlier 1-process run are
            # dead (a stale best.msgpack must not shadow this best)
            for name in os.listdir(self.directory):
                m = self._match_state_file(self._BEST_PROC_PAT, name)
                if m and int(m.group(1)) != step:
                    os.remove(os.path.join(self.directory, name))
            for stale in (self._best_path, self._best_path + ".sha256"):
                try:
                    os.remove(stale)
                except FileNotFoundError:
                    pass  # never existed (or a peer already removed it)
        _sync(f"best_done_{step}")
        self._best_meta_cache = {"step": step, "value": float(value)}
        return path

    def _best_artifact(self):
        """(kind, meta, payload) of the live best artifact, or
        (None, None, None). ``payload`` is the already-verified, parsed
        best.msgpack content for the "single" kind (None for "sharded") —
        returned so restore_best never re-reads and re-hashes a
        potentially multi-GB file the arbitration below just processed.

        Each save deletes the OTHER kind, so both coexist only in the
        tiny crash window between writing the new artifact and unlinking
        the old — arbitrate by step, newer wins (tie → the single-file
        artifact: it is self-contained). Without the tiebreak a stale
        best.msgpack from an earlier 1-process run would permanently
        shadow every later sharded best."""
        single = sharded = None
        payload = None
        if os.path.exists(self._best_path):
            # OSError propagates: transient IO is not corruption — retry,
            # don't destroy discoverability (same policy as restore_latest)
            try:
                payload = self._classified_parse(
                    self._best_path, self._read_verified(self._best_path),
                    serialization.msgpack_restore)
                single = {"step": int(payload["step"]),
                          "value": float(payload["value"])}
            except CorruptCheckpointError as e:
                # a CORRUPT best must not crash best_meta/restore_best (or
                # shadow a valid sharded best): set it aside and move on
                print(f"checkpoint: QUARANTINING corrupt best.msgpack: "
                      f"{e}", flush=True)
                for p in (self._best_path, self._best_path + ".sha256"):
                    try:
                        os.replace(p, p + ".quarantined")
                    except OSError:
                        pass  # best effort; discovery will retry it
        try:
            with open(self._best_marker) as f:
                meta = json.loads(f.read())
        except FileNotFoundError:
            # no sharded best (the common single-process layout); the
            # exists()-then-open this replaces could race a concurrent
            # save_best's marker removal into a crash
            pass
        else:
            sharded = {"step": int(meta["step"]),
                       "value": float(meta["value"]),
                       "writers": int(meta["writers"])}
        if single is not None and (sharded is None
                                   or sharded["step"] <= single["step"]):
            return "single", single, payload
        if sharded is not None:
            return "sharded", sharded, None
        return None, None, None

    def best_meta(self) -> dict | None:
        """{step, value} of the saved best checkpoint (from the
        AUTHORITATIVE artifact, not the advisory sidecar; cached after the
        first read — the state-bearing file is parsed once, not once per
        caller), or None. Used to seed the train loop's best-so-far across
        restarts so a resumed run can never overwrite a better best with a
        worse one."""
        if self._best_meta_cache is not None:
            return dict(self._best_meta_cache)
        self.wait()
        kind, meta, _ = self._best_artifact()
        if kind is None:
            return None
        self._best_meta_cache = {"step": meta["step"], "value": meta["value"]}
        return dict(self._best_meta_cache)

    def restore_best(self, template):
        """Restore the best-metric checkpoint (None if never saved).
        Handles both artifact kinds: the single-process ``best.msgpack``
        and the sharded ``best_<step>.proc<k>`` set named by
        ``best.complete`` — a sharded best restores (resharded onto the
        template) even under a LATER different process count, like any
        sharded step checkpoint."""
        self.wait()
        kind, meta, payload = self._best_artifact()
        if kind is None:
            return None
        if kind == "single":
            # payload was read, verified and parsed by _best_artifact —
            # no second multi-GB read/hash of the same file
            restored = serialization.from_bytes(template, payload["state"])
            return self._reshard_like(template, restored)
        step, writers = meta["step"], meta["writers"]
        paths = []
        for name in sorted(os.listdir(self.directory)):
            m = self._BEST_PROC_PAT.match(name)
            if m and int(m.group(1)) == step and int(m.group(2)) < writers:
                paths.append(os.path.join(self.directory, name))
        try:
            if len(paths) < writers:
                raise CorruptCheckpointError(
                    f"best step {step}: only {len(paths)} of {writers} "
                    "proc files present"
                )
            return self._assemble_from_procs(template, paths, step)
        except CorruptCheckpointError as e:
            # same contract as the single-file best and restore_latest:
            # corruption quarantines the artifact and reports "no best"
            # instead of crashing a --resume-best run
            print(f"checkpoint: QUARANTINING corrupt sharded best "
                  f"(step {step}): {e}", flush=True)
            for p in [*paths, *(p + ".sha256" for p in paths),
                      self._best_marker]:
                try:
                    os.replace(p, p + ".quarantined")
                except OSError:
                    pass  # already gone (or a peer quarantined it first)
            self._best_meta_cache = None
            return None

    # module-level atomic_write/read_verified (extracted so the serve
    # session disk tier shares the exact durability core), bound as
    # staticmethods to keep every existing call site working
    _atomic_write = staticmethod(atomic_write)

    @staticmethod
    def _classified_parse(path: str, data: bytes, parse):
        """Parse state ``data`` read from ``path``, classifying failure by
        the module's ONE corruption policy: checksum-VERIFIED bytes that
        fail to parse mean a structural/config mismatch (re-raised loudly
        — quarantining would destroy valid checkpoints), while a legacy
        unchecksummed file that fails to parse is indistinguishable from
        truncation (→ CorruptCheckpointError, favoring recovery)."""
        try:
            return parse(data)
        except Exception as e:
            if os.path.exists(path + ".sha256"):
                raise  # verified bytes: not corruption — surface it
            raise CorruptCheckpointError(
                f"{path}: cannot deserialize legacy (unchecksummed) file: "
                f"{type(e).__name__}: {e}"
            ) from e

    _read_verified = staticmethod(read_verified)

    def _quarantine_step(self, step: int, reason: str) -> None:
        """Rename every file of a corrupt step to ``*.quarantined`` so the
        discovery patterns stop matching it (restore falls back to the
        next-newest step) while the evidence stays on disk for forensics.
        Quarantined files are exempt from keep-N cleanup."""
        print(f"checkpoint: QUARANTINING step {step}: {reason}", flush=True)
        for f in self._files_for_step(step):
            try:
                os.replace(f, f + ".quarantined")
            except OSError:
                pass  # best effort: a vanished file is already "gone"

    def _save_single(self, host_state) -> str:
        step = int(host_state.step)
        path = self._path_for(step)
        self._atomic_write(path, serialization.to_bytes(host_state),
                           checksum=True)
        # chaos drills: an armed ckpt_corrupt fault tears THIS file now,
        # after the write completed — the restore-side checksum must catch
        # it and fall back (tests/test_chaos*.py)
        _faults.maybe_corrupt_checkpoint(path, step)
        return path

    def _local_shards_payload(self, state, step: int) -> dict:
        """This process's contribution to a sharded checkpoint: for each
        leaf, the addressable shards it uniquely owns (``replica_id == 0``
        dedupe — exactly one writer per global index across the job);
        host-side leaves belong to process 0. Shared by the step and best
        sharded writers."""
        pid = jax.process_index()
        leaves = jax.tree.leaves(state)
        payload: dict = {"step": step, "leaves": {}}
        for i, leaf in enumerate(leaves):
            recs = []
            if isinstance(leaf, jax.Array) and hasattr(leaf, "addressable_shards"):
                for sh in leaf.addressable_shards:
                    if sh.replica_id != 0:
                        continue  # exactly one writer per global index
                    idx = sh.index  # tuple of slices into the global shape
                    recs.append({
                        "start": [int(s.start or 0) for s in idx],
                        "stop": [
                            int(s.stop if s.stop is not None else d)
                            for s, d in zip(idx, leaf.shape)
                        ],
                        "data": np.asarray(sh.data),
                    })
            else:  # host-side leaf: process 0 owns it
                if pid == 0:
                    a = np.asarray(leaf)
                    recs.append({
                        "start": [0] * a.ndim,
                        "stop": list(a.shape),
                        "data": a,
                    })
            if recs:
                payload["leaves"][str(i)] = recs
        return payload

    def _save_sharded(self, state) -> str:
        # state.step is replicated → locally readable on every process
        step = int(jax.device_get(state.step))
        pid = jax.process_index()
        # Clear any leftovers for this step from a previously crashed save
        # (possibly with a DIFFERENT process count): stale proc files would
        # otherwise merge into a later restore and corrupt it.
        if pid == 0:
            for f in self._files_for_step(step):
                os.remove(f)
        _sync(f"ckpt_clean_{step}")
        payload = self._local_shards_payload(state, step)
        path = os.path.join(self.directory, f"step_{step}.proc{pid}.msgpack")
        self._atomic_write(path, serialization.msgpack_serialize(payload),
                           checksum=True)
        # chaos drills fire on sharded saves too (process 0 tears its own
        # proc file — deterministic, no cross-process marker race), so a
        # multi-process ckpt_corrupt drill exercises _restore_sharded's
        # corruption fallback instead of silently never firing
        if pid == 0:
            _faults.maybe_corrupt_checkpoint(path, step)
        # every process must finish writing before the step is marked
        # restorable (assumes a shared filesystem, the standard pod setup)
        _sync(f"ckpt_save_{step}")
        if pid == 0:
            done = os.path.join(self.directory, f"step_{step}.complete")
            # marker records the writer count: restore only merges proc
            # files below it (second guard against stale files)
            self._atomic_write(done, str(jax.process_count()).encode())
        _sync(f"ckpt_done_{step}")
        return path

    # -- restore -----------------------------------------------------------

    def restore_latest(self, template):
        """Restore the newest checkpoint into the structure of ``template``
        (same model/optimizer config); None if no checkpoint exists.

        Template leaves that are sharded jax.Arrays get the restored values
        RESHARDED onto their shardings (works across a changed process
        count / mesh layout); host leaves come back as host arrays.

        Corruption fallback: a newest checkpoint that is CORRUPT — checksum
        mismatch, an undeserializable legacy (unchecksummed) file, or a
        sharded step missing proc files — is QUARANTINED (files renamed
        ``*.quarantined``, kept for forensics) and the next-newest step is
        tried, until a valid one restores or none remain (→ None, with a
        loud warning per quarantined step). A truncated write must cost
        one checkpoint interval, not the run. Deliberately NOT swallowed:
        OSError (transient IO is not corruption — retry, don't destroy
        discoverability) and deserialization failures of checksum-VERIFIED
        files (bytes are exactly what was written, so the template/model
        config is wrong — quarantining every checkpoint would silently
        restart the run from step 0). Each step is attempted at most once
        per call, so a quarantine that cannot rename (read-only dir) still
        terminates.
        """
        self.wait()  # never read around an in-flight write
        attempted: set[int] = set()
        while True:
            steps = [s for s in self._steps() if s not in attempted]
            if not steps:
                return None
            step = steps[-1]
            attempted.add(step)
            single = self._path_for(step)
            try:
                if os.path.exists(single):
                    restored = self._deserialize_verified(template, single)
                    return self._reshard_like(template, restored)
                return self._restore_sharded(template, step)
            except CorruptCheckpointError as e:
                self._quarantine_step(step, str(e))
            except FileNotFoundError as e:
                # NOT the transient-IO class: a file that existed at
                # discovery and is gone at read was quarantined by a PEER
                # process racing the same corrupt step (multi-process
                # restore). Fall back like the peer did, so every process
                # converges on the same older step and the sync barriers
                # stay aligned.
                self._quarantine_step(step, f"vanished mid-read "
                                            f"(peer quarantine?): {e}")

    def _deserialize_verified(self, template, path: str):
        """Checksum-check then deserialize one single-file checkpoint,
        classifying failures: checksum mismatch → CorruptCheckpointError
        (quarantine + fall back); parse failure of a VERIFIED file →
        re-raised as-is (the bytes are intact, so the template/config is
        wrong — a loud error, not a quarantine); parse failure of a legacy
        unchecksummed file → CorruptCheckpointError (truncation and
        mismatch are indistinguishable there — favor recovery)."""
        data = self._read_verified(path)  # raises CorruptCheckpointError
        return self._classified_parse(
            path, data, lambda d: serialization.from_bytes(template, d))

    def _restore_sharded(self, template, step: int):
        done = os.path.join(self.directory, f"step_{step}.complete")
        try:
            with open(done) as f:
                n_writers = int(f.read().strip() or 0)
        except (OSError, ValueError):
            n_writers = None  # legacy "ok" marker: accept all proc files
        paths = []
        for name in sorted(os.listdir(self.directory)):
            m = self._PROC_PAT.match(name)
            if not m or int(m.group(1)) != step:
                continue
            if n_writers is not None and int(m.group(2)) >= n_writers:
                continue  # stale file from an older, larger job
            paths.append(os.path.join(self.directory, name))
        if n_writers is not None and len(paths) < n_writers:
            # a marked-complete step with vanished proc files is damage,
            # not a config problem: fall back like any other corruption
            raise CorruptCheckpointError(
                f"checkpoint step {step}: only {len(paths)} of {n_writers} "
                "proc files present"
            )
        return self._assemble_from_procs(template, paths, step)

    def _assemble_from_procs(self, template, paths: list, step: int):
        """Merge per-process shard files and reassemble every template
        leaf, resharding onto the template's shardings (shared by the
        step and best restore paths)."""
        merged: dict[int, list] = {}
        for p in paths:
            payload = self._classified_parse(
                p, self._read_verified(p), serialization.msgpack_restore)
            for k, recs in payload["leaves"].items():
                merged.setdefault(int(k), []).extend(recs)
        t_leaves, treedef = jax.tree.flatten(template)
        out = []
        # Assemble + place ONE LEAF AT A TIME so peak host memory is the
        # largest single leaf, not the whole model. (Each process still
        # materializes the full leaf before resharding — acceptable until a
        # single leaf outgrows host RAM.)
        for i, t in enumerate(t_leaves):
            recs = merged.pop(i, None)
            if not recs:
                raise ValueError(
                    f"checkpoint step {step} is missing leaf {i}; "
                    "was it written with a different model config?"
                )
            shape = tuple(np.asarray(t).shape) if not isinstance(t, jax.Array) \
                else t.shape
            full = np.empty(shape, dtype=np.asarray(recs[0]["data"]).dtype)
            for r in recs:
                idx = tuple(
                    slice(int(a), int(b)) for a, b in zip(r["start"], r["stop"])
                )
                full[idx] = r["data"]
            out.append(self._place_leaf(t, full))
            del full, recs
        return jax.tree.unflatten(treedef, out)

    @staticmethod
    def _place_leaf(t, v):
        """Place one restored host value onto its template leaf's sharding.

        Reshards only onto MULTI-device template shardings. Leaves whose
        template is host-side or single-device stay as host numpy —
        committing them (e.g. the step scalar) to one local device would
        conflict with the global arrays at the next jit call."""
        if (
            isinstance(t, jax.Array)
            and hasattr(t, "sharding")
            and getattr(t.sharding, "num_devices", 1) > 1
            and not isinstance(v, jax.Array)
        ):
            host = np.asarray(v)
            return jax.make_array_from_callback(
                host.shape, t.sharding, lambda idx: host[idx]
            )
        return v

    def _reshard_like(self, template, restored):
        return jax.tree.map(self._place_leaf, template, restored)
