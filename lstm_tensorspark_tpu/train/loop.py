"""Single-program training loop: jitted train step + host-side epoch driver.

Reference parity: SURVEY.md §3.1 — the reference's outer hot loop is
broadcast(params) → mapPartitions(train_partition) → treeAggregate(grads) →
driver update, with full param/grad serialization over TCP each round. Here
the whole round is ONE jitted XLA program: forward, BPTT (jax.grad), and the
optimizer update run on-device; the host only sees scalar metrics. Under the
data-parallel backend (parallel/data_parallel.py) the same step body runs
under shard_map with a psum in place of treeAggregate (SURVEY.md §3.3).

Buffer donation (`donate_argnums=0`) reuses the parameter/optimizer memory
across steps — the rebuilt equivalent of "weights live on-device, zero host
round-trips per step" (SURVEY.md §2 native-capability table).
"""

from __future__ import annotations

import math
import os
import time
from typing import Any, Callable, Iterable, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from .. import obs
from ..ops import parallel_scan as _pscan
from ..resilience import faults as _faults


class AnomalousTrainingError(RuntimeError):
    """Raised by :func:`train_loop` after ``anomaly_limit`` CONSECUTIVE
    non-finite steps: the model is diverged (or the data/hardware is
    producing garbage) and continuing would only burn budget skipping
    updates. The CLI maps it to ``resilience.exit_codes.ANOMALY_RC`` so the
    supervisor restarts from the last checkpoint — whose params are clean,
    because the guard skipped every anomalous update."""

    def __init__(self, consecutive: int, total: int, step: int):
        self.consecutive = consecutive
        self.total = total
        self.step = step
        super().__init__(
            f"{consecutive} consecutive non-finite steps at step {step} "
            f"({total} anomalous total); aborting for supervisor restart"
        )


class TrainState(NamedTuple):
    step: jax.Array  # scalar int32
    params: Any
    opt_state: Any
    rng: jax.Array
    # Recurrent state carried across contiguous windows (stateful truncated
    # BPTT). None for stateless training; per-layer (h, c) otherwise.
    carries: Any = None


def init_train_state(params, optimizer, rng, *, carries=None) -> TrainState:
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=optimizer.init(params),
        rng=rng,
        carries=carries,
    )


def _donation_supported() -> bool:
    # Buffer donation is a memory optimisation (in-place param/opt-state
    # update). The tunneled TPU backend in this environment rejects donated
    # buffers on real train steps with an opaque INVALID_ARGUMENT *and*
    # poisons the process afterwards, so it cannot be probed-and-recovered
    # in-process. Default off; set LSTM_TSP_DONATE=1 on platforms with
    # working donation (standard TPU/GPU/CPU runtimes).
    return os.environ.get("LSTM_TSP_DONATE", "0") == "1"


def call_loss(loss_fn, params, batch, rng, carries, *, stateful: bool):
    """Uniform invocation of the (stateless|stateful) loss_fn signature."""
    if stateful:
        return loss_fn(params, batch, rng, carries)
    return loss_fn(params, batch, rng)


def accumulate_grads(loss_fn, params, batch, rng, *, grad_accum: int):
    """Microbatched gradient accumulation: split the (per-shard) batch into
    ``grad_accum`` equal microbatches along the leading axis and `lax.scan`
    value_and_grad over them, keeping a running mean of grads and loss.

    Peak activation memory drops to one microbatch's worth (the BPTT
    activations of [B/N, T] instead of [B, T]) at the cost of N sequential
    grad passes — the standard large-model trade. Equal microbatch sizes make
    the mean-of-means exactly the full-batch mean, so the update is
    numerically the full-batch update (tests/test_grad_accum.py)."""
    micro = jax.tree.map(
        lambda a: a.reshape(grad_accum, a.shape[0] // grad_accum, *a.shape[1:]),
        batch,
    )

    def body(acc, inp):
        i, mb = inp
        (loss, _), grads = jax.value_and_grad(
            lambda p: call_loss(
                loss_fn, p, mb, jax.random.fold_in(rng, i), None, stateful=False
            ),
            has_aux=True,
        )(params)
        g_acc, l_acc = acc
        g_acc = jax.tree.map(lambda a, b: a + b / grad_accum, g_acc, grads)
        return (g_acc, l_acc + loss / grad_accum), None

    zero = jax.tree.map(jnp.zeros_like, params)
    (grads, loss), _ = jax.lax.scan(
        body, (zero, jnp.zeros((), jnp.float32)), (jnp.arange(grad_accum), micro)
    )
    return loss, grads


def step_body(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    state: TrainState,
    batch,
    *,
    stateful: bool = False,
    rng_transform: Callable | None = None,
    reduce_fn: Callable | None = None,
    grad_accum: int = 1,
):
    """The ONE train-step body shared by the single-chip and data-parallel
    paths (keeps them provably identical — test_dp.py's loss-parity relies on
    it). ``rng_transform`` perturbs the per-step dropout key (DP folds in the
    shard index); ``reduce_fn(grads, loss)`` inserts the cross-shard mean
    (DP: lax.pmean — the treeAggregate replacement); ``grad_accum > 1``
    microbatches the gradient computation (stateless losses only — recurrent
    carries are batch-aligned and do not split)."""
    rng, sub = jax.random.split(state.rng)
    if rng_transform is not None:
        sub = rng_transform(sub)
    if grad_accum > 1:
        if stateful:
            raise ValueError("grad_accum is not supported with stateful TBPTT")
        loss, grads = accumulate_grads(
            loss_fn, state.params, batch, sub, grad_accum=grad_accum
        )
        carries = state.carries
    else:
        (loss, aux), grads = jax.value_and_grad(
            lambda p: call_loss(
                loss_fn, p, batch, sub, state.carries, stateful=stateful
            ),
            has_aux=True,
        )(state.params)
        carries = jax.lax.stop_gradient(aux["carries"]) if stateful else state.carries
    grads = _faults.tamper_grads(grads, state.step)  # identity when unarmed
    if reduce_fn is not None:
        grads, loss = reduce_fn(grads, loss)
    updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    gnorm = optax.global_norm(grads)
    # Non-finite guard: a NaN/Inf loss or gradient must not poison the
    # params/optimizer moments (one bad batch would otherwise end the run —
    # every later step inherits the NaNs). Skip the whole update (params,
    # moments, AND carries — a diverged forward pass taints the recurrent
    # state too), advance step/rng so the budget and data order hold, and
    # surface the skip as metrics["anomalous"] for the host loop to count.
    # Under DP the guard decision is uniform across shards: loss and grads
    # are pmean'd before the check.
    finite = jnp.isfinite(loss) & jnp.isfinite(gnorm)
    keep = lambda new, old: jnp.where(finite, new, old)  # noqa: E731
    params = jax.tree.map(keep, params, state.params)
    opt_state = jax.tree.map(keep, opt_state, state.opt_state)
    if stateful:
        carries = jax.tree.map(keep, carries, state.carries)
    metrics = {
        "loss": loss,
        "grad_norm": gnorm,
        "anomalous": (~finite).astype(jnp.float32),
    }
    return TrainState(state.step + 1, params, opt_state, rng, carries), metrics


def dp_rng_transform(axis: str = "data"):
    """Per-shard dropout-key perturbation: fold the shard index into the
    step key (distinct dropout per shard, common everything else). The ONE
    definition shared by every DP step builder (parallel/data_parallel.py,
    multistep.py, device_step.py). Lives here — the dependency-free base
    module — to avoid train↔parallel import cycles."""
    return lambda sub: jax.random.fold_in(sub, jax.lax.axis_index(axis))


def dp_reduce_fn(axis: str = "data"):
    """The treeAggregate replacement: mean grads (and loss, for logging)
    across shards with one ICI all-reduce. The ONE definition shared by
    every DP step builder — change the gradient-reduction contract here."""
    return lambda grads, loss: (
        jax.lax.pmean(grads, axis),
        jax.lax.pmean(loss, axis),
    )


def summarize_scan_metrics(ms) -> dict:
    """Reduce per-step metrics stacked by a K-step `lax.scan` to the logging
    contract shared by every multi-step path (multistep.py, device_step.py):
    ``loss`` = mean over the K steps, ``loss_last``/``grad_norm`` = final
    step's, ``anomalous`` (when the body reports it) = COUNT of skipped
    (non-finite) steps in the window."""
    out = {
        "loss": jnp.mean(ms["loss"]),
        "loss_last": ms["loss"][-1],
        "grad_norm": ms["grad_norm"][-1],
    }
    if "anomalous" in ms:
        out["anomalous"] = jnp.sum(ms["anomalous"])
    return out


def make_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    *,
    jit: bool = True,
    donate: bool | None = None,
    stateful: bool = False,
    grad_accum: int = 1,
):
    """Build the jitted step.

    Stateless (default): ``loss_fn(params, batch, dropout_rng) -> (loss, aux)``.
    Stateful TBPTT (``stateful=True``): ``loss_fn(params, batch, dropout_rng,
    carries) -> (loss, aux)`` with ``aux["carries"]`` the final recurrent
    state; it is gradient-stopped and fed to the next window (truncated BPTT
    over the contiguous stream — SURVEY.md §5 "Long-context" row).
    """

    def train_step(state: TrainState, batch):
        return step_body(
            loss_fn, optimizer, state, batch,
            stateful=stateful, grad_accum=grad_accum,
        )

    if jit:
        if donate is None:
            donate = _donation_supported()
        train_step = jax.jit(train_step, donate_argnums=(0,) if donate else ())
    return train_step


def make_eval_step(loss_fn: Callable, *, jit: bool = True, stateful: bool = False):
    """Forward-only step (SURVEY.md §3.4): loss, no grads, no update.

    Stateful variant returns ``({"loss": ...}, carries)`` so evaluation can
    carry recurrent state across contiguous windows."""

    def _metrics(loss, aux):
        # Token count for exact token-weighted averaging in evaluate();
        # losses are per-token means, so the cross-batch mean must be
        # weighted by tokens to stay exact under unequal batch sizes
        # (dropped remainders, variable-length buckets).
        m = {"loss": loss}
        if isinstance(aux, dict) and "tokens" in aux:
            m["tokens"] = aux["tokens"]
        return m

    if stateful:

        def eval_step(params, batch, carries):
            loss, aux = loss_fn(params, batch, None, carries)
            return _metrics(loss, aux), aux["carries"]

    else:

        def eval_step(params, batch):
            loss, aux = loss_fn(params, batch, None)
            return _metrics(loss, aux)

    if jit:
        eval_step = jax.jit(eval_step)
    return eval_step


def evaluate(
    eval_step, params, batches: Iterable, *, carries=None
) -> dict[str, float]:
    """Token-weighted mean loss + perplexity over batches. Pass ``carries``
    (with a stateful eval_step) to thread recurrent state through the
    contiguous stream.

    Batch losses are weighted by their token count (when the loss aux
    reports one) so perplexity is the exact corpus-level value under any
    batching — equal-size batches, dropped remainders, or variable-length
    buckets all give the same answer."""
    stateful = carries is not None
    # keep every batch's metric HANDLES and fetch once after the loop:
    # float(...) inside the loop would block on each batch's device
    # program (B host round-trips per eval sweep), serializing dispatch
    # with readback exactly like per-token decode used to. The handles
    # are O(1) scalars each, so holding B of them is free.
    handles = []
    for batch in batches:
        if stateful:
            m, carries = eval_step(params, batch, carries)
        else:
            m = eval_step(params, batch)
        handles.append(m)
    total, weight = 0.0, 0.0
    for m in jax.device_get(handles):
        w = float(m["tokens"]) if "tokens" in m else 1.0
        total += float(m["loss"]) * w
        weight += w
    loss = total / max(weight, 1.0)
    return eval_metrics(loss)


def eval_metrics(loss: float) -> dict[str, float]:
    """The ONE loss→metrics mapping shared by host-side `evaluate()` and the
    fused on-device eval (device_step.py) so their records are comparable."""
    # math.exp, not jnp.exp: the jnp spelling dispatched a whole device
    # program (and a blocking readback) to exponentiate ONE host scalar
    # on every eval record
    loss = float(loss)
    return {
        "eval_loss": loss,
        "eval_ppl": math.exp(min(loss, 30.0)),
    }


def train_loop(
    state: TrainState,
    train_step: Callable,
    batches: Iterable,
    *,
    num_steps: int | None = None,
    log_every: int = 50,
    logger=None,
    eval_fn: Callable[[Any], dict] | None = None,
    eval_every: int = 0,
    checkpoint_fn: Callable[[TrainState], None] | None = None,
    checkpoint_every: int = 0,
    tokens_per_batch: int | None = None,
    steps_per_call: int = 1,
    fused_eval: Callable[[dict], dict] | None = None,
    flops_per_token: float | None = None,
    peak_tflops: float | None = None,
    best_fn: Callable | None = None,
    best_metric: str = "eval_loss",
    best_mode: str = "min",
    best_init: float | None = None,
    anomaly_limit: int = 0,
) -> TrainState:
    """Drive the jitted step over a batch iterator, logging scalar metrics.

    The only host↔device traffic per logged step is the scalar metric fetch
    (and even that is amortised over ``log_every`` async-dispatched steps).

    With ``steps_per_call=K`` (the multi-step path, train/multistep.py) each
    iteration is one K-step dispatch: ``num_steps``/``log_every``/
    ``eval_every``/``checkpoint_every`` count CALLS, and throughput metrics
    are scaled by K to stay in optimizer-steps/tokens per second.

    With ``fused_eval`` set (device_step.py's train+eval builders) the step
    signature is ``train_step(state, batch, do_eval)`` and the eval record
    is ``fused_eval(metrics)`` — a task-specific mapper from the step's own
    eval scalars (the LM derives perplexity, the classifier reads accuracy)
    — instead of calling ``eval_fn``: one executable for both cadences,
    zero train/eval program swaps.

    ``best_fn(state, value)`` (e.g. Checkpointer.save_best) fires whenever
    an eval improves ``best_metric`` under ``best_mode`` ("min"/"max") —
    best-checkpoint tracking, independent of the periodic rotation.
    ``best_init`` seeds the best-so-far (a resumed run passes the saved
    best's value so it can never overwrite a better checkpoint with a
    worse one).

    ``anomaly_limit=K`` (off at 0) aborts with
    :class:`AnomalousTrainingError` after K CONSECUTIVE anomalous
    (non-finite, update-skipped) steps — the supervisor restarts from
    checkpoint with the dedicated exit code. Enabling it fetches the
    per-step ``anomalous`` scalar, which adds one host sync per loop
    iteration (the same cost a per-step loss fetch would have): leave it 0
    on dispatch-bound runs that don't need the watchdog. With
    ``steps_per_call=K'`` the fetched value is the window COUNT; a fully
    anomalous window extends the consecutive run, a partially anomalous
    one resets it (it contained at least one finite step).
    """
    window_start = time.perf_counter()
    # telemetry (obs/): step-time/tokens-per-sec recorded at the log
    # cadence from the SAME window timings the JSONL records use (no
    # extra host sync); anomalous steps counted wherever the scalar is
    # already fetched. MetricsLogger.log_registry snapshots these.
    _m_step = obs.REGISTRY.histogram(
        "train_step_seconds", "mean optimizer-step wall time per log window",
        buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 300.0))
    _m_tps = obs.REGISTRY.gauge(
        "train_tokens_per_sec", "training throughput at the last log point")
    _m_steps = obs.REGISTRY.counter(
        "train_steps_total", "optimizer steps driven (log-window granular)")
    _m_anomalous = obs.REGISTRY.counter(
        "train_anomalous_steps_total",
        "non-finite steps whose update was skipped")
    # bptt-mode observability (ops/parallel_scan.py): traces happen on the
    # first dispatch inside this loop, so the fallback delta across the
    # loop captures this run's resolutions. Surfaced in metrics_snapshot
    # (cli.py adds the requested mode string) so a supervised restart can
    # detect a bptt-mode flip between resume legs.
    _m_bptt_fb = obs.REGISTRY.counter(
        "train_bptt_assoc_fallbacks_total",
        "auto bptt resolutions that fell back to the sequential backward")
    _m_bptt_tr = obs.REGISTRY.counter(
        "train_bptt_assoc_traces_total",
        "scans traced with the associative-scan backward")
    _bptt0 = _pscan.assoc_stats()
    if num_steps is not None and num_steps <= 0:
        return state  # eval-only budget: never pull a batch from the feed
    try:
        state = _run_train_loop(
            state, train_step, batches, num_steps=num_steps,
            log_every=log_every, logger=logger, eval_fn=eval_fn,
            eval_every=eval_every, checkpoint_fn=checkpoint_fn,
            checkpoint_every=checkpoint_every,
            tokens_per_batch=tokens_per_batch, steps_per_call=steps_per_call,
            fused_eval=fused_eval, flops_per_token=flops_per_token,
            peak_tflops=peak_tflops, best_fn=best_fn,
            best_metric=best_metric, best_mode=best_mode, best_init=best_init,
            anomaly_limit=anomaly_limit, window_start=window_start,
            _m_step=_m_step, _m_tps=_m_tps, _m_steps=_m_steps,
            _m_anomalous=_m_anomalous,
        )
    finally:
        # counted on every exit path — an anomaly abort's final
        # metrics_snapshot must still carry the bptt evidence
        _b = _pscan.assoc_stats()
        fb = _b["sequential_fallbacks"] - _bptt0["sequential_fallbacks"]
        tr = _b["assoc_traces"] - _bptt0["assoc_traces"]
        if fb:
            _m_bptt_fb.inc(fb)
        if tr:
            _m_bptt_tr.inc(tr)
    return state


def _run_train_loop(
    state, train_step, batches, *, num_steps, log_every, logger, eval_fn,
    eval_every, checkpoint_fn, checkpoint_every, tokens_per_batch,
    steps_per_call, fused_eval, flops_per_token, peak_tflops, best_fn,
    best_metric, best_mode, best_init, anomaly_limit, window_start,
    _m_step, _m_tps, _m_steps, _m_anomalous,
):
    """The drive loop proper (split from `train_loop` so the bptt trace
    accounting above wraps every exit path in one place)."""
    last_metrics = None
    anomalous_total = 0
    anomalous_consec = 0
    best_val = best_init
    for i, batch in enumerate(batches):
        if num_steps is not None and i >= num_steps:
            break
        step = i + 1
        if fused_eval:
            do_eval = bool(eval_every) and step % eval_every == 0
            state, metrics = train_step(state, batch, np.bool_(do_eval))
        else:
            state, metrics = train_step(state, batch)
        last_metrics = metrics
        if anomaly_limit and "anomalous" in metrics:
            bad = int(float(metrics["anomalous"]))  # sync point (documented)
            anomalous_total += bad
            if bad:
                _m_anomalous.inc(bad)
            if bad >= steps_per_call:
                anomalous_consec += bad
            else:
                anomalous_consec = 0
            if anomalous_consec >= anomaly_limit:
                if logger is not None:
                    logger.log({"step": int(state.step),
                                "note": "anomaly abort",
                                "anomalous_steps": anomalous_total,
                                "anomalous_consecutive": anomalous_consec})
                raise AnomalousTrainingError(
                    anomalous_consec, anomalous_total, int(state.step))
        if log_every and step % log_every == 0:
            loss = float(metrics["loss"])  # sync point
            now = time.perf_counter()
            dt = now - window_start
            window_start = now
            window_steps = log_every * steps_per_call
            _m_step.observe(dt / window_steps)
            _m_steps.inc(window_steps)
            record = {
                "step": int(state.step),
                "loss": loss,
                "grad_norm": float(metrics["grad_norm"]),
                "steps_per_sec": log_every * steps_per_call / dt,
            }
            if anomaly_limit:
                # cumulative (exact: every step was fetched above)
                if anomalous_total:
                    record["anomalous_steps"] = anomalous_total
            elif "anomalous" in metrics:
                # watchdog off: report the logged step/window's own count
                # (no per-step fetch, so no cumulative claim)
                bad = float(metrics["anomalous"])
                if bad:
                    record["anomalous"] = bad
                    _m_anomalous.inc(bad)
            if tokens_per_batch:
                tps = tokens_per_batch * log_every * steps_per_call / dt
                record["tokens_per_sec"] = tps
                _m_tps.set(tps)
                if flops_per_token:
                    # live MFU: achieved model TFLOP/s (train = 3x forward
                    # matmul accounting, utils/flops.py). ``peak_tflops``
                    # is the AGGREGATE peak of every participating chip —
                    # tokens_per_sec is the global rate, so dividing by one
                    # chip's peak would overstate MFU by the device count.
                    record["model_tflops"] = tps * flops_per_token / 1e12
                    if peak_tflops:
                        record["mfu"] = round(
                            record["model_tflops"] / peak_tflops, 4
                        )
            if logger is not None:
                logger.log(record)
        if eval_every and step % eval_every == 0:
            if fused_eval is not None:
                ev = fused_eval(metrics)
            elif eval_fn is not None:
                ev = eval_fn(state.params)
            else:
                ev = None
            if ev is not None and logger is not None:
                logger.log({"step": int(state.step), **ev})
            if best_fn is not None and ev is not None and best_metric in ev:
                v = float(ev[best_metric])
                # NaN must never become (or remain) the best: it would win
                # once (any comparison with None/NaN) and then never be
                # beaten, pinning the best checkpoint to a diverged model
                # forever — a NaN seeded via best_init (legacy file)
                # counts as "no best yet"
                no_best = best_val is None or best_val != best_val
                improved = v == v and (no_best or (
                    v < best_val if best_mode == "min" else v > best_val
                ))
                if improved:
                    best_val = v
                    best_fn(state, v)
                    if logger is not None:
                        logger.log({"step": int(state.step),
                                    "note": f"new best {best_metric}",
                                    best_metric: v})
        if checkpoint_fn is not None and checkpoint_every and step % checkpoint_every == 0:
            checkpoint_fn(state)
    if last_metrics is not None:
        jax.block_until_ready(last_metrics["loss"])
    return state
