from .optimizer import make_optimizer
from .loop import TrainState, make_train_step, make_eval_step, train_loop
from .multistep import make_multi_train_step, make_dp_multi_train_step
from .device_step import (
    TrainStepCompileCache,
    make_device_train_step,
    make_device_dp_train_step,
    make_device_lm_train_step,
    make_device_dp_lm_train_step,
)

__all__ = [
    "TrainStepCompileCache",
    "make_optimizer",
    "TrainState",
    "make_train_step",
    "make_eval_step",
    "train_loop",
    "make_multi_train_step",
    "make_dp_multi_train_step",
    "make_device_train_step",
    "make_device_dp_train_step",
    "make_device_lm_train_step",
    "make_device_dp_lm_train_step",
]
