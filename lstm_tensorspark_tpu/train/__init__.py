from .optimizer import make_optimizer
from .loop import TrainState, make_train_step, make_eval_step, train_loop

__all__ = [
    "make_optimizer",
    "TrainState",
    "make_train_step",
    "make_eval_step",
    "train_loop",
]
