"""lstm_tensorspark_tpu — a TPU-native LSTM training framework.

A from-scratch rebuild of the capabilities of
EmanuelOverflow/LSTM-TensorSpark (a hand-rolled TensorFlow LSTM trained
data-parallel via PySpark mapPartitions/treeAggregate/broadcast), redesigned
for TPU: the cell is a pure function unrolled with `jax.lax.scan` and
jit-compiled by XLA; gradient averaging is `lax.psum` over the ICI mesh
(`shard_map`); parameters live replicated on-device, so the reference's
per-round parameter broadcast and gradient tree-reduce disappear.

Reference provenance: the reference mount was empty during the survey
(SURVEY.md §0), so parity claims cite SURVEY.md sections (tagged [D]/[P]/[I])
rather than file:line.

Layout:
  ops/       — LSTM cell math, scan unroll, remat, masking (SURVEY.md §2 L2/L1)
  models/    — LM / classifier / seq2seq model families (SURVEY.md §6 configs)
  parallel/  — mesh, data/tensor/sequence parallel backends (SURVEY.md §2 L3)
  train/     — train loop, optimizer, checkpoint, metrics (SURVEY.md §2 L4)
  data/      — corpora, vocab, batching (SURVEY.md §2 "Data pipeline")
  cli.py     — reference-parity CLI entrypoint (SURVEY.md §2 L5)
"""

__version__ = "0.1.0"
