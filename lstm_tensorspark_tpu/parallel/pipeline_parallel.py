"""Pipeline parallelism for stacked LSTM layers over the "pipe" mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2 strategy inventory:
"not required for parity") — this is new first-class capability, built on
the same wavefront machinery as sequence parallelism (DESIGN.md notes the
wavefront is PP's natural substrate).

Layout: the L stacked layers are split into S = |pipe| stages of L/S layers
each; stage s owns layers [s*L/S, (s+1)*L/S). Layer parameters (and their
optimizer state) are *sharded* over "pipe" — each device stores only its
stage's weights, the point of PP. Embedding and head are replicated; only
stage 0 reads the embedding and only stage S-1 applies the head, so their
gradients are nonzero on exactly one stage and shard_map's transpose psums
them back to consistency.

Schedule: GPipe-style wavefront over M microbatches — at tick t, stage s
processes microbatch m = t - s and hands its activations [b, T, H] one hop
right via `lax.ppermute` (ICI neighbor traffic only). Utilization is
M/(M+S-1): the (S-1)-tick fill/drain bubble amortises away as M grows.
`lax.cond` on the per-device active predicate skips real compute during
bubble ticks (safe here: no collectives inside a stage's scan).

Autodiff: `jax.grad` through the shard_map reverses the wavefront
(ppermute transposes to the opposite ring), giving pipelined BPTT with the
same schedule in reverse. The train step does grad/update at the jit level —
shard_map's transpose inserts the psums for replicated inputs, and GSPMD
propagates the P("pipe") param sharding to the optimizer state, so each
stage's Adam moments etc. also live only on that stage.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.4.35
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from ..models.lstm_lm import LMConfig
from ..ops.embedding import embed_lookup, selected_logits
from ..ops.lstm_cell import LSTMParams
from ..ops.scan import auto_lstm_scan, lstm_scan
from ..train.loop import TrainState, step_body


def stack_layers(layers: list[LSTMParams]) -> LSTMParams:
    """Stack per-layer params into one LSTMParams of [L, ...] arrays so the
    layer axis can be sharded over "pipe".

    Non-uniform input sizes (embed_size != hidden_size makes layer 0's W
    rows differ) are zero-PADDED to the max input size. Padding is exact:
    the padded W rows multiply zero-padded activations (pp_lm_loss pads its
    inter-layer tensors), contribute nothing to the forward, and receive
    identically-zero gradients (dW_pad = x_pad^T @ dz = 0), so they stay
    zero under any optax transform."""
    dmax = max(p.input_size for p in layers)

    def pad_w(p: LSTMParams) -> LSTMParams:
        pad = dmax - p.input_size
        if pad == 0:
            return p
        pw = lambda a: jnp.pad(a, ((0, pad), (0, 0)))
        return p._replace(W_i=pw(p.W_i), W_f=pw(p.W_f),
                          W_g=pw(p.W_g), W_o=pw(p.W_o))

    return jax.tree.map(lambda *a: jnp.stack(a), *[pad_w(p) for p in layers])


def unstack_layers(
    stacked: LSTMParams, input_sizes: list[int] | None = None
) -> list[LSTMParams]:
    """Invert stack_layers; ``input_sizes`` slices each layer's W back to
    its true row count (None = uniform stack, no slicing)."""
    L = stacked.W_i.shape[0]
    layers = [jax.tree.map(lambda a: a[j], stacked) for j in range(L)]
    if input_sizes is None:
        return layers

    def cut(p: LSTMParams, d: int) -> LSTMParams:
        cw = lambda a: a[:d]
        return p._replace(W_i=cw(p.W_i), W_f=cw(p.W_f),
                          W_g=cw(p.W_g), W_o=cw(p.W_o))

    return [cut(p, d) for p, d in zip(layers, input_sizes)]


def stack_lm_params(params):
    """LM params with the per-layer list replaced by a stacked pytree."""
    return {**params, "layers": stack_layers(params["layers"])}


def unstack_lm_params(params):
    """Invert stack_lm_params, recovering the true per-layer W row counts
    (layer 0: embed dim from the embedding table; rest: hidden)."""
    embed = params["embedding"].shape[1]
    hidden = params["layers"].U_i.shape[-1]
    L = params["layers"].W_i.shape[0]
    sizes = [embed] + [hidden] * (L - 1)
    return {**params, "layers": unstack_layers(params["layers"], sizes)}


def pp_lm_param_specs(params_stacked):
    """shard_map in_specs: stacked layers sharded over "pipe" (the MANUAL
    axis), everything else replicated. TP does not appear here — "model" is
    an AUTO axis handled by GSPMD from the jit-level shardings below."""
    specs = {
        k: jax.tree.map(lambda _: P(), v)
        for k, v in params_stacked.items()
        if k != "layers"
    }
    specs["layers"] = jax.tree.map(lambda _: P("pipe"), params_stacked["layers"])
    return specs


def pp_lm_param_shardings(params_stacked, *, tp: bool = False):
    """jit-level PartitionSpecs: layers over "pipe" and (with ``tp``) gate/
    hidden dims over "model" — the hybrid manual-PP/auto-TP composition.
    Stacked layer arrays are [L, D, 4H] (W), [L, H, 4H] (U), [L, 4H] (b)."""
    model = "model" if tp else None
    mat = P("pipe", None, model)
    vec = P("pipe", model)
    layer_specs = LSTMParams(
        W_i=mat, W_f=mat, W_g=mat, W_o=mat,
        U_i=mat, U_f=mat, U_g=mat, U_o=mat,
        b_i=vec, b_f=vec, b_g=vec, b_o=vec,
    )
    specs = {"embedding": P(), "layers": layer_specs}
    head = {"bias": P()}
    if "kernel" in params_stacked["head"]:
        head["kernel"] = P(model, None)  # [H/P, V] row-parallel
    specs["head"] = head
    return specs


def place_pp_lm_params(params_stacked, mesh: Mesh, *, tp: bool = False):
    return jax.tree.map(
        lambda a, s: jax.device_put(a, NamedSharding(mesh, s)),
        params_stacked,
        pp_lm_param_shardings(params_stacked, tp=tp),
    )


def pp_zero1_opt_specs(optimizer, params_stacked, mesh: Mesh, *,
                       tp: bool = False):
    """The ZeRO-1 x PP optimizer-state spec tree — the ONE derivation
    every consumer shares (the train step's shardings pin, the CLI's and
    dryrun's initial placement, tests): each moment leaf's stage-sharded
    spec extended with the data axis (zero.zero1_tp_opt_specs applied to
    the stacked param specs)."""
    from .zero import zero1_tp_opt_specs

    return zero1_tp_opt_specs(
        optimizer, params_stacked,
        pp_lm_param_shardings(params_stacked, tp=tp), mesh,
    )


def place_pp_zero1_opt_state(opt_state, optimizer, params_stacked,
                             mesh: Mesh, *, tp: bool = False):
    """Place a fresh/restored optimizer state on its stage x data shards
    up front — no device ever materializes a data-replicated copy."""
    return jax.tree.map(
        lambda a, s: jax.device_put(a, NamedSharding(mesh, s)),
        opt_state,
        pp_zero1_opt_specs(optimizer, params_stacked, mesh, tp=tp),
        is_leaf=lambda x: isinstance(x, jax.Array) or x is None,
    )


def pp_lm_loss(
    params,
    batch,
    cfg: LMConfig,
    *,
    microbatches: int = 1,
    pipe_axis: str = "pipe",
    data_axis: str = "data",
    dropout_rng: jax.Array | None = None,
    uniform: bool = False,
    use_pallas: bool = False,
):
    """Global-mean LM loss under the pipeline wavefront.

    MUST run inside shard_map, manual over {pipe_axis, data_axis}. ``params``
    is the local view: layers [L/S, ...] (this stage's slice), embedding and
    head full. ``batch`` is this data-shard's {"inputs","targets"} [B_local,
    T], replicated over "pipe". Returns the already-reduced global scalar.

    embed_size != hidden_size is handled by the stack_layers zero-padding:
    every inter-layer/inter-stage tensor is carried at width
    Dmax = max(embed, hidden) with exact zero lanes (see stack_layers).

    With ``dropout_rng`` set and cfg.dropout > 0, inter-layer dropout
    applies after every layer except the globally-last one, with masks
    independent per (data shard, microbatch, layer) — the same fold-in
    scheme the DP backend uses for per-shard dropout.

    ``uniform=True`` (REQUIRED when "model" is an auto TP axis): every
    stage computes every tick and bubble results are masked with where()
    instead of skipped with lax.cond — GSPMD-inserted TP collectives must
    execute in lockstep across devices, and divergent cond branches would
    deadlock them (the same constraint as sp_lstm_scan's uniform mode).

    ``use_pallas`` runs each stage-interior recurrence through the fused
    Pallas kernel (ops/pallas_lstm.py) — legal because a stage's scan
    contains NO collectives (the only inter-device traffic is the ppermute
    between ticks), so the kernel sits entirely inside this device's manual
    shard. Callers must keep it off when "model" is an auto TP axis: GSPMD
    cannot partition a pallas_call over the sharded hidden dim.
    """
    S = lax.axis_size(pipe_axis)
    s = lax.axis_index(pipe_axis)
    M = microbatches
    inputs, targets = batch["inputs"], batch["targets"]
    B, T = inputs.shape
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    b = B // M
    H = cfg.hidden_size
    Dmax = max(cfg.embed, H)

    embedding = params["embedding"]
    head = params["head"]
    kernel = embedding.T if cfg.tie_embeddings else head["kernel"]
    local_layers = unstack_layers(params["layers"])  # padded widths kept
    n_local = len(local_layers)
    cdtype = None if cfg.cdtype == jnp.float32 else cfg.cdtype
    L_total = n_local * S
    use_dropout = dropout_rng is not None and cfg.dropout > 0.0
    if use_dropout:
        # distinct masks per data shard; pipe/microbatch/layer fold below
        dropout_rng = jax.random.fold_in(dropout_rng, lax.axis_index(data_axis))

    inputs_m = inputs.reshape(M, b, T)
    targets_m = targets.reshape(M, b, T)

    def pad_d(x):
        """[b, T, d] -> [b, T, Dmax] with exact zero lanes."""
        d = x.shape[-1]
        return x if d == Dmax else jnp.pad(x, ((0, 0), (0, 0), (0, Dmax - d)))

    def run_stage(src, rng):
        ys = src  # [b, T, Dmax]
        for i, layer in enumerate(local_layers):
            _, ys = auto_lstm_scan(
                layer, ys,
                compute_dtype=cdtype,
                remat_chunk=cfg.remat_chunk,
                unroll=cfg.scan_unroll,
                use_pallas=use_pallas,
            )
            g = s * n_local + i  # global layer index (traced: s is an
            # axis_index, so gate "not the last layer" with where, not if)
            if use_dropout:
                from ..ops.masking import dropout_with_key

                dropped = dropout_with_key(
                    jax.random.fold_in(rng, i), cfg.dropout, ys
                )
                ys = jnp.where(g == L_total - 1, ys, dropped)
            ys = pad_d(ys)
        return ys  # [b, T, Dmax]

    def mb_loss(ys, tgt):
        logits = (
            jnp.dot(ys[..., :H].astype(kernel.dtype), kernel,
                    preferred_element_type=cfg.ldtype)
            + head["bias"].astype(cfg.ldtype)
        )
        # logsumexp form — keep identical to lm_loss (parity tests compare
        # the two bit-for-bit) and skip the [b,T,V] log-prob array
        lg = logits.astype(jnp.float32)
        lse = jax.nn.logsumexp(lg, axis=-1)
        t_ = selected_logits(lg, tgt)
        return jnp.mean(lse - t_)

    x_in = jnp.zeros((b, T, Dmax), jnp.float32)
    loss_acc = jnp.zeros((), jnp.float32)
    right = [(i, i + 1) for i in range(S - 1)]  # linear chain, no wraparound
    is_last = s == S - 1

    for t in range(M + S - 1):
        m = t - s  # microbatch this stage works on at tick t
        active = jnp.logical_and(m >= 0, m < M)
        m_c = jnp.clip(m, 0, M - 1)
        tok = lax.dynamic_index_in_dim(inputs_m, m_c, axis=0, keepdims=False)
        tgt = lax.dynamic_index_in_dim(targets_m, m_c, axis=0, keepdims=False)
        # stage 0 sources from the embedding; later stages from the left
        # neighbor's activations. where() zeroes the embedding gradient on
        # stages > 0, so the psum'd embedding grad is exactly stage 0's.
        emb_x = pad_d(embed_lookup(embedding, tok).astype(jnp.float32))
        src = jnp.where(s == 0, emb_x, x_in)
        rng_t = (
            jax.random.fold_in(dropout_rng, m_c * S + s) if use_dropout
            else jnp.zeros((2,), jnp.uint32)
        )
        if uniform:
            # lockstep ticks: compute unconditionally, mask bubble results —
            # auto-axis (TP) collectives inside the stage must not sit under
            # divergent control flow
            ys = jnp.where(active, run_stage(src, rng_t), 0.0)
            loss_acc = loss_acc + jnp.where(
                jnp.logical_and(active, is_last), mb_loss(ys, tgt), 0.0
            )
        else:
            ys = lax.cond(
                active,
                run_stage,
                lambda x, r: jnp.zeros((b, T, Dmax), jnp.float32),
                src, rng_t,
            )
            loss_acc = loss_acc + lax.cond(
                jnp.logical_and(active, is_last),
                mb_loss,
                lambda ys, tgt: jnp.zeros((), jnp.float32),
                ys, tgt,
            )
        if S > 1:
            x_in = lax.ppermute(ys, pipe_axis, right)

    loss = lax.psum(loss_acc, pipe_axis) / M  # only the last stage contributed
    return lax.pmean(loss, data_axis)


def make_pp_lm_eval_step(
    cfg: LMConfig,
    mesh: Mesh,
    params_stacked,
    *,
    microbatches: int | None = None,
    tp: bool = False,
):
    """Forward-only eval on the STAGE-SHARDED params (VERDICT r1 weak #7):
    the wavefront runs exactly as in training, deterministic; no host
    gather — the point of PP is that one device cannot hold the model.
    Reports the global token count for exact token-weighted evaluate()."""
    S = mesh.shape["pipe"]
    if microbatches is None:
        microbatches = max(S, 1)
    use_pallas = cfg.use_pallas and not tp
    loss_shard = shard_map(
        lambda p, bt: pp_lm_loss(
            p, bt, cfg, microbatches=microbatches, uniform=tp,
            use_pallas=use_pallas,
        ),
        mesh=mesh,
        in_specs=(pp_lm_param_specs(params_stacked),
                  {"inputs": P("data"), "targets": P("data")}),
        out_specs=P(),
        # Mosaic refuses a pallas_call inside a PARTIALLY-manual shard_map;
        # with the fused kernel live (no TP ⇒ "model"/"seq" are size 1) make
        # every mesh axis manual — semantically identical, Mosaic-legal.
        axis_names=(set(mesh.axis_names) if use_pallas else {"pipe", "data"}),
        check_vma=False,
    )

    def eval_step(params, batch):
        loss = loss_shard(params, batch)
        # jit-level shapes are global, so this is the global token count
        tokens = jnp.asarray(batch["targets"].size, jnp.float32)
        return {"loss": loss, "tokens": tokens}

    param_shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        pp_lm_param_shardings(params_stacked, tp=tp),
        is_leaf=lambda x: isinstance(x, P),
    )
    batch_shardings = {
        "inputs": NamedSharding(mesh, P("data")),
        "targets": NamedSharding(mesh, P("data")),
    }
    return jax.jit(eval_step, in_shardings=(param_shardings, batch_shardings))


def make_pp_lm_train_step(
    cfg: LMConfig,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    params_stacked,
    *,
    microbatches: int | None = None,
    donate: bool | None = None,
    tp: bool = False,
    zero1: bool = False,
):
    """Build the DP x PP (x TP with ``tp=True``) train step on stacked params.

    Batch: {"inputs","targets"} [B, T], B % (data axis * microbatches) == 0.
    ``microbatches`` defaults to the pipe size (pipeline full at steady
    state). Grad/update happen at the jit level: shard_map's transpose
    produces correct grads (psum'd for replicated embedding/head, local for
    the stage-sharded layers), and jit propagates P("pipe") to opt state.

    ``zero1`` composes ZeRO-1 with the stage sharding (VERDICT r3 item 6):
    the optimizer-state moment leaves get their param's spec EXTENDED with
    the "data" axis on an unsharded divisible dimension
    (`zero.zero1_tp_opt_specs` — the same GSPMD weight-update-sharding
    spec tree the TP task runners use, applied to the STACKED
    stage-sharded specs), and the step's in/out shardings PIN them there.
    Each chip then stores 1/(pipe*data) of the moments — the
    memory-relevant pairing for stacked-LSTM scale (config 5). Leaves
    keep full logical shapes, so checkpoints reshard across any later
    dp x pp like plain PP state.

    TP composition is hybrid manual/auto (the train_step.py pattern): the
    shard_map is MANUAL over {"pipe", "data"} only; "model" stays an AUTO
    axis, so GSPMD shards the gate/hidden dims from the jit-level param
    annotations and derives the TP collectives inside each stage's scan.
    Inter-layer dropout (cfg.dropout > 0) uses per-(shard, microbatch,
    layer) folded keys — see pp_lm_loss.
    """
    S = mesh.shape["pipe"]
    L = params_stacked["layers"].W_i.shape[0]
    if L % S != 0:
        raise ValueError(f"{L} layers not divisible by {S} pipeline stages")
    if tp and mesh.shape["model"] > 1 and cfg.hidden_size % mesh.shape["model"]:
        raise ValueError(
            f"hidden {cfg.hidden_size} not divisible by model axis "
            f"{mesh.shape['model']}"
        )
    if microbatches is None:
        microbatches = max(S, 1)

    param_specs = pp_lm_param_specs(params_stacked)
    batch_spec = {"inputs": P("data"), "targets": P("data")}
    # the auto "model" axis cannot partition a pallas_call, so the fused
    # stage-interior kernel is PP-only (no TP hybrid)
    use_pallas = cfg.use_pallas and not tp
    loss_shard = shard_map(
        lambda p, bt, rng: pp_lm_loss(
            p, bt, cfg, microbatches=microbatches, dropout_rng=rng,
            uniform=tp,  # TP collectives need lockstep ticks
            use_pallas=use_pallas,
        ),
        mesh=mesh,
        in_specs=(param_specs, batch_spec, P()),
        out_specs=P(),
        # "model" stays auto (GSPMD TP) — except with the fused kernel live,
        # where Mosaic requires a FULLY-manual shard_map; no TP ⇒ the extra
        # axes are size 1, so making them manual changes nothing semantically
        axis_names=(set(mesh.axis_names) if use_pallas else {"pipe", "data"}),
        check_vma=False,
    )

    def loss_fn(params, batch, rng):
        loss = loss_shard(params, batch, rng)
        return loss, {"loss": loss}

    def step(state: TrainState, batch):
        return step_body(loss_fn, optimizer, state, batch)

    param_shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        pp_lm_param_shardings(params_stacked, tp=tp),
        is_leaf=lambda x: isinstance(x, P),
    )
    if zero1:
        opt_shardings = jax.tree.map(
            lambda s: NamedSharding(mesh, s),
            pp_zero1_opt_specs(optimizer, params_stacked, mesh, tp=tp),
            is_leaf=lambda x: isinstance(x, P),
        )
    else:
        opt_shardings = None  # propagated from params by XLA
    state_shardings = TrainState(
        step=NamedSharding(mesh, P()),
        params=param_shardings,
        opt_state=opt_shardings,
        rng=NamedSharding(mesh, P()),
        carries=None,
    )
    batch_shardings = {
        "inputs": NamedSharding(mesh, P("data")),
        "targets": NamedSharding(mesh, P("data")),
    }

    from ..train.loop import _donation_supported

    if donate is None:
        donate = _donation_supported()
    return jax.jit(
        step,
        in_shardings=(state_shardings, batch_shardings),
        # pin the output state to the input shardings so steps CHAIN: with
        # an auto "model" axis GSPMD may otherwise pick a different layout
        # for e.g. the updated embedding, and the next call's in_shardings
        # pin would reject the committed array
        out_shardings=(state_shardings, None),
        donate_argnums=(0,) if donate else (),
    )
