"""Pipeline parallelism for stacked LSTM layers over the "pipe" mesh axis.

The reference has no pipeline parallelism (SURVEY.md §2 strategy inventory:
"not required for parity") — this is new first-class capability, built on
the same wavefront machinery as sequence parallelism (DESIGN.md notes the
wavefront is PP's natural substrate).

Layout: the L stacked layers are split into S = |pipe| stages of L/S layers
each; stage s owns layers [s*L/S, (s+1)*L/S). Layer parameters (and their
optimizer state) are *sharded* over "pipe" — each device stores only its
stage's weights, the point of PP. Embedding and head are replicated; only
stage 0 reads the embedding and only stage S-1 applies the head, so their
gradients are nonzero on exactly one stage and shard_map's transpose psums
them back to consistency.

Schedule: GPipe-style wavefront over M microbatches — at tick t, stage s
processes microbatch m = t - s and hands its activations [b, T, H] one hop
right via `lax.ppermute` (ICI neighbor traffic only). Utilization is
M/(M+S-1): the (S-1)-tick fill/drain bubble amortises away as M grows.
`lax.cond` on the per-device active predicate skips real compute during
bubble ticks (safe here: no collectives inside a stage's scan).

Autodiff: `jax.grad` through the shard_map reverses the wavefront
(ppermute transposes to the opposite ring), giving pipelined BPTT with the
same schedule in reverse. The train step does grad/update at the jit level —
shard_map's transpose inserts the psums for replicated inputs, and GSPMD
propagates the P("pipe") param sharding to the optimizer state, so each
stage's Adam moments etc. also live only on that stage.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.4.35
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from ..models.lstm_lm import LMConfig
from ..ops.lstm_cell import LSTMParams, fuse_params, zero_carry
from ..ops.scan import lstm_scan
from ..train.loop import TrainState, step_body


def stack_layers(layers: list[LSTMParams]) -> LSTMParams:
    """Stack per-layer params into one LSTMParams of [L, ...] arrays so the
    layer axis can be sharded over "pipe". Requires uniform input size
    (embed_size == hidden_size), or the stack would be ragged."""
    sizes = {p.input_size for p in layers}
    if len(sizes) != 1:
        raise ValueError(
            f"pipeline parallelism needs uniform layer input sizes, got {sizes} "
            "(set embed_size == hidden_size)"
        )
    return jax.tree.map(lambda *a: jnp.stack(a), *layers)


def unstack_layers(stacked: LSTMParams) -> list[LSTMParams]:
    L = stacked.W_i.shape[0]
    return [jax.tree.map(lambda a: a[j], stacked) for j in range(L)]


def stack_lm_params(params):
    """LM params with the per-layer list replaced by a stacked pytree."""
    return {**params, "layers": stack_layers(params["layers"])}


def unstack_lm_params(params):
    return {**params, "layers": unstack_layers(params["layers"])}


def pp_lm_param_specs(params_stacked):
    """PartitionSpecs: stacked layers sharded over "pipe", rest replicated."""
    specs = {
        k: jax.tree.map(lambda _: P(), v)
        for k, v in params_stacked.items()
        if k != "layers"
    }
    specs["layers"] = jax.tree.map(lambda _: P("pipe"), params_stacked["layers"])
    return specs


def place_pp_lm_params(params_stacked, mesh: Mesh):
    return jax.tree.map(
        lambda a, s: jax.device_put(a, NamedSharding(mesh, s)),
        params_stacked,
        pp_lm_param_specs(params_stacked),
    )


def pp_lm_loss(
    params,
    batch,
    cfg: LMConfig,
    *,
    microbatches: int = 1,
    pipe_axis: str = "pipe",
    data_axis: str = "data",
):
    """Global-mean LM loss under the pipeline wavefront.

    MUST run inside shard_map, manual over {pipe_axis, data_axis}. ``params``
    is the local view: layers [L/S, ...] (this stage's slice), embedding and
    head full. ``batch`` is this data-shard's {"inputs","targets"} [B_local,
    T], replicated over "pipe". Returns the already-reduced global scalar.
    """
    S = lax.axis_size(pipe_axis)
    s = lax.axis_index(pipe_axis)
    M = microbatches
    inputs, targets = batch["inputs"], batch["targets"]
    B, T = inputs.shape
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    b = B // M
    H = cfg.hidden_size
    if cfg.embed != H:
        raise ValueError("pipeline parallelism requires embed_size == hidden_size")

    embedding = params["embedding"]
    head = params["head"]
    kernel = embedding.T if cfg.tie_embeddings else head["kernel"]
    local_layers = unstack_layers(params["layers"])
    cdtype = None if cfg.cdtype == jnp.float32 else cfg.cdtype

    inputs_m = inputs.reshape(M, b, T)
    targets_m = targets.reshape(M, b, T)

    def run_stage(src):
        ys = src
        for layer in local_layers:
            _, ys = lstm_scan(
                layer, ys,
                compute_dtype=cdtype,
                remat_chunk=cfg.remat_chunk,
                unroll=cfg.scan_unroll,
            )
        return ys

    def mb_loss(ys, tgt):
        logits = (
            jnp.dot(ys.astype(kernel.dtype), kernel,
                    preferred_element_type=jnp.float32)
            + head["bias"]
        )
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
        return jnp.mean(nll)

    x_in = jnp.zeros((b, T, H), jnp.float32)
    loss_acc = jnp.zeros((), jnp.float32)
    right = [(i, i + 1) for i in range(S - 1)]  # linear chain, no wraparound
    is_last = s == S - 1

    for t in range(M + S - 1):
        m = t - s  # microbatch this stage works on at tick t
        active = jnp.logical_and(m >= 0, m < M)
        m_c = jnp.clip(m, 0, M - 1)
        tok = lax.dynamic_index_in_dim(inputs_m, m_c, axis=0, keepdims=False)
        tgt = lax.dynamic_index_in_dim(targets_m, m_c, axis=0, keepdims=False)
        # stage 0 sources from the embedding; later stages from the left
        # neighbor's activations. where() zeroes the embedding gradient on
        # stages > 0, so the psum'd embedding grad is exactly stage 0's.
        emb_x = jnp.take(embedding, tok, axis=0).astype(jnp.float32)
        src = jnp.where(s == 0, emb_x, x_in)
        ys = lax.cond(
            active,
            run_stage,
            lambda x: jnp.zeros((b, T, H), jnp.float32),
            src,
        )
        loss_acc = loss_acc + lax.cond(
            jnp.logical_and(active, is_last),
            mb_loss,
            lambda ys, tgt: jnp.zeros((), jnp.float32),
            ys, tgt,
        )
        if S > 1:
            x_in = lax.ppermute(ys, pipe_axis, right)

    loss = lax.psum(loss_acc, pipe_axis) / M  # only the last stage contributed
    return lax.pmean(loss, data_axis)


def make_pp_lm_train_step(
    cfg: LMConfig,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    params_stacked,
    *,
    microbatches: int | None = None,
    donate: bool | None = None,
):
    """Build the DP x PP train step on stacked params.

    Batch: {"inputs","targets"} [B, T], B % (data axis * microbatches) == 0.
    ``microbatches`` defaults to the pipe size (pipeline full at steady
    state). Grad/update happen at the jit level: shard_map's transpose
    produces correct grads (psum'd for replicated embedding/head, local for
    the stage-sharded layers), and jit propagates P("pipe") to opt state.
    """
    S = mesh.shape["pipe"]
    L = params_stacked["layers"].W_i.shape[0]
    if L % S != 0:
        raise ValueError(f"{L} layers not divisible by {S} pipeline stages")
    if cfg.dropout > 0.0:
        raise ValueError(
            "pipeline-parallel training is deterministic (no inter-layer "
            "dropout support); set dropout=0"
        )
    if microbatches is None:
        microbatches = max(S, 1)

    param_specs = pp_lm_param_specs(params_stacked)
    batch_spec = {"inputs": P("data"), "targets": P("data")}
    loss_shard = shard_map(
        lambda p, bt: pp_lm_loss(p, bt, cfg, microbatches=microbatches),
        mesh=mesh,
        in_specs=(param_specs, batch_spec),
        out_specs=P(),
        check_vma=False,
    )

    def loss_fn(params, batch, rng):
        del rng
        loss = loss_shard(params, batch)
        return loss, {"loss": loss}

    def step(state: TrainState, batch):
        return step_body(loss_fn, optimizer, state, batch)

    param_shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), param_specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    state_shardings = TrainState(
        step=NamedSharding(mesh, P()),
        params=param_shardings,
        opt_state=None,  # propagated from params by XLA
        rng=NamedSharding(mesh, P()),
        carries=None,
    )
    batch_shardings = {
        "inputs": NamedSharding(mesh, P("data")),
        "targets": NamedSharding(mesh, P("data")),
    }

    from ..train.loop import _donation_supported

    if donate is None:
        donate = _donation_supported()
    return jax.jit(
        step,
        in_shardings=(state_shardings, batch_shardings),
        donate_argnums=(0,) if donate else (),
    )
