"""Sequence/context parallelism for the LSTM recurrence.

The reference has NO long-context story beyond a fixed unroll inside one
worker (SURVEY.md §5 "Long-context / sequence parallelism": none). This
module is new first-class capability: the time axis is sharded over the
"seq" mesh axis, so each device stores only T/S of the activations for BPTT
— the memory scaling that makes very long sequences trainable (the LSTM
analogue of ring-attention's motivation; attention itself is n/a to this
architecture).

An LSTM is sequential in T, so the chunks form a dependency chain: device s
needs device s-1's final (h, c). The schedule is a classic WAVEFRONT:

  tick 0: dev0 scans microbatch 0 | others idle
  tick 1: dev1 scans mb 0 (carry from dev0) | dev0 scans mb 1 | ...
  ...

with the carry handed right one hop per tick via `lax.ppermute` (ICI
neighbor traffic only — 2*b*H floats per tick). With M microbatches,
utilization is M/(M+S-1): M=1 gives pure memory scaling; M >= S recovers
throughput (pipeline full).

Under `shard_map`, `lax.cond` on a per-device predicate compiles to a real
branch (not a select), so idle ticks cost no scan compute. Autodiff reverses
the wavefront (ppermute transposes to the opposite ring), giving BPTT with
the same memory scaling.

``uniform=True`` replaces the cond with where-masking: every device executes
every tick (same wall-clock — the pipeline bubble just burns compute instead
of idling). REQUIRED whenever the scan body contains collectives the devices
must hit in lockstep — e.g. composing with tensor parallelism on an auto
"model" axis, where GSPMD inserts all-gathers inside the scan: divergent
branches would deadlock the rendezvous.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.lstm_cell import LSTMParams, fuse_params, lstm_step, zero_carry
from ..ops.scan import lstm_scan


def sp_lstm_scan(
    params: LSTMParams,
    xs_local: jax.Array,
    *,
    axis: str = "seq",
    microbatches: int = 1,
    compute_dtype=None,
    remat_chunk: int | None = None,
    unroll: int = 1,
    uniform: bool = False,
    use_pallas: bool = False,
    pallas_interpret: bool = False,
    bptt: str = "sequential",
) -> jax.Array:
    """Wavefront LSTM scan over a sequence-sharded batch.

    MUST be called inside a `shard_map` program whose mesh has ``axis``.
    ``xs_local`` is this device's time-chunk ``[B, C, D]`` (C = T/S).
    Returns the local outputs ``ys`` ``[B, C, H]`` (hidden per local step).
    Zero initial carry (sequence starts on device 0).

    ``use_pallas`` runs each local chunk through the fused kernel
    (ops/pallas_lstm.py) at the per-microbatch shard shape [b, C, D] —
    legal with the SAME condition as the PP wavefront (VERDICT r3 item
    4): the chunk contains no collectives (the only inter-device traffic
    is the carry ppermute between ticks), so the kernel sits entirely in
    this device's manual shard — but the caller's shard_map must make
    EVERY mesh axis manual (Mosaic refuses a pallas_call under a
    partially-manual shard_map), which make_sharded_lm_train_step does
    exactly when "model" is unused. Falls back to the plain scan when
    the kernel's cost model rejects the shard shape.
    ``pallas_interpret`` forces the kernel in interpret mode (CPU parity
    tests of the kernel-in-wavefront composition).

    ``bptt`` != "sequential" routes each local chunk through
    `ops.scan.lstm_scan` with the parallel-scan backward knob — the
    device's T/S time-chunk is the natural tile of the assoc scan tree
    (ops/parallel_scan.py), and the assoc path contains no collectives,
    so it is legal inside the manual shard exactly like the Pallas
    kernel. The default keeps the original inline scan untouched."""
    S = lax.axis_size(axis)
    s = lax.axis_index(axis)
    B, C, _ = xs_local.shape
    M = microbatches
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by microbatches {M}")
    b = B // M
    H = params.hidden_size
    fused = fuse_params(params, compute_dtype=compute_dtype)
    use_kernel = False
    if use_pallas:
        from ..ops.pallas_lstm import pallas_lstm_scan, supported

        pbytes = 2 if compute_dtype == jnp.bfloat16 else 4
        use_kernel = pallas_interpret or supported(
            b, H, param_dtype_bytes=pbytes)
    if bptt == "assoc":
        # explicit assoc wins over the fused forward kernel — the same
        # precedence as auto_lstm_scan ("auto" defers to the kernel)
        use_kernel = False

    def chunk_scan(carry, x_chunk):
        """One microbatch's pass over the local chunk: [b, C, D] -> [b, C, H]."""
        if use_kernel:
            new_carry, ys = pallas_lstm_scan(
                params, x_chunk, carry, compute_dtype=compute_dtype,
                remat_chunk=remat_chunk, unroll=unroll,
                interpret=pallas_interpret,
            )
            return new_carry, ys
        if bptt != "sequential":
            # parallel-scan backward over the local chunk (resolved per
            # shard shape; "auto" falls back to the inline scan below
            # through lstm_scan's own resolution)
            return lstm_scan(
                params, x_chunk, carry, compute_dtype=compute_dtype,
                remat_chunk=remat_chunk, unroll=unroll, bptt=bptt,
            )
        xs_t = jnp.moveaxis(x_chunk, 0, 1)  # [C, b, D]

        def step(c, x):
            return lstm_step(fused, c, x)

        if remat_chunk is not None:
            if C % remat_chunk != 0:
                raise ValueError(f"C={C} not divisible by remat_chunk={remat_chunk}")

            def inner(c, xs_chunk):
                return lax.scan(step, c, xs_chunk, unroll=unroll)

            inner = jax.checkpoint(inner, prevent_cse=False)
            chunked = xs_t.reshape(C // remat_chunk, remat_chunk, b, -1)
            new_carry, ys = lax.scan(inner, carry, chunked)
            ys = ys.reshape(C, b, H)
        else:
            new_carry, ys = lax.scan(step, carry, xs_t, unroll=unroll)
        return new_carry, jnp.moveaxis(ys, 0, 1)  # [b, C, H]

    xs_m = xs_local.reshape(M, b, C, -1)
    ys_buf = jnp.zeros((M, b, C, H), jnp.float32)
    zc = zero_carry(b, H)
    # carry_in: the carry for the microbatch this device processes next tick
    carry_in = zc
    right = [(i, i + 1) for i in range(S - 1)]  # linear chain, no wraparound

    for t in range(M + S - 1):
        m = t - s  # which microbatch this device works on at tick t
        active = jnp.logical_and(m >= 0, m < M)
        m_c = jnp.clip(m, 0, M - 1)
        x_m = lax.dynamic_index_in_dim(xs_m, m_c, axis=0, keepdims=False)

        if uniform:
            # collective-safe: all devices scan every tick, results masked
            scanned_carry, ys = chunk_scan(carry_in, x_m)
            carry_out = jax.tree.map(
                lambda new, old: jnp.where(active, new, old),
                scanned_carry, carry_in,
            )
            updated = lax.dynamic_update_index_in_dim(ys_buf, ys, m_c, axis=0)
            ys_buf = jnp.where(active, updated, ys_buf)
        else:

            def do_scan(carry, x):
                return chunk_scan(carry, x)

            def skip(carry, x):
                return carry, jnp.zeros((b, C, H), jnp.float32)

            carry_out, ys = lax.cond(active, do_scan, skip, carry_in, x_m)
            ys_buf = lax.cond(
                active,
                lambda buf, y: lax.dynamic_update_index_in_dim(buf, y, m_c, axis=0),
                lambda buf, y: buf,
                ys_buf, ys,
            )
        # hand the finished microbatch's carry to the right neighbor
        received = lax.ppermute(carry_out, axis, right)
        # device 0 always starts each microbatch from zero carry
        carry_in = jax.tree.map(
            lambda r, z: jnp.where(s == 0, z, r), received, zc
        )

    return ys_buf.reshape(B, C, H)
