"""ZeRO-1 data parallelism: optimizer-state sharding over the data axis.

The reference's DP (and this repo's default `make_dp_train_step`) keeps
params AND optimizer state fully replicated — every chip stores Adam's two
moment pytrees for the whole model. This module shards the OPTIMIZER state
1/dp per chip (the ZeRO stage-1 recipe, arXiv:1910.02054, re-derived
TPU-natively): per-shard gradients are `psum_scatter`-reduced so each chip
receives only its 1/dp slice of the summed gradient vector, updates its
slice of the raveled parameter vector with its slice of the optimizer
state, and an `all_gather` rebuilds the full (replicated) params for the
next forward. Communication volume per step is the SAME as the pmean DP
step (reduce-scatter + all-gather = one all-reduce, ring-wise), so the
memory saving is free at the collective level.

Numerics: the update is elementwise (SGD/momentum/Adam/AdamW/RMSProp on a
contiguous slice of the raveled vector ≡ the same transform leaf-wise), so
trajectories match plain DP to float-reassociation. The one NON-elementwise
transform — global-norm clipping — cannot run per-slice (each shard would
clip by a different norm and slices would diverge), so clipping is done
HERE from the globally-psum'd norm, and the optimizer chain passed in must
exclude its own clip stage (`make_zero1_train_step(clip_norm=...)`).

Scope: stateless losses; composes with K-step dispatch
(``steps_per_call`` — the scan runs inside the shard_map). Params stay
replicated — sharding them too (ZeRO-3) would re-gather per layer per
step; at LSTM sizes the win is in the moments, which dominate optimizer
memory.

TWO implementations live here, because the raveled-flat form above is
hostile to tensor parallelism (raveling a model-sharded leaf would gather
it):

- the shard_map/ravel form (`make_zero1_train_step`) for the pure-DP
  backend — explicit reduce-scatter/all-gather, K-step scan inside;
- a GSPMD form (`zero1_tp_opt_specs`) for the TP task runners: the
  optimizer-state moment leaves get a PartitionSpec that ADDS the data
  axis on a dimension the param leaves unsharded (the classic XLA
  weight-update-sharding recipe — annotate, let GSPMD place the update).
  Grads stay logically replicated over data, so global-norm clipping
  needs no special casing, and the sharded leaves keep their full
  logical shapes, so checkpoints reshard across ANY later dp×tp (no
  padded-flat-length contract like the ravel form).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.flatten_util import ravel_pytree
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.4.35
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from ..train.loop import TrainState, dp_rng_transform


def _flat_meta(params, dp: int):
    """(n, chunk) for the raveled parameter vector padded to dp chunks."""
    n = sum(int(jnp.size(a)) for a in jax.tree.leaves(params))
    chunk = -(-n // dp)  # ceil
    return n, chunk


def _local_slice(flat_pad: jax.Array, chunk: int, axis: str) -> jax.Array:
    idx = lax.axis_index(axis)
    return lax.dynamic_slice(flat_pad, (idx * chunk,), (chunk,))


def _opt_state_specs(optimizer, chunk: int, axis: str):
    """out_specs for the chunked optimizer state: vector leaves shard over
    ``axis``, scalar leaves (e.g. Adam's count) stay replicated."""
    shapes = jax.eval_shape(optimizer.init, jnp.zeros((chunk,), jnp.float32))
    return jax.tree.map(lambda s: P() if s.ndim == 0 else P(axis), shapes)


def make_zero1_opt_init(
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    axis: str = "data",
):
    """Jitted initializer: full (replicated) params -> optimizer state over
    each shard's [chunk] parameter slice, sharded P(axis) on vector leaves.
    Use its result as TrainState.opt_state for `make_zero1_train_step` (and
    as the checkpoint template — the checkpointer's per-leaf reshard
    handles the sharded leaves like any PP-sharded state)."""
    dp = mesh.shape[axis]

    def per_shard_init(params):
        n, chunk = _flat_meta(params, dp)
        flat, _ = ravel_pytree(params)
        flat = jnp.pad(flat.astype(jnp.float32), (0, dp * chunk - n))
        return optimizer.init(_local_slice(flat, chunk, axis))

    def build(params):
        n, chunk = _flat_meta(params, dp)
        return jax.jit(shard_map(
            per_shard_init,
            mesh=mesh,
            in_specs=(P(),),
            out_specs=_opt_state_specs(optimizer, chunk, axis),
            check_vma=False,
        ))(params)

    return build


def make_zero1_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    axis: str = "data",
    clip_norm: float | None = None,
    jit: bool = True,
    donate: bool | None = None,
    steps_per_call: int = 1,
):
    """Build the ZeRO-1 DP train step.

    ``loss_fn(params, batch, dropout_rng) -> (loss, aux)`` — the same
    per-shard body as every other step builder. ``optimizer`` must NOT
    include a global-norm clip stage; pass ``clip_norm`` here instead
    (module docstring: clipping needs the GLOBAL norm, computed by psum
    before the sliced update). ``donate`` follows the repo's step-builder
    contract (default: platform-gated buffer donation of the state — the
    memory-saving step must not hold a second copy of params + moments).

    ``steps_per_call=K`` scans the per-shard step over K stacked batches
    ([K, b_local, ...]) INSIDE the shard_map — K optimizer steps per host
    dispatch, the same amortization as train/multistep.py. Collectives
    inside the scan are uniform across shards (same trip count
    everywhere), so the composition is lockstep-safe; metrics follow the
    multi-step contract (mean loss + final step's loss/grad_norm).

    CHECKPOINT SHAPE CONTRACT: the sharded moment leaves bake in the
    padded flat length dp*ceil(n_params/dp), so a ZeRO-1 checkpoint
    resumes at the SAME data-shard count it was written with. To change
    dp across a restart, round-trip through a non-zero1 run (restore
    full state, re-save), or re-init the moments.
    """
    dp = mesh.shape[axis]

    def per_shard_step(state: TrainState, batch):
        rng, sub = jax.random.split(state.rng)
        sub = dp_rng_transform(axis)(sub)
        (loss, aux), grads = jax.value_and_grad(
            lambda p: loss_fn(p, batch, sub), has_aux=True
        )(state.params)
        from ..resilience import faults as _faults

        grads = _faults.tamper_grads(grads, state.step)  # identity unarmed

        n, chunk = _flat_meta(state.params, dp)
        g_flat, _ = ravel_pytree(grads)
        g_flat = jnp.pad(g_flat.astype(jnp.float32), (0, dp * chunk - n))
        # reduce-scatter: this shard receives the cross-shard SUM of its
        # 1/dp gradient slice; /dp makes it the treeAggregate-style mean
        g_local = lax.psum_scatter(g_flat, axis, tiled=True) / dp

        # global grad norm from the scattered slices (pad lanes are zero)
        gsq = lax.psum(jnp.sum(jnp.square(g_local)), axis)
        gnorm = jnp.sqrt(gsq)
        if clip_norm is not None:
            g_local = g_local * jnp.minimum(1.0, clip_norm / (gnorm + 1e-12))

        p_flat, unravel = ravel_pytree(state.params)
        p_dtype = p_flat.dtype
        p_flat = jnp.pad(p_flat.astype(jnp.float32), (0, dp * chunk - n))
        p_local = _local_slice(p_flat, chunk, axis)

        updates, opt_state = optimizer.update(g_local, state.opt_state, p_local)
        p_new = optax.apply_updates(p_local, updates)

        loss = lax.pmean(loss, axis)
        # Non-finite guard (same contract as train/loop.py step_body): skip
        # the sliced update AND the moment update when loss/grad-norm is
        # NaN/Inf. Both predicates are collective results (pmean'd loss,
        # psum'd norm), so every shard takes the same branch and the
        # all-gather below rebuilds consistent params either way.
        finite = jnp.isfinite(loss) & jnp.isfinite(gnorm)
        p_local = jnp.where(finite, p_new, p_local)
        opt_state = jax.tree.map(
            lambda new, old: jnp.where(finite, new, old),
            opt_state, state.opt_state,
        )

        p_flat = lax.all_gather(p_local, axis, tiled=True)[:n].astype(p_dtype)
        params = unravel(p_flat)

        metrics = {"loss": loss, "grad_norm": gnorm,
                   "anomalous": (~finite).astype(jnp.float32)}
        return (
            TrainState(state.step + 1, params, opt_state, rng, state.carries),
            metrics,
        )

    if steps_per_call > 1:
        from ..train.loop import summarize_scan_metrics

        inner = per_shard_step

        def per_shard_multi(state: TrainState, batches):
            state, ms = lax.scan(inner, state, batches)
            return state, summarize_scan_metrics(ms)

        per_shard = per_shard_multi
        batch_spec = P(None, axis)  # [K, b_local, ...]
    else:
        per_shard = per_shard_step
        batch_spec = P(axis)

    def build_specs(params):
        n, chunk = _flat_meta(params, dp)
        opt_spec = _opt_state_specs(optimizer, chunk, axis)
        state_spec = TrainState(
            step=P(), params=P(), opt_state=opt_spec, rng=P(), carries=P(),
        )
        return state_spec

    def step(state: TrainState, batch):
        state_spec = build_specs(state.params)
        fn = shard_map(
            per_shard,
            mesh=mesh,
            in_specs=(state_spec, batch_spec),
            out_specs=(state_spec, P()),
            check_vma=False,
        )
        return fn(state, batch)

    if not jit:
        return step
    from ..train.loop import _donation_supported

    if donate is None:
        donate = _donation_supported()
    return jax.jit(step, donate_argnums=(0,) if donate else ())


def _zero1_leaf_spec(spec: P, shape, dp: int, dp_axis: str) -> P:
    """Extend a param leaf's PartitionSpec with ``dp_axis`` on the first
    dimension the param leaves unsharded and the axis divides. A leaf with
    no such dimension keeps the param's own sharding (no memory win on it,
    but nothing breaks — GSPMD just replicates it over data as before)."""
    parts = list(spec) + [None] * (len(shape) - len(spec))
    for i, (p, dim) in enumerate(zip(parts, shape)):
        if p is None and dim >= dp and dim % dp == 0:
            parts[i] = dp_axis
            break
    while parts and parts[-1] is None:
        parts.pop()
    return P(*parts)


def zero1_tp_opt_specs(
    optimizer: optax.GradientTransformation,
    params_template,
    param_specs,
    mesh: Mesh,
    *,
    dp_axis: str = "data",
):
    """PartitionSpec pytree for the optimizer state that composes ZeRO-1
    with GSPMD tensor parallelism (the TP task runners' recipe).

    Moment leaves mirror the params tree inside optax's state NamedTuples;
    they are matched to their param by TREE-PATH SUFFIX (an adam ``mu``
    leaf at ``[0].mu['fwd'][0].W_i`` matches the param path
    ``['fwd'][0].W_i``), guarded by shape equality, longest suffix wins.
    Matched leaves get the param's spec extended with the data axis
    (`_zero1_leaf_spec`); scalars and unmatched leaves stay replicated.
    Use the result as ``opt_state_specs`` for `make_tp_train_step` and to
    `place_params` the initial/restored state."""
    from jax.tree_util import tree_flatten_with_path, tree_unflatten

    dp = mesh.shape[dp_axis]
    param_leaves, _ = tree_flatten_with_path(params_template)
    spec_flat, _ = tree_flatten_with_path(
        param_specs, is_leaf=lambda x: isinstance(x, P))
    # pair by PATH, not position: a same-count tree with a typoed key
    # would silently mispair under zip and the step would then PIN wrong
    # placements with no error
    spec_by_path = {tuple(path): spec for path, spec in spec_flat}
    param_paths = {tuple(p) for p, _ in param_leaves}
    if spec_by_path.keys() != param_paths:
        from jax.tree_util import keystr
        odd = [keystr(p) for p in
               (param_paths ^ spec_by_path.keys())][:3]
        raise ValueError(
            "param_specs does not mirror params_template "
            f"(mismatched leaf paths, e.g. {odd})")
    by_path = [
        (tuple(path), leaf.shape, spec_by_path[tuple(path)])
        for path, leaf in param_leaves
    ]
    by_path.sort(key=lambda t: -len(t[0]))  # longest suffix wins

    shapes = jax.eval_shape(optimizer.init, params_template)
    flat, treedef = tree_flatten_with_path(shapes)
    matched = 0

    def match(path, shape):
        nonlocal matched
        for q, qshape, spec in by_path:
            if (len(path) >= len(q) and tuple(path[-len(q):]) == q
                    and tuple(shape) == tuple(qshape)):
                matched += 1
                return _zero1_leaf_spec(spec, shape, dp, dp_axis)
        return P()

    out = tree_unflatten(treedef, [match(tuple(p), s.shape) for p, s in flat])
    if matched == 0 and any(s.ndim > 0 for _, s in flat):
        # nothing mirrors the params (e.g. a factored optimizer like
        # adafactor): pinning everything P() would use MORE memory than
        # plain propagation — refuse rather than silently regress
        raise ValueError(
            "no optimizer-state leaf mirrors the params (factored "
            "optimizer?) — GSPMD ZeRO-1 only shards param-shaped moments; "
            "drop opt_state_specs and let propagation place this state")
    return out
