"""Device mesh construction and multi-host initialization.

Reference parity: SURVEY.md §2 L3 — the reference's cluster layer is Apache
Spark (JVM, Py4J, netty RPC, cluster manager). TPU-native replacement: a
`jax.sharding.Mesh` over the ICI torus with named axes, XLA emitting the
collectives; the control plane is `jax.distributed.initialize` (one process
per host), replacing Spark master/executor scheduling (SURVEY.md §2 native
table, "Cluster scheduling/launch" row).

Axis convention (used across parallel/):
  "data"  — data parallel (the reference's RDD partitions [D])
  "model" — tensor parallel over the hidden/gate dimension (new capability)
  "seq"   — sequence/context parallel over time chunks (new capability)
  "pipe"  — pipeline parallel over stacked layers (new capability)

(Expert parallelism has no axis: the architecture has no MoE layers —
SURVEY.md §2 strategy inventory marks EP "n/a".)
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

AXES = ("data", "model", "seq", "pipe")


def local_device_count() -> int:
    return jax.device_count()


def make_mesh(
    dp: int | None = None,
    tp: int = 1,
    sp: int = 1,
    pp: int = 1,
    *,
    devices=None,
) -> Mesh:
    """Build a ("data", "model", "seq", "pipe") mesh.

    ``dp=None`` absorbs all remaining devices into the data axis — the moral
    equivalent of the reference's default partition count. XLA maps the mesh
    onto the physical ICI topology; for multi-slice/DCN deployments put the
    slowest-varying axis ("data") across slices so psum rides ICI within a
    slice (scaling-book recipe; SURVEY.md §5 comm-backend row).
    """
    devices = np.asarray(devices if devices is not None else jax.devices())
    n = devices.size
    if dp is None:
        if n % (tp * sp * pp) != 0:
            raise ValueError(f"{n} devices not divisible by tp*sp*pp={tp * sp * pp}")
        dp = n // (tp * sp * pp)
    if dp * tp * sp * pp != n:
        raise ValueError(f"dp*tp*sp*pp={dp * tp * sp * pp} != device count {n}")
    return Mesh(devices.reshape(dp, tp, sp, pp), AXES)


def distributed_init(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Multi-host control plane (SURVEY.md §7 step 4). No-op when single
    process (the common local case); on a pod slice each host calls this
    before touching devices."""
    if num_processes is None or num_processes <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
