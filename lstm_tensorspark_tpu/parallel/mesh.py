"""Device mesh construction and multi-host initialization.

Reference parity: SURVEY.md §2 L3 — the reference's cluster layer is Apache
Spark (JVM, Py4J, netty RPC, cluster manager). TPU-native replacement: a
`jax.sharding.Mesh` over the ICI torus with named axes, XLA emitting the
collectives; the control plane is `jax.distributed.initialize` (one process
per host), replacing Spark master/executor scheduling (SURVEY.md §2 native
table, "Cluster scheduling/launch" row).

Axis convention (used across parallel/):
  "data"  — data parallel (the reference's RDD partitions [D])
  "model" — tensor parallel over the hidden/gate dimension (new capability)
  "seq"   — sequence/context parallel over time chunks (new capability)
  "pipe"  — pipeline parallel over stacked layers (new capability)

(Expert parallelism has no axis: the architecture has no MoE layers —
SURVEY.md §2 strategy inventory marks EP "n/a".)
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

AXES = ("data", "model", "seq", "pipe")


def local_device_count() -> int:
    return jax.device_count()


def make_mesh(
    dp: int | None = None,
    tp: int = 1,
    sp: int = 1,
    pp: int = 1,
    *,
    devices=None,
) -> Mesh:
    """Build a ("data", "model", "seq", "pipe") mesh.

    ``dp=None`` absorbs all remaining devices into the data axis — the moral
    equivalent of the reference's default partition count. XLA maps the mesh
    onto the physical ICI topology; for multi-slice/DCN deployments put the
    slowest-varying axis ("data") across slices so psum rides ICI within a
    slice (scaling-book recipe; SURVEY.md §5 comm-backend row).
    """
    devices = np.asarray(devices if devices is not None else jax.devices())
    n = devices.size
    if dp is None:
        if n % (tp * sp * pp) != 0:
            raise ValueError(f"{n} devices not divisible by tp*sp*pp={tp * sp * pp}")
        dp = n // (tp * sp * pp)
    if dp * tp * sp * pp != n:
        raise ValueError(f"dp*tp*sp*pp={dp * tp * sp * pp} != device count {n}")
    return Mesh(devices.reshape(dp, tp, sp, pp), AXES)


def slice_groups(devices) -> list[list]:
    """Group devices by interconnect domain, fastest first: TPU multi-slice
    deployments report ``slice_index`` (ICI within a slice, DCN between);
    everywhere else the process boundary is the domain boundary (a host's
    local devices talk fast, cross-process traffic rides the network — the
    2-process Gloo tests exercise exactly this). Groups come back sorted by
    domain id, devices within a group sorted by device id."""
    groups: dict = {}
    for d in devices:
        key = getattr(d, "slice_index", None)
        if key is None:
            key = d.process_index
        groups.setdefault(key, []).append(d)
    return [sorted(g, key=lambda d: d.id) for _, g in sorted(groups.items())]


def make_hybrid_mesh(
    dp: int | None = None,
    tp: int = 1,
    sp: int = 1,
    pp: int = 1,
    *,
    devices=None,
) -> Mesh:
    """DCN-aware variant of `make_mesh`: same four named axes, devices
    ordered SLICE-MAJOR before the reshape.

    Why ordering is the whole feature (scaling-book recipe; SURVEY.md §5
    comm-backend row): with the data axis slowest-varying and each
    tp*sp*pp block contiguous, (a) every model/seq/pipe block lands
    inside ONE interconnect domain — the latency-sensitive per-timestep
    collectives (TP's h all-gather, SP's ppermute, PP's activation hops)
    ride ICI only — and (b) `psum("data")`'s topology decomposes into an
    intra-slice ICI phase plus one inter-slice DCN phase, which XLA
    derives from device placement; no collective code changes. On a
    single slice/process this degenerates to `make_mesh` exactly (one
    group, same device order), so it is safe as a default.

    Raises when tp*sp*pp does not divide the per-domain device count —
    that layout would force a per-timestep collective across DCN, which
    is a configuration error, not something to paper over.
    """
    devices = list(devices) if devices is not None else jax.devices()
    groups = slice_groups(devices)
    sizes = {len(g) for g in groups}
    if len(sizes) > 1:
        raise ValueError(
            f"unequal interconnect domains {sorted(len(g) for g in groups)}: "
            "a hybrid mesh needs the same device count per slice/process"
        )
    block = tp * sp * pp
    per = sizes.pop()
    if per % block != 0:
        raise ValueError(
            f"model block tp*sp*pp={block} does not divide the slice size "
            f"{per}: a model/seq/pipe collective would straddle the DCN "
            "boundary (build such a layout explicitly with make_mesh if "
            "you really mean it)"
        )
    ordered = [d for g in groups for d in g]
    return make_mesh(dp, tp, sp, pp, devices=ordered)


def make_serve_mesh(shards: int, *, devices=None) -> Mesh:
    """One-axis ``("model",)`` mesh for a tensor-parallel SERVE engine
    (serve/engine.py ``mesh_shards``): the hidden/gate dimension of one
    replica's params and state-cache slots shards over these devices,
    with XLA deriving the per-step collectives from the same
    `tensor_parallel.lm_param_specs` annotations training uses. Distinct
    from :func:`make_mesh` on purpose — a serve replica owns a small,
    explicit device group (disjoint groups per replica behind the
    router), not the whole host's device set."""
    if shards < 1:
        raise ValueError(f"mesh shards must be >= 1, got {shards}")
    devices = list(devices) if devices is not None else jax.devices()
    if len(devices) < shards:
        raise ValueError(
            f"mesh of {shards} shards needs {shards} devices, have "
            f"{len(devices)} (on CPU, set XLA_FLAGS="
            f"--xla_force_host_platform_device_count=N for virtual ones)")
    return Mesh(np.asarray(devices[:shards]), ("model",))


def distributed_init(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Multi-host control plane (SURVEY.md §7 step 4). No-op when single
    process (the common local case); on a pod slice each host calls this
    before touching devices."""
    if num_processes is None or num_processes <= 1:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
