"""Combined 3D-parallel LM train step: DP x TP x SP on one mesh.

Axis responsibilities (mesh.py convention):
  "data"  — batch sharding, grads pmean'd (the reference's only strategy [D])
  "seq"   — time-chunk sharding via the wavefront scan (sequence parallel)
  "model" — gate/hidden sharding (tensor parallel)

Hybrid manual/auto sharding: `shard_map` is MANUAL over {"data","seq"} (the
wavefront's ppermute needs explicit neighbor collectives the compiler cannot
infer), while "model" stays an AUTO axis — inside the body all hidden-dim
tensors remain global and GSPMD shards them from the jit-level param
annotations (tensor_parallel.lm_param_specs), deriving the h all-gather,
logits psum and gradient reductions automatically.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.4.35
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from ..models.lstm_lm import LMConfig
from ..ops.embedding import embed_lookup, selected_logits
from ..train.loop import TrainState, step_body
from .sequence_parallel import sp_lstm_scan
from .tensor_parallel import lm_param_specs


def sp_lm_loss(params, batch, cfg: LMConfig, *, seq_axis: str = "seq",
               microbatches: int = 1, dropout_rng=None,
               use_pallas: bool = False):
    """LM loss over a sequence-sharded batch (called inside shard_map).

    batch: {"inputs","targets"} each [b_local, C] (B sharded over "data",
    T over "seq"). Stacked layers each run the wavefront scan; layer
    boundaries need NO communication (chunks stay resident).

    Inter-layer dropout (``dropout_rng`` set + cfg.dropout > 0) draws masks
    on the shard-local [b_local, C, H] activations; the caller's
    rng_transform already folds the (data, seq) shard index, so masks are
    independent per shard — the DP backend's scheme extended to SP.
    """
    use_dropout = dropout_rng is not None and cfg.dropout > 0.0
    xs = embed_lookup(params["embedding"], batch["inputs"])
    n = len(params["layers"])
    for idx, layer in enumerate(params["layers"]):
        xs = sp_lstm_scan(
            layer, xs,
            axis=seq_axis,
            microbatches=microbatches,
            compute_dtype=None if cfg.cdtype == jnp.float32 else cfg.cdtype,
            remat_chunk=cfg.remat_chunk,
            unroll=cfg.scan_unroll,
            # "model" is an auto axis here: GSPMD inserts TP collectives
            # inside the scan, so ticks must execute in lockstep
            uniform=True,
            # fused kernel per local chunk — only when the caller made
            # every mesh axis manual (no TP; see make_sharded_lm_train_step)
            use_pallas=use_pallas,
            # parallel-scan backward over each local chunk (the SP chunk
            # is the assoc tree's tile); collective-free, shard-legal
            bptt=cfg.bptt,
        )
        if use_dropout and idx < n - 1:
            from ..ops.masking import dropout_with_key

            xs = dropout_with_key(
                jax.random.fold_in(dropout_rng, idx), cfg.dropout, xs
            )
    head = params["head"]
    kernel = params["embedding"].T if cfg.tie_embeddings else head["kernel"]
    logits = (
        jnp.dot(xs.astype(kernel.dtype), kernel,
                preferred_element_type=cfg.ldtype)
        + head["bias"].astype(cfg.ldtype)
    )
    # logsumexp form — keep identical to lm_loss (parity tests compare
    # the two bit-for-bit) and skip the [b,C,V] log-prob array
    lg = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(lg, axis=-1)
    tgt = selected_logits(lg, batch["targets"])
    loss = jnp.mean(lse - tgt)  # local mean; caller pmeans over data+seq
    return loss, {"loss": loss}


def make_sharded_lm_eval_step(
    cfg: LMConfig,
    mesh: Mesh,
    params_template,
    *,
    microbatches: int = 1,
):
    """Forward-only eval on the SHARDED params (VERDICT r1 weak #7: eval
    must not funnel through one device — for the configs where TP/SP
    matter, the model may not fit one). Same wavefront body as training,
    deterministic; loss pmean'd over the manual axes; reports the global
    token count so evaluate() token-weights exactly."""

    use_pallas = cfg.use_pallas and mesh.shape.get("model", 1) == 1

    def eval_body(params, batch):
        loss, _ = sp_lm_loss(params, batch, cfg, microbatches=microbatches,
                             use_pallas=use_pallas)
        loss = jax.lax.pmean(loss, ("data", "seq"))
        tokens = jax.lax.psum(
            jnp.asarray(batch["targets"].size, jnp.float32), ("data", "seq")
        )
        return {"loss": loss, "tokens": tokens}

    sharded = shard_map(
        eval_body,
        mesh=mesh,
        in_specs=(P(), {"inputs": P("data", "seq"), "targets": P("data", "seq")}),
        out_specs=P(),
        # Mosaic refuses a pallas_call inside a PARTIALLY-manual shard_map;
        # with the fused kernel live (no TP ⇒ "model"/"pipe" are size 1)
        # make every mesh axis manual — semantically identical, Mosaic-legal
        # (the same trick as the PP wavefront, pipeline_parallel.py).
        axis_names=(set(mesh.axis_names) if use_pallas else {"data", "seq"}),
        check_vma=False,
    )
    param_shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        lm_param_specs(params_template),
        is_leaf=lambda x: isinstance(x, P),
    )
    batch_shardings = {
        "inputs": NamedSharding(mesh, P("data", "seq")),
        "targets": NamedSharding(mesh, P("data", "seq")),
    }
    return jax.jit(sharded, in_shardings=(param_shardings, batch_shardings))


def make_sharded_lm_train_step(
    cfg: LMConfig,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    params_template,
    *,
    microbatches: int = 1,
    donate: bool | None = None,
):
    """Build the DP x TP x SP train step. Batch: {"inputs","targets"} [B, T]
    with B % (data axis) == 0 and T % (seq axis) == 0."""

    use_pallas = cfg.use_pallas and mesh.shape.get("model", 1) == 1
    # all-manual when the fused kernel is live (Mosaic refuses pallas_call
    # under a partially-manual shard_map; "model"/"pipe" are size 1 here so
    # the program is semantically identical) — the PP wavefront's trick
    manual = set(mesh.axis_names) if use_pallas else {"data", "seq"}

    def loss_fn(params, batch, rng):
        return sp_lm_loss(
            params, batch, cfg, microbatches=microbatches, dropout_rng=rng,
            use_pallas=use_pallas,
        )

    def body(state: TrainState, batch):
        return step_body(
            loss_fn, optimizer, state, batch,
            rng_transform=lambda sub: jax.random.fold_in(
                sub,
                jax.lax.axis_index("data") * jax.lax.axis_size("seq")
                + jax.lax.axis_index("seq"),
            ),
            reduce_fn=lambda grads, loss: (
                jax.lax.pmean(grads, ("data", "seq")),
                jax.lax.pmean(loss, ("data", "seq")),
            ),
        )

    state_spec = TrainState(step=P(), params=P(), opt_state=P(), rng=P(), carries=P())
    batch_spec = {"inputs": P("data", "seq"), "targets": P("data", "seq")}
    sharded = shard_map(
        body,
        mesh=mesh,
        in_specs=(state_spec, batch_spec),
        out_specs=(state_spec, P()),
        axis_names=manual,
        check_vma=False,
    )

    # TP placement happens at the jit level (auto axis "model").
    param_shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        lm_param_specs(params_template),
        is_leaf=lambda x: isinstance(x, P),
    )
    state_shardings = TrainState(
        step=NamedSharding(mesh, P()),
        params=param_shardings,
        opt_state=None,  # propagated from params by XLA
        rng=NamedSharding(mesh, P()),
        carries=None,
    )
    batch_shardings = {
        "inputs": NamedSharding(mesh, P("data", "seq")),
        "targets": NamedSharding(mesh, P("data", "seq")),
    }

    from ..train.loop import _donation_supported

    if donate is None:
        donate = _donation_supported()
    return jax.jit(
        sharded,
        in_shardings=(state_shardings, batch_shardings),
        donate_argnums=(0,) if donate else (),
    )
