"""Tensor parallelism for the LSTM: gate/hidden dimensions sharded over the
"model" mesh axis.

Not in the reference (SURVEY.md §2 parallelism inventory: TP "no"); new
capability. Design is compiler-first (the pjit/GSPMD recipe: annotate
shardings, let XLA insert the collectives — PAPERS.md "Scalable Training of
Language Models using JAX pjit and TPUv4" describes the approach): every
gate kernel is column-sharded ``[D, H/P]``, recurrent kernels ``[H, H/P]``,
the LM head row-sharded ``[H/P, V]``. XLA then emits the per-step all-gather
of h (column-parallel matmul) and the logits psum (row-parallel matmul) plus
the correct gradient reductions — no hand-written collective can drift out
of sync with the backward pass.

This composes with data parallelism on the same mesh: batch over "data",
params over "model", both handled by GSPMD from the same annotations.
"""

from __future__ import annotations

from typing import Callable

import jax
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.lstm_cell import LSTMParams
from ..train.loop import TrainState, step_body


def lstm_param_specs(tp_axis: str = "model") -> LSTMParams:
    """PartitionSpecs for one cell: gate output dim sharded over tp_axis."""
    col = P(None, tp_axis)  # W [D, H/P], U [H, H/P]
    vec = P(tp_axis)  # b [H/P]
    return LSTMParams(
        W_i=col, W_f=col, W_g=col, W_o=col,
        U_i=col, U_f=col, U_g=col, U_o=col,
        b_i=vec, b_f=vec, b_g=vec, b_o=vec,
    )


def lm_param_specs(params, tp_axis: str = "model"):
    """PartitionSpec pytree for the LM param dict (models/lstm_lm.py):
    embedding replicated, cells column-sharded, head row-sharded."""
    specs = {
        "embedding": P(),
        "layers": [lstm_param_specs(tp_axis) for _ in params["layers"]],
    }
    head = {"bias": P()}
    if "kernel" in params["head"]:
        head["kernel"] = P(tp_axis, None)  # [H/P, V] row-parallel
    specs["head"] = head
    return specs


def place_lm_params(params, mesh: Mesh, tp_axis: str = "model"):
    """Device_put the LM params with TP shardings on ``mesh``."""
    specs = lm_param_specs(params, tp_axis)
    return jax.tree.map(
        lambda a, s: jax.device_put(a, NamedSharding(mesh, s)),
        params, specs,
        is_leaf=lambda x: isinstance(x, jax.Array) or x is None,
    )


def classifier_param_specs(params, tp_axis: str = "model"):
    """PartitionSpec pytree for the bi-LSTM classifier (models/classifier.py):
    both directions' cells column-sharded, embedding replicated, head
    row-sharded [2H/P, C]. Same GSPMD recipe as the LM: annotate, let XLA
    derive the per-step h all-gather and the logits psum."""
    return {
        "embedding": P(),
        "fwd": [lstm_param_specs(tp_axis) for _ in params["fwd"]],
        "bwd": [lstm_param_specs(tp_axis) for _ in params["bwd"]],
        "head": {"kernel": P(tp_axis, None), "bias": P()},
    }


def seq2seq_param_specs(params, tp_axis: str = "model"):
    """PartitionSpec pytree for the seq2seq forecaster (models/seq2seq.py):
    encoder/decoder cells column-sharded, projection row-sharded [H/P, F]."""
    return {
        "encoder": [lstm_param_specs(tp_axis) for _ in params["encoder"]],
        "decoder": [lstm_param_specs(tp_axis) for _ in params["decoder"]],
        "proj": {"kernel": P(tp_axis, None), "bias": P()},
    }


def place_params(params, specs, mesh: Mesh):
    """Device_put any param pytree with the given PartitionSpec pytree."""
    return jax.tree.map(
        lambda a, s: jax.device_put(a, NamedSharding(mesh, s)),
        params, specs,
        is_leaf=lambda x: isinstance(x, jax.Array) or x is None,
    )


def make_tp_eval_step(
    fn: Callable,
    mesh: Mesh,
    param_specs,
    *,
    dp_axis: str = "data",
):
    """Forward-only eval on the DEVICE-RESIDENT TP-sharded params (VERDICT
    r2 weak #6: eval must not funnel the model through one device/host —
    under TP no single device need hold it). Same GSPMD recipe as the train
    step: param shardings in, batch leading dim over ``dp_axis``, XLA
    derives the collectives. ``fn(params, batch) -> metrics/preds``."""
    shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), param_specs,
        is_leaf=lambda x: isinstance(x, P),
    )
    return jax.jit(
        fn, in_shardings=(shardings, NamedSharding(mesh, P(dp_axis)))
    )


def make_tp_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    params_template,
    *,
    dp_axis: str = "data",
    tp_axis: str = "model",
    stateful: bool = False,
    donate: bool | None = None,
    param_specs=None,
    opt_state_specs=None,
    metric_fn: Callable | None = None,
    metric_keys=(),
):
    """Compiler-sharded (GSPMD) train step: TP via param shardings, DP via
    batch sharding — no shard_map, no manual collectives.

    ``params_template`` provides the pytree structure for the sharding
    annotations; ``param_specs`` overrides the default LM specs (pass
    classifier_param_specs/seq2seq_param_specs results for those models).
    The batch's leading dim is sharded over ``dp_axis``; XLA derives every
    collective (h all-gather per step, logits psum, grad reductions) from
    the annotations.

    ``opt_state_specs`` (a PartitionSpec pytree from
    `parallel.zero.zero1_tp_opt_specs`) turns on the GSPMD form of ZeRO-1:
    moment leaves shard over ``dp_axis`` too, and the step's in/out
    shardings PIN them there — without the pin, XLA's propagation from the
    params would replicate the moments over data and silently undo the
    memory saving.

    With ``metric_fn`` set, returns the FUSED train+eval step
    ``train_step(state, batch, eval_batches, do_eval)`` — the same
    lax.cond-gated weighted eval as the device_step builders, legal here
    because this is a pure GSPMD jit program (uniform replicated predicate;
    no manual-axis collectives to diverge on — the hazard that keeps fused
    eval out of the LM's wavefront steps). Eval batches arrive replicated
    (stage_stacked_batches' placement — matching the DP fused builders) and
    stay unconstrained in the jit signature; XLA partitions the eval branch
    like any other code.
    """
    if param_specs is None:
        param_specs = lm_param_specs(params_template, tp_axis)
    state_shardings = TrainState(
        step=NamedSharding(mesh, P()),
        params=jax.tree.map(
            lambda s: NamedSharding(mesh, s), param_specs,
            is_leaf=lambda x: isinstance(x, P),
        ),
        # without zero1 specs the opt_state stays unconstrained: XLA
        # propagates the params' shardings onto the matching moment leaves
        opt_state=None if opt_state_specs is None else jax.tree.map(
            lambda s: NamedSharding(mesh, s), opt_state_specs,
            is_leaf=lambda x: isinstance(x, P),
        ),
        rng=NamedSharding(mesh, P()),
        carries=NamedSharding(mesh, P(dp_axis)) if stateful else None,
    )

    from ..train.loop import _donation_supported

    if donate is None:
        donate = _donation_supported()

    if metric_fn is None:

        def train_step(state: TrainState, batch):
            return step_body(loss_fn, optimizer, state, batch,
                             stateful=stateful)

        in_shardings = (state_shardings, NamedSharding(mesh, P(dp_axis)))
    else:
        from ..train.device_step import _gated_eval_batches

        keys = tuple(metric_keys)

        def train_step(state: TrainState, batch, eval_batches, do_eval):
            state, ms = step_body(loss_fn, optimizer, state, batch,
                                  stateful=stateful)
            return state, _gated_eval_batches(
                metric_fn, state, eval_batches, do_eval, ms, keys
            )

        in_shardings = (
            state_shardings,
            NamedSharding(mesh, P(dp_axis)),
            None,  # eval batches: replicated placement stands
            None,  # do_eval scalar
        )
    out_shardings = None
    if opt_state_specs is not None:
        # pin the OUTPUT state too: propagation from the (replicated-over-
        # data) params would otherwise be free to emit replicated moments
        out_shardings = (state_shardings, None)
    return jax.jit(
        train_step,
        in_shardings=in_shardings,
        out_shardings=out_shardings,
        donate_argnums=(0,) if donate else (),
    )
