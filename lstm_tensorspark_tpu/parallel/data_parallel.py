"""Data-parallel training over the device mesh — the north-star replacement
for the reference's Spark backend (BASELINE.json north_star; SURVEY.md §3.3).

Mapping, component by component:
  Spark ``sc.broadcast(weights)``      → params replicated on-device (no
                                         per-step broadcast exists at all)
  ``rdd.mapPartitions(train_partition)`` → the same per-shard step body
                                         running under `shard_map` on every
                                         device's batch shard
  ``treeAggregate`` grad tree-reduce   → `lax.psum` (ICI all-reduce); being
                                         an all-reduce, every device gets the
                                         averaged grads, which also deletes
                                         the re-broadcast (SURVEY.md §3.3)
  driver-side ``params -= lr*grad``    → optimizer update runs replicated
                                         on-device inside the same XLA program

The entire reference round (3 process boundaries, 2 network serializations)
compiles to ONE jitted program per step.
"""

from __future__ import annotations

from typing import Callable

import jax
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.4.35 exposes shard_map at top level
    from jax import shard_map  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

# TrainState plus re-exports from train.loop (their dependency-free
# home): the per-shard rng fold-in and the pmean gradient reduction
# shared by every DP step builder here, in train/multistep.py and
# train/device_step.py.
from ..train.loop import (  # noqa: F401
    TrainState,
    dp_reduce_fn,
    dp_rng_transform,
)


def shard_batch(batch, mesh: Mesh, axis: str = "data", *, dim: int = 0):
    """Place a host batch with dim ``dim`` sharded over ``axis`` (replicated
    over the other mesh axes). ``dim=1`` is the K-steps-per-call layout
    [K, B, ...] where B is the sharded batch axis (train/multistep.py)."""
    sharding = NamedSharding(mesh, P(*([None] * dim), axis))
    return jax.tree.map(lambda a: jax.device_put(a, sharding), batch)


def replicate(tree, mesh: Mesh):
    """Fully-replicated placement — the reference's broadcast, done once."""
    sharding = NamedSharding(mesh, P())
    return jax.tree.map(lambda a: jax.device_put(a, sharding), tree)


def make_dp_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    axis: str = "data",
    jit: bool = True,
    donate: bool | None = None,
    stateful: bool = False,
    grad_accum: int = 1,
):
    """Build the data-parallel train step.

    ``loss_fn(params, batch, dropout_rng) -> (loss, aux)`` — the identical
    per-shard body used single-chip (SURVEY.md §3.2's train_partition), so
    single-device and DP runs are the same program modulo the psum.

    With ``stateful=True`` the loss_fn also takes/returns recurrent carries
    (see train/loop.py); carries live sharded over the data axis — each
    shard's stream keeps its own recurrent state, exactly like a Spark
    partition's worker-local state.
    """

    from ..train.loop import step_body

    def per_shard_step(state: TrainState, batch):
        return step_body(
            loss_fn,
            optimizer,
            state,
            batch,
            stateful=stateful,
            grad_accum=grad_accum,
            rng_transform=dp_rng_transform(axis),
            # treeAggregate + broadcast, collapsed into one ICI all-reduce:
            reduce_fn=dp_reduce_fn(axis),
        )

    state_spec = TrainState(
        step=P(), params=P(), opt_state=P(), rng=P(),
        carries=P(axis) if stateful else P(),
    )
    sharded = shard_map(
        per_shard_step,
        mesh=mesh,
        in_specs=(state_spec, P(axis)),
        out_specs=(state_spec, P()),
        check_vma=False,
    )
    if jit:
        from ..train.loop import _donation_supported

        if donate is None:
            donate = _donation_supported()
        sharded = jax.jit(sharded, donate_argnums=(0,) if donate else ())
    return sharded


def make_dp_eval_step(
    loss_fn: Callable,
    mesh: Mesh,
    *,
    axis: str = "data",
    jit: bool = True,
    stateful: bool = False,
):
    from ..train.loop import call_loss

    def _metrics(loss, aux):
        # Mirror make_eval_step's token reporting so evaluate() token-weights
        # DP eval identically to single-device eval. Shards are equal-shape,
        # so pmean of per-shard per-token means is the exact batch mean; the
        # batch's total token count is the psum of shard counts.
        m = {"loss": jax.lax.pmean(loss, axis)}
        if isinstance(aux, dict) and "tokens" in aux:
            m["tokens"] = jax.lax.psum(aux["tokens"], axis)
        return m

    if stateful:

        def per_shard_eval(params, batch, carries):
            loss, aux = call_loss(loss_fn, params, batch, None, carries, stateful=True)
            return _metrics(loss, aux), aux["carries"]

        sharded = shard_map(
            per_shard_eval,
            mesh=mesh,
            in_specs=(P(), P(axis), P(axis)),
            out_specs=(P(), P(axis)),
            check_vma=False,
        )
    else:

        def per_shard_eval(params, batch):
            loss, aux = loss_fn(params, batch, None)
            return _metrics(loss, aux)

        sharded = shard_map(
            per_shard_eval,
            mesh=mesh,
            in_specs=(P(), P(axis)),
            out_specs=P(),
            check_vma=False,
        )
    if jit:
        sharded = jax.jit(sharded)
    return sharded
