from .mesh import (
    distributed_init,
    local_device_count,
    make_hybrid_mesh,
    make_mesh,
    make_serve_mesh,
    slice_groups,
)
from .zero import (
    make_zero1_opt_init,
    make_zero1_train_step,
    zero1_tp_opt_specs,
)
from .data_parallel import make_dp_train_step, make_dp_eval_step, shard_batch
from .sequence_parallel import sp_lstm_scan
from .tensor_parallel import (
    lm_param_specs,
    make_tp_train_step,
    place_lm_params,
)
from .pipeline_parallel import (
    make_pp_lm_train_step,
    place_pp_lm_params,
    place_pp_zero1_opt_state,
    stack_lm_params,
    unstack_lm_params,
)
from .train_step import make_sharded_lm_train_step

__all__ = [
    "make_pp_lm_train_step",
    "place_pp_lm_params",
    "place_pp_zero1_opt_state",
    "stack_lm_params",
    "unstack_lm_params",
    "make_hybrid_mesh",
    "make_mesh",
    "make_serve_mesh",
    "make_zero1_opt_init",
    "make_zero1_train_step",
    "zero1_tp_opt_specs",
    "slice_groups",
    "local_device_count",
    "distributed_init",
    "make_dp_train_step",
    "make_dp_eval_step",
    "shard_batch",
    "sp_lstm_scan",
    "lm_param_specs",
    "make_tp_train_step",
    "place_lm_params",
    "make_sharded_lm_train_step",
]
