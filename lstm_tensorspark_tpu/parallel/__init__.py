from .mesh import make_mesh, local_device_count, distributed_init
from .data_parallel import make_dp_train_step, make_dp_eval_step, shard_batch

__all__ = [
    "make_mesh",
    "local_device_count",
    "distributed_init",
    "make_dp_train_step",
    "make_dp_eval_step",
    "shard_batch",
]
