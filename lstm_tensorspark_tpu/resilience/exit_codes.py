"""The process exit-code table — ONE authority for every failure class.

These codes are a cross-process protocol: the training CLI, the supervisor,
bench.py, tools/chip_recovery.py and tools/chip_watch.sh all route on them,
so they live in a module with NO third-party imports (the supervisor and
shell tooling must be able to read them without initialising a backend).

History (why a table, not inline literals): bench.py's liveness contract
used to exit 3 — the same code as chip_recovery.py's throughput-regression
gate — forcing the recovery tooling to scan stdout for a marker string to
tell a wedged chip from a real regression (ADVICE r5 finding 1). Dedicated,
documented codes make the routing structural.

| code | name            | meaning                                          | retry? |
|------|-----------------|--------------------------------------------------|--------|
| 2    | USAGE_RC        | argparse/flag-validation error (deterministic)   | no     |
| 3    | REGRESSION_RC   | chip_recovery.py's throughput-regression gate    | no     |
| 70   | CHILD_FAIL_RC   | recovery-queue child failed for a non-wedge      | no     |
|      |                 | reason (EX_SOFTWARE)                             |        |
| 75   | WEDGE_RC        | chip wedged / re-wedged (EX_TEMPFAIL): the       | yes    |
|      |                 | watcher resumes probing                          |        |
| 76   | LIVENESS_RC     | bench.py liveness contract fired (probe window   | yes    |
|      |                 | exhausted or whole-run watchdog) — the 0-value   |        |
|      |                 | JSON record precedes it                          |        |
| 77   | ANOMALY_RC      | train loop aborted after K consecutive           | yes    |
|      |                 | non-finite (NaN/Inf) steps: restart from         |        |
|      |                 | checkpoint (updates were skipped, params clean)  |        |
| 78   | POISON_RC       | supervisor gave up: restarts are not advancing   | no     |
|      |                 | the restored checkpoint step (crash loop)        |        |
| 81   | FAULT_CRASH_RC  | injected process crash (resilience/faults.py     | yes    |
|      |                 | drill) — retryable by construction               |        |

``RETRYABLE_RCS`` is the set the supervisor must relaunch even when the
child died fast (its sub-second "deterministic failure" heuristic must not
eat them): these codes are emitted deliberately by code that EXPECTS a
restart-from-checkpoint to make progress.
"""

USAGE_RC = 2
REGRESSION_RC = 3
CHILD_FAIL_RC = 70
WEDGE_RC = 75
LIVENESS_RC = 76
ANOMALY_RC = 77
POISON_RC = 78
FAULT_CRASH_RC = 81

RETRYABLE_RCS = frozenset({WEDGE_RC, LIVENESS_RC, ANOMALY_RC, FAULT_CRASH_RC})
