"""Checkpoint filename layout — the ONE naming authority, jax-free.

``train/checkpoint.py`` (the writer/reader) and ``supervise.py`` (the
forward-progress poison detector) both route on these patterns, but the
supervisor must stay import-light (no jax/backend init), so the patterns
live here — next to ``exit_codes.py``, the same shared-contract precedent.
Change the layout HERE and both sides move together.

Layout (see train/checkpoint.py for semantics):

- ``step_<N>.msgpack``            single-process checkpoint
- ``step_<N>.proc<K>.msgpack``    one process's shards of a sharded step
- ``step_<N>.complete``           marker: sharded step N is restorable
- ``<file>.sha256``               integrity sidecar of a state file
- ``<file>.quarantined``          corrupt file set aside by restore
"""

import re

STEP_PAT = re.compile(r"step_(\d+)\.msgpack$")
PROC_PAT = re.compile(r"step_(\d+)\.proc(\d+)\.msgpack$")
DONE_PAT = re.compile(r"step_(\d+)\.complete$")

# A RESTORABLE step for progress accounting: a single-file checkpoint, or
# a sharded step's completion marker. (Sidecars and quarantined files are
# excluded by the ``$`` anchors.)
RESTORABLE_PAT = re.compile(r"step_(\d+)\.(?:msgpack|complete)$")
