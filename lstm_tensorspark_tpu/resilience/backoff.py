"""Capped exponential backoff with jitter — the ONE implementation.

Shared by the crash supervisor (restart delays between child relaunches)
and the serve loadgen client (retry delays after a 429 shed, floored by
the server's ``Retry-After`` hint). Keeping a single function is the
point: two backoff curves that drift apart make incident math lie —
"the client retried after X" must mean the same X everywhere.

No jax, no project imports: the supervisor imports this before any
accelerator runtime exists.
"""

from __future__ import annotations

import random


def backoff_delay(base: float, attempt: int, *, cap: float = 30.0,
                  jitter: float = 0.5, rand=None) -> float:
    """Delay for ``attempt`` (1-based): exponential from ``base`` with up
    to ``+jitter`` fractional randomization, then capped — the cap bounds
    the SLEPT delay, jitter included (an operator's cap flag is a
    promise, not a suggestion). Jitter de-synchronizes a fleet of
    retriers hammering a shared resource (filesystem, coordinator, an
    overloaded serve router) after a common-cause failure; ``rand`` is
    injectable for deterministic tests."""
    if base <= 0:
        return 0.0
    delay = base * (2.0 ** max(attempt - 1, 0))
    r = random.random() if rand is None else rand()
    return min(delay * (1.0 + jitter * r), cap)
