"""Deterministic, seeded fault-injection plane (chaos drills).

The recovery machinery — non-finite-gradient guards, checksum-verified
checkpoints with quarantine-and-fall-back, the restarting supervisor — is
only trustworthy if its failure modes can be PROVOKED on demand, the same
way DrJAX argues the distributed-execution plane should be an explicit,
testable program construct rather than ambient behavior. This module is
that provocation plane: arm it with an env var or CLI flag and a scripted
schedule of faults fires at exact step numbers, so chaos tests can assert
the whole crash→restart→resume cycle deterministically on CPU.

Arming (either form; the CLI flag also exports the env var so child
processes inherit the schedule)::

    LSTM_TSP_FAULTS="crash@5;nan_grads@3x2;ckpt_corrupt@4" python -m ...
    python -m lstm_tensorspark_tpu.cli --faults "crash@5" ...

Spec grammar — semicolon-separated ``kind@arg`` clauses:

- ``crash@N``        hard process exit (``FAULT_CRASH_RC``) before step N;
- ``nan_grads@N[xK]`` NaN gradients for the K steps N..N+K-1 (default 1);
- ``ckpt_corrupt@N`` truncate the checkpoint file written at step N,
  AFTER its write completes (a torn write the checksum must catch);
- ``data_error@N``   raise :class:`InjectedFault` from the batch feed
  before step N;
- ``serve_error@N``  raise :class:`InjectedFault` from the Nth
  ``ServeEngine.decode`` call of the process;
- ``seed@S``         seed for the corruption byte schedule (default 0).

Serve-plane faults (chaos drills for the replicated/tiered serve stack —
``tools/chaos_serve.py``; counts start at arming, fire once per process):

- ``replica_die@R[xK]``   raise :class:`InjectedFault` out of replica R's
  Kth scheduler step after arming (default 1) — the scheduler thread
  exits, the router must retire the replica (requeue / migrate / honest
  in-flight failure);
- ``replica_wedge@R[xK]`` replica R's Kth step after arming blocks for
  ``wedge_secs`` — thread alive, heartbeat stale (the wedge case);
- ``wedge_secs@S``        wedge duration in seconds (default 120);
- ``disk_write_err@N``    the Nth disk-tier session write raises
  ``OSError`` (durability lost, correctness kept —
  ``serve_tier_lost_total{reason="disk_error"}``);
- ``disk_read_err@N``     the Nth disk-tier session read raises
  ``OSError`` (an honest miss/"state lost", never wrong tokens);
- ``session_corrupt@N``   truncate + byte-flip the session file of the
  Nth successful disk-tier write AFTER it lands (the sha256 verify must
  quarantine it at fill time);
- ``spill_stall@N[xS]``   the Nth spill-worker batch sleeps S seconds
  (default 1) before its device fetch — the write-behind stall drill;
- ``slow_readback@N[xMS]`` the Nth decode-window readback sleeps MS
  milliseconds (default 250) — slow device→host fetch.

Network faults (injected inside ``serve/transport.py`` ``PeerTransport``
so heartbeat, residency, and generate RPCs all see the same wire; peer
numbers are the transport's ``peer`` index, windows run from arming on
the monotonic clock):

- ``net_latency@N[xMS]``  the Nth generate RPC attempt after arming is
  delayed MS milliseconds (default 100) before the wire;
- ``net_drop@N``          the Nth generate RPC attempt executes on the
  wire but the client drops the response — an indeterminate failure
  (``executed=None``) that must resolve via request_id replay, never a
  double decode;
- ``net_blackhole@R[xS]`` peer R is blackholed (connects time out,
  nothing delivered) for S seconds — omit ``xS`` for "until disarm",
  the partition drill's heal switch;
- ``net_flap@R[xS]``      peer R's link alternates ok/fail per RPC
  attempt for S seconds (default 10) — the flap-damping drill.

Step numbers are the 1-based global optimizer step about to be computed —
resume-stable, so a restarted child reasons in the same coordinates.

One-shot semantics: ``crash``/``data_error``/``ckpt_corrupt`` faults fire
ONCE per schedule, recorded as marker files under ``<state_dir>/.faults/``
(the checkpoint directory, when the CLI arms the plane). Without the
marker a restarted child would resume below step N, re-reach it, and
re-fire forever — a synthetic crash loop the supervisor would (correctly)
classify as poison. ``nan_grads`` deliberately re-fires on replay: it is a
pure function of the step number, and the guard must skip it identically
every time. ``serve_error`` is call-count based and fires once per
process.

No jax at import time: the supervisor imports this package, and plane
checks on hot paths are a ``None`` test when unarmed.
"""

from __future__ import annotations

import os
import re
import sys
import threading
import time

from .exit_codes import FAULT_CRASH_RC

ENV_VAR = "LSTM_TSP_FAULTS"

_KINDS = ("crash", "nan_grads", "ckpt_corrupt", "data_error", "serve_error",
          "seed", "replica_die", "replica_wedge", "wedge_secs",
          "disk_write_err", "disk_read_err", "session_corrupt",
          "spill_stall", "slow_readback",
          "net_latency", "net_drop", "net_blackhole", "net_flap")

#: kinds whose ``xK`` suffix is meaningful (everything else rejects it)
_XK_KINDS = ("nan_grads", "replica_die", "replica_wedge", "spill_stall",
             "slow_readback", "net_latency", "net_blackhole", "net_flap")


class InjectedFault(RuntimeError):
    """An exception raised BY the fault plane (never by real code): chaos
    tests assert on this type to prove the failure they saw was the one
    they scheduled."""


def _crash() -> None:
    """The injected hard crash — ``os._exit`` skips atexit/finally blocks,
    like a real OOM-kill would. Module-level so in-process tests can
    monkeypatch it into a raise."""
    os._exit(FAULT_CRASH_RC)


class FaultPlane:
    """A parsed, armed fault schedule. Construct via :func:`arm` (module
    singleton) or directly in tests."""

    _CLAUSE = re.compile(r"^(\w+)@(\d+)(?:x(\d+))?$")

    def __init__(self, spec: str, *, state_dir: str | None = None):
        self.spec = spec
        self.state_dir = state_dir
        self.seed = 0
        self.crash_steps: set[int] = set()
        self.nan_grad_steps: tuple[int, ...] = ()
        self.ckpt_corrupt_steps: set[int] = set()
        self.data_error_steps: set[int] = set()
        self.serve_error_calls: set[int] = set()
        self._serve_calls = 0
        self._fired_mem: set[str] = set()
        # serve-plane schedules (counts start at arming — the in-process
        # drill arms mid-run to target an exact moment deterministically)
        self.replica_die: dict[int, int] = {}    # replica -> its Kth step
        self.replica_wedge: dict[int, int] = {}  # replica -> its Kth step
        self.wedge_secs = 120
        self.disk_write_err_calls: set[int] = set()
        self.disk_read_err_calls: set[int] = set()
        self.session_corrupt_writes: set[int] = set()
        self.spill_stall_batches: dict[int, int] = {}   # batch N -> seconds
        self.slow_readback_calls: dict[int, int] = {}   # call N -> millis
        # network faults (PeerTransport): windows run from arming time
        self.net_latency_calls: dict[int, int] = {}     # gen call N -> ms
        self.net_drop_calls: set[int] = set()           # gen call N
        self.net_blackhole: dict[int, int | None] = {}  # peer -> secs|None
        self.net_flap: dict[int, int] = {}              # peer -> secs
        self._armed_at = time.monotonic()
        self._net_generate_calls = 0
        self._net_flap_calls: dict[int, int] = {}
        self._net_announced: set[str] = set()
        # serve hooks fire from several threads (scheduler threads, the
        # spill worker, HTTP threads) — count under one small lock so
        # "fires exactly once at the Nth call" stays true under races
        self._serve_lock = threading.Lock()
        self._step_counts: dict[int, int] = {}
        self._disk_writes = 0
        self._disk_reads = 0
        self._disk_puts_ok = 0
        self._spill_batches = 0
        self._readback_calls = 0
        nan: list[int] = []
        for raw in spec.split(";"):
            clause = raw.strip()
            if not clause:
                continue
            m = self._CLAUSE.match(clause)
            if not m:
                raise ValueError(
                    f"bad fault clause {clause!r} (expected kind@N or "
                    f"kind@NxK; kinds: {', '.join(_KINDS)})"
                )
            kind, n, k = m.group(1), int(m.group(2)), m.group(3)
            if kind not in _KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r} (kinds: {', '.join(_KINDS)})"
                )
            if k is not None and kind not in _XK_KINDS:
                raise ValueError(
                    f"{clause!r}: xK suffix only with "
                    f"{', '.join(_XK_KINDS)}")
            if kind == "seed":
                self.seed = n
            elif kind == "crash":
                self.crash_steps.add(n)
            elif kind == "nan_grads":
                nan.extend(range(n, n + int(k or 1)))
            elif kind == "ckpt_corrupt":
                self.ckpt_corrupt_steps.add(n)
            elif kind == "data_error":
                self.data_error_steps.add(n)
            elif kind == "serve_error":
                self.serve_error_calls.add(n)
            elif kind == "replica_die":
                self.replica_die[n] = int(k or 1)
            elif kind == "replica_wedge":
                self.replica_wedge[n] = int(k or 1)
            elif kind == "wedge_secs":
                self.wedge_secs = n
            elif kind == "disk_write_err":
                self.disk_write_err_calls.add(n)
            elif kind == "disk_read_err":
                self.disk_read_err_calls.add(n)
            elif kind == "session_corrupt":
                self.session_corrupt_writes.add(n)
            elif kind == "spill_stall":
                self.spill_stall_batches[n] = int(k or 1)
            elif kind == "slow_readback":
                self.slow_readback_calls[n] = int(k or 250)
            elif kind == "net_latency":
                self.net_latency_calls[n] = int(k or 100)
            elif kind == "net_drop":
                self.net_drop_calls.add(n)
            elif kind == "net_blackhole":
                self.net_blackhole[n] = None if k is None else int(k)
            elif kind == "net_flap":
                self.net_flap[n] = int(k or 10)
        self.nan_grad_steps = tuple(sorted(set(nan)))

    # ---- one-shot bookkeeping -----------------------------------------

    def _marker_path(self, fault_id: str) -> str | None:
        if self.state_dir is None:
            return None
        return os.path.join(self.state_dir, ".faults", fault_id + ".fired")

    def fired(self, fault_id: str) -> bool:
        if fault_id in self._fired_mem:
            return True
        path = self._marker_path(fault_id)
        return path is not None and os.path.exists(path)

    def mark_fired(self, fault_id: str) -> None:
        """Record BEFORE the fault takes effect: a crash between the effect
        and the record would re-fire on restart — the exact loop the
        markers exist to prevent."""
        self._fired_mem.add(fault_id)
        path = self._marker_path(fault_id)
        if path is not None:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            with open(path, "w") as f:
                f.write(self.spec + "\n")

    def _announce(self, msg: str) -> None:
        # stderr + flush: the supervisor's stall watchdog merges streams,
        # and a crash fault must leave its forensics before os._exit
        print(f"fault-injection: {msg}", file=sys.stderr, flush=True)

    # ---- train-path hooks ---------------------------------------------

    def wrap_batches(self, batches, *, start_step: int = 0,
                     steps_per_call: int = 1):
        """Wrap the training batch feed: fire ``crash``/``data_error``
        faults whose step falls inside the window the next dispatch will
        compute (steps ``[i*K+1, (i+1)*K]`` past ``start_step``)."""
        if not (self.crash_steps or self.data_error_steps):
            return batches

        def gen():
            step = start_step
            for batch in batches:
                lo, hi = step + 1, step + steps_per_call
                for s in sorted(self.crash_steps):
                    fid = f"crash@{s}"
                    if lo <= s <= hi and not self.fired(fid):
                        self.mark_fired(fid)
                        self._announce(
                            f"hard crash before step {s} "
                            f"(exit {FAULT_CRASH_RC})")
                        _crash()
                for s in sorted(self.data_error_steps):
                    fid = f"data_error@{s}"
                    if lo <= s <= hi and not self.fired(fid):
                        self.mark_fired(fid)
                        self._announce(f"data-batch exception before step {s}")
                        raise InjectedFault(
                            f"injected data-batch exception before step {s}")
                yield batch
                step = hi

        return gen()

    def tamper_grads(self, grads, step):
        """Inside-jit NaN burst: poison every gradient leaf when the step
        being computed (``state.step + 1``) is in the schedule. The
        schedule is baked into the compiled program as a constant — fully
        deterministic, works under ``lax.scan`` and across resume because
        ``state.step`` is traced."""
        if not self.nan_grad_steps:
            return grads
        import jax
        import jax.numpy as jnp

        bad = jnp.isin(step + 1, jnp.asarray(self.nan_grad_steps))
        return jax.tree.map(
            lambda g: jnp.where(bad, jnp.asarray(jnp.nan, g.dtype), g), grads
        )

    # ---- checkpoint hook ----------------------------------------------

    def maybe_corrupt_checkpoint(self, path: str, step: int) -> None:
        """Torn-write simulation, called by the checkpointer AFTER a save
        completes: truncate the file to half and overwrite a seeded byte
        — the on-disk damage a crash mid-write (or bit rot) leaves, which
        the checksum sidecar must catch at restore."""
        for s in sorted(self.ckpt_corrupt_steps):
            fid = f"ckpt_corrupt@{s}"
            if s == step and not self.fired(fid):
                self.mark_fired(fid)
                size = os.path.getsize(path)
                keep = size // 2
                with open(path, "r+b") as f:
                    f.truncate(keep)
                    if keep > 0:
                        pos = (self.seed * 2654435761 + s) % keep
                        f.seek(pos)
                        byte = f.read(1)
                        f.seek(pos)
                        f.write(bytes([(byte[0] ^ 0xFF) if byte else 0xFF]))
                self._announce(
                    f"corrupted checkpoint {os.path.basename(path)} "
                    f"(step {step}: {size} -> {keep} bytes + byte flip)")

    # ---- serve hook ----------------------------------------------------

    def serve_decode_hook(self) -> None:
        """Fire an exception out of the Nth ``ServeEngine.decode`` call of
        this process (count-based: decode has no global step)."""
        if not self.serve_error_calls:
            return
        self._serve_calls += 1
        if self._serve_calls in self.serve_error_calls:
            self._announce(
                f"serve-engine exception on decode call {self._serve_calls}")
            raise InjectedFault(
                f"injected serve-engine exception on decode call "
                f"{self._serve_calls}")

    # ---- serve-plane hooks (chaos_serve drills) ------------------------

    def serve_step_hook(self, replica: int) -> None:
        """Called at the top of every ``Batcher.step``: fire the replica's
        scheduled death (InjectedFault → the scheduler thread exits → the
        router retires it) or wedge (block with the heartbeat stale while
        ``is_alive()`` stays true — the case /healthz must out) at its Kth
        step since arming."""
        die = self.replica_die.get(replica)
        wedge = self.replica_wedge.get(replica)
        if die is None and wedge is None:
            return
        with self._serve_lock:
            n = self._step_counts.get(replica, 0) + 1
            self._step_counts[replica] = n
        if die is not None and n == die:
            self._announce(
                f"replica {replica} scheduler death on its step {n}")
            raise InjectedFault(
                f"injected replica {replica} scheduler death (step {n})")
        if wedge is not None and n == wedge:
            self._announce(
                f"replica {replica} wedged for {self.wedge_secs}s "
                f"on its step {n}")
            time.sleep(self.wedge_secs)

    def serve_disk_hook(self, op: str) -> None:
        """Fire an ``OSError`` out of the Nth disk-tier session write or
        read. Placed so the error takes the SAME path a real filesystem
        failure would: a failed write counts ``disk_error`` and keeps the
        state in RAM; a failed read is an honest miss ("state lost")."""
        if op == "write":
            if not self.disk_write_err_calls:
                return
            with self._serve_lock:
                self._disk_writes += 1
                fire = self._disk_writes in self.disk_write_err_calls
                n = self._disk_writes
        else:
            if not self.disk_read_err_calls:
                return
            with self._serve_lock:
                self._disk_reads += 1
                fire = self._disk_reads in self.disk_read_err_calls
                n = self._disk_reads
        if fire:
            self._announce(f"disk-tier {op} OSError on call {n}")
            raise OSError(f"injected disk-tier {op} failure (call {n})")

    def maybe_corrupt_session(self, path: str) -> None:
        """Truncate + byte-flip the session file of the Nth SUCCESSFUL
        disk-tier write, after it lands — the damage the embedded sha256
        must catch at fill time (quarantine + honest "state lost")."""
        if not self.session_corrupt_writes:
            return
        with self._serve_lock:
            self._disk_puts_ok += 1
            if self._disk_puts_ok not in self.session_corrupt_writes:
                return
            n = self._disk_puts_ok
        size = os.path.getsize(path)
        keep = max(size // 2, 1)
        with open(path, "r+b") as f:
            f.truncate(keep)
            pos = (self.seed * 2654435761 + n) % keep
            f.seek(pos)
            byte = f.read(1)
            f.seek(pos)
            f.write(bytes([(byte[0] ^ 0xFF) if byte else 0xFF]))
        self._announce(
            f"corrupted session file {os.path.basename(path)} "
            f"(write {n}: {size} -> {keep} bytes + byte flip)")

    def serve_spill_hook(self) -> None:
        """Stall the Nth spill-worker batch before its device fetch — the
        write-behind delay drill (flush() must still be a real barrier,
        fills must keep finding the pending capture)."""
        if not self.spill_stall_batches:
            return
        with self._serve_lock:
            self._spill_batches += 1
            n = self._spill_batches
            secs = self.spill_stall_batches.get(n)
        if secs:
            self._announce(f"spill worker stalled {secs}s on batch {n}")
            time.sleep(secs)

    def serve_readback_hook(self) -> None:
        """Delay the Nth decode-window readback (slow device→host fetch):
        the scheduler must absorb it as latency, never as a wrong
        token or a health flap below the staleness bound."""
        if not self.slow_readback_calls:
            return
        with self._serve_lock:
            self._readback_calls += 1
            n = self._readback_calls
            ms = self.slow_readback_calls.get(n)
        if ms:
            self._announce(f"readback delayed {ms}ms on fetch {n}")
            time.sleep(ms / 1000.0)

    def serve_net_hook(self, peer: int, method: str):
        """Consulted by ``PeerTransport._attempt`` before every wire
        attempt.  Returns ``None`` (no fault) or an action tuple the
        transport enacts: ``("blackhole",)`` — connect times out, nothing
        delivered; ``("fail",)`` — connection reset (flap); ``("latency",
        ms)`` — delay then proceed; ``("drop",)`` — execute for real,
        then lose the response client-side (indeterminate)."""
        if not (self.net_blackhole or self.net_flap
                or self.net_latency_calls or self.net_drop_calls):
            return None
        elapsed = time.monotonic() - self._armed_at
        window = self.net_blackhole.get(peer, False)
        if window is not False and (window is None or elapsed <= window):
            if f"bh{peer}" not in self._net_announced:
                self._net_announced.add(f"bh{peer}")
                self._announce(
                    f"peer {peer} blackholed "
                    + ("until disarm" if window is None
                       else f"for {window}s"))
            return ("blackhole",)
        secs = self.net_flap.get(peer)
        if secs is not None and elapsed <= secs:
            with self._serve_lock:
                n = self._net_flap_calls.get(peer, 0) + 1
                self._net_flap_calls[peer] = n
            if n % 2 == 1:
                return ("fail",)
        if method == "generate" and \
                (self.net_latency_calls or self.net_drop_calls):
            with self._serve_lock:
                self._net_generate_calls += 1
                n = self._net_generate_calls
            ms = self.net_latency_calls.get(n)
            if ms:
                self._announce(f"generate RPC {n} delayed {ms}ms")
                return ("latency", ms)
            if n in self.net_drop_calls:
                self._announce(f"generate RPC {n} response dropped")
                return ("drop",)
        return None


# ---- module singleton ---------------------------------------------------

_active: FaultPlane | None = None


def arm(spec: str, *, state_dir: str | None = None) -> FaultPlane:
    """Parse and install ``spec`` as the process-wide plane (replacing any
    previous one). ``state_dir`` hosts the one-shot markers — pass the
    checkpoint directory so restarted children share them."""
    global _active
    _active = FaultPlane(spec, state_dir=state_dir)
    return _active


def arm_from_env(*, state_dir: str | None = None) -> FaultPlane | None:
    """Arm from ``LSTM_TSP_FAULTS`` if set (child processes of a supervised
    drill inherit the schedule this way). With the variable unset this
    DISARMS instead: an entrypoint that re-runs in one interpreter (tests,
    notebooks) must not inherit a stale plane from an earlier run."""
    spec = os.environ.get(ENV_VAR, "").strip()
    if not spec:
        disarm()
        return None
    return arm(spec, state_dir=state_dir)


def arm_from_flag_or_env(spec: str | None, *,
                         state_dir: str | None = None) -> FaultPlane | None:
    """The ONE entrypoint arming sequence (training CLI main and the serve
    subcommand share it): an explicit ``--faults`` spec wins and is
    exported to ``LSTM_TSP_FAULTS`` so child processes inherit the
    schedule; otherwise the env var decides (set → arm, unset → disarm any
    stale plane from an earlier in-process run)."""
    if spec:
        os.environ[ENV_VAR] = spec
        return arm(spec, state_dir=state_dir)
    return arm_from_env(state_dir=state_dir)


def disarm() -> None:
    global _active
    _active = None


def active() -> FaultPlane | None:
    return _active


def tamper_grads(grads, step):
    """Unarmed-safe hook for jitted step bodies (identity when no plane)."""
    plane = _active
    if plane is None:
        return grads
    return plane.tamper_grads(grads, step)


def serve_decode_hook() -> None:
    plane = _active
    if plane is not None:
        plane.serve_decode_hook()


def serve_step_hook(replica: int) -> None:
    """Unarmed-safe scheduler-step hook (Batcher.step)."""
    plane = _active
    if plane is not None:
        plane.serve_step_hook(replica)


def serve_disk_hook(op: str) -> None:
    """Unarmed-safe disk-tier IO hook (_DiskTier.put/get)."""
    plane = _active
    if plane is not None:
        plane.serve_disk_hook(op)


def maybe_corrupt_session(path: str) -> None:
    """Unarmed-safe post-write session-file corruption hook."""
    plane = _active
    if plane is not None:
        plane.maybe_corrupt_session(path)


def serve_spill_hook() -> None:
    """Unarmed-safe spill-worker batch hook (SessionTiers)."""
    plane = _active
    if plane is not None:
        plane.serve_spill_hook()


def serve_readback_hook() -> None:
    """Unarmed-safe decode-window readback hook (Batcher)."""
    plane = _active
    if plane is not None:
        plane.serve_readback_hook()


def serve_net_hook(peer: int, method: str):
    """Unarmed-safe transport wire hook (serve/transport.py)."""
    plane = _active
    if plane is None:
        return None
    return plane.serve_net_hook(peer, method)


def maybe_corrupt_checkpoint(path: str, step: int) -> None:
    plane = _active
    if plane is not None:
        plane.maybe_corrupt_checkpoint(path, step)
