"""Resilience plane: shared exit-code contract + deterministic fault injection.

Two halves, deliberately dependency-light (no jax at import time — the
supervisor and shell tooling import from here without paying backend init):

- :mod:`.exit_codes` — the ONE table of process exit codes used by the
  training loop, the supervisor, bench.py's liveness contract and
  tools/chip_recovery.py. Replaces the magic numbers that used to be
  scattered (and once collided: bench's liveness failure reused the
  regression gate's rc=3).
- :mod:`.faults` — a seeded, deterministic fault-injection plane
  (``LSTM_TSP_FAULTS`` / ``--faults``) that provokes the failure modes the
  self-healing code claims to survive: process crash at step N, NaN/Inf
  gradient bursts, checkpoint truncation after write, data-batch
  exceptions, serve-engine exceptions mid-decode. Chaos tests
  (tests/test_chaos*.py, tools/chaos_smoke.py) arm it and assert the
  crash→restart→resume cycle completes the full step budget.
"""

from .exit_codes import (  # noqa: F401
    ANOMALY_RC,
    CHILD_FAIL_RC,
    FAULT_CRASH_RC,
    LIVENESS_RC,
    POISON_RC,
    REGRESSION_RC,
    RETRYABLE_RCS,
    USAGE_RC,
    WEDGE_RC,
)
from .faults import (  # noqa: F401
    FaultPlane,
    InjectedFault,
    active,
    arm,
    arm_from_flag_or_env,
    disarm,
)
