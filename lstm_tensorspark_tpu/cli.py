"""Training entrypoint — reference CLI parity (SURVEY.md §2 L5 [D]: "keeps
its CLI ... launches on a TPU pod with no Spark JVM").

The reference's flag surface (hidden units, layers, epochs, learning rate,
partitions, data path — SURVEY.md §1 L5 row) is preserved; ``--num-partitions``
maps to the number of mesh devices on the data axis, the direct successor of
the RDD partition count. Where ``spark-submit main.py --flags`` launched a
JVM driver, ``python main.py --flags`` (or ``python -m
lstm_tensorspark_tpu.cli``) builds a device mesh and jit-compiles the train
step; multi-host pods launch the same script once per host with
``--num-processes/--process-id/--coordinator``.
"""

from __future__ import annotations

import argparse
import sys

import jax
import numpy as np

# The LM task family (word/char language modelling) — ONE definition for
# task dispatch and every LM-specific CLI gate.
LM_DATASETS = ("ptb_char", "wikitext2", "wikitext103")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="lstm_tensorspark_tpu",
        description="TPU-native LSTM training (LSTM-TensorSpark capabilities, no Spark)",
        epilog="Inference serving is a subcommand with its own flags: "
               "`... serve {--selftest | --loadgen | --http}` — run "
               "`... serve --help` (dispatched before this parser, so "
               "`serve` must be the first argument).",
    )
    # --- reference flag surface (SURVEY.md §1 L5) ---
    p.add_argument("--data-path", type=str, default=None, help="corpus directory (falls back to synthetic stand-in)")
    p.add_argument("--hidden-units", type=int, default=128)
    p.add_argument("--num-layers", type=int, default=1)
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--learning-rate", type=float, default=1.0)
    p.add_argument("--num-partitions", type=int, default=None,
                   help="data-parallel shards (reference: RDD partitions) — defaults to all devices")
    # --- capability extensions ---
    p.add_argument("--dataset", type=str, default="ptb_char",
                   choices=["ptb_char", "wikitext2", "wikitext103", "imdb", "uci_electricity"])
    p.add_argument("--batch-size", type=int, default=32, help="global batch size")
    p.add_argument("--seq-len", type=int, default=None,
                   help="window/context length (defaults: LM 64, imdb 400, uci 168)")
    p.add_argument("--optimizer", type=str, default="sgd",
                   choices=["sgd", "momentum", "adam", "adamw", "rmsprop"])
    p.add_argument("--momentum", type=float, default=0.0)
    p.add_argument("--clip-norm", type=float, default=None)
    p.add_argument("--weight-decay", type=float, default=0.0, help="adamw only")
    p.add_argument("--warmup-steps", type=int, default=0,
                   help="linear LR warmup steps (enables warmup-cosine schedule)")
    p.add_argument("--decay-steps", type=int, default=None,
                   help="cosine decay horizon in steps (enables the schedule)")
    p.add_argument("--dropout", type=float, default=0.0)
    p.add_argument("--tie-embeddings", action="store_true")
    p.add_argument("--compute-dtype", type=str, default="bfloat16",
                   choices=["float32", "bfloat16"])
    p.add_argument("--logits-dtype", type=str, default="float32",
                   choices=["float32", "bfloat16"],
                   help="dtype of the materialized [B,T,V] LM logits; "
                        "bfloat16 halves every HBM pass over that array "
                        "(+25%% measured at V=33k) while the logsumexp/NLL "
                        "still runs in f32 over the upcast values — "
                        "opt-in numerics trade, LM tasks only (no effect "
                        "on the chunked-xent path at V>=131072, which "
                        "never materializes the array)")
    p.add_argument("--remat-chunk", type=int, default=None,
                   help="jax.checkpoint chunk size over time (long sequences)")
    p.add_argument("--scan-unroll", type=int, default=1)
    p.add_argument("--bptt-mode", type=str, default="auto",
                   choices=["auto", "assoc", "sequential"],
                   help="backward pass through the recurrence "
                        "(ops/parallel_scan.py): 'assoc' = parallel-scan "
                        "BPTT (associative scan of per-step adjoint "
                        "operators, O(log T) depth), 'sequential' = the "
                        "ordinary reverse scan, 'auto' = assoc only when "
                        "the memory plan fits and T is long enough "
                        "(docs/OPERATIONS.md 'BPTT mode')")
    p.add_argument("--use-pallas", action="store_true",
                   help="fused Pallas recurrence kernel (TPU, B%%8==0; any H — "
                        "padded/tiled internally). Its fused backward saves "
                        "O(T) f32 activations in HBM; above ~4 GB (env "
                        "LSTM_TSP_RESIDUAL_HBM_MB) or with --remat-chunk set "
                        "it switches to the recompute backward instead")
    p.add_argument("--stateful", action="store_true",
                   help="stateful truncated BPTT: carry recurrent state across contiguous windows")
    p.add_argument("--grad-accum", type=int, default=1,
                   help="gradient-accumulation microbatches per optimizer step "
                        "(splits the per-shard batch; activation memory drops "
                        "to one microbatch's worth)")
    p.add_argument("--steps-per-call", type=int, default=1,
                   help="K optimizer steps per host dispatch (lax.scan over K "
                        "staged batches — amortises dispatch for small models; "
                        "log/eval/checkpoint cadences then count K-step calls)")
    p.add_argument("--prefetch", type=int, default=0,
                   help="device-prefetch depth for the input feed (0 = off; "
                        "background-thread device_put can hurt on tunneled/"
                        "shared backends — measure before enabling)")
    p.add_argument("--zero1", action="store_true",
                   help="shard the OPTIMIZER state 1/dp over the data axis "
                        "(ZeRO-1): grads reduce-scattered, each shard "
                        "updates its slice of the raveled params with its "
                        "slice of the moments, all-gather rebuilds params "
                        "— same per-step collective volume as plain DP, "
                        "optimizer memory /dp (Adam: 2x params -> "
                        "2x params/dp). Composes with --steps-per-call. "
                        "Requires a DP mesh; not with --stateful/"
                        "--grad-accum/--device-data/--fused-eval/TP/SP/PP. "
                        "ZeRO-1 checkpoints resume at the SAME "
                        "--num-partitions (the sharded moments bake in "
                        "the shard count)")
    p.add_argument("--device-data", action="store_true",
                   help="stage the dataset in device HBM once and build "
                        "batches on-device (LM: window slices; imdb: row "
                        "gather; uci: series windows) — per-dispatch host "
                        "traffic shrinks to indices; the cached-RDD "
                        "equivalent; dataset must fit HBM")
    p.add_argument("--fused-eval", action="store_true",
                   help="run the eval pass INSIDE the train executable on "
                        "device-resident eval data (every task; composes "
                        "with --device-data or the host-fed feed — only the "
                        "EVAL split must fit HBM — and, for the classifier/"
                        "forecaster, with --tensor-parallel): one program "
                        "for both cadences, so an eval costs zero "
                        "train/eval executable swaps — the swap is "
                        "~3 s/eval on dispatch-expensive backends and "
                        "dominates small-model runs")
    # --- inference / generation (LM tasks) ---
    p.add_argument("--generate-tokens", type=int, default=0,
                   help="after training, sample N continuation tokens from the LM")
    p.add_argument("--prompt", type=str, default=None,
                   help="generation prompt text (defaults to the corpus start)")
    p.add_argument("--temperature", type=float, default=1.0)
    p.add_argument("--top-k", type=int, default=None)
    p.add_argument("--top-p", type=float, default=None,
                   help="nucleus sampling mass in (0, 1]")
    p.add_argument("--greedy", action="store_true", help="argmax decoding")
    p.add_argument("--num-steps", type=int, default=None,
                   help="total step budget for the job, resume-inclusive "
                        "(overrides epochs). An explicit 0 runs ZERO "
                        "training steps — the eval-only recipe with "
                        "--resume (unset falls back to the epoch count)")
    p.add_argument("--eval-every", type=int, default=0)
    p.add_argument("--eval-batches", type=int, default=None,
                   help="cap each eval pass at N batches (default: the full "
                        "held-out split) — bounds eval cost at large dims")
    p.add_argument("--log-every", type=int, default=50)
    p.add_argument("--log-flops", action="store_true",
                   help="add live model-TFLOP/s and MFU (vs the bf16 peak, "
                        "env LSTM_TSP_PEAK_TFLOPS) to every throughput log "
                        "record — matmul-only accounting, train = 3x "
                        "forward, same formulas as bench.py")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--anomaly-limit", type=int, default=0,
                   help="abort with the dedicated anomaly exit code "
                        "(resilience/exit_codes.py) after K CONSECUTIVE "
                        "non-finite (NaN/Inf) steps, so the supervisor "
                        "restarts from checkpoint; the guard itself (skip "
                        "the update, count the step) is always on — this "
                        "only adds the abort watchdog, at the cost of one "
                        "host sync per step while enabled (0 = off)")
    p.add_argument("--faults", type=str, default=None,
                   help="ARM FAULT INJECTION (chaos drills only): a "
                        "schedule like 'crash@50;nan_grads@30x2;"
                        "ckpt_corrupt@40' — see resilience/faults.py for "
                        "the grammar; exported as LSTM_TSP_FAULTS to "
                        "children; one-shot faults record their firing "
                        "under --checkpoint-dir/.faults so supervised "
                        "restarts don't re-fire them")
    p.add_argument("--jsonl", type=str, default=None, help="metrics JSONL path")
    p.add_argument("--checkpoint-dir", type=str, default=None)
    p.add_argument("--checkpoint-every", type=int, default=0)
    p.add_argument("--keep-best", action="store_true",
                   help="additionally track the BEST-eval checkpoint "
                        "(best.msgpack + best.json in --checkpoint-dir; "
                        "multi-process runs write sharded "
                        "best_<step>.proc<k> files + a best.complete "
                        "marker instead), overwritten on each improvement "
                        "of the task's eval metric: LM perplexity / "
                        "classifier accuracy / forecast MSE — outside the "
                        "keep-N rotation; requires --checkpoint-dir and "
                        "--eval-every")
    p.add_argument("--async-checkpoint", action="store_true",
                   help="overlap checkpoint serialization + file IO with "
                        "training: save() blocks only for the device-to-"
                        "host snapshot, the write runs on a background "
                        "thread (single-process runs; multi-process saves "
                        "stay synchronous for their barriers)")
    p.add_argument("--resume", action="store_true", help="resume from latest checkpoint in --checkpoint-dir")
    p.add_argument("--resume-best", action="store_true",
                   help="ONE-TIME REWIND to the best-eval checkpoint "
                        "(--keep-best's best.msgpack) — e.g. to fine-tune "
                        "the best model after overfitting. Deletes step_N "
                        "checkpoints newer than the best and re-saves the "
                        "rewound point, so later --resume runs continue "
                        "THIS lineage; mutually exclusive with --resume "
                        "(the supervisor converts it to --resume on "
                        "relaunch); single-process only")
    p.add_argument("--compilation-cache", type=str, default=None,
                   help="persistent XLA compilation-cache directory: repeat "
                        "runs of the same program shapes skip compilation "
                        "entirely (first TPU compile is ~20-40 s — for "
                        "short production runs the cache is the difference "
                        "between launch-to-quality and post-compile time)")
    p.add_argument("--profile-dir", type=str, default=None, help="jax.profiler trace output dir")
    p.add_argument("--trace", type=str, default=None,
                   help="host-side span trace output (Chrome trace-event "
                        "JSON; device-side profiling is --profile-dir)")
    p.add_argument("--backend", type=str, default="auto", choices=["auto", "single", "dp"],
                   help="auto: dp when >1 device/partition")
    # --- advanced parallelism (LM task; new capability beyond the reference) ---
    p.add_argument("--tensor-parallel", type=int, default=1,
                   help="'model' mesh axis size: gate/hidden dims sharded (GSPMD)")
    p.add_argument("--seq-parallel", type=int, default=1,
                   help="'seq' mesh axis size: wavefront sequence parallelism")
    p.add_argument("--pipeline-stages", type=int, default=1,
                   help="'pipe' mesh axis size: GPipe pipeline over stacked layers")
    p.add_argument("--microbatches", type=int, default=None,
                   help="wavefront microbatches for --seq-parallel/--pipeline-stages")
    # --- multi-host control plane (SURVEY.md §7 step 4) ---
    p.add_argument("--coordinator", type=str, default=None)
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    return p


def main(argv=None) -> int:
    if argv is None:
        import sys

        argv = sys.argv[1:]
    if argv and argv[0] == "serve":
        return _run_serve(argv[1:])
    if argv and argv[0] == "distill":
        return _run_distill(argv[1:])
    args = build_parser().parse_args(argv)
    if args.temperature <= 0.0:
        raise SystemExit(f"--temperature must be > 0, got {args.temperature}")
    if args.top_k is not None and args.top_k < 1:
        raise SystemExit(f"--top-k must be >= 1, got {args.top_k}")
    if args.top_p is not None and not 0.0 < args.top_p <= 1.0:
        raise SystemExit(f"--top-p must be in (0, 1], got {args.top_p}")
    if args.eval_batches is not None and args.eval_batches < 1:
        raise SystemExit(f"--eval-batches must be >= 1, got {args.eval_batches}")
    # one shared gate for every task runner: the fused kernel cannot run on
    # a "model"-axis-sharded hidden dim (GSPMD cannot partition pallas_call);
    # it DOES compose with --pipeline-stages AND --seq-parallel (their
    # wavefront bodies are collective-free per chunk; both steps make every
    # mesh axis manual when the kernel is live)
    if args.use_pallas and args.tensor_parallel > 1:
        raise SystemExit("--use-pallas is not supported with --tensor-parallel "
                         "(the GSPMD-sharded hidden dim cannot enter the fused "
                         "kernel)")
    if args.fused_eval and max(args.seq_parallel, args.pipeline_stages) > 1:
        raise SystemExit("--fused-eval is not supported with --seq-parallel/"
                         "--pipeline-stages (a lax.cond around their manual "
                         "wavefront collectives would diverge); it composes "
                         "with --backend single/dp and, for the classifier/"
                         "forecaster, with --tensor-parallel")
    if args.fused_eval and args.tensor_parallel > 1 and args.dataset in (
            LM_DATASETS):
        raise SystemExit("--fused-eval with --tensor-parallel is supported "
                         "for the classifier/forecaster (pure GSPMD jit "
                         "steps); the LM's TP step is a manual {data,seq} "
                         "shard_map where a gated eval branch could diverge "
                         "on the auto-axis collectives")
    if args.fused_eval and not args.eval_every:
        raise SystemExit("--fused-eval needs --eval-every > 0 (it fuses the "
                         "PERIODIC eval pass into the train executable; "
                         "without a cadence it would stage eval data and "
                         "compile the eval branch for nothing)")
    if args.keep_best and not (args.checkpoint_dir and args.eval_every):
        raise SystemExit("--keep-best needs --checkpoint-dir (where "
                         "best.msgpack lives) and --eval-every > 0 (the "
                         "metric it tracks)")
    # --keep-best composes with multi-process runs since r4: save_best
    # routes through the sharded writer (best_<step>.proc<k> files + a
    # best.complete marker — train/checkpoint.py)
    if args.resume_best and not args.checkpoint_dir:
        raise SystemExit("--resume-best needs --checkpoint-dir (where the "
                         "producing run's best.msgpack lives) — without it "
                         "the run would silently train from random init")
    if args.resume_best and args.resume:
        raise SystemExit("--resume-best and --resume are mutually exclusive "
                         "(rewind vs continue are different intents; the "
                         "supervisor converts --resume-best to --resume on "
                         "relaunch so a crashed fine-tune continues its own "
                         "lineage)")
    # --resume-best composes with multi-process runs since r4: the rewind's
    # fence deletes on process 0 behind barriers (train/checkpoint.py
    # fence_after), restore/re-save use the sharded writer machinery

    if args.compilation_cache:
        # cache EVERY executable (the defaults skip sub-second compiles,
        # which is exactly the small-config regime where fixed costs bite)
        jax.config.update("jax_compilation_cache_dir", args.compilation_cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

    from .parallel import distributed_init
    distributed_init(args.coordinator, args.num_processes, args.process_id)

    from .resilience import faults
    # --faults wins (and is exported to children); a supervised drill arms
    # the CHILDREN via the env var instead
    faults.arm_from_flag_or_env(args.faults, state_dir=args.checkpoint_dir)

    from .train.metrics import MetricsLogger

    # context-managed: the JSONL handle closes on EVERY exit path (a
    # SystemExit out of a task runner used to leak it)
    with MetricsLogger(args.jsonl) as logger:
        from .utils import Tracer, set_tracer
        tracer = None
        if args.trace:
            tracer = Tracer()
            set_tracer(tracer)

        from .train.loop import AnomalousTrainingError

        try:
            if args.dataset in LM_DATASETS:
                rc = _run_lm(args, logger)
            elif args.generate_tokens > 0:
                raise SystemExit(
                    "--generate-tokens applies to the LM datasets only "
                    f"(got --dataset {args.dataset})"
                )
            elif args.dataset == "imdb":
                rc = _run_classifier(args, logger)
            else:
                rc = _run_forecaster(args, logger)
        except AnomalousTrainingError as e:
            # dedicated exit code: the supervisor relaunches with --resume
            # and restores the last (clean — updates were skipped) checkpoint
            import sys

            from .resilience.exit_codes import ANOMALY_RC

            print(f"anomaly abort: {e} (exit {ANOMALY_RC})", file=sys.stderr)
            rc = ANOMALY_RC
        finally:
            if tracer is not None:
                set_tracer(None)  # uninstall first: a failed save must not leak it
                try:
                    tracer.save(args.trace)
                except OSError as e:
                    # never mask the run's own outcome with a trace-write error
                    print(f"warning: could not write --trace file: {e}")
        # final registry snapshot into the JSONL: the run's step-time /
        # tokens-per-sec / anomalous-step telemetry (obs/), same numbers a
        # live /metrics scrape would show. The bptt context rides along
        # (requested mode string + trace/fallback counts) so a supervised
        # restart can detect a bptt-mode flip between resume legs.
        from .obs import REGISTRY

        extra = None
        if getattr(args, "bptt_mode", None):
            from .ops import parallel_scan

            pstats = parallel_scan.assoc_stats()
            extra = {"bptt_mode": args.bptt_mode,
                     "bptt_assoc_traces": pstats["assoc_traces"],
                     "bptt_sequential_fallbacks":
                         pstats["sequential_fallbacks"]}
        logger.log_registry(REGISTRY, extra=extra)
    return rc


def make_cli_optimizer(args, *, clip: bool = True):
    """The one optimizer constructor for every task runner — full flag
    surface (optimizer family, momentum, clipping, weight decay, warmup/
    cosine schedule). ``clip=False`` builds the chain WITHOUT the
    global-norm clip stage — required by the ZeRO-1 step, which clips
    from the psum'd global norm itself (parallel/zero.py)."""
    from .train import make_optimizer

    return make_optimizer(
        args.optimizer, args.learning_rate,
        momentum=args.momentum,
        clip_norm=args.clip_norm if clip else None,
        weight_decay=getattr(args, "weight_decay", 0.0),
        warmup_steps=getattr(args, "warmup_steps", 0),
        decay_steps=getattr(args, "decay_steps", None),
    )


def _select_backend(args):
    """Resolve (mesh or None, shards). None mesh → single-chip path.

    ``--backend dp`` is honored even with one device/partition (a 1-wide
    shard_map — useful to validate DP semantics anywhere); ``auto`` picks
    dp only when more than one shard is in play."""
    n_devices = jax.device_count()
    shards = args.num_partitions or n_devices
    if args.backend == "single" or (args.backend == "auto" and shards <= 1):
        return None, 1
    if shards > n_devices:
        raise SystemExit(
            f"--num-partitions {shards} exceeds {n_devices} available devices"
        )
    return _build_mesh(dp=shards,
                       devices=np.asarray(jax.devices()[:shards])), shards


def _build_mesh(**kw):
    """Slice-aware mesh construction: order devices DCN-slowest
    (make_hybrid_mesh — a no-op layout on one slice/process) so data-axis
    psums decompose into ICI + one DCN phase and model/seq/pipe
    collectives never cross slices. Falls back to the plain ordering ONLY
    when a truncated device list leaves unequal domains (pathological but
    previously legal — e.g. 6 partitions over 2 hosts of 4); a model
    block that would straddle DCN stays the hard error mesh.py makes it."""
    from .parallel import make_hybrid_mesh, make_mesh
    try:
        return make_hybrid_mesh(**kw)
    except ValueError as e:
        if "unequal" not in str(e):
            raise
        return make_mesh(**kw)


def _setup_training(
    args,
    logger,
    *,
    loss_fn,
    params,
    optimizer,
    rng,
    stateful: bool = False,
    carries0=None,
):
    """Shared orchestration for every task runner: backend selection,
    divisibility check, checkpoint wiring (restore BEFORE device placement),
    replication onto the mesh, and batch-stream sharding.

    Returns (state, train_step, mesh, shards, wrap_stream, checkpoint_fn).
    """
    from .data import prefetch_to_device, stacked_batches
    from .parallel import make_dp_train_step, shard_batch
    from .parallel.data_parallel import replicate
    from .train import (
        make_dp_multi_train_step,
        make_multi_train_step,
        make_train_step,
    )
    from .train.loop import init_train_state

    mesh, shards = _select_backend(args)
    if args.batch_size % max(shards, 1) != 0:
        raise SystemExit(
            f"--batch-size {args.batch_size} not divisible by {shards} partitions"
        )
    k = getattr(args, "steps_per_call", 1)
    k = 1 if k is None else k
    if k < 1:
        raise SystemExit(f"--steps-per-call must be >= 1, got {k}")
    accum = getattr(args, "grad_accum", 1) or 1
    if accum < 1:
        raise SystemExit(f"--grad-accum must be >= 1, got {accum}")
    if accum > 1:
        if stateful:
            raise SystemExit("--grad-accum is not supported with --stateful "
                             "(recurrent carries do not microbatch)")
        per_shard = args.batch_size // max(shards, 1)
        if per_shard % accum != 0:
            raise SystemExit(
                f"per-shard batch {per_shard} not divisible by --grad-accum {accum}"
            )
    # write the normalized values back so later branches (e.g. --device-data)
    # reuse THIS validation instead of re-deriving their own
    args.steps_per_call = k
    args.grad_accum = accum

    zero1 = bool(getattr(args, "zero1", False))
    if zero1:
        for bad, why in (
            (mesh is None, "requires a DP mesh (--num-partitions > 1 or "
                           "--backend dp)"),
            (accum > 1, "not with --grad-accum"),
            (stateful, "not with --stateful"),
            (getattr(args, "device_data", False), "not with --device-data"),
            (getattr(args, "fused_eval", False), "not with --fused-eval"),
        ):
            if bad:
                raise SystemExit(f"--zero1: {why}")
        # The ZeRO-1 step clips from the psum'd GLOBAL norm itself; the
        # optax chain must not contain its own (per-slice) clip stage.
        # Rebuilding from args is safe because every task runner's
        # ``optimizer`` comes 1:1 from make_cli_optimizer(args) — if a
        # caller ever passes a custom chain, strip its clip stage there
        # and thread it through instead of relying on this rebuild.
        optimizer = make_cli_optimizer(args, clip=False)

    state = init_train_state(params, optimizer, rng, carries=carries0)
    if zero1:
        from .parallel.zero import make_zero1_opt_init

        # sharded moments from the start — also the checkpoint template,
        # so restore reshards onto exactly these leaves
        state = state._replace(
            opt_state=make_zero1_opt_init(optimizer, mesh)(state.params))

    restored, checkpoint_fn = _wire_checkpoint(args, logger, lambda: state)
    if restored is not None:
        state = restored

    depth = getattr(args, "prefetch", 0) or 0

    if mesh is None:
        if k > 1:
            train_step = make_multi_train_step(
                loss_fn, optimizer, stateful=stateful, grad_accum=accum
            )
        else:
            train_step = make_train_step(
                loss_fn, optimizer, stateful=stateful, grad_accum=accum
            )

        def wrap_stream(it, always_stack=False):
            # always_stack: the fused host-fed train+eval step is a K-step
            # (multistep) program even at K=1, so its feed needs the
            # leading axis regardless of --steps-per-call
            if k > 1 or always_stack:
                it = stacked_batches(it, k)
            if depth > 0:
                it = prefetch_to_device(it, depth)
            return it

    else:
        if zero1:
            from .parallel.zero import make_zero1_train_step

            train_step = make_zero1_train_step(
                loss_fn, optimizer, mesh, clip_norm=args.clip_norm,
                steps_per_call=k,
            )
        elif k > 1:
            train_step = make_dp_multi_train_step(
                loss_fn, optimizer, mesh, stateful=stateful, grad_accum=accum
            )
        else:
            train_step = make_dp_train_step(
                loss_fn, optimizer, mesh, stateful=stateful, grad_accum=accum
            )
        state = state._replace(
            params=replicate(state.params, mesh),
            # zero1: the moments are already sharded P("data") — replicate
            # would gather them back onto every shard
            opt_state=state.opt_state if zero1
            else replicate(state.opt_state, mesh),
            carries=shard_batch(state.carries, mesh) if stateful else None,
        )

        from jax.sharding import NamedSharding, PartitionSpec as P

        def wrap_stream(it, always_stack=False):
            stacked = k > 1 or always_stack
            dim = 1 if stacked else 0
            if stacked:
                it = stacked_batches(it, k)
            if depth > 0:
                sharding = NamedSharding(mesh, P(*([None] * dim), "data"))
                return prefetch_to_device(it, depth, sharding=sharding)
            return (shard_batch(b, mesh, dim=dim) for b in it)

    return state, train_step, mesh, shards, wrap_stream, checkpoint_fn


def _setup_tp_training(args, logger, *, loss_fn, params, optimizer, rng,
                       specs_fn, hidden: int, metric_fn=None,
                       metric_keys=()):
    """Tensor-parallel (GSPMD dp×tp) setup for the classifier/forecaster
    tasks — the compiler-first recipe: annotate param shardings, let XLA
    insert the collectives. Returns the same tuple as _setup_training.

    With ``metric_fn`` set (fused eval), the returned train_step has the
    fused signature ``(state, batch, eval_batches, do_eval)`` — built ONCE
    here, not rebuilt by the task runner.
    """
    from .parallel.tensor_parallel import make_tp_train_step, place_params
    from .train.loop import init_train_state

    tp = args.tensor_parallel
    if getattr(args, "steps_per_call", 1) and args.steps_per_call > 1:
        raise SystemExit("--steps-per-call is not supported with --tensor-parallel")
    if getattr(args, "grad_accum", 1) and args.grad_accum > 1:
        raise SystemExit("--grad-accum is not supported with --tensor-parallel")
    if getattr(args, "device_data", False):
        raise SystemExit("--device-data is not supported with --tensor-parallel")
    if getattr(args, "prefetch", 0):
        raise SystemExit("--prefetch is not supported with --tensor-parallel")
    if hidden % tp != 0:
        raise SystemExit(f"--hidden-units {hidden} not divisible by "
                         f"--tensor-parallel {tp}")
    args.steps_per_call = 1
    args.grad_accum = 1
    n = jax.device_count()
    dp = args.num_partitions or max(n // tp, 1)
    if dp * tp > n:
        raise SystemExit(f"mesh dp*tp={dp * tp} exceeds {n} devices")
    if args.batch_size % dp != 0:
        raise SystemExit(f"--batch-size {args.batch_size} not divisible by dp={dp}")
    mesh = _build_mesh(dp=dp, tp=tp,
                       devices=np.asarray(jax.devices()[: dp * tp]))

    state = init_train_state(params, optimizer, rng)
    restored, checkpoint_fn = _wire_checkpoint(args, logger, lambda: state)
    if restored is not None:
        state = restored
    specs = specs_fn(params)
    # place params with their TP shardings; opt_state (possibly restored —
    # re-initializing would lose momenta) is unconstrained in the step's
    # in_shardings, so jit reshards it to match the params on first call
    state = state._replace(params=place_params(state.params, specs, mesh))

    opt_specs = None
    if getattr(args, "zero1", False):
        # GSPMD ZeRO-1 (parallel/zero.py): moment leaves shard over the
        # data axis too; placing the (fresh or restored) state here means
        # no device ever materializes a replicated copy of the moments
        from .parallel.zero import zero1_tp_opt_specs

        opt_specs = zero1_tp_opt_specs(optimizer, params, specs, mesh)
        state = state._replace(
            opt_state=place_params(state.opt_state, opt_specs, mesh))

    train_step = make_tp_train_step(
        loss_fn, optimizer, mesh, params, param_specs=specs,
        opt_state_specs=opt_specs,
        metric_fn=metric_fn, metric_keys=metric_keys,
    )
    # jit's in_shardings place each host batch; the stream passes through
    return state, train_step, mesh, dp, (lambda it: it), checkpoint_fn


def _wire_checkpoint(args, logger, template_fn):
    """Shared checkpoint/resume wiring. ``template_fn()`` produces the
    restore template lazily — only called when a checkpoint actually exists,
    so fresh --resume runs on sharded state skip the host gather.

    Returns (restored_state_or_None, checkpoint_fn_or_None)."""
    if not args.checkpoint_dir:
        return None, None
    from .train.checkpoint import Checkpointer

    ckpt = Checkpointer(args.checkpoint_dir,
                        async_save=getattr(args, "async_checkpoint", False))
    restored = None
    if getattr(args, "resume_best", False):
        meta = ckpt.best_meta()
        if meta is None:
            raise SystemExit("--resume-best: no best checkpoint in "
                             f"{args.checkpoint_dir} (was --keep-best on "
                             "in the producing run?)")
        restored = ckpt.restore_best(template_fn())
        if restored is None:
            # restore_best quarantines a corrupt best and reports None
            # (train/checkpoint.py): abort BEFORE the fence below, which
            # would destroy the run's valid newer step checkpoints
            raise SystemExit("--resume-best: the best checkpoint in "
                             f"{args.checkpoint_dir} is corrupt (now "
                             "quarantined); no rewind performed")
        # the rewind is a commitment: fence the abandoned lineage (its
        # later step_N checkpoints must not win a future restore_latest)
        # and make the rewound point itself durable as a step checkpoint —
        # a crash before the fine-tune's first own save then resumes HERE,
        # not from random init
        ckpt.fence_after(meta["step"])
        ckpt.save(restored)
        logger.log({"note": f"resumed from BEST checkpoint at step "
                            f"{int(restored.step)}", **meta})
    elif args.resume and ckpt.has_checkpoint():
        restored = ckpt.restore_latest(template_fn())
        if restored is not None:
            logger.log({"note": f"resumed at step {int(restored.step)}"})
        else:
            # checkpoints EXISTED but every one failed verification and
            # was quarantined (train/checkpoint.py): silently training
            # from random init would discard the run's progress without
            # anyone noticing — abort loudly instead (an empty dir, by
            # contrast, is a legitimate fresh start under --resume: the
            # supervisor injects the flag before the first save exists)
            raise SystemExit(
                f"--resume: every checkpoint in {args.checkpoint_dir} "
                "failed verification (now quarantined); refusing to "
                "silently restart from step 0 — inspect the "
                "*.quarantined files")
    elif args.resume and ckpt.has_quarantined():
        # the refusal must PERSIST across a supervisor relaunch: after the
        # quarantine above, has_checkpoint() is False on the next attempt,
        # and without this gate the relaunch would fresh-start from step 0
        # — exactly the silent outcome the abort exists to prevent
        raise SystemExit(
            f"--resume: {args.checkpoint_dir} holds no valid checkpoint "
            "but contains *.quarantined files (a previous attempt found "
            "them corrupt); refusing to silently restart from step 0 — "
            "inspect or clear the quarantined files first")

    def checkpoint_fn(state):
        return ckpt.save(state)

    # EXPLICIT finalizer contract (not attribute-sniffing a bound method):
    # _make_logged_loop calls .finalize after the loop so the last async
    # write is durable before the process reads checkpoints or exits, and
    # a failed final write fails the run. Anyone wrapping checkpoint_fn
    # must carry the attributes forward (.save_best serves --keep-best).
    checkpoint_fn.finalize = ckpt.wait
    checkpoint_fn.save_best = ckpt.save_best
    checkpoint_fn.best_meta = ckpt.best_meta
    return restored, checkpoint_fn


def _mfu_logging(args, fwd_flops_per_token, mesh):
    """(flops_per_token, peak_tflops) for train_loop's live-MFU records, or
    (None, None) without --log-flops. THE one place the accounting policy
    lives: train = 3x forward (utils/flops.py), and the peak aggregates
    every chip in the mesh — throughput records are global rates, so
    per-chip MFU must divide by the global peak."""
    if not getattr(args, "log_flops", False):
        return None, None
    from .utils.flops import PEAK_TFLOPS, TRAIN_FLOPS_MULTIPLIER

    n = mesh.size if mesh is not None else 1
    return (TRAIN_FLOPS_MULTIPLIER * fwd_flops_per_token,
            PEAK_TFLOPS * max(n, 1))


def _make_logged_loop(args, state, train_step, batches, steps_per_epoch, logger,
                      eval_fn=None, checkpoint_fn=None, tokens_per_batch=None,
                      fused_eval=None, flops_per_token=None, peak_tflops=None,
                      best_metric="eval_loss", best_mode="min"):
    from .train.loop import train_loop

    best_fn, best_init = None, None
    if getattr(args, "keep_best", False) and checkpoint_fn is not None:
        best_fn = getattr(checkpoint_fn, "save_best", None)
        # seed best-so-far from a previously saved best (resume/restart
        # must never overwrite a better checkpoint with a worse one)
        meta_fn = getattr(checkpoint_fn, "best_meta", None)
        if best_fn is not None and meta_fn is not None:
            meta = meta_fn()
            if meta is not None:
                best_init = meta["value"]

    # explicit `--num-steps 0` means ZERO training steps (the eval-only
    # recipe: resume a checkpoint, skip straight to the final eval) — only
    # an UNSET budget falls back to the epoch count
    total = (args.num_steps if args.num_steps is not None
             else args.epochs * steps_per_epoch)
    # --resume restores state.step; train only the REMAINING budget
    total = max(total - int(state.step), 0)
    k = getattr(args, "steps_per_call", 1)
    k = 1 if k is None or k < 1 else k
    if k > 1:
        # each loop iteration is one K-step dispatch; round up so the step
        # budget is never undershot
        total = -(-total // k)
    from .resilience import faults
    plane = faults.active()
    if plane is not None:
        # chaos drills: crash/data_error faults fire from the batch feed,
        # windowed in GLOBAL step coordinates (resume-stable) — one wrap
        # point covers every task runner and feed kind
        batches = plane.wrap_batches(
            batches, start_step=int(state.step), steps_per_call=k
        )
    if args.profile_dir:
        jax.profiler.start_trace(args.profile_dir)
    try:
        state = train_loop(
            state,
            train_step,
            batches,
            num_steps=total,
            log_every=args.log_every,
            logger=logger,
            eval_fn=eval_fn,
            eval_every=args.eval_every,
            checkpoint_fn=checkpoint_fn,
            checkpoint_every=args.checkpoint_every,
            tokens_per_batch=tokens_per_batch,
            steps_per_call=k,
            fused_eval=fused_eval,
            flops_per_token=flops_per_token,
            peak_tflops=peak_tflops,
            best_fn=best_fn,
            best_metric=best_metric,
            best_mode=best_mode,
            best_init=best_init,
            anomaly_limit=getattr(args, "anomaly_limit", 0) or 0,
        )
    finally:
        if args.profile_dir:
            jax.profiler.stop_trace()
        # finalize async checkpointing (the _wire_checkpoint contract): the
        # LAST write must be durable before this process reads checkpoints
        # (same-process --resume) or exits, and a failed final write must
        # fail the run, not vanish.
        fin = getattr(checkpoint_fn, "finalize", None)
        if fin is not None:
            fin()
    return state


def _run_lm(args, logger) -> int:
    from .data import get_dataset, lm_batch_stream, lm_epoch_batches
    from .models import LMConfig, init_lm, lm_loss
    from .train import make_optimizer, make_eval_step
    from .train.loop import evaluate
    from .parallel import make_dp_eval_step, shard_batch

    from .utils import span

    seq_len = args.seq_len or 64
    with span("load_dataset", dataset=args.dataset):
        data = get_dataset(args.dataset, args.data_path)
    if data["synthetic"]:
        logger.log({"note": f"dataset {args.dataset}: no files at --data-path, using synthetic stand-in"})
    vocab = data["vocab"]
    cfg = LMConfig(
        vocab_size=len(vocab),
        hidden_size=args.hidden_units,
        num_layers=args.num_layers,
        dropout=args.dropout,
        tie_embeddings=args.tie_embeddings,
        compute_dtype=args.compute_dtype,
        remat_chunk=args.remat_chunk,
        scan_unroll=args.scan_unroll,
        use_pallas=args.use_pallas,
        logits_dtype=args.logits_dtype,
        bptt=args.bptt_mode,
    )

    if max(args.tensor_parallel, args.seq_parallel, args.pipeline_stages) > 1:
        return _run_lm_advanced(args, logger, cfg, data, seq_len)

    stateful = args.stateful

    if stateful:

        def loss_fn(params, batch, dropout_rng, carries):
            return lm_loss(
                params, batch, cfg, carries=carries,
                dropout_rng=dropout_rng,
                deterministic=dropout_rng is None or cfg.dropout == 0.0,
            )

    else:

        def loss_fn(params, batch, dropout_rng):
            return lm_loss(
                params, batch, cfg,
                dropout_rng=dropout_rng,
                deterministic=dropout_rng is None or cfg.dropout == 0.0,
            )

    key = jax.random.PRNGKey(args.seed)
    kparams, krng = jax.random.split(key)
    with span("setup", hidden=cfg.hidden_size, layers=cfg.num_layers):
        params = init_lm(kparams, cfg)
        optimizer = make_cli_optimizer(args)
        from .models.lstm_lm import init_carries
        carries0 = init_carries(cfg, args.batch_size) if stateful else None

        state, train_step, mesh, shards, wrap_stream, checkpoint_fn = _setup_training(
            args, logger,
            loss_fn=loss_fn, params=params, optimizer=optimizer, rng=krng,
            stateful=stateful, carries0=carries0,
        )

    train_tokens, valid_tokens = data["train"], data["valid"]
    steps_per_epoch = max((len(train_tokens) - 1) // (args.batch_size * seq_len), 1)
    # The valid split can be smaller than one training-size window; evaluate
    # with the largest batch that fits (multiple of the shard count).
    eval_bs = min(args.batch_size, max((len(valid_tokens) - 1) // seq_len, 0))
    eval_bs -= eval_bs % max(shards, 1)

    fused_eval = bool(args.fused_eval)
    if fused_eval and eval_bs <= 0:
        logger.log({"note": "fused-eval: valid split smaller than one "
                            "window; falling back to host-driven eval"})
        fused_eval = False
    # data-exact resume: fast-forward every stream to the restored step so a
    # resumed run sees exactly the windows the uninterrupted run would
    start_step = int(state.step)
    if args.device_data:
        if args.prefetch:
            raise SystemExit("--device-data has no host feed; drop --prefetch")
        from .data import stage_lm_data, window_index_stream
        from .train import (
            make_device_dp_lm_train_step,
            make_device_lm_train_step,
        )

        # values below were normalized+validated by _setup_training
        k = args.steps_per_call
        ddata = stage_lm_data(train_tokens, args.batch_size, seq_len, mesh=mesh)
        edata = (stage_lm_data(valid_tokens, eval_bs, seq_len, mesh=mesh)
                 if fused_eval else None)
        if mesh is None:
            dstep = make_device_lm_train_step(
                loss_fn, optimizer, ddata, eval_data=edata,
                eval_windows=args.eval_batches, steps_per_call=k,
                stateful=stateful, grad_accum=args.grad_accum,
            )
        else:
            dstep = make_device_dp_lm_train_step(
                loss_fn, optimizer, ddata, mesh, eval_data=edata,
                eval_windows=args.eval_batches, steps_per_call=k,
                stateful=stateful, grad_accum=args.grad_accum,
            )
        if fused_eval:
            ev_carries0 = init_carries(cfg, eval_bs) if stateful else None
            if mesh is not None and stateful:
                ev_carries0 = shard_batch(ev_carries0, mesh)
            train_step = lambda state, w0, do_eval: dstep(  # noqa: E731
                state, ddata.arrays, w0, edata.arrays, do_eval, ev_carries0
            )
        else:
            train_step = lambda state, w0: dstep(state, ddata.arrays, w0)  # noqa: E731
        batches = window_index_stream(ddata, k, start_step=start_step)
    else:
        stream = lm_batch_stream(
            train_tokens, args.batch_size, seq_len, start_step=start_step
        )
        if fused_eval:
            # host-fed train feed + fused in-executable eval: only the VALID
            # split must fit HBM (the case where the train set exceeds it)
            from .data import stage_lm_data
            from .train import make_dp_multi_train_step, make_multi_train_step

            edata = stage_lm_data(valid_tokens, eval_bs, seq_len, mesh=mesh)
            ev_carries0 = init_carries(cfg, eval_bs) if stateful else None
            if mesh is not None and stateful:
                ev_carries0 = shard_batch(ev_carries0, mesh)
            if mesh is None:
                mstep = make_multi_train_step(
                    loss_fn, optimizer, eval_data=edata,
                    eval_windows=args.eval_batches,
                    stateful=stateful, grad_accum=args.grad_accum,
                )
            else:
                mstep = make_dp_multi_train_step(
                    loss_fn, optimizer, mesh, eval_data=edata,
                    eval_windows=args.eval_batches,
                    stateful=stateful, grad_accum=args.grad_accum,
                )
            train_step = lambda state, b, do_eval: mstep(  # noqa: E731
                state, b, edata.arrays, do_eval, ev_carries0
            )
            batches = wrap_stream(stream, always_stack=True)
        else:
            batches = wrap_stream(stream)

    if mesh is None:
        eval_step = make_eval_step(loss_fn, stateful=stateful)
    else:
        eval_step = make_dp_eval_step(loss_fn, mesh, stateful=stateful)

    from .data.batching import cap_batches

    def eval_fn(params):
        if eval_bs <= 0:
            return {"eval_skipped": 1}
        ev = cap_batches(lm_epoch_batches(valid_tokens, eval_bs, seq_len),
                         args.eval_batches)
        ev_carries = init_carries(cfg, eval_bs) if stateful else None
        if mesh is not None:
            ev = (shard_batch(b, mesh) for b in ev)
            if stateful:
                ev_carries = shard_batch(ev_carries, mesh)
        return evaluate(eval_step, params, ev, carries=ev_carries)

    logger.log({
        "note": "start", "dataset": args.dataset, "vocab": len(vocab),
        "devices": jax.device_count(), "partitions": shards,
        "steps_per_epoch": steps_per_epoch, "backend": "dp" if mesh is not None else "single",
    })
    from .train.loop import eval_metrics

    from .utils.flops import lm_fwd_flops_per_token

    flops_per_token, peak = _mfu_logging(
        args,
        lm_fwd_flops_per_token(cfg.vocab_size, cfg.hidden_size,
                               cfg.num_layers, cfg.embed),
        mesh,
    )

    with span("train", steps_per_epoch=steps_per_epoch, backend="dp" if mesh is not None else "single"):
        state = _make_logged_loop(
            args, state, train_step, batches, steps_per_epoch, logger,
            eval_fn=None if fused_eval else (eval_fn if args.eval_every else None),
            checkpoint_fn=checkpoint_fn,
            tokens_per_batch=args.batch_size * seq_len,
            fused_eval=(lambda ms: eval_metrics(float(ms["eval_loss"])))
            if fused_eval else None,
            flops_per_token=flops_per_token,
            peak_tflops=peak,
        )
    with span("eval_final"):
        final = eval_fn(state.params)
    logger.log({"step": int(state.step), **final, "note": "final"})
    if args.generate_tokens > 0:
        with span("generate", tokens=args.generate_tokens):
            _generate_text(args, logger, cfg, data, jax.device_get(state.params))
    return 0


def _generate_text(args, logger, cfg, data, params_host) -> None:
    """Post-training sampling (models/generate.py): encode the prompt, run
    the jitted prefill+decode program, print/log the decoded continuation."""
    from .models import make_generate_fn

    level = "char" if args.dataset == "ptb_char" else "word"
    vocab = data["vocab"]
    if args.prompt:
        prompt_ids = vocab.encode_text(args.prompt, level)
        if prompt_ids.size == 0:
            prompt_ids = np.asarray(data["train"][:8], np.int32)
    else:
        prompt_ids = np.asarray(data["train"][:32], np.int32)
    gen = make_generate_fn(
        cfg,
        max_new_tokens=args.generate_tokens,
        temperature=args.temperature,
        top_k=args.top_k,
        top_p=args.top_p,
        greedy=args.greedy,
    )
    rng = jax.random.PRNGKey(args.seed + 17)
    out = np.asarray(gen(params_host, prompt_ids[None, :], rng))[0]
    sep = "" if level == "char" else " "
    prompt_txt = sep.join(vocab.decode(prompt_ids))
    cont_txt = sep.join(vocab.decode(out[prompt_ids.size:]))
    logger.log({
        "note": "generate", "prompt": prompt_txt, "continuation": cont_txt,
        "temperature": args.temperature, "top_k": args.top_k,
        "top_p": args.top_p, "greedy": bool(args.greedy),
    })
    print(f"--- prompt ---\n{prompt_txt}\n--- continuation ---\n{cont_txt}")


def _run_lm_advanced(args, logger, cfg, data, seq_len) -> int:
    """LM training under tensor/sequence/pipeline parallelism (± DP) on an
    explicit 4-axis mesh — the CLI surface for the strategies beyond the
    reference's data-parallel-only scope (DESIGN.md parallelism table).

    Eval runs SHARDED on the device-resident params (pp/tp/sp eval steps) —
    no host gather; only post-training generation pulls params to host
    (sequential small-batch decode).
    """
    if getattr(args, "zero1", False) and args.pipeline_stages <= 1:
        raise SystemExit(
            "--zero1 with the LM's --tensor-parallel/--seq-parallel steps "
            "is not supported (their update runs inside a manual "
            "{data,seq} shard_map, where the GSPMD weight-update-sharding "
            "form cannot pin the moments). It DOES compose with "
            "--pipeline-stages (stage x data sharded moments) and with "
            "the classifier/forecaster --tensor-parallel runners "
            "(parallel/zero.py).")
    from .data import lm_batch_stream, lm_epoch_batches
    from .models import init_lm
    from .parallel import (
        make_pp_lm_train_step,
        make_sharded_lm_train_step,
        place_pp_lm_params,
        stack_lm_params,
        unstack_lm_params,
    )
    from .parallel.tensor_parallel import place_lm_params
    from .train import make_optimizer
    from .train.loop import evaluate, init_train_state

    tp, sp, pp = args.tensor_parallel, args.seq_parallel, args.pipeline_stages
    if getattr(args, "steps_per_call", 1) > 1:
        raise SystemExit("--steps-per-call is not supported with "
                         "--tensor-parallel/--seq-parallel/--pipeline-stages")
    if getattr(args, "grad_accum", 1) > 1:
        raise SystemExit("--grad-accum is not supported with --tensor-parallel/"
                         "--seq-parallel/--pipeline-stages (use --microbatches "
                         "for the wavefront schedules)")
    if getattr(args, "device_data", False):
        raise SystemExit("--device-data is not supported with --tensor-parallel/"
                         "--seq-parallel/--pipeline-stages (these steps place "
                         "their own shardings)")
    if getattr(args, "prefetch", 0) > 0:
        raise SystemExit("--prefetch is not supported with "
                         "--tensor-parallel/--seq-parallel/--pipeline-stages "
                         "(these steps place their own shardings)")
    if args.stateful:
        raise SystemExit("--stateful is not supported with --tensor-parallel/"
                         "--seq-parallel/--pipeline-stages")
    if pp > 1 and sp > 1:
        raise SystemExit("--pipeline-stages cannot combine with --seq-parallel "
                         "(both schedule the wavefront; tp composes with either)")
    # --use-pallas composes with --seq-parallel since r4: each wavefront
    # chunk runs the fused kernel at the local [b, T/S, D] shard (no
    # collectives inside a chunk; the step's shard_map goes all-manual —
    # parallel/train_step.py). The remaining exclusion is TP, already
    # rejected by the shared gate above (GSPMD cannot partition the kernel).
    if args.microbatches is not None and args.microbatches < 1:
        raise SystemExit(f"--microbatches must be >= 1, got {args.microbatches}")
    n = jax.device_count()
    dp = args.num_partitions or max(n // (tp * sp * pp), 1)
    total = dp * tp * sp * pp
    if total > n:
        raise SystemExit(f"mesh dp*tp*sp*pp={total} exceeds {n} devices")
    if tp > 1 and args.hidden_units % tp != 0:
        raise SystemExit(f"--hidden-units {args.hidden_units} not divisible by "
                         f"--tensor-parallel {tp}")
    if seq_len % max(sp, 1) != 0:
        raise SystemExit(f"--seq-len {seq_len} not divisible by --seq-parallel {sp}")
    mb = args.microbatches if args.microbatches is not None else (pp if pp > 1 else 1)
    if args.batch_size % (dp * mb) != 0:
        raise SystemExit(f"--batch-size {args.batch_size} not divisible by "
                         f"dp*microbatches = {dp}*{mb}")
    mesh = _build_mesh(dp=dp, tp=tp, sp=sp, pp=pp,
                       devices=np.asarray(jax.devices()[:total]))

    optimizer = make_cli_optimizer(args)
    params = init_lm(jax.random.PRNGKey(args.seed), cfg)
    zero1 = bool(getattr(args, "zero1", False)) and pp > 1
    if pp > 1:
        stacked = stack_lm_params(params)
        train_step = make_pp_lm_train_step(
            cfg, optimizer, mesh, stacked, microbatches=mb, tp=tp > 1,
            zero1=zero1,
        )
        placed = place_pp_lm_params(stacked, mesh, tp=tp > 1)
    else:
        train_step = make_sharded_lm_train_step(
            cfg, optimizer, mesh, params, microbatches=mb
        )
        placed = place_lm_params(params, mesh)
    state = init_train_state(placed, optimizer, jax.random.PRNGKey(args.seed + 1))
    if zero1:
        from .parallel.pipeline_parallel import place_pp_zero1_opt_state

        state = state._replace(opt_state=place_pp_zero1_opt_state(
            state.opt_state, optimizer, stacked, mesh, tp=tp > 1))

    restored, checkpoint_fn = _wire_checkpoint(
        args, logger, lambda: jax.device_get(state)
    )
    if restored is not None:
        state = restored

    # Sharded eval on the DEVICE-RESIDENT params — no host gather (the point
    # of PP/TP is that one device need not hold the model); loss/token math
    # runs under the same wavefront as training, deterministic.
    if pp > 1:
        from .parallel.pipeline_parallel import make_pp_lm_eval_step

        eval_step = make_pp_lm_eval_step(
            cfg, mesh, stacked, microbatches=mb, tp=tp > 1
        )
    else:
        from .parallel.train_step import make_sharded_lm_eval_step

        eval_step = make_sharded_lm_eval_step(cfg, mesh, params, microbatches=mb)
    valid_tokens = data["valid"]
    eval_bs = min(args.batch_size, max((len(valid_tokens) - 1) // seq_len, 0))
    # the wavefront divisibility contracts hold for eval batches too
    eval_quantum = dp * mb if pp > 1 else dp
    eval_bs -= eval_bs % max(eval_quantum, 1)

    from .data.batching import cap_batches

    def eval_fn(params_dev):
        if eval_bs <= 0:
            return {"eval_skipped": 1}
        ev = cap_batches(lm_epoch_batches(valid_tokens, eval_bs, seq_len),
                         args.eval_batches)
        return evaluate(eval_step, params_dev, ev)

    train_tokens = data["train"]
    steps_per_epoch = max((len(train_tokens) - 1) // (args.batch_size * seq_len), 1)
    # data-exact resume (same contract as _run_lm's streams)
    batches = lm_batch_stream(train_tokens, args.batch_size, seq_len,
                              start_step=int(state.step))

    logger.log({
        "note": "start", "dataset": args.dataset, "vocab": cfg.vocab_size,
        "devices": n, "mesh": {"dp": dp, "tp": tp, "sp": sp, "pp": pp},
        "microbatches": mb, "steps_per_epoch": steps_per_epoch,
        "backend": "pp" if pp > 1 else "tp/sp",
    })
    from .utils.flops import lm_fwd_flops_per_token

    flops_per_token, peak = _mfu_logging(
        args,
        lm_fwd_flops_per_token(cfg.vocab_size, cfg.hidden_size,
                               cfg.num_layers, cfg.embed),
        mesh,
    )
    state = _make_logged_loop(
        args, state, train_step, batches, steps_per_epoch, logger,
        eval_fn=eval_fn if args.eval_every else None,
        checkpoint_fn=checkpoint_fn,
        tokens_per_batch=args.batch_size * seq_len,
        flops_per_token=flops_per_token,
        peak_tflops=peak,
    )
    final = eval_fn(state.params)
    logger.log({"step": int(state.step), **final, "note": "final"})
    if args.generate_tokens > 0:
        params_host = jax.device_get(state.params)
        if pp > 1:
            params_host = unstack_lm_params(params_host)
        _generate_text(args, logger, cfg, data, params_host)
    return 0


def build_serve_parser() -> argparse.ArgumentParser:
    """``serve`` subcommand: the inference engine's CLI surface (serve/)."""
    p = argparse.ArgumentParser(
        prog="lstm_tensorspark_tpu serve",
        description="continuous-batching LM inference (serve/): HTTP "
                    "endpoint, --selftest parity check, --loadgen "
                    "latency/throughput report",
    )
    mode = p.add_mutually_exclusive_group()
    mode.add_argument("--selftest", action="store_true",
                      help="decode a batch of concurrent sessions and "
                           "verify greedy output is token-identical to "
                           "models/generate.py; rc 0 on PASS")
    mode.add_argument("--loadgen", action="store_true",
                      help="offline load generation: p50/p99 latency, "
                           "tokens/sec, concurrency sweep (--compare)")
    mode.add_argument("--http", action="store_true",
                      help="run the JSON HTTP endpoint (default mode)")
    # --- model (must match the producing training run) ---
    p.add_argument("--vocab-size", type=int, default=89)
    p.add_argument("--hidden-units", type=int, default=64)
    p.add_argument("--num-layers", type=int, default=2)
    p.add_argument("--tie-embeddings", action="store_true")
    p.add_argument("--compute-dtype", type=str, default="float32",
                   choices=["float32", "bfloat16"])
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--checkpoint-dir", type=str, default=None,
                   help="restore trained params (template built from the "
                        "model flags + --optimizer, which must match the "
                        "training run); random init otherwise")
    p.add_argument("--optimizer", type=str, default="sgd",
                   choices=["sgd", "momentum", "adam", "adamw", "rmsprop"],
                   help="checkpoint-template optimizer (restore only)")
    p.add_argument("--learning-rate", type=float, default=1.0)
    # --- engine / batcher (docs/OPERATIONS.md "Serving") ---
    p.add_argument("--replicas", type=str, default="1",
                   help="data-parallel serving replicas (serve/router.py): "
                        "N engine+scheduler replicas behind one admission "
                        "router with session→replica affinity — thread-per-"
                        "replica on CPU, device-per-replica when multiple "
                        "accelerators exist. --num-slots/--max-active are "
                        "PER REPLICA; --queue-size is the global admission "
                        "bound. With --loadgen a comma list (e.g. '1,2') "
                        "runs the replica-scaling comparison instead: the "
                        "same workload at each level, aggregate tokens/s + "
                        "greedy parity reported (BENCH_serve_r02.json)")
    p.add_argument("--num-slots", type=int, default=64,
                   help="state-cache slots (= max resident sessions)")
    p.add_argument("--prefill-buckets", type=str, default="8,16,32,64,128",
                   help="prompt-length pad buckets; the largest is the "
                        "prompt-length admission limit")
    p.add_argument("--batch-buckets", type=str, default="1,2,4,8,16",
                   help="batch-size pad buckets; the largest bounds one "
                        "packed step")
    p.add_argument("--max-active", type=int, default=16,
                   help="concurrent decode sessions (<= --num-slots)")
    p.add_argument("--queue-size", type=int, default=64,
                   help="bounded submit queue; beyond it requests are "
                        "rejected (HTTP 429). This is the PRIORITY-class "
                        "bound; best-effort sheds earlier "
                        "(--best-effort-queue-frac)")
    p.add_argument("--class-weights", type=str, default="4,1",
                   help="weighted-dequeue shares 'priority,best_effort' "
                        "(serve/batcher.py): out of every P+B admissions "
                        "with both classes waiting, P are priority — the "
                        "SLO lever that keeps priority TTFT flat while a "
                        "best-effort burst queues")
    p.add_argument("--best-effort-queue-frac", type=float, default=0.5,
                   help="best-effort requests are 429-shed once the live "
                        "queue reaches this fraction of --queue-size "
                        "(priority keeps the remaining headroom); sheds "
                        "carry Retry-After from the live queue-wait p99")
    p.add_argument("--deadline-priority-s", type=float, default=0,
                   help="default request deadline (seconds) for the "
                        "priority class; expiry is enforced at admission, "
                        "in the queue and at decode-window boundaries, "
                        "producing an honest 'timeout' outcome with "
                        "partial output. 0 = no default (clients can "
                        "still send deadline_s / X-Deadline-S)")
    p.add_argument("--deadline-best-effort-s", type=float, default=0,
                   help="default request deadline (seconds) for the "
                        "best_effort class; 0 = no default")
    p.add_argument("--replica-stale-s", type=float, default=60.0,
                   help="scheduler-heartbeat staleness bound (seconds) "
                        "before a replica counts wedged: excluded from "
                        "fresh routing and /healthz health (previously a "
                        "hardcoded 60 s)")
    p.add_argument("--replica-sweep-s", type=float, default=0,
                   help="periodic replica death-sweep interval (seconds): "
                        "retire dead replicas (requeue/migrate) within "
                        "this bound even on a quiet server with no "
                        "traffic or probes. 0 = piggyback-only (the "
                        "previous behavior: sweeps run on every submit "
                        "and health probe)")
    p.add_argument("--mesh-shards", type=int, default=1,
                   help="tensor-parallel SHARDS per replica: each "
                        "replica's engine shards its params and "
                        "state-cache slots over a mesh_shards-device "
                        "('model',) mesh (GSPMD — parallel/"
                        "tensor_parallel.py specs), so a model one chip "
                        "cannot hold serves behind the router as one "
                        "replica. Replicas get disjoint device groups "
                        "when the host has replicas*shards devices, and "
                        "share one group otherwise. Token-identical to "
                        "a single-device engine (greedy AND sampled). "
                        "On CPU use XLA_FLAGS="
                        "--xla_force_host_platform_device_count=N for "
                        "virtual devices. 1 = off")
    p.add_argument("--remote-replica", action="append", default=[],
                   metavar="URL",
                   help="add a REMOTE replica behind the router: the "
                        "base URL of a peer `cli serve --http` process "
                        "(repeatable). Generate RPCs ride its "
                        "/v1/generate, liveness its /replica/heartbeat, "
                        "session affinity its /replica/has_session — so "
                        "admission becomes a front-of-fleet tier. Share "
                        "one --session-dir across hosts and a killed "
                        "host loses no kept session (continuations fill "
                        "from the shared disk tier on survivors; "
                        "docs/OPERATIONS.md 'Mesh serving')")
    p.add_argument("--remote-timeout-s", type=float, default=120.0,
                   help="client-side wait bound (seconds) for one remote "
                        "generate RPC (--remote-replica): past it the "
                        "front settles the request honestly instead of "
                        "holding the slot forever. 0 = no bound; a "
                        "request deadline always tightens it. Negative "
                        "rejected at construction")
    p.add_argument("--decode-window", type=str, default="auto",
                   help="multi-token decode window: 'auto' (adaptive "
                        "ladder 1/4/8 — large windows in steady-state "
                        "decode, 1 whenever requests are queued), an int "
                        "N (the ladder capped at N, N as top rung), or 1 "
                        "to pin the per-token path (lowest inter-token "
                        "latency; see docs/OPERATIONS.md). Every window "
                        "size is one XLA compile key per batch bucket.")
    p.add_argument("--decode-kernel", type=str, default="auto",
                   help="decode-window kernel: 'scan' (the lax.scan "
                        "window), 'pallas' (fused VMEM-resident window "
                        "kernel, ops/pallas_decode.py — interpreter mode "
                        "off-TPU, token-identical but slow there), or "
                        "'auto' (pallas on TPU when the VMEM plan fits, "
                        "scan otherwise). With --loadgen a comma list "
                        "(e.g. 'pallas,scan') runs the kernel comparison "
                        "instead: same workload per kernel, tokens/s + "
                        "ITL deltas + greedy parity "
                        "(BENCH_serve_r05.json). See docs/OPERATIONS.md "
                        "for when to pin 'scan'.")
    p.add_argument("--prefix-cache", type=str, default="on",
                   choices=["on", "off"],
                   help="shared-prompt prefix-state cache: fresh prompts "
                        "resume prefill from the longest cached prefix "
                        "(an LSTM prefix state is ONE (h, c) pair — reuse "
                        "is a slot copy). Greedy output is token-identical "
                        "on or off; 'off' frees the backing slots "
                        "(docs/OPERATIONS.md)")
    p.add_argument("--prefix-stride", type=int, default=8,
                   help="prefix-cache insert granularity (tokens): entries "
                        "live at stride-aligned prompt lengths")
    p.add_argument("--prefix-entries", type=int, default=16,
                   help="max cached prefix entries (each holds one "
                        "state-cache slot; LRU beyond this)")
    p.add_argument("--prefix-fabric", type=str, default="off",
                   choices=["on", "off"],
                   help="prefix-state FABRIC (serve/prefix_trie.py): "
                        "replaces the exact-match prefix cache with a "
                        "radix trie over token sequences — lookups match "
                        "the LONGEST shared prefix (tenant preambles, "
                        "few-shot templates), cold nodes spill to the "
                        "host tier under --prefix-host-mb, and hot "
                        "inserts propagate to --remote-replica peers "
                        "(idempotent by token hash). Supersedes "
                        "--prefix-cache when on; greedy output stays "
                        "token-identical (docs/OPERATIONS.md)")
    p.add_argument("--prefix-nodes", type=int, default=64,
                   help="max stateful trie nodes per replica with "
                        "--prefix-fabric on (device-resident ones each "
                        "hold a state-cache slot; eviction is leaf-first "
                        "LRU over zero-ref nodes)")
    p.add_argument("--prefix-host-mb", type=float, default=64.0,
                   help="host-RAM bound (MiB) for SPILLED fabric nodes "
                        "(a spilled node is one (h, c) pair per layer "
                        "held by the tiers); the coldest zero-ref "
                        "spilled nodes are dropped past this")
    p.add_argument("--tiered-cache", type=str, default="on",
                   choices=["on", "off"],
                   help="tiered session-state cache (serve/state_cache.py "
                        "SessionTiers): LRU-evicted session states spill "
                        "ASYNC to host RAM (tier 1) with a durable disk "
                        "tier below (--session-dir); continuations of "
                        "spilled sessions fill back for one state copy "
                        "instead of failing 'expired' — the long-tail "
                        "multi-tenant lever (thousands of mostly-idle "
                        "sessions over a few device slots). 'off' keeps "
                        "the fixed-slot behavior (evicted = expired)")
    p.add_argument("--host-tier-entries", type=int, default=256,
                   help="max spilled session states held in host RAM "
                        "(each is one tiny (h, c) pair per layer); "
                        "overflow cascades to --session-dir or is "
                        "dropped honestly")
    p.add_argument("--session-dir", type=str, default=None,
                   help="disk tier + serve-session checkpoints: kept "
                        "sessions are write-behind checkpointed here at "
                        "each request boundary (sha256-verified atomic "
                        "files), so a supervised kill/restart resumes "
                        "them token-identically; also the overflow tier "
                        "below --host-tier-entries. Implies the tiered "
                        "cache even with --tiered-cache off")
    p.add_argument("--prefill-chunk", type=int, default=0,
                   help="chunked prefill: consume prompts <= N tokens per "
                        "program, <= 1 prefill program per scheduler "
                        "iteration — bounds how long a cold long prompt "
                        "can stall running sessions' decode (and lifts "
                        "the prompt-length cap). 0 = off (monolithic "
                        "bucketed prefill)")
    # --- online autotuner (serve/autotune.py) ---
    p.add_argument("--autotune", type=str, default="off",
                   choices=["on", "off"],
                   help="online serve autotuner: a controller thread "
                        "watches WINDOWED deltas of the live TTFT/ITL/"
                        "queue-wait histograms + tier occupancy/spill-"
                        "thrash counters and moves the decode-window "
                        "cap, the prefill-chunk size, the host-tier "
                        "bound and the best-effort admission fraction — "
                        "each within PRE-WARMED bounds, so it can never "
                        "trigger a mid-traffic compile. Decisions land "
                        "in /stats 'autotune' + serve_autotune_moves_"
                        "total{knob,direction}. Needs --telemetry on. "
                        "'off' (default) = today's static operating "
                        "point, byte-identical")
    p.add_argument("--autotune-interval", type=float, default=0.25,
                   help="seconds between autotuner control windows "
                        "(each window reads one histogram delta)")
    p.add_argument("--slo-ms", type=float, default=250.0,
                   help="the TTFT p99 SLO (ms) the autotuner protects: "
                        "pressure/headroom thresholds are fractions of "
                        "it (smaller K / larger chunks as the p99 "
                        "approaches it; larger K only well below it)")
    p.add_argument("--autotune-chunks", type=str, default=None,
                   help="warmed prefill-chunk choice set the autotuner "
                        "moves --prefill-chunk among (comma list; each "
                        "entry must satisfy the same bucket/stride "
                        "constraints as --prefill-chunk). Default: "
                        "half/base/double of --prefill-chunk, invalid "
                        "entries dropped. Ignored without "
                        "--prefill-chunk")
    p.add_argument("--autotune-host-tier-max", type=int, default=0,
                   help="ceiling the autoscaler leg may grow "
                        "--host-tier-entries to under spill thrash "
                        "(0 = 4x the configured entries)")
    p.add_argument("--autotune-be-floor", type=float, default=0.1,
                   help="lowest best-effort admission fraction the "
                        "autotuner may tighten --best-effort-queue-frac "
                        "to when the state plane thrashes at its "
                        "capacity ceiling")
    # --- model registry + rolling rollout (serve/registry.py, rollout.py) ---
    p.add_argument("--registry-dir", type=str, default=None,
                   help="model registry directory (serve/registry.py): "
                        "attaches a rollout controller so POST /rollout "
                        "(or a supervising trainer's publication) can "
                        "roll a new model version across the replicas "
                        "WITHOUT a restart — drain one replica (kept "
                        "sessions migrate, queued work requeues), swap "
                        "params, re-warm the compile-key lattice "
                        "off-path, rejoin; one replica at a time, so "
                        "capacity never drops below N-1. Also unlocks "
                        "the autotuner's device-slot capacity leg")
    p.add_argument("--model-id", type=str, default="default",
                   help="model id this fleet boots as (the registry/"
                        "routing namespace for the checkpoint loaded at "
                        "startup; requests with no 'model' field route "
                        "here)")
    p.add_argument("--canary-every", type=int, default=0,
                   help="canary routing during a rollout: shadow every "
                        "Nth stateless request onto the first upgraded "
                        "replica and token-diff its output against the "
                        "primary before rolling the rest (report in "
                        "/rollout 'last_canary' + serve_canary_diff_"
                        "total{verdict}). 0 = no canary phase")
    p.add_argument("--require-canary-match", action="store_true",
                   help="abort the rollout (outcome 'canary_regression') "
                        "when any canary pair token-diffs; without it "
                        "the diff report is informational (sampled "
                        "traffic diffs legitimately)")
    # --- speculative decoding (train/distill.py, serve/engine.py) ---
    p.add_argument("--speculative", action="store_true",
                   help="lossless speculative decoding: a distilled "
                        "DRAFT LM (published by `cli distill`, loaded "
                        "from --registry-dir as a verified pair with "
                        "the target) proposes K_draft tokens per step "
                        "and the target verifies all of them in ONE "
                        "teacher-forced window pass — greedy output is "
                        "token-identical to plain decode by "
                        "construction, rejection is an O(1) carry "
                        "restore. Applies to greedy default-model "
                        "traffic; sampled/named-model requests decode "
                        "plain. Requires --registry-dir "
                        "(docs/OPERATIONS.md 'Speculative decoding')")
    p.add_argument("--draft-model", type=str, default=None,
                   help="registry id of the draft artifact (default: "
                        "'<--model-id>-draft', the id `cli distill` "
                        "publishes under). Its config_hash/parent "
                        "record must verify against the serving "
                        "target or boot refuses the pair")
    p.add_argument("--spec-ladder", type=str, default="2,4",
                   help="warmed K_draft rungs the speculative window "
                        "can dispatch (comma list; rung 0 = plain "
                        "decode is always included). Each rung is one "
                        "compile key per batch bucket, all covered by "
                        "warmup — the autotuner's spec_k knob moves "
                        "within this set")
    p.add_argument("--spec-k", type=int, default=None,
                   help="initial K_draft (must be a --spec-ladder rung "
                        "or 0; default: the top rung). 0 starts at "
                        "plain decode with speculation armed — the "
                        "autotuner can still probe upward")
    # --- per-tenant rate limiting (serve/router.py) ---
    p.add_argument("--tenant-rate", type=float, default=0,
                   help="per-tenant token-bucket rate limit (requests/s "
                        "per distinct 'tenant' request field) on top of "
                        "the class policy; over-rate requests 429 with "
                        "an honest Retry-After (time to the next token, "
                        "floored by the shared queue-drain policy). "
                        "0 = off; untenanted requests are never limited")
    p.add_argument("--tenant-burst", type=float, default=5.0,
                   help="token-bucket burst allowance per tenant "
                        "(requests that may arrive back-to-back before "
                        "the rate limit engages)")
    # --- sampling defaults (selftest is always greedy) ---
    p.add_argument("--temperature", type=float, default=1.0)
    p.add_argument("--top-k", type=int, default=None)
    p.add_argument("--top-p", type=float, default=None)
    p.add_argument("--greedy", action="store_true")
    # --- loadgen workload ---
    p.add_argument("--sessions", type=int, default=8)
    p.add_argument("--requests-per-session", type=int, default=4)
    p.add_argument("--prompt-len", type=int, default=8)
    p.add_argument("--max-new-tokens", type=int, default=16)
    p.add_argument("--mode", type=str, default="closed",
                   choices=["closed", "open"])
    p.add_argument("--rate", type=float, default=None,
                   help="open-loop arrival rate (req/s)")
    p.add_argument("--arrival", type=str, default="fixed",
                   choices=["fixed", "burst", "sine"],
                   help="open-loop arrival shape: 'fixed' = constant "
                        "--rate; 'burst' = --burst-n simultaneous "
                        "arrivals every --burst-gap seconds; 'sine' = "
                        "diurnal-shaped rate --rate*(1+amp*sin(2pi*t/"
                        "period)) — the phase-shifting workloads the "
                        "autotuner bench drives")
    p.add_argument("--arrival-trace", type=str, default=None,
                   help="open-loop trace replay: a file of sorted "
                        "seconds-from-start arrival offsets, one per "
                        "line ('#' comments ignored); a trace shorter "
                        "than the workload loops, shifted by its span. "
                        "Overrides --arrival/--rate")
    p.add_argument("--burst-n", type=int, default=8,
                   help="--arrival burst: requests per burst")
    p.add_argument("--burst-gap", type=float, default=0.5,
                   help="--arrival burst: seconds between burst starts")
    p.add_argument("--sine-period", type=float, default=2.0,
                   help="--arrival sine: modulation period (seconds)")
    p.add_argument("--sine-amp", type=float, default=0.8,
                   help="--arrival sine: modulation amplitude in [0, 1)")
    p.add_argument("--compare", type=str, default=None,
                   help="closed-loop concurrency sweep levels (default "
                        "1,8; empty string: single run at --sessions)")
    p.add_argument("--shared-prefix-len", type=int, default=0,
                   help="loadgen: every prompt shares its first N tokens "
                        "(the shared-system-prompt workload the prefix "
                        "cache targets); 0 = fully random prompts")
    p.add_argument("--inject-prompt-len", type=int, default=0,
                   help="loadgen: submit ONE extra cold request with a "
                        "prompt this long mid-run (head-of-line-blocking "
                        "probe, reported separately); 0 = off")
    p.add_argument("--inject-delay", type=float, default=0.25,
                   help="seconds into the run to submit the injected "
                        "request")
    p.add_argument("--workload", type=str, default="random",
                   choices=["random", "template-mix"],
                   help="loadgen prompt shape: 'random' = the classic "
                        "per-session random prompts; 'template-mix' = "
                        "tenant preamble x few-shot template x unique "
                        "suffix (--tenants/--templates/--preamble-len/"
                        "--template-len/--suffix-len) — the shared-"
                        "structure workload the prefix-state fabric is "
                        "gated on (radix lookup reuses the preamble+"
                        "template prefix; exact-match only full re-"
                        "prompts). Runs on a bounded worker pool, so "
                        "--sessions can be 10k+")
    p.add_argument("--tenants", type=int, default=4,
                   help="--workload template-mix: distinct tenant "
                        "preambles")
    p.add_argument("--templates", type=int, default=25,
                   help="--workload template-mix: few-shot templates per "
                        "tenant")
    p.add_argument("--preamble-len", type=int, default=128,
                   help="--workload template-mix: tenant preamble tokens")
    p.add_argument("--template-len", type=int, default=32,
                   help="--workload template-mix: template tokens")
    p.add_argument("--suffix-len", type=int, default=8,
                   help="--workload template-mix: unique per-session "
                        "suffix tokens")
    p.add_argument("--workers", type=int, default=32,
                   help="--workload template-mix: bounded worker-pool "
                        "size (closed-loop threads)")
    p.add_argument("--idle-churn", action="store_true",
                   help="loadgen: long-tail multi-tenant workload — "
                        "--sessions LIVE kept sessions (size it ~10x "
                        "--num-slots) continued by Zipf-popularity draws "
                        "(--zipf-s), so the idle tail is LRU-evicted and "
                        "must fill from the tiers (or re-prefill its full "
                        "history with --tiered-cache off). Reports "
                        "per-tier hit rates, re-prefill cost and hot-set "
                        "tokens/s — the tiered-cache gate workload")
    p.add_argument("--zipf-s", type=float, default=1.1,
                   help="--idle-churn popularity exponent: session rank r "
                        "is drawn with weight (r+1)^-s (higher = hotter "
                        "hot set)")
    p.add_argument("--priority-frac", type=float, default=1.0,
                   help="loadgen: fraction of traffic submitted as the "
                        "priority class (the rest best_effort, "
                        "interleaved) — per-class shed/retry/TTFT "
                        "percentiles land in the report's 'classes' "
                        "section")
    p.add_argument("--deadline-s", type=float, default=0,
                   help="loadgen: per-request deadline in seconds "
                        "(server-side; expiry = honest timeout with "
                        "partial output). 0 = none")
    p.add_argument("--retry-max", type=int, default=0,
                   help="loadgen: retry a 429 shed up to N times, "
                        "sleeping the server's Retry-After floored by "
                        "the shared capped exponential backoff + jitter "
                        "(resilience/backoff.py). 0 = count sheds, no "
                        "retry")
    p.add_argument("--json", type=str, default=None,
                   help="also write the loadgen report (machine-readable "
                        "JSON) to this path")
    # --- endpoint / observability ---
    p.add_argument("--host", type=str, default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    p.add_argument("--telemetry", type=str, default="on",
                   choices=["on", "off"],
                   help="metrics registry (obs/): 'on' serves GET /metrics "
                        "(Prometheus text exposition: server-side TTFT/ITL/"
                        "queue-wait histograms, compile + cache counters) "
                        "and histogram summaries in /stats; 'off' swaps in "
                        "no-op instruments (near-zero record cost) and "
                        "/metrics reports telemetry disabled")
    p.add_argument("--trace", type=str, default=None,
                   help="host-side span trace output (Chrome trace JSON; "
                        "includes one admit→queue→prefill→decode→readback "
                        "timeline row per request — open in Perfetto)")
    p.add_argument("--faults", type=str, default=None,
                   help="ARM FAULT INJECTION (chaos drills only): e.g. "
                        "'serve_error@2' raises from the 2nd decode call "
                        "— resilience/faults.py grammar, same flag as the "
                        "training CLI; also armable via LSTM_TSP_FAULTS")
    return p


def _parse_window_ladder(spec: str) -> tuple[int, ...]:
    """--decode-window → a Batcher window ladder: 'auto' = the default
    ladder (1, 4, 8); an int N = that ladder capped at N, with N itself
    as the top rung (so `--decode-window 8` == auto, `6` → (1, 4, 6),
    `1` pins the per-token path)."""
    from .serve import Batcher

    if spec.strip().lower() == "auto":
        return Batcher.DEFAULT_WINDOW_LADDER
    try:
        n = int(spec)
    except ValueError:
        raise SystemExit(
            f"--decode-window: expected 'auto' or a positive int, got "
            f"{spec!r}")
    if n < 1:
        raise SystemExit(f"--decode-window: window must be >= 1, got {n}")
    return tuple(sorted(
        {1, n} | {k for k in Batcher.DEFAULT_WINDOW_LADDER if k < n}
    ))


def _parse_spec_ladder(spec: str) -> tuple[int, ...]:
    """--spec-ladder → the warmed K_draft rung set (rung 0 — plain
    decode — is always added by the Batcher)."""
    try:
        rungs = tuple(int(x) for x in spec.split(",") if x.strip())
    except ValueError:
        raise SystemExit(
            f"--spec-ladder: expected comma-separated ints, got {spec!r}")
    if not rungs or any(k < 1 for k in rungs):
        raise SystemExit(
            f"--spec-ladder: need at least one rung >= 1, got {spec!r}")
    return rungs


def _autotune_chunk_choices(args, chunk: int | None) -> tuple[int, ...] | None:
    """The warmed prefill-chunk choice set the autotuner moves among.
    Explicit ``--autotune-chunks`` entries must each satisfy the same
    bucket/stride constraints as ``--prefill-chunk`` (fail fast with the
    flag's own message); the derived default is half/base/double of the
    configured chunk with invalid candidates silently dropped. None when
    chunking is off — the chunk knob stays pinned."""
    if chunk is None:
        if args.autotune_chunks:
            raise SystemExit(
                "--autotune-chunks needs --prefill-chunk (the knob moves "
                "among chunk sizes, it cannot turn chunking on)")
        return None
    max_bucket = max(_parse_buckets(args.prefill_buckets,
                                    "--prefill-buckets"))

    def ok(c: int) -> bool:
        if c < 1 or c > max_bucket:
            return False
        return (args.prefix_cache != "on" or c % args.prefix_stride == 0
                or args.prefix_stride % c == 0)

    if args.autotune_chunks:
        try:
            choices = tuple(int(x) for x in args.autotune_chunks.split(",")
                            if x.strip())
        except ValueError:
            raise SystemExit(
                f"--autotune-chunks: expected comma-separated ints, got "
                f"{args.autotune_chunks!r}")
        bad = [c for c in choices if not ok(c)]
        if not choices or bad:
            raise SystemExit(
                f"--autotune-chunks: entries must be in [1, {max_bucket}] "
                f"and stride-compatible with --prefix-stride "
                f"{args.prefix_stride}; bad: {bad or 'empty'}")
        return tuple(sorted(set(choices) | {chunk}))
    derived = {c for c in (chunk // 2, chunk, chunk * 2) if ok(c)}
    return tuple(sorted(derived | {chunk}))


def _parse_buckets(spec: str, flag: str) -> tuple[int, ...]:
    try:
        buckets = tuple(int(x) for x in spec.split(",") if x.strip())
    except ValueError:
        raise SystemExit(f"{flag}: expected comma-separated ints, got {spec!r}")
    if not buckets or any(b < 1 for b in buckets):
        raise SystemExit(f"{flag}: need at least one positive bucket")
    return buckets


def _parse_replicas(spec: str, flag: str = "--replicas") -> tuple[int, ...]:
    try:
        levels = tuple(int(x) for x in spec.split(",") if x.strip())
    except ValueError:
        raise SystemExit(f"{flag}: expected an int or comma-separated ints, "
                         f"got {spec!r}")
    if not levels or any(n < 1 for n in levels):
        raise SystemExit(f"{flag}: need positive replica counts, got {spec!r}")
    return levels


def _parse_decode_kernels(spec: str) -> tuple[str, ...]:
    kernels = tuple(dict.fromkeys(
        k.strip() for k in spec.split(",") if k.strip()))
    from .serve.engine import DECODE_KERNELS

    bad = [k for k in kernels if k not in DECODE_KERNELS]
    if not kernels or bad:
        raise SystemExit(
            f"--decode-kernel: expected one of {DECODE_KERNELS} (or a "
            f"comma list for the --loadgen comparison), got {spec!r}")
    return kernels


def _single_decode_kernel(args) -> str:
    kernels = _parse_decode_kernels(getattr(args, "decode_kernel", "auto"))
    if len(kernels) > 1:
        raise SystemExit(
            f"--decode-kernel {args.decode_kernel!r}: a comma list is the "
            "--loadgen comparison mode; this mode needs a single kernel")
    return kernels[0]


def _single_replica_count(args, mode: str) -> int:
    levels = _parse_replicas(args.replicas)
    if len(levels) > 1:
        raise SystemExit(
            f"--replicas {args.replicas!r}: a comma list is the --loadgen "
            f"comparison mode; {mode} needs a single count")
    return levels[0]


def _build_serve_stack(args, n_replicas: int = 1, registry=None):
    """(params, cfg, started-server) from the serve flags.

    ``n_replicas`` > 1 builds one engine per replica (each with its own
    state/prefix caches and compiled programs) behind the admission
    router; when the host exposes multiple accelerators the engines are
    committed round-robin across ``jax.devices()`` (device-per-replica),
    otherwise they share the one device (thread-per-replica).
    ``registry`` overrides the --telemetry-selected registry (the replica
    sweep scopes one fresh registry per level so the per-level reports
    don't accumulate each other's samples)."""
    from .models import LMConfig, init_lm
    from .serve import ServeEngine, ServeServer

    chunk = args.prefill_chunk or None
    if (chunk is not None and chunk > 0 and args.prefix_cache == "on"
            and chunk % args.prefix_stride != 0
            and args.prefix_stride % chunk != 0):
        # same constraint Batcher.__init__ enforces, checked here so a bad
        # flag combo fails in ms, before params init / checkpoint restore
        raise SystemExit(
            f"--prefill-chunk {chunk} must be a multiple or divisor of "
            f"--prefix-stride {args.prefix_stride} (chunk stops are "
            "stride-aligned prefix insert points), or use --prefix-cache off")
    cfg = LMConfig(
        vocab_size=args.vocab_size,
        hidden_size=args.hidden_units,
        num_layers=args.num_layers,
        tie_embeddings=args.tie_embeddings,
        compute_dtype=args.compute_dtype,
    )
    params = init_lm(jax.random.PRNGKey(args.seed), cfg)
    if args.checkpoint_dir:
        from .train import make_optimizer
        from .train.checkpoint import Checkpointer
        from .train.loop import init_train_state

        ckpt = Checkpointer(args.checkpoint_dir)
        if not ckpt.has_checkpoint():
            raise SystemExit(f"no checkpoint in {args.checkpoint_dir}")
        optimizer = make_optimizer(args.optimizer, args.learning_rate)
        template = init_train_state(params, optimizer,
                                    jax.random.PRNGKey(args.seed))
        state = ckpt.restore_latest(template)
        if state is None:
            # every checkpoint failed verification and was quarantined
            # (train/checkpoint.py) — refuse to serve random init
            raise SystemExit(
                f"every checkpoint in {args.checkpoint_dir} is corrupt "
                "(now quarantined); refusing to serve an untrained model")
        params = jax.device_get(state.params)
    from .obs import NULL_REGISTRY, REGISTRY

    if registry is None:
        registry = (NULL_REGISTRY
                    if getattr(args, "telemetry", "on") == "off"
                    else REGISTRY)
    devices = jax.devices()
    shards = int(getattr(args, "mesh_shards", 1) or 1)
    if shards < 1:
        raise SystemExit(f"--mesh-shards must be >= 1, got {shards}")
    if shards > 1 and len(devices) < shards:
        raise SystemExit(
            f"--mesh-shards {shards} needs {shards} devices, host has "
            f"{len(devices)} (on CPU set XLA_FLAGS="
            "--xla_force_host_platform_device_count=N)")

    def _mesh_devices(i: int):
        """Replica i's device group: disjoint groups when the host has
        replicas*shards devices (mesh-per-replica), the shared leading
        group otherwise (thread-per-replica over one mesh — the CPU
        virtual-device analog of thread-per-replica on one chip)."""
        if shards == 1:
            return None
        if len(devices) >= n_replicas * shards:
            return devices[i * shards:(i + 1) * shards]
        return devices[:shards]
    engines = [
        ServeEngine(
            params, cfg,
            num_slots=args.num_slots,
            prefill_buckets=_parse_buckets(args.prefill_buckets,
                                           "--prefill-buckets"),
            batch_buckets=_parse_buckets(args.batch_buckets,
                                         "--batch-buckets"),
            # distinct per-replica sampling chains (greedy is unaffected)
            rng_seed=args.seed + i,
            prefix_cache=args.prefix_cache == "on",
            prefix_stride=args.prefix_stride,
            prefix_entries=args.prefix_entries,
            # prefix-state fabric: the radix-trie store supersedes the
            # exact-match cache when on (engine picks trie over cache)
            prefix_fabric=getattr(args, "prefix_fabric", "off") == "on",
            prefix_nodes=getattr(args, "prefix_nodes", 64),
            prefix_host_mb=getattr(args, "prefix_host_mb", 64.0),
            # tiered session-state cache: host-RAM spill of evicted
            # slots + durable disk tier / restart-surviving session
            # checkpoints under --session-dir (shared by all replicas —
            # session files are replica-agnostic, so any replica can
            # restore any session after a restart)
            tiered_cache=args.tiered_cache == "on",
            host_tier_entries=args.host_tier_entries,
            session_dir=args.session_dir,
            # the registry/routing namespace the boot checkpoint serves
            # under (requests with no 'model' field route here)
            model_id=getattr(args, "model_id", "default"),
            replica=i,
            decode_kernel=_single_decode_kernel(args),
            # one registry argument scopes the whole serve stack's
            # telemetry (engine, caches, batcher, router, /metrics);
            # off = no-op instruments
            registry=registry,
            # mesh-per-replica (--mesh-shards > 1) or device-per-replica
            # when the host has more than one device
            mesh_shards=shards,
            mesh_devices=_mesh_devices(i),
            device=(devices[i % len(devices)]
                    if shards == 1 and len(devices) > 1 else None),
        )
        for i in range(n_replicas)
    ]
    spec_kw = {}
    if getattr(args, "speculative", False):
        if not getattr(args, "registry_dir", None):
            raise SystemExit(
                "--speculative needs --registry-dir (the draft loads "
                "from the registry as a verified pair with the target; "
                "publish one with `cli distill`)")
        if shards > 1:
            raise SystemExit(
                "--speculative is not supported with --mesh-shards > 1 "
                "(the draft's state is replica-local)")
        from .train.distill import load_draft

        try:
            dmeta, dparams, dcfg = load_draft(
                args.registry_dir,
                cfg,
                teacher_id=getattr(args, "model_id", "default"),
                draft_id=getattr(args, "draft_model", None) or None,
            )
        except Exception as e:
            raise SystemExit(f"--speculative: cannot load draft: {e}")
        for eng in engines:
            eng.attach_draft(dparams, dcfg, version=dmeta["version"])
        spec_kw = {
            "speculative": True,
            "spec_ladder": _parse_spec_ladder(
                getattr(args, "spec_ladder", "2,4")),
        }
        if getattr(args, "spec_k", None) is not None:
            spec_kw["spec_k"] = args.spec_k
    try:
        wp, wb = (int(x) for x in args.class_weights.split(","))
    except ValueError:
        raise SystemExit(
            f"--class-weights: expected 'P,B' positive ints, got "
            f"{args.class_weights!r}")
    if wp < 1 or wb < 1:
        # fail in ms with the flag's own message — not a Batcher
        # traceback mid-stack-build
        raise SystemExit(
            f"--class-weights: weights must be >= 1, got "
            f"{args.class_weights!r}")
    autotune_cfg = None
    chunk_choices = None
    if getattr(args, "autotune", "off") == "on":
        if getattr(args, "telemetry", "on") == "off":
            # the controller steers on the live histograms — a blind
            # controller would simply never move, which reads like a bug
            raise SystemExit(
                "--autotune on needs --telemetry on (the controller "
                "watches the live serve histograms)")
        from .serve import AutoTuneConfig

        if args.autotune_interval <= 0:
            raise SystemExit(
                f"--autotune-interval must be > 0, got "
                f"{args.autotune_interval}")
        if args.slo_ms <= 0:
            raise SystemExit(f"--slo-ms must be > 0, got {args.slo_ms}")
        chunk_choices = _autotune_chunk_choices(args, chunk)
        autotune_cfg = AutoTuneConfig(
            interval_s=args.autotune_interval,
            slo_s=args.slo_ms / 1e3,
            host_tier_max=args.autotune_host_tier_max or None,
            best_effort_floor=args.autotune_be_floor,
        )
    server = ServeServer(engines if n_replicas > 1 else engines[0],
                         max_active=args.max_active,
                         queue_size=args.queue_size,
                         window_ladder=_parse_window_ladder(args.decode_window),
                         prefill_chunk=args.prefill_chunk or None,
                         prefill_chunk_choices=chunk_choices,
                         autotune=autotune_cfg,
                         tenant_rate=getattr(args, "tenant_rate", 0) or None,
                         tenant_burst=getattr(args, "tenant_burst", 5.0),
                         class_weights=(wp, wb),
                         health_stale_after=args.replica_stale_s,
                         best_effort_queue_frac=args.best_effort_queue_frac,
                         sweep_interval=args.replica_sweep_s or None,
                         deadline_defaults={
                             "priority": args.deadline_priority_s or None,
                             "best_effort":
                                 args.deadline_best_effort_s or None,
                         },
                         remote_replicas=tuple(
                             getattr(args, "remote_replica", []) or ()),
                         remote_timeout_s=getattr(
                             args, "remote_timeout_s", 120.0),
                         model_registry=getattr(args, "registry_dir",
                                                None) or None,
                         rollout_kw={
                             "canary_every":
                                 getattr(args, "canary_every", 0),
                             "require_canary_match":
                                 getattr(args, "require_canary_match",
                                         False),
                         },
                         **spec_kw)
    return params, cfg, server


def _serve_sampling(args):
    from .serve import SamplingParams

    return SamplingParams(temperature=args.temperature, top_k=args.top_k,
                          top_p=args.top_p, greedy=args.greedy)


def _serve_selftest(args) -> int:
    """Acceptance check: a batch of concurrent sessions decoded through the
    full server path must be token-identical to `models/generate.py` with
    the same params/prompt (greedy)."""
    import json
    import threading

    from .models import make_generate_fn
    from .serve import InprocessClient

    params, cfg, server = _build_serve_stack(
        args, _single_replica_count(args, "--selftest"))
    rng = np.random.RandomState(args.seed)
    lengths = [3, 5, 8, 13, 2, 7][: max(args.sessions, 2)]
    while len(lengths) < args.sessions:
        lengths.append(int(rng.randint(2, min(21, server.engine.max_prompt_len))))
    prompts = [rng.randint(0, cfg.vocab_size, size=t).astype(np.int32)
               for t in lengths]
    n_new = args.max_new_tokens

    got: list[list[int] | None] = [None] * len(prompts)
    errors: list[str] = []
    client = InprocessClient(server)

    def run_one(i):
        try:
            got[i] = client.generate(prompts[i], max_new_tokens=n_new)
        except Exception as e:  # surface, don't hang the join
            errors.append(f"session {i}: {type(e).__name__}: {e}")

    with server:
        threads = [threading.Thread(target=run_one, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    if errors:
        print("\n".join(errors))
        print("serve selftest: FAIL (request errors)")
        return 1
    gen = make_generate_fn(cfg, max_new_tokens=n_new, greedy=True)
    bad = 0
    for i, prompt in enumerate(prompts):
        ref = np.asarray(gen(params, prompt[None, :],
                             jax.random.PRNGKey(args.seed)))[0, prompt.size:]
        if not np.array_equal(np.asarray(got[i], np.int32), ref):
            bad += 1
            print(f"session {i}: MISMATCH serve={got[i]} ref={ref.tolist()}")
    print(json.dumps({
        "note": "serve_selftest", "sessions": len(prompts),
        "tokens_per_session": n_new, "mismatches": bad,
        "compiles_prefill": server.engine.num_compiles("prefill"),
        "compiles_decode": server.engine.num_compiles("decode"),
        "compiles_decode_window": server.engine.num_compiles("decode_window"),
        **server.stats()["batcher"],
    }))
    print(f"serve selftest: {'PASS' if bad == 0 else 'FAIL'}")
    return 0 if bad == 0 else 1


def _serve_loadgen(args) -> int:
    import json

    from .serve import run_loadgen
    from .serve.loadgen import concurrency_sweep

    # fail in milliseconds, not after the full warmup lattice compiles
    if args.shared_prefix_len and args.shared_prefix_len >= args.prompt_len:
        print(f"error: --shared-prefix-len {args.shared_prefix_len} must be "
              f"< --prompt-len {args.prompt_len} (each prompt needs >= 1 "
              "unshared token)", file=sys.stderr)
        return 2
    if (args.arrival != "fixed" or args.arrival_trace) and args.mode != "open":
        print("error: --arrival burst/sine and --arrival-trace shape "
              "OPEN-loop arrivals; add --mode open", file=sys.stderr)
        return 2
    kernels = _parse_decode_kernels(args.decode_kernel)
    replica_levels = _parse_replicas(args.replicas)
    if len(kernels) > 1:
        if len(replica_levels) > 1 or args.idle_churn:
            print("error: --decode-kernel comparison runs at one replica "
                  "count without --idle-churn", file=sys.stderr)
            return 2
        return _serve_loadgen_kernel_sweep(args, kernels,
                                           replica_levels[0])
    if args.idle_churn:
        if len(replica_levels) > 1:
            print("error: --idle-churn runs at one replica count "
                  "(--replicas N, not a comma list)", file=sys.stderr)
            return 2
        return _serve_loadgen_longtail(args, replica_levels[0])
    if getattr(args, "workload", "random") == "template-mix":
        if len(replica_levels) > 1:
            print("error: --workload template-mix runs at one replica "
                  "count (--replicas N, not a comma list)",
                  file=sys.stderr)
            return 2
        return _serve_loadgen_template_mix(args, replica_levels[0])
    if len(replica_levels) > 1:
        return _serve_loadgen_replica_sweep(args, replica_levels)
    _, cfg, server = _build_serve_stack(args, replica_levels[0])
    sampling = _serve_sampling(args)
    # the prefix/inject probes are single-run workloads (the sweep does not
    # thread them through) — never let the default --compare silently drop
    # them, and never silently drop an EXPLICIT --compare either
    probe_run = bool(args.shared_prefix_len or args.inject_prompt_len)
    if probe_run and args.compare:
        print("note: --shared-prefix-len/--inject-prompt-len run single-run "
              f"at --sessions {args.sessions}; ignoring --compare "
              f"{args.compare!r}", file=sys.stderr)
    compare = "1,8" if args.compare is None else args.compare
    with server:
        if compare and args.mode == "closed" and not probe_run:
            levels = tuple(
                sorted({int(x) for x in compare.split(",") if x.strip()}
                       | {args.sessions})
            )
            out = concurrency_sweep(
                server, vocab_size=cfg.vocab_size, levels=levels,
                requests_per_session=args.requests_per_session,
                prompt_len=args.prompt_len,
                max_new_tokens=args.max_new_tokens,
                sampling=sampling, seed=args.seed,
            )
        else:
            lens = {args.prompt_len}
            # an unchunked inject longer than the largest bucket has no
            # program to warm — admission rejects it and loadgen reports
            # it under injected["error"]; warming it would just crash
            if args.inject_prompt_len and (
                    server.batcher.prefill_chunk is not None
                    or args.inject_prompt_len
                    <= server.batcher.engine.max_prompt_len):
                lens.add(args.inject_prompt_len)
            server.warmup(sampling, prompt_lens=tuple(lens))
            out = run_loadgen(
                server, vocab_size=cfg.vocab_size, sessions=args.sessions,
                requests_per_session=args.requests_per_session,
                prompt_len=args.prompt_len,
                max_new_tokens=args.max_new_tokens,
                sampling=sampling, mode=args.mode, rate=args.rate,
                seed=args.seed, shared_prefix_len=args.shared_prefix_len,
                inject_prompt_len=args.inject_prompt_len,
                inject_delay_s=args.inject_delay,
                priority_frac=args.priority_frac,
                deadline_s=args.deadline_s or None,
                retry_max=args.retry_max,
                arrival=args.arrival,
                arrival_times=_read_arrival_trace(args.arrival_trace),
                burst_n=args.burst_n, burst_gap_s=args.burst_gap,
                sine_period_s=args.sine_period, sine_amp=args.sine_amp,
            )
    # aggregate across replicas — a --replicas N run spreads traffic, and
    # replica-0-only counters would silently halve every number vs /stats
    from .serve.loadgen import prefix_totals

    compiles_by_key: dict = {}
    cache_tot: dict = {}
    for rep in server.replicas:
        es = rep.engine.stats()
        for k, v in es["compiles"].items():
            compiles_by_key[k] = compiles_by_key.get(k, 0) + v
        for k, v in es["cache"].items():
            if k == "slots" and cache_tot:
                continue  # per-replica config, not a counter to sum
            cache_tot[k] = cache_tot.get(k, 0) + v
    prefix_tot = prefix_totals(server)
    out["engine"] = {
        "compiles_prefill": sum(
            r.engine.num_compiles("prefill") for r in server.replicas),
        "compiles_prefill_chunk": sum(
            r.engine.num_compiles("prefill_chunk") for r in server.replicas),
        "compiles_decode": sum(
            r.engine.num_compiles("decode") for r in server.replicas),
        "compiles_decode_window": sum(
            r.engine.num_compiles("decode_window") for r in server.replicas),
        "compiles_by_key": compiles_by_key,
        "prefix_cache": prefix_tot,
        **cache_tot,
    }
    bstats = server.stats()["batcher"]  # the cross-replica aggregate
    out["batcher"] = {
        k: bstats[k]
        for k in ("window_ladder", "windows_dispatched", "windows_pipelined",
                  "prefill_chunk", "prefill_chunks_dispatched",
                  "prefix_resumed", "prefix_tokens_saved")
    }
    # absolute router counters (incl. retired list) under a DISTINCT key —
    # each run report's "router" section stays the per-run delta view
    out["router_totals"] = server.router.stats()
    # server-side registry view (histogram p50/p99 + counters) so the
    # loadgen JSON carries both measurement sides — see also the per-run
    # "server_histograms" inside each report
    out["server_metrics"] = server.metrics_summary()
    print(json.dumps(out))
    # the one-line human summary (stats live in the JSON above)
    r = out.get("levels", {}).get(args.sessions, out)
    px = r.get("prefix_cache") or {}
    print(
        f"loadgen summary: {r.get('completed', '?')} req, "
        f"{r.get('tokens_per_sec', '?')} tok/s, "
        f"ttft p50 {r.get('p50_ttft_ms', '?')} ms, "
        f"itl p99 {r.get('p99_itl_ms', '?')} ms, "
        f"prefix hit rate {px.get('hit_rate', 'n/a')}, "
        f"compiles {out['engine']['compiles_prefill']}p"
        f"+{out['engine']['compiles_prefill_chunk']}pc"
        f"+{out['engine']['compiles_decode']}d"
        f"+{out['engine']['compiles_decode_window']}w, "
        f"swap generation {out['engine']['generation']}",
        file=sys.stderr)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=1, sort_keys=True)
        print(f"loadgen: report written to {args.json}", file=sys.stderr)
    return 0


def _read_arrival_trace(path: str | None) -> list[float] | None:
    """``--arrival-trace``: sorted seconds-from-start offsets, one float
    per line, blank lines and '#' comments ignored (loadgen validates
    ordering/sign so a bad trace fails with its own message)."""
    if not path:
        return None
    try:
        with open(path) as f:
            lines = f.read().splitlines()
    except OSError as e:
        raise SystemExit(f"--arrival-trace: cannot read {path!r}: {e}")
    out: list[float] = []
    for ln in lines:
        ln = ln.split("#", 1)[0].strip()
        if not ln:
            continue
        try:
            out.append(float(ln))
        except ValueError:
            raise SystemExit(
                f"--arrival-trace: bad offset {ln!r} in {path!r}")
    if not out:
        raise SystemExit(f"--arrival-trace: {path!r} has no offsets")
    return out


def _serve_loadgen_longtail(args, n_replicas: int) -> int:
    """``serve --loadgen --idle-churn``: the long-tail multi-tenant
    workload the tiered cache is gated on — N live kept sessions over
    few device slots, Zipf-popularity continuations, per-tier hit rates
    + re-prefill cost + hot-set tokens/s in one machine-readable report
    (tools/bench_serve.py --tiered-cache writes BENCH_serve_r03.json)."""
    import json

    from .serve import run_longtail

    _, cfg, server = _build_serve_stack(args, n_replicas)
    sampling = _serve_sampling(args)
    with server:
        # warm the full final-prefill lattice: re-prefills (tiers off /
        # lost state) replay a session's whole history, whose length
        # lands on arbitrary buckets — an unwarmed one would charge a
        # mid-run compile to exactly the workload being measured
        server.warmup(sampling, prompt_lens=tuple(
            set(server.engine.prefill_buckets) | {args.prompt_len}))
        out = run_longtail(
            server, vocab_size=cfg.vocab_size, sessions=args.sessions,
            requests_per_session=args.requests_per_session,
            prompt_len=args.prompt_len,
            max_new_tokens=args.max_new_tokens,
            sampling=sampling, zipf_s=args.zipf_s, seed=args.seed,
        )
        out["tier_stats_total"] = {
            r.index: r.engine.stats()["tiers"] for r in server.replicas
        }
    print(json.dumps(out))
    t = out.get("tiers") or {}
    hr = t.get("hit_rates", {})
    hot = out.get("hot_set", {})
    print(
        f"longtail summary: {out['completed']} req over {args.sessions} "
        f"sessions, {out['tokens_per_sec']} tok/s "
        f"(hot set {hot.get('tokens_per_sec', '?')} tok/s), tier hits "
        f"device {hr.get('device', '?')} / host {hr.get('host', '?')} / "
        f"disk {hr.get('disk', '?')}, re-prefills {out['re_prefills']} "
        f"({out['re_prefill_tokens']} tokens)", file=sys.stderr)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=1, sort_keys=True)
        print(f"loadgen: report written to {args.json}", file=sys.stderr)
    return 0


def _serve_loadgen_template_mix(args, n_replicas: int) -> int:
    """``serve --loadgen --workload template-mix``: the shared-structure
    workload the prefix-state fabric is gated on — tenant preamble x
    few-shot template x unique suffix on a bounded worker pool, with
    computed-vs-offered prefill token accounting in the report
    (tools/bench_serve.py --prefix-trie pairs this against the
    exact-match cache for BENCH_serve_r11.json)."""
    import json

    from .serve import run_template_mix

    _, cfg, server = _build_serve_stack(args, n_replicas)
    sampling = _serve_sampling(args)
    prompt_len = args.preamble_len + args.template_len + args.suffix_len
    with server:
        # one final-prefill length (all prompts are the same shape) plus
        # the resume lattice the batcher's warmup derives from it
        server.warmup(sampling, prompt_lens=(prompt_len,))
        out = run_template_mix(
            server, vocab_size=cfg.vocab_size, sessions=args.sessions,
            tenants=args.tenants, templates=args.templates,
            preamble_len=args.preamble_len,
            template_len=args.template_len, suffix_len=args.suffix_len,
            max_new_tokens=args.max_new_tokens, sampling=sampling,
            workers=args.workers, seed=args.seed,
        )
        out["engine"] = {
            "compiles_prefill": sum(
                r.engine.num_compiles("prefill") for r in server.replicas),
            "compiles_prefill_chunk": sum(
                r.engine.num_compiles("prefill_chunk")
                for r in server.replicas),
        }
    print(json.dumps(out))
    pf = out.get("prefill", {})
    px = out.get("prefix_cache") or {}
    print(
        f"template-mix summary: {out['completed']} req over "
        f"{args.sessions} sessions ({args.tenants}x{args.templates} "
        f"pairs), {out.get('tokens_per_sec', '?')} tok/s, prefill "
        f"computed {pf.get('tokens_computed', '?')}/"
        f"{pf.get('tokens_offered', '?')} offered "
        f"(ratio {pf.get('compute_ratio', '?')}), prefix mode "
        f"{px.get('mode', 'n/a')} hit rate {px.get('hit_rate', 'n/a')}",
        file=sys.stderr)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=1, sort_keys=True)
        print(f"loadgen: report written to {args.json}", file=sys.stderr)
    return 0


def _serve_loadgen_kernel_sweep(args, kernels: tuple[str, ...],
                                n_replicas: int = 1) -> int:
    """``serve --loadgen --decode-kernel pallas,scan``: the decode-kernel
    comparison — the same closed-loop workload on a fresh stack per
    kernel, tokens/s + TTFT/ITL deltas + greedy token parity in one
    machine-readable report (the BENCH_serve_r05.json probe)."""
    import copy
    import json

    from .serve.loadgen import kernel_sweep

    if args.mode != "closed":
        print("error: --decode-kernel comparison is closed-loop only",
              file=sys.stderr)
        return 2
    sampling = _serve_sampling(args)

    def make_server(kern):
        from .obs import MetricsRegistry

        a = copy.copy(args)
        a.decode_kernel = kern
        reg = (None if getattr(args, "telemetry", "on") == "off"
               else MetricsRegistry())
        # honor a plain --replicas N: each kernel's stack is built at the
        # requested replica count, not silently at 1
        return _build_serve_stack(a, n_replicas, registry=reg)[2]

    out = kernel_sweep(
        make_server, vocab_size=args.vocab_size, kernels=kernels,
        sessions=args.sessions,
        requests_per_session=args.requests_per_session,
        prompt_len=args.prompt_len, max_new_tokens=args.max_new_tokens,
        sampling=sampling, seed=args.seed,
    )
    print(json.dumps(out))
    vs = out.get("pallas_vs_scan", {})
    print(f"kernel sweep: tokens/s "
          f"{ {k: r['tokens_per_sec'] for k, r in out['kernels'].items()} }, "
          f"pallas/scan ratio {vs.get('tokens_per_sec_ratio', 'n/a')}, "
          f"p99 ITL delta {vs.get('p99_itl_delta_ms', 'n/a')} ms, "
          f"parity_ok {out.get('parity_ok', 'n/a')}", file=sys.stderr)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=1, sort_keys=True)
        print(f"loadgen: report written to {args.json}", file=sys.stderr)
    return 0 if out.get("parity_ok", True) else 1


def _serve_loadgen_replica_sweep(args, levels: tuple[int, ...]) -> int:
    """``serve --loadgen --replicas 1,2``: the data-parallel scaling
    comparison — same closed-loop workload on a fresh n-replica stack per
    level, aggregate tokens/s + greedy parity in one machine-readable
    report (the BENCH_serve_r02.json gate)."""
    import json

    from .serve import replica_sweep

    if args.compare or args.shared_prefix_len or args.inject_prompt_len:
        print("note: --replicas comparison runs the plain closed-loop "
              "workload; ignoring --compare/--shared-prefix-len/"
              "--inject-prompt-len", file=sys.stderr)
    if args.mode != "closed":
        print("error: --replicas comparison is closed-loop only",
              file=sys.stderr)
        return 2
    sampling = _serve_sampling(args)

    def make_server(n):
        # fresh registry per level (telemetry on): a sweep's levels build
        # separate servers, and sharing the process registry would fold
        # level 1's samples into level 2's embedded summaries
        from .obs import MetricsRegistry

        reg = (None if getattr(args, "telemetry", "on") == "off"
               else MetricsRegistry())
        return _build_serve_stack(args, n, registry=reg)[2]

    out = replica_sweep(
        make_server, vocab_size=args.vocab_size, levels=levels,
        sessions=args.sessions,
        requests_per_session=args.requests_per_session,
        prompt_len=args.prompt_len, max_new_tokens=args.max_new_tokens,
        sampling=sampling, seed=args.seed,
    )
    print(json.dumps(out))
    sc = out["scaling"]
    print(f"replica sweep: tokens/s {sc['tokens_per_sec']}, "
          f"speedup {sc['speedup_top_vs_base']}x "
          f"({sc['top_level']} vs {sc['base_level']} replicas), "
          f"parity_ok {out.get('parity_ok', 'n/a')}", file=sys.stderr)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(out, f, indent=1, sort_keys=True)
        print(f"loadgen: report written to {args.json}", file=sys.stderr)
    return 0 if out.get("parity_ok", True) else 1


def _serve_http(args) -> int:
    from .serve.server import make_http_server

    _, _, server = _build_serve_stack(
        args, _single_replica_count(args, "--http"))
    # pre-compile the bucket lattice for the default sampling config BEFORE
    # taking traffic: on TPU a compile is ~20-40 s, which would both time
    # out first requests and starve the scheduler heartbeat long enough to
    # flip /healthz 503 on a healthy warming server (an orchestrator would
    # then kill-loop it). Selftest/loadgen warm implicitly; --http must too.
    print(f"serve: warming the compile lattice "
          f"({len(server.replicas)} replica(s))...", flush=True)
    n = server.warmup(_serve_sampling(args),
                      prompt_lens=tuple(server.engine.prefill_buckets))
    print(f"serve: {n} programs compiled across "
          f"{len(server.replicas)} replica(s)", flush=True)
    httpd = make_http_server(server, args.host, args.port)
    host, port = httpd.server_address[:2]
    print(f"serving on http://{host}:{port} (POST /v1/generate, "
          "GET /healthz, GET /v1/stats, GET /metrics) — ctrl-C to stop",
          flush=True)
    with server:
        try:
            httpd.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            httpd.server_close()
    return 0


def _run_serve(argv) -> int:
    args = build_serve_parser().parse_args(argv)
    from .resilience import faults

    # serve chaos drills (serve_error@N): flag wins, env is the fallback
    faults.arm_from_flag_or_env(args.faults)
    from .utils import Tracer, set_tracer

    tracer = None
    if args.trace:
        tracer = Tracer()
        set_tracer(tracer)
    try:
        if args.selftest:
            return _serve_selftest(args)
        if args.loadgen:
            return _serve_loadgen(args)
        return _serve_http(args)
    finally:
        if tracer is not None:
            set_tracer(None)
            try:
                tracer.save(args.trace)
            except OSError as e:
                print(f"warning: could not write --trace file: {e}")


def build_distill_parser() -> argparse.ArgumentParser:
    """``distill`` subcommand: train + publish a speculative-decoding
    draft LM against a trained target (train/distill.py)."""
    p = argparse.ArgumentParser(
        prog="lstm_tensorspark_tpu distill",
        description="distill a draft LM (H/4, 1 layer, shared vocab) "
                    "against a trained target's logits with a KL+CE "
                    "mixed loss, and publish it to the model registry "
                    "as a verified pair — the artifact `serve "
                    "--speculative` loads",
    )
    # --- teacher (must match the producing training run) ---
    p.add_argument("--vocab-size", type=int, default=89)
    p.add_argument("--hidden-units", type=int, default=64)
    p.add_argument("--num-layers", type=int, default=2)
    p.add_argument("--tie-embeddings", action="store_true")
    p.add_argument("--compute-dtype", type=str, default="float32",
                   choices=["float32", "bfloat16"])
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--registry-dir", type=str, required=True,
                   help="model registry (serve/registry.py): the "
                        "teacher loads from here when --checkpoint-dir "
                        "is not given, and the draft publishes here as "
                        "'<--model-id>-draft'")
    p.add_argument("--model-id", type=str, default="default",
                   help="the teacher's registry id (the id the serving "
                        "fleet boots as)")
    p.add_argument("--draft-id", type=str, default=None,
                   help="publish the draft under this id instead of "
                        "'<--model-id>-draft'")
    p.add_argument("--checkpoint-dir", type=str, default=None,
                   help="restore the teacher from a training "
                        "checkpoint instead of the registry (template "
                        "built from the model flags + --optimizer)")
    p.add_argument("--optimizer", type=str, default="sgd",
                   choices=["sgd", "momentum", "adam", "adamw", "rmsprop"],
                   help="checkpoint-template optimizer (teacher "
                        "restore only — the draft trains with "
                        "--distill-optimizer)")
    p.add_argument("--learning-rate", type=float, default=1.0,
                   help="checkpoint-template learning rate (restore "
                        "only)")
    # --- corpus (the logit-harvest stream) ---
    p.add_argument("--data-path", type=str, default=None,
                   help="corpus directory (falls back to the dataset's "
                        "synthetic stand-in)")
    p.add_argument("--dataset", type=str, default="ptb_char",
                   choices=list(LM_DATASETS))
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--seq-len", type=int, default=32)
    # --- distillation ---
    p.add_argument("--steps", type=int, default=200,
                   help="draft optimizer steps (each scores one [B,T] "
                        "window through the teacher first)")
    p.add_argument("--alpha", type=float, default=0.5,
                   help="KL(teacher||student) weight in [0,1]; 1-alpha "
                        "weights the hard-label cross-entropy")
    p.add_argument("--distill-temperature", type=float, default=2.0,
                   help="softmax temperature of the KL term (Hinton "
                        "tau; the loss scales by tau^2)")
    p.add_argument("--distill-optimizer", type=str, default="adam",
                   choices=["sgd", "momentum", "adam", "adamw", "rmsprop"])
    p.add_argument("--distill-lr", type=float, default=1e-3)
    p.add_argument("--log-every", type=int, default=50)
    p.add_argument("--jsonl", type=str, default=None,
                   help="metrics JSONL path for the distill run")
    return p


def _run_distill(argv) -> int:
    args = build_distill_parser().parse_args(argv)
    import json

    from .data.batching import lm_batch_stream
    from .data.datasets import get_dataset
    from .models import LMConfig, init_lm
    from .serve.registry import ModelRegistry, config_fingerprint
    from .train.distill import distill, publish_draft
    from .train.metrics import MetricsLogger

    cfg = LMConfig(
        vocab_size=args.vocab_size,
        hidden_size=args.hidden_units,
        num_layers=args.num_layers,
        tie_embeddings=args.tie_embeddings,
        compute_dtype=args.compute_dtype,
    )
    registry = ModelRegistry(args.registry_dir)
    if args.checkpoint_dir:
        from .train import make_optimizer
        from .train.checkpoint import Checkpointer
        from .train.loop import init_train_state

        ckpt = Checkpointer(args.checkpoint_dir)
        if not ckpt.has_checkpoint():
            raise SystemExit(f"no checkpoint in {args.checkpoint_dir}")
        optimizer = make_optimizer(args.optimizer, args.learning_rate)
        template = init_train_state(
            init_lm(jax.random.PRNGKey(args.seed), cfg), optimizer,
            jax.random.PRNGKey(args.seed))
        state = ckpt.restore_latest(template)
        if state is None:
            raise SystemExit(
                f"every checkpoint in {args.checkpoint_dir} is corrupt "
                "(now quarantined); refusing to distill an untrained "
                "teacher")
        tparams = jax.device_get(state.params)
    else:
        template = init_lm(jax.random.PRNGKey(args.seed), cfg)
        try:
            meta, tparams = registry.load_params(args.model_id, template)
        except Exception as e:
            raise SystemExit(
                f"cannot load teacher {args.model_id!r} from "
                f"{args.registry_dir}: {e} (publish one, or pass "
                "--checkpoint-dir)")
        if (meta.get("config_hash")
                and meta["config_hash"] != config_fingerprint(cfg)):
            raise SystemExit(
                f"teacher {args.model_id} v{meta['version']} was "
                f"published for config {meta['config_hash']}, the model "
                f"flags describe {config_fingerprint(cfg)} — align the "
                "flags with the producing run")
    ds = get_dataset(args.dataset, args.data_path)
    if len(ds["vocab"]) > cfg.vocab_size:
        raise SystemExit(
            f"corpus vocab ({len(ds['vocab'])}) exceeds --vocab-size "
            f"({cfg.vocab_size}); the teacher cannot score tokens "
            "outside its embedding")
    logger = MetricsLogger(jsonl_path=args.jsonl)
    dparams, dcfg = distill(
        tparams, cfg, lm_batch_stream(ds["train"], args.batch_size,
                                      args.seq_len),
        num_steps=args.steps, alpha=args.alpha,
        temperature=args.distill_temperature,
        optimizer=args.distill_optimizer, learning_rate=args.distill_lr,
        seed=args.seed, log_every=args.log_every, logger=logger,
    )
    meta = publish_draft(registry, dparams, dcfg, cfg,
                         teacher_id=args.model_id, draft_id=args.draft_id)
    print(json.dumps({
        "distill": {
            "draft": meta["model"], "version": meta["version"],
            "hidden_size": dcfg.hidden_size,
            "num_layers": dcfg.num_layers,
            "config_hash": meta["config_hash"],
            "parent": meta["parent"],
            "payload_bytes": meta["payload_bytes"],
            "steps": args.steps,
        }
    }))
    return 0


def _run_classifier(args, logger) -> int:
    from .tasks.classification import run_classifier
    return run_classifier(args, logger)


def _run_forecaster(args, logger) -> int:
    from .tasks.forecasting import run_forecaster
    return run_forecaster(args, logger)


if __name__ == "__main__":
    raise SystemExit(main())
