"""Training entrypoint — reference CLI parity (SURVEY.md §2 L5 [D]: "keeps
its CLI ... launches on a TPU pod with no Spark JVM").

The reference's flag surface (hidden units, layers, epochs, learning rate,
partitions, data path — SURVEY.md §1 L5 row) is preserved; ``--num-partitions``
maps to the number of mesh devices on the data axis, the direct successor of
the RDD partition count. Where ``spark-submit main.py --flags`` launched a
JVM driver, ``python main.py --flags`` (or ``python -m
lstm_tensorspark_tpu.cli``) builds a device mesh and jit-compiles the train
step; multi-host pods launch the same script once per host with
``--num-processes/--process-id/--coordinator``.
"""

from __future__ import annotations

import argparse

import jax
import numpy as np


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="lstm_tensorspark_tpu",
        description="TPU-native LSTM training (LSTM-TensorSpark capabilities, no Spark)",
    )
    # --- reference flag surface (SURVEY.md §1 L5) ---
    p.add_argument("--data-path", type=str, default=None, help="corpus directory (falls back to synthetic stand-in)")
    p.add_argument("--hidden-units", type=int, default=128)
    p.add_argument("--num-layers", type=int, default=1)
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--learning-rate", type=float, default=1.0)
    p.add_argument("--num-partitions", type=int, default=None,
                   help="data-parallel shards (reference: RDD partitions) — defaults to all devices")
    # --- capability extensions ---
    p.add_argument("--dataset", type=str, default="ptb_char",
                   choices=["ptb_char", "wikitext2", "wikitext103", "imdb", "uci_electricity"])
    p.add_argument("--batch-size", type=int, default=32, help="global batch size")
    p.add_argument("--seq-len", type=int, default=None,
                   help="window/context length (defaults: LM 64, imdb 400, uci 168)")
    p.add_argument("--optimizer", type=str, default="sgd",
                   choices=["sgd", "momentum", "adam", "adamw", "rmsprop"])
    p.add_argument("--momentum", type=float, default=0.0)
    p.add_argument("--clip-norm", type=float, default=None)
    p.add_argument("--dropout", type=float, default=0.0)
    p.add_argument("--tie-embeddings", action="store_true")
    p.add_argument("--compute-dtype", type=str, default="bfloat16",
                   choices=["float32", "bfloat16"])
    p.add_argument("--remat-chunk", type=int, default=None,
                   help="jax.checkpoint chunk size over time (long sequences)")
    p.add_argument("--scan-unroll", type=int, default=1)
    p.add_argument("--use-pallas", action="store_true",
                   help="fused Pallas recurrence kernel (TPU, B%%8==0, H%%128==0)")
    p.add_argument("--stateful", action="store_true",
                   help="stateful truncated BPTT: carry recurrent state across contiguous windows")
    p.add_argument("--num-steps", type=int, default=None,
                   help="total step budget for the job, resume-inclusive (overrides epochs)")
    p.add_argument("--eval-every", type=int, default=0)
    p.add_argument("--log-every", type=int, default=50)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--jsonl", type=str, default=None, help="metrics JSONL path")
    p.add_argument("--checkpoint-dir", type=str, default=None)
    p.add_argument("--checkpoint-every", type=int, default=0)
    p.add_argument("--resume", action="store_true", help="resume from latest checkpoint in --checkpoint-dir")
    p.add_argument("--profile-dir", type=str, default=None, help="jax.profiler trace output dir")
    p.add_argument("--backend", type=str, default="auto", choices=["auto", "single", "dp"],
                   help="auto: dp when >1 device/partition")
    # --- multi-host control plane (SURVEY.md §7 step 4) ---
    p.add_argument("--coordinator", type=str, default=None)
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    from .parallel import distributed_init
    distributed_init(args.coordinator, args.num_processes, args.process_id)

    from .train.metrics import MetricsLogger
    logger = MetricsLogger(args.jsonl)

    if args.dataset in ("ptb_char", "wikitext2", "wikitext103"):
        rc = _run_lm(args, logger)
    elif args.dataset == "imdb":
        rc = _run_classifier(args, logger)
    else:
        rc = _run_forecaster(args, logger)
    logger.close()
    return rc


def _select_backend(args):
    """Resolve (mesh or None, shards). None mesh → single-chip path.

    ``--backend dp`` is honored even with one device/partition (a 1-wide
    shard_map — useful to validate DP semantics anywhere); ``auto`` picks
    dp only when more than one shard is in play."""
    from .parallel import make_mesh
    n_devices = jax.device_count()
    shards = args.num_partitions or n_devices
    if args.backend == "single" or (args.backend == "auto" and shards <= 1):
        return None, 1
    if shards > n_devices:
        raise SystemExit(
            f"--num-partitions {shards} exceeds {n_devices} available devices"
        )
    devices = np.asarray(jax.devices()[:shards])
    return make_mesh(dp=shards, devices=devices), shards


def _setup_training(
    args,
    logger,
    *,
    loss_fn,
    params,
    optimizer,
    rng,
    stateful: bool = False,
    carries0=None,
):
    """Shared orchestration for every task runner: backend selection,
    divisibility check, checkpoint wiring (restore BEFORE device placement),
    replication onto the mesh, and batch-stream sharding.

    Returns (state, train_step, mesh, shards, wrap_stream, checkpoint_fn).
    """
    from .parallel import make_dp_train_step, shard_batch
    from .parallel.data_parallel import replicate
    from .train import make_train_step
    from .train.loop import init_train_state

    mesh, shards = _select_backend(args)
    if args.batch_size % max(shards, 1) != 0:
        raise SystemExit(
            f"--batch-size {args.batch_size} not divisible by {shards} partitions"
        )

    state = init_train_state(params, optimizer, rng, carries=carries0)

    checkpoint_fn = None
    if args.checkpoint_dir:
        from .train.checkpoint import Checkpointer

        ckpt = Checkpointer(args.checkpoint_dir)
        if args.resume:
            restored = ckpt.restore_latest(state)
            if restored is not None:
                state = restored
                logger.log({"note": f"resumed at step {int(state.step)}"})
        checkpoint_fn = ckpt.save

    if mesh is None:
        train_step = make_train_step(loss_fn, optimizer, stateful=stateful)

        def wrap_stream(it):
            return it

    else:
        train_step = make_dp_train_step(loss_fn, optimizer, mesh, stateful=stateful)
        state = state._replace(
            params=replicate(state.params, mesh),
            opt_state=replicate(state.opt_state, mesh),
            carries=shard_batch(state.carries, mesh) if stateful else None,
        )

        def wrap_stream(it):
            return (shard_batch(b, mesh) for b in it)

    return state, train_step, mesh, shards, wrap_stream, checkpoint_fn


def _make_logged_loop(args, state, train_step, batches, steps_per_epoch, logger,
                      eval_fn=None, checkpoint_fn=None, tokens_per_batch=None):
    from .train.loop import train_loop

    total = args.num_steps or args.epochs * steps_per_epoch
    # --resume restores state.step; train only the REMAINING budget
    total = max(total - int(state.step), 0)
    if args.profile_dir:
        jax.profiler.start_trace(args.profile_dir)
    try:
        state = train_loop(
            state,
            train_step,
            batches,
            num_steps=total,
            log_every=args.log_every,
            logger=logger,
            eval_fn=eval_fn,
            eval_every=args.eval_every,
            checkpoint_fn=checkpoint_fn,
            checkpoint_every=args.checkpoint_every,
            tokens_per_batch=tokens_per_batch,
        )
    finally:
        if args.profile_dir:
            jax.profiler.stop_trace()
    return state


def _run_lm(args, logger) -> int:
    from .data import get_dataset, lm_batch_stream, lm_epoch_batches
    from .models import LMConfig, init_lm, lm_loss
    from .train import make_optimizer, make_eval_step
    from .train.loop import evaluate
    from .parallel import make_dp_eval_step, shard_batch

    seq_len = args.seq_len or 64
    data = get_dataset(args.dataset, args.data_path)
    if data["synthetic"]:
        logger.log({"note": f"dataset {args.dataset}: no files at --data-path, using synthetic stand-in"})
    vocab = data["vocab"]
    cfg = LMConfig(
        vocab_size=len(vocab),
        hidden_size=args.hidden_units,
        num_layers=args.num_layers,
        dropout=args.dropout,
        tie_embeddings=args.tie_embeddings,
        compute_dtype=args.compute_dtype,
        remat_chunk=args.remat_chunk,
        scan_unroll=args.scan_unroll,
        use_pallas=args.use_pallas,
    )

    stateful = args.stateful

    if stateful:

        def loss_fn(params, batch, dropout_rng, carries):
            return lm_loss(
                params, batch, cfg, carries=carries,
                dropout_rng=dropout_rng,
                deterministic=dropout_rng is None or cfg.dropout == 0.0,
            )

    else:

        def loss_fn(params, batch, dropout_rng):
            return lm_loss(
                params, batch, cfg,
                dropout_rng=dropout_rng,
                deterministic=dropout_rng is None or cfg.dropout == 0.0,
            )

    key = jax.random.PRNGKey(args.seed)
    kparams, krng = jax.random.split(key)
    params = init_lm(kparams, cfg)
    optimizer = make_optimizer(
        args.optimizer, args.learning_rate,
        momentum=args.momentum, clip_norm=args.clip_norm,
    )
    from .models.lstm_lm import init_carries
    carries0 = init_carries(cfg, args.batch_size) if stateful else None

    state, train_step, mesh, shards, wrap_stream, checkpoint_fn = _setup_training(
        args, logger,
        loss_fn=loss_fn, params=params, optimizer=optimizer, rng=krng,
        stateful=stateful, carries0=carries0,
    )

    train_tokens, valid_tokens = data["train"], data["valid"]
    steps_per_epoch = max((len(train_tokens) - 1) // (args.batch_size * seq_len), 1)
    batches = wrap_stream(lm_batch_stream(train_tokens, args.batch_size, seq_len))

    if mesh is None:
        eval_step = make_eval_step(loss_fn, stateful=stateful)
    else:
        eval_step = make_dp_eval_step(loss_fn, mesh, stateful=stateful)

    # The valid split can be smaller than one training-size window; evaluate
    # with the largest batch that fits (multiple of the shard count).
    eval_bs = min(args.batch_size, max((len(valid_tokens) - 1) // seq_len, 0))
    eval_bs -= eval_bs % max(shards, 1)

    def eval_fn(params):
        if eval_bs <= 0:
            return {"eval_skipped": 1}
        ev = lm_epoch_batches(valid_tokens, eval_bs, seq_len)
        ev_carries = init_carries(cfg, eval_bs) if stateful else None
        if mesh is not None:
            ev = (shard_batch(b, mesh) for b in ev)
            if stateful:
                ev_carries = shard_batch(ev_carries, mesh)
        return evaluate(eval_step, params, ev, carries=ev_carries)

    logger.log({
        "note": "start", "dataset": args.dataset, "vocab": len(vocab),
        "devices": jax.device_count(), "partitions": shards,
        "steps_per_epoch": steps_per_epoch, "backend": "dp" if mesh is not None else "single",
    })
    state = _make_logged_loop(
        args, state, train_step, batches, steps_per_epoch, logger,
        eval_fn=eval_fn if args.eval_every else None,
        checkpoint_fn=checkpoint_fn,
        tokens_per_batch=args.batch_size * seq_len,
    )
    final = eval_fn(state.params)
    logger.log({"step": int(state.step), **final, "note": "final"})
    return 0


def _run_classifier(args, logger) -> int:
    from .tasks.classification import run_classifier
    return run_classifier(args, logger)


def _run_forecaster(args, logger) -> int:
    from .tasks.forecasting import run_forecaster
    return run_forecaster(args, logger)


if __name__ == "__main__":
    raise SystemExit(main())
