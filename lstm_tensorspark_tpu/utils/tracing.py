"""Host-side structured tracing: named spans → Chrome trace JSON.

Reference parity: SURVEY.md §5 "Tracing / profiling" — the reference's only
observability was the Spark web UI's per-stage/task timing, external to the
repo. This module supplies the in-framework equivalent for the host side of
a run (data load, compile, train loop, eval, checkpoint, generation, and —
via serve/batcher.py — per-request admit→queue→prefill→decode→readback
timelines), saved in the Chrome trace-event format (load in
chrome://tracing or https://ui.perfetto.dev). Device-side profiling is
separate and richer: ``--profile-dir`` streams XLA/TPU traces via
``jax.profiler`` (see cli.py).

Zero overhead when disabled: the module-level ``span``/``instant`` helpers
no-op unless a Tracer is installed with ``set_tracer``.

Bounded memory when enabled: events live in a RING buffer
(``max_events``, default 200k) — a long serving run keeps the newest
events instead of growing without limit; ``dropped`` counts what the ring
displaced, and ``save`` records it in the trace.

Rows: events carry the FULL thread ident as ``tid`` (no truncation — the
old ``tid & 0xFFFF`` could collide two threads onto one row) and ``save``
emits ``thread_name`` metadata events so Perfetto labels each row with
the Python thread's name. :meth:`Tracer.set_tid_name` names synthetic
rows (e.g. one row per request id for serve timelines); :meth:`Tracer.
complete` records a span from explicit ``time.perf_counter()`` stamps —
how cross-iteration request phases are traced after the fact.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import deque


class Tracer:
    """Collects trace events; thread-safe appends; ``save`` writes the
    Chrome trace-event JSON ({"traceEvents": [...]})."""

    def __init__(self, max_events: int = 200_000) -> None:
        if max_events < 1:
            raise ValueError(f"max_events must be >= 1, got {max_events}")
        self.max_events = max_events
        self._events: deque[dict] = deque(maxlen=max_events)
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()
        self._tid_names: dict[int, str] = {}
        self.dropped = 0

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def _record(self, ev: dict) -> None:
        tid = ev["tid"]
        with self._lock:
            if tid not in self._tid_names:
                # only real threads auto-name; synthetic tids (requests)
                # are named explicitly via set_tid_name
                if tid == threading.get_ident():
                    self._tid_names[tid] = threading.current_thread().name
            if len(self._events) >= self.max_events:
                self.dropped += 1
            self._events.append(ev)

    def set_tid_name(self, tid: int, name: str) -> None:
        """Name a (possibly synthetic) ``tid`` row — emitted as a
        ``thread_name`` metadata event at :meth:`save`."""
        with self._lock:
            self._tid_names[int(tid)] = name

    @contextlib.contextmanager
    def span(self, name: str, **args):
        """Complete-event span ("ph": "X") around the with-block."""
        ts = self._now_us()
        try:
            yield self
        finally:
            dur = self._now_us() - ts
            ev = {"name": name, "ph": "X", "ts": ts, "dur": dur,
                  "pid": os.getpid(), "tid": threading.get_ident()}
            if args:
                ev["args"] = args
            self._record(ev)

    def complete(self, name: str, start_s: float, end_s: float, *,
                 tid: int | None = None, **args) -> None:
        """Record a complete event from explicit ``time.perf_counter()``
        stamps (taken while the phase ran, recorded later) — the serve
        batcher emits each finished request's phase timeline this way,
        one synthetic ``tid`` row per request."""
        ev = {"name": name, "ph": "X",
              "ts": (start_s - self._t0) * 1e6,
              "dur": max((end_s - start_s) * 1e6, 0.0),
              "pid": os.getpid(),
              "tid": threading.get_ident() if tid is None else int(tid)}
        if args:
            ev["args"] = args
        self._record(ev)

    def instant(self, name: str, **args) -> None:
        ev = {"name": name, "ph": "i", "ts": self._now_us(), "s": "g",
              "pid": os.getpid(), "tid": threading.get_ident()}
        if args:
            ev["args"] = args
        self._record(ev)

    def save(self, path: str) -> None:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with self._lock:
            events = list(self._events)
            names = dict(self._tid_names)
            dropped = self.dropped
        pid = os.getpid()
        meta = [
            {"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
             "args": {"name": name}}
            for tid, name in sorted(names.items())
        ]
        if dropped:
            meta.append({
                # tid -1: a sentinel row no real thread or synthetic
                # request id can own (request rows use non-negative ids)
                "name": "tracer_dropped_events", "ph": "i", "ts": 0.0,
                "s": "g", "pid": pid, "tid": -1,
                "args": {"dropped": dropped, "max_events": self.max_events},
            })
        with open(path, "w") as f:
            json.dump({"traceEvents": meta + events,
                       "displayTimeUnit": "ms"}, f)


_tracer: Tracer | None = None


def set_tracer(tracer: Tracer | None) -> None:
    global _tracer
    _tracer = tracer


def get_tracer() -> Tracer | None:
    return _tracer


@contextlib.contextmanager
def span(name: str, **args):
    """Module-level span: records on the installed tracer, no-op otherwise."""
    t = _tracer
    if t is None:
        yield None
    else:
        with t.span(name, **args):
            yield t


def instant(name: str, **args) -> None:
    t = _tracer
    if t is not None:
        t.instant(name, **args)
