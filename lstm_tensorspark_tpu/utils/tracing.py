"""Host-side structured tracing: named spans → Chrome trace JSON.

Reference parity: SURVEY.md §5 "Tracing / profiling" — the reference's only
observability was the Spark web UI's per-stage/task timing, external to the
repo. This module supplies the in-framework equivalent for the host side of
a run (data load, compile, train loop, eval, checkpoint, generation), saved
in the Chrome trace-event format (load in chrome://tracing or Perfetto).
Device-side profiling is separate and richer: ``--profile-dir`` streams
XLA/TPU traces via ``jax.profiler`` (see cli.py).

Zero overhead when disabled: the module-level ``span``/``instant`` helpers
no-op unless a Tracer is installed with ``set_tracer``.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time


class Tracer:
    """Collects trace events; thread-safe appends; ``save`` writes the
    Chrome trace-event JSON ({"traceEvents": [...]})."""

    def __init__(self) -> None:
        self._events: list[dict] = []
        self._lock = threading.Lock()
        self._t0 = time.perf_counter()

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    @contextlib.contextmanager
    def span(self, name: str, **args):
        """Complete-event span ("ph": "X") around the with-block."""
        ts = self._now_us()
        try:
            yield self
        finally:
            dur = self._now_us() - ts
            ev = {"name": name, "ph": "X", "ts": ts, "dur": dur,
                  "pid": os.getpid(), "tid": threading.get_ident() & 0xFFFF}
            if args:
                ev["args"] = args
            with self._lock:
                self._events.append(ev)

    def instant(self, name: str, **args) -> None:
        ev = {"name": name, "ph": "i", "ts": self._now_us(), "s": "g",
              "pid": os.getpid(), "tid": threading.get_ident() & 0xFFFF}
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    def save(self, path: str) -> None:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with self._lock:
            events = list(self._events)
        with open(path, "w") as f:
            json.dump({"traceEvents": events, "displayTimeUnit": "ms"}, f)


_tracer: Tracer | None = None


def set_tracer(tracer: Tracer | None) -> None:
    global _tracer
    _tracer = tracer


def get_tracer() -> Tracer | None:
    return _tracer


@contextlib.contextmanager
def span(name: str, **args):
    """Module-level span: records on the installed tracer, no-op otherwise."""
    t = _tracer
    if t is None:
        yield None
    else:
        with t.span(name, **args):
            yield t


def instant(name: str, **args) -> None:
    t = _tracer
    if t is not None:
        t.instant(name, **args)
