"""Model-FLOPs accounting — ONE source shared by the benchmark harness
(bench.py) and runtime logging (--log-flops).

Matmul-only counts (the MXU work; embedding gathers and elementwise ops
are excluded, matching standard MFU practice). Training ≈ 3× forward:
the backward pass does ~2× the forward matmul work (dL/dW and dL/dx per
matmul).
"""

from __future__ import annotations

import os

# bf16 peak for MFU. TPU v5 lite (v5e): 197 TFLOP/s bf16 (public spec).
# Override with LSTM_TSP_PEAK_TFLOPS on other chips.
PEAK_TFLOPS = float(os.environ.get("LSTM_TSP_PEAK_TFLOPS", 197.0))

# fwd + bwd(2x) matmul accounting
TRAIN_FLOPS_MULTIPLIER = 3.0


def lm_fwd_flops_per_token(V: int, H: int, L: int,
                           E: int | None = None) -> float:
    """Matmul-only forward FLOPs per token: per layer x@W (2*Din*4H) +
    h@U (2*H*4H), plus the softmax head (2*H*V). Embedding gather ~0."""
    E = E or H
    f = 0.0
    for layer in range(L):
        din = E if layer == 0 else H
        f += 8.0 * H * (din + H)
    return f + 2.0 * H * V


def classifier_fwd_flops_per_token(V: int, H: int, L: int,
                                   E: int | None = None) -> float:
    """Bi-LSTM: two directions per layer; layer 0 input E, later 2H.
    The [2H, C] head is per-sequence and negligible."""
    E = E or H
    f = 0.0
    for layer in range(L):
        din = E if layer == 0 else 2 * H
        f += 2 * 8.0 * H * (din + H)
    return f


def seq2seq_fwd_flops_per_seq(F: int, H: int, L: int, T: int,
                              horizon: int) -> float:
    """Encoder over T context steps + teacher-forced decoder over the
    horizon + per-step projection [H, F]."""
    enc = dec = 0.0
    for layer in range(L):
        din = F if layer == 0 else H
        enc += 8.0 * H * (din + H)
        dec += 8.0 * H * (din + H)
    return T * enc + horizon * (dec + 2.0 * H * F)
