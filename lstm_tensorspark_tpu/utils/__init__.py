from .tracing import Tracer, get_tracer, set_tracer, span, instant
from .flops import (
    PEAK_TFLOPS,
    TRAIN_FLOPS_MULTIPLIER,
    classifier_fwd_flops_per_token,
    lm_fwd_flops_per_token,
    seq2seq_fwd_flops_per_seq,
)

__all__ = [
    "Tracer", "get_tracer", "set_tracer", "span", "instant",
    "PEAK_TFLOPS", "TRAIN_FLOPS_MULTIPLIER",
    "classifier_fwd_flops_per_token", "lm_fwd_flops_per_token",
    "seq2seq_fwd_flops_per_seq",
]
