from .tracing import Tracer, get_tracer, set_tracer, span, instant

__all__ = ["Tracer", "get_tracer", "set_tracer", "span", "instant"]
