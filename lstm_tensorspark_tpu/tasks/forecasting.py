"""UCI-Electricity seq2seq forecasting task runner (BASELINE.md config 4).

Teacher-forced MSE training of the encoder-decoder LSTM
(models/seq2seq.py) via the shared cli._setup_training orchestration
(single-chip or DP, checkpoint/resume), with free-running autoregressive
evaluation on the held-out tail of the series.
"""

from __future__ import annotations

import jax
import numpy as np


def run_forecaster(args, logger) -> int:
    from ..cli import _make_logged_loop, _setup_training
    from ..data import get_dataset
    from ..data.batching import forecast_windows
    from ..models.seq2seq import Seq2SeqConfig, forecast, init_seq2seq, seq2seq_loss

    if args.stateful:
        raise SystemExit(
            "--stateful applies to contiguous-stream LM training only "
            "(forecast windows are independent)"
        )
    data = get_dataset("uci_electricity", args.data_path)
    if data["synthetic"]:
        logger.log({"note": "dataset uci_electricity: using synthetic stand-in"})
    context_len = args.seq_len or 168  # one week of hours
    horizon = 24
    # --use-pallas + --tensor-parallel is rejected centrally in cli.main()
    cfg = Seq2SeqConfig(
        num_features=data["num_features"],
        hidden_size=args.hidden_units,
        num_layers=args.num_layers,
        horizon=horizon,
        compute_dtype=args.compute_dtype,
        remat_chunk=args.remat_chunk,
        use_pallas=args.use_pallas,
        bptt=getattr(args, "bptt_mode", "sequential"),
    )

    def loss_fn(params, batch, dropout_rng):
        return seq2seq_loss(params, batch, cfg)

    key = jax.random.PRNGKey(args.seed)
    kp, kr = jax.random.split(key)
    params = init_seq2seq(kp, cfg)
    from ..cli import make_cli_optimizer
    optimizer = make_cli_optimizer(args)

    train_series, valid_series = data["train"], data["valid"]
    n_windows = max(len(train_series) - context_len - horizon + 1, 0)
    if n_windows < args.batch_size:
        raise SystemExit(
            f"train series too short: {n_windows} windows < batch {args.batch_size}"
        )
    steps_per_epoch = max(n_windows // args.batch_size, 1)

    fused_eval = bool(getattr(args, "fused_eval", False))
    if fused_eval and len(valid_series) < context_len + horizon:
        logger.log({"note": "fused-eval: valid series shorter than one "
                            "window; falling back to host-driven eval"})
        fused_eval = False
    if fused_eval:
        # Fused in-executable eval (works with BOTH feeds — device-data and
        # host-fed — and with --tensor-parallel): the free-running forecast
        # and its masked MSE/MAE sums run over the stacked host eval batches
        # (same `eval_batches` constructor as eval_fn, so the two paths can
        # never see different batches).
        import jax.numpy as jnp

        def metric_fn(p, b):
            preds = forecast(p, b["context"], cfg)
            w = b["valid"].astype(jnp.float32)
            n = jnp.maximum(w.sum(), 1.0)
            err = (preds - b["targets"]) * w[:, None, None]
            per_elem = float(horizon * preds.shape[-1])
            mse = (err ** 2).sum() / (n * per_elem)
            mae = jnp.abs(err).sum() / (n * per_elem)
            return {"eval_mse": mse, "eval_mae": mae}, w.sum()

        metric_keys = ("eval_mse", "eval_mae")
    else:
        metric_fn, metric_keys = None, ()

    if max(args.seq_parallel, args.pipeline_stages) > 1:
        raise SystemExit("--seq-parallel/--pipeline-stages apply to the LM "
                         "task; the forecaster supports --tensor-parallel")
    if args.tensor_parallel > 1:
        # metric_fn threads through so the (possibly fused) TP step is
        # built exactly ONCE
        from ..cli import _setup_tp_training
        from ..parallel.tensor_parallel import seq2seq_param_specs

        state, train_step, mesh, shards, wrap_stream, checkpoint_fn = (
            _setup_tp_training(
                args, logger, loss_fn=loss_fn, params=params,
                optimizer=optimizer, rng=kr,
                specs_fn=seq2seq_param_specs, hidden=cfg.hidden_size,
                metric_fn=metric_fn, metric_keys=metric_keys,
            )
        )
    else:
        state, train_step, mesh, shards, wrap_stream, checkpoint_fn = (
            _setup_training(
                args, logger, loss_fn=loss_fn, params=params,
                optimizer=optimizer, rng=kr,
            )
        )

    # data-exact resume: epoch seeds and in-epoch offsets follow the
    # restored step (same contract as the classifier runner)
    start_step = int(state.step)

    from ..data.batching import cap_batches

    def eval_batches(eval_quantum: int = 1):
        """THE eval-batch constructor shared by the host eval_fn and the
        fused-eval staging — one source, so the two paths can never see
        different batches. ``eval_quantum`` keeps the static batch shape a
        multiple of the TP data axis (host AND fused eval under
        --tensor-parallel both pass mesh.shape['data'])."""
        eval_bs = min(args.batch_size, 64)
        eval_bs = max(eval_bs - eval_bs % eval_quantum, eval_quantum)
        return cap_batches(
            forecast_windows(valid_series, context_len, horizon, eval_bs,
                             drop_remainder=False),
            getattr(args, "eval_batches", None),
        )

    # TP eval shards contexts over "data": the static batch shape must be a
    # multiple of the axis — ONE quantum shared by host eval_fn and the
    # fused-eval staging
    eval_quantum = mesh.shape["data"] if args.tensor_parallel > 1 else 1
    if fused_eval:
        from ..data import stage_stacked_batches

        ev_stacked = stage_stacked_batches(eval_batches(eval_quantum),
                                           mesh=mesh)

    if getattr(args, "device_data", False):
        # HBM-staged series; (context, horizon) windows sliced on-device from
        # per-step start indices — same shuffled order as forecast_windows,
        # so host-fed and device-resident runs see identical batches.
        import functools

        from ..data import slice_forecast_batch, stage_series
        from ..train import make_device_dp_train_step, make_device_train_step

        if args.prefetch:
            raise SystemExit("--device-data has no host feed; drop --prefetch")
        k = args.steps_per_call
        staged = stage_series(train_series, context_len, horizon, mesh=mesh)
        window_fn = functools.partial(
            slice_forecast_batch, context_len=context_len, horizon=horizon
        )
        from jax.sharding import PartitionSpec as P

        if mesh is None:
            dstep = make_device_train_step(
                loss_fn, optimizer, window_fn, metric_fn=metric_fn,
                metric_keys=metric_keys, grad_accum=args.grad_accum,
            )
        else:
            dstep = make_device_dp_train_step(
                loss_fn, optimizer, window_fn, mesh, {"series": P()},
                metric_fn=metric_fn, metric_keys=metric_keys,
                idx_spec=P(None, "data"), grad_accum=args.grad_accum,
            )
        if fused_eval:
            train_step = lambda state, idxs, do_eval: dstep(  # noqa: E731
                state, staged.arrays, idxs, ev_stacked, do_eval
            )
        else:
            train_step = lambda state, idxs: dstep(state, staged.arrays, idxs)  # noqa: E731

        from ..data.batching import forecast_starts, index_groups

        stream = index_groups(
            lambda epoch: forecast_starts(
                staged.num_windows, shuffle_seed=args.seed + epoch
            ),
            args.batch_size, k, start_step=start_step,
        )
    else:
        from ..data.batching import epoch_stream

        raw = epoch_stream(
            lambda epoch: forecast_windows(
                train_series, context_len, horizon, args.batch_size,
                shuffle_seed=args.seed + epoch,
            ),
            steps_per_epoch=steps_per_epoch, start_step=start_step,
        )
        if fused_eval and args.tensor_parallel > 1:
            # the TP step from _setup_tp_training already carries the gated
            # eval tail (uniform cond in a pure GSPMD jit program — no
            # manual-axis collectives to diverge on); bind its eval operand
            tstep = train_step
            train_step = lambda state, b, do_eval: tstep(  # noqa: E731
                state, b, ev_stacked, do_eval
            )
            stream = wrap_stream(raw)
        elif fused_eval:
            # host-fed feed + fused in-executable eval
            from ..train import make_dp_multi_train_step, make_multi_train_step

            if mesh is None:
                mstep = make_multi_train_step(
                    loss_fn, optimizer, metric_fn=metric_fn,
                    metric_keys=metric_keys, grad_accum=args.grad_accum,
                )
            else:
                mstep = make_dp_multi_train_step(
                    loss_fn, optimizer, mesh, metric_fn=metric_fn,
                    metric_keys=metric_keys, grad_accum=args.grad_accum,
                )
            train_step = lambda state, b, do_eval: mstep(  # noqa: E731
                state, b, ev_stacked, do_eval
            )
            stream = wrap_stream(raw, always_stack=True)
        else:
            stream = wrap_stream(raw)
    if args.tensor_parallel > 1:
        # eval on the DEVICE-RESIDENT sharded params — no host gather
        # (VERDICT r2 weak #6); contexts shard over the data axis
        from ..parallel.tensor_parallel import (
            make_tp_eval_step, seq2seq_param_specs,
        )

        fc = make_tp_eval_step(
            lambda p, ctx: forecast(p, ctx, cfg), mesh,
            seq2seq_param_specs(params),
        )
    else:
        fc = jax.jit(lambda p, ctx: forecast(p, ctx, cfg))

    def eval_fn(params):
        """Free-running (no teacher forcing) MSE/MAE over the valid tail,
        weighted by valid rows (filler rows in the last batch excluded)."""
        if len(valid_series) < context_len + horizon:
            return {"eval_skipped": 1}
        tot_n = tot_mse = tot_mae = 0.0
        for b in eval_batches(eval_quantum):
            preds = np.asarray(fc(params, b["context"]))
            err = (preds - b["targets"])[b["valid"]]
            n = b["valid"].sum()
            tot_mse += float((err**2).mean()) * n
            tot_mae += float(np.abs(err).mean()) * n
            tot_n += n
        tot_n = max(tot_n, 1.0)
        return {"eval_mse": tot_mse / tot_n, "eval_mae": tot_mae / tot_n}

    logger.log({
        "note": "start", "dataset": "uci_electricity",
        "features": data["num_features"], "context": context_len,
        "horizon": horizon, "devices": jax.device_count(), "partitions": shards,
        "steps_per_epoch": steps_per_epoch,
        "backend": "dp" if mesh is not None else "single",
    })
    from ..cli import _mfu_logging
    from ..utils.flops import seq2seq_fwd_flops_per_seq

    # tokens_per_batch counts context positions; spread the per-sequence
    # FLOPs (encoder + decoder + projection) over them so
    # tokens/sec x flops_per_token = sequences/sec x flops_per_seq
    flops_per_token, peak = _mfu_logging(
        args,
        seq2seq_fwd_flops_per_seq(cfg.num_features, cfg.hidden_size,
                                  cfg.num_layers, context_len,
                                  horizon) / context_len,
        mesh,
    )
    state = _make_logged_loop(
        args, state, train_step, stream, steps_per_epoch, logger,
        eval_fn=None if fused_eval else (eval_fn if args.eval_every else None),
        checkpoint_fn=checkpoint_fn,
        tokens_per_batch=args.batch_size * context_len,
        fused_eval=(lambda ms: {"eval_mse": float(ms["eval_mse"]),
                                "eval_mae": float(ms["eval_mae"])})
        if fused_eval else None,
        flops_per_token=flops_per_token,
        peak_tflops=peak,
        best_metric="eval_mse", best_mode="min",
    )
    # final eval on the device-resident params (TP: sharded in place; DP:
    # replicated) — no host round-trip of the model
    final = eval_fn(state.params)
    logger.log({"step": int(state.step), **final, "note": "final"})
    return 0
