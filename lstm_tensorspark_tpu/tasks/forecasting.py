"""UCI-Electricity seq2seq forecasting task (BASELINE.md config 4).

Placeholder entrypoint — the encoder-decoder model lands with the
model-families milestone; until then fail fast with a clear message instead
of an import error.
"""


def run_forecaster(args, logger) -> int:
    raise SystemExit(
        "--dataset uci_electricity: the seq2seq forecasting task is not wired "
        "into the CLI yet (model families milestone); the uci_electricity "
        "dataset builder is available as a library."
    )
