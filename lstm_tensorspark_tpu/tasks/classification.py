"""IMDB-style bi-LSTM classification task (BASELINE.md config 2).

Placeholder entrypoint — the bidirectional classifier model lands with the
model-families milestone; until then fail fast with a clear message instead
of an import error.
"""


def run_classifier(args, logger) -> int:
    raise SystemExit(
        "--dataset imdb: the bi-LSTM classification task is not wired into the "
        "CLI yet (model families milestone); the imdb dataset builder and "
        "masking/batching utilities are available as a library."
    )
