"""IMDB-style bi-LSTM classification task runner (BASELINE.md config 2).

Wires the bi-LSTM classifier (models/classifier.py) into the CLI: epochs of
bucketed padded batches, single-chip or data-parallel training (via the
shared cli._setup_training orchestration, including checkpoint/resume),
accuracy eval. Evaluation runs single-device on the small held-out split
(params are replicated, so any device's copy works).
"""

from __future__ import annotations

import jax


def run_classifier(args, logger) -> int:
    from ..cli import _make_logged_loop, _setup_training
    from ..data import get_dataset, padded_batches
    from ..models.classifier import ClassifierConfig, classifier_loss, init_classifier

    if args.stateful:
        raise SystemExit(
            "--stateful applies to contiguous-stream LM training only "
            "(classification examples are independent)"
        )
    max_len = args.seq_len or 400  # config-2 default
    data = get_dataset("imdb", args.data_path, max_len=max_len)
    if data["synthetic"]:
        logger.log({"note": "dataset imdb: using synthetic stand-in"})
    vocab = data["vocab"]
    # --use-pallas + --tensor-parallel is rejected centrally in cli.main()
    cfg = ClassifierConfig(
        vocab_size=len(vocab),
        num_classes=data["num_classes"],
        hidden_size=args.hidden_units,
        num_layers=args.num_layers,
        dropout=args.dropout,
        compute_dtype=args.compute_dtype,
        remat_chunk=args.remat_chunk,
        use_pallas=args.use_pallas,
        bptt=getattr(args, "bptt_mode", "sequential"),
    )

    def loss_fn(params, batch, dropout_rng):
        return classifier_loss(
            params, batch, cfg,
            dropout_rng=dropout_rng,
            deterministic=dropout_rng is None or cfg.dropout == 0.0,
        )

    key = jax.random.PRNGKey(args.seed)
    kp, kr = jax.random.split(key)
    params = init_classifier(kp, cfg)
    from ..cli import make_cli_optimizer
    optimizer = make_cli_optimizer(args)

    train_seqs, train_labels = data["train"]
    valid_seqs, valid_labels = data["valid"]
    if len(train_seqs) < args.batch_size:
        raise SystemExit(
            f"train set too small: {len(train_seqs)} examples < batch {args.batch_size}"
        )
    steps_per_epoch = max(len(train_seqs) // args.batch_size, 1)

    fused_eval = bool(getattr(args, "fused_eval", False))
    if fused_eval and not valid_seqs:
        logger.log({"note": "fused-eval: empty valid split; "
                            "falling back to host-driven eval"})
        fused_eval = False
    if fused_eval:
        # Fused in-executable eval (works with BOTH feeds — device-data and
        # host-fed — and with --tensor-parallel): the weighted accuracy/loss
        # sums run over the stacked host eval batches (same `eval_batches`
        # constructor as eval_fn, so the two paths can never see different
        # batches).
        import numpy as np

        def metric_fn(p, b):
            _, aux = classifier_loss(p, b, cfg)
            w = b["valid"].astype(np.float32).sum()
            return ({"eval_loss": aux["loss"],
                     "eval_accuracy": aux["accuracy"]}, w)

        metric_keys = ("eval_loss", "eval_accuracy")
    else:
        metric_fn, metric_keys = None, ()

    if max(args.seq_parallel, args.pipeline_stages) > 1:
        raise SystemExit("--seq-parallel/--pipeline-stages apply to the LM "
                         "task; the classifier supports --tensor-parallel")
    if args.tensor_parallel > 1:
        # metric_fn threads through so the (possibly fused) TP step is
        # built exactly ONCE
        from ..cli import _setup_tp_training
        from ..parallel.tensor_parallel import classifier_param_specs

        state, train_step, mesh, shards, wrap_stream, checkpoint_fn = (
            _setup_tp_training(
                args, logger, loss_fn=loss_fn, params=params,
                optimizer=optimizer, rng=kr,
                specs_fn=classifier_param_specs, hidden=cfg.hidden_size,
                metric_fn=metric_fn, metric_keys=metric_keys,
            )
        )
    else:
        state, train_step, mesh, shards, wrap_stream, checkpoint_fn = (
            _setup_training(
                args, logger, loss_fn=loss_fn, params=params,
                optimizer=optimizer, rng=kr,
            )
        )

    # data-exact resume: epoch seeds and in-epoch offsets follow the
    # restored step, so the resumed shuffle order matches the
    # uninterrupted run exactly
    start_step = int(state.step)

    from ..data.batching import cap_batches, padded_batches

    def eval_batches(eval_quantum: int = 1):
        """THE eval-batch constructor shared by the host eval_fn and the
        fused-eval staging — one source, so the two paths can never see
        different batches. ``eval_quantum`` keeps the static batch shape a
        multiple of the TP data axis (host AND fused eval under
        --tensor-parallel both pass mesh.shape['data'])."""
        eval_bs = min(args.batch_size, len(valid_seqs))
        eval_bs = max(eval_bs - eval_bs % eval_quantum, eval_quantum)
        return cap_batches(
            padded_batches(valid_seqs, valid_labels, eval_bs, max_len,
                           drop_remainder=False),
            getattr(args, "eval_batches", None),
        )

    # TP eval shards batch rows over "data": the static batch shape must be
    # a multiple of the axis — ONE quantum shared by host eval_fn and the
    # fused-eval staging
    eval_quantum = mesh.shape["data"] if args.tensor_parallel > 1 else 1
    if fused_eval:
        from ..data import stage_stacked_batches

        ev_stacked = stage_stacked_batches(eval_batches(eval_quantum),
                                           mesh=mesh)

    if getattr(args, "device_data", False):
        # HBM-staged padded example matrix; batches gathered on-device by
        # row indices in the same shuffle+bucket order as padded_batches.
        import numpy as np

        from ..data import stage_examples, take_batch
        from ..train import make_device_dp_train_step, make_device_train_step

        if args.prefetch:
            raise SystemExit("--device-data has no host feed; drop --prefetch")
        k = args.steps_per_call
        N = len(train_seqs)
        toks = np.zeros((N, max_len), np.int32)
        lens = np.zeros((N,), np.int32)
        for r, seq in enumerate(train_seqs):
            seq = seq[:max_len]
            toks[r, : len(seq)] = seq
            lens[r] = len(seq)
        staged = stage_examples(
            {
                "tokens": toks,
                "lengths": lens,
                "labels": np.asarray(train_labels, np.int32),
                "valid": np.ones((N,), bool),
            },
            mesh=mesh,
        )
        from jax.sharding import PartitionSpec as P

        arrays_spec = {k2: P() for k2 in staged.arrays}
        if mesh is None:
            dstep = make_device_train_step(
                loss_fn, optimizer, take_batch, metric_fn=metric_fn,
                metric_keys=metric_keys, grad_accum=args.grad_accum,
            )
        else:
            dstep = make_device_dp_train_step(
                loss_fn, optimizer, take_batch, mesh, arrays_spec,
                metric_fn=metric_fn, metric_keys=metric_keys,
                idx_spec=P(None, "data"), grad_accum=args.grad_accum,
            )
        if fused_eval:
            train_step = lambda state, idxs, do_eval: dstep(  # noqa: E731
                state, staged.arrays, idxs, ev_stacked, do_eval
            )
        else:
            train_step = lambda state, idxs: dstep(state, staged.arrays, idxs)  # noqa: E731

        from ..data.batching import example_order, index_groups

        lengths_all = [len(s) for s in train_seqs]
        stream = index_groups(
            lambda epoch: example_order(
                lengths_all, shuffle_seed=args.seed + epoch
            ),
            args.batch_size, k, start_step=start_step,
        )
    else:
        from ..data.batching import epoch_stream

        raw = epoch_stream(
            lambda epoch: padded_batches(
                train_seqs, train_labels, args.batch_size, max_len,
                shuffle_seed=args.seed + epoch,
            ),
            steps_per_epoch=steps_per_epoch, start_step=start_step,
        )
        if fused_eval and args.tensor_parallel > 1:
            # the TP step from _setup_tp_training already carries the gated
            # eval tail (uniform cond in a pure GSPMD jit program — no
            # manual-axis collectives to diverge on); bind its eval operand
            tstep = train_step
            train_step = lambda state, b, do_eval: tstep(  # noqa: E731
                state, b, ev_stacked, do_eval
            )
            stream = wrap_stream(raw)
        elif fused_eval:
            # host-fed feed + fused in-executable eval
            from ..train import make_dp_multi_train_step, make_multi_train_step

            if mesh is None:
                mstep = make_multi_train_step(
                    loss_fn, optimizer, metric_fn=metric_fn,
                    metric_keys=metric_keys, grad_accum=args.grad_accum,
                )
            else:
                mstep = make_dp_multi_train_step(
                    loss_fn, optimizer, mesh, metric_fn=metric_fn,
                    metric_keys=metric_keys, grad_accum=args.grad_accum,
                )
            train_step = lambda state, b, do_eval: mstep(  # noqa: E731
                state, b, ev_stacked, do_eval
            )
            stream = wrap_stream(raw, always_stack=True)
        else:
            stream = wrap_stream(raw)
    if args.tensor_parallel > 1:
        # eval on the DEVICE-RESIDENT sharded params — no host gather
        # (VERDICT r2 weak #6); batches shard over the data axis
        from ..parallel.tensor_parallel import (
            classifier_param_specs, make_tp_eval_step,
        )

        eval_step = make_tp_eval_step(
            lambda p, b: classifier_loss(p, b, cfg)[1], mesh,
            classifier_param_specs(params),
        )
    else:
        eval_step = jax.jit(lambda p, b: classifier_loss(p, b, cfg)[1])

    def eval_fn(params):
        if not valid_seqs:
            return {"eval_skipped": 1}
        tot_w = tot_loss = tot_acc = 0.0
        for b in eval_batches(eval_quantum):
            m = eval_step(params, b)
            w = float(b["valid"].sum())
            tot_loss += float(m["loss"]) * w
            tot_acc += float(m["accuracy"]) * w
            tot_w += w
        tot_w = max(tot_w, 1.0)
        return {"eval_loss": tot_loss / tot_w, "eval_accuracy": tot_acc / tot_w}

    logger.log({
        "note": "start", "dataset": "imdb", "vocab": len(vocab),
        "max_len": max_len, "devices": jax.device_count(), "partitions": shards,
        "steps_per_epoch": steps_per_epoch,
        "backend": "dp" if mesh is not None else "single",
    })
    from ..cli import _mfu_logging
    from ..utils.flops import classifier_fwd_flops_per_token

    flops_per_token, peak = _mfu_logging(
        args,
        classifier_fwd_flops_per_token(cfg.vocab_size, cfg.hidden_size,
                                       cfg.num_layers, cfg.embed),
        mesh,
    )
    state = _make_logged_loop(
        args, state, train_step, stream, steps_per_epoch, logger,
        eval_fn=None if fused_eval else (eval_fn if args.eval_every else None),
        checkpoint_fn=checkpoint_fn,
        tokens_per_batch=args.batch_size * max_len,
        fused_eval=(lambda ms: {"eval_loss": float(ms["eval_loss"]),
                                "eval_accuracy": float(ms["eval_accuracy"])})
        if fused_eval else None,
        flops_per_token=flops_per_token,
        peak_tflops=peak,
        best_metric="eval_accuracy", best_mode="max",
    )
    # final eval on the device-resident params (TP: sharded in place; DP:
    # replicated) — no host round-trip of the model
    final = eval_fn(state.params)
    logger.log({"step": int(state.step), **final, "note": "final"})
    return 0
