"""Unified telemetry plane: one process-wide registry of counters,
gauges, and fixed-bucket streaming histograms, exposed two ways —
``GET /metrics`` Prometheus text exposition on the serve HTTP endpoint
(serve/server.py) and histogram summaries inside the ``/stats`` JSON —
plus per-request span timelines through utils/tracing.

Production TPU serving treats step-time/throughput telemetry and
per-request latency breakdowns as first-class (PAPERS.md, "Scalable
Training of Language Models using JAX pjit and TPUv4"): the K-vs-latency
and prefix-cache tradeoffs are tunable from a LIVE server only if the
server itself reports TTFT/ITL/queue-wait distributions, not just
loadgen-side percentiles.

Recording sites (all take a registry parameter, defaulting to
``REGISTRY``; ``NULL_REGISTRY`` disables with no-op instruments):

- serve/batcher.py — queue depth/wait, scheduler-iteration duration,
  per-request TTFT + inter-token-latency histograms, window-K choice,
  prefill-chunk progress, request outcomes;
- serve/engine.py — per-phase compile counts (at trace time),
  window-dispatch timestamps for dispatch→fetch readback latency;
- serve/state_cache.py — state-cache evictions/swaps, prefix-cache
  hit/miss/insert/evict/invalidate;
- train/loop.py — step time, tokens/s, anomalous steps;
- supervise.py — restarts, backoff time, poison/stall verdicts.
"""

from .metrics import (
    DEFAULT_LATENCY_BUCKETS,
    NULL_REGISTRY,
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    NullRegistry,
    parse_exposition,
)

__all__ = [
    "Counter",
    "DEFAULT_LATENCY_BUCKETS",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "NullRegistry",
    "NULL_REGISTRY",
    "REGISTRY",
    "parse_exposition",
]
