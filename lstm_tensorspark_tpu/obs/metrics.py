"""Process-wide metrics plane: counters, gauges, fixed-bucket streaming
histograms, and Prometheus text exposition.

Design constraints (the serving hot path records per TOKEN):

- **thread-safe**: every instrument guards its state with one small lock;
  an ``observe``/``inc`` is a lock + an add (+ one bisect for histograms)
  — no allocation, no formatting, no I/O;
- **fixed buckets**: histograms are streaming — they never store samples,
  only per-bucket counts plus ``sum``/``count``, so memory is O(buckets)
  regardless of traffic, and quantiles (p50/p99) are estimated by linear
  interpolation inside the target bucket (the same estimate Prometheus'
  ``histogram_quantile`` computes server-side);
- **near-zero overhead when unregistered**: components take a registry
  parameter; passing :data:`NULL_REGISTRY` hands back no-op instruments
  (``cli serve --telemetry off``), so disabling telemetry costs one
  no-op method call per record site;
- **idempotent registration**: asking a registry for an existing name
  returns the existing family (so module A and module B can both say
  "give me ``serve_itl_seconds``"), but re-registering with a different
  kind/labelset is a hard error — two meanings for one name is how
  dashboards lie.

Exposition: :meth:`MetricsRegistry.render_prometheus` emits the text
format (``# HELP``/``# TYPE``, histograms as CUMULATIVE ``_bucket{le=}``
series plus ``_sum``/``_count``); :func:`parse_exposition` is the
matching validator — tools/serve_smoke.py and tests/test_obs.py parse
what the server serves with it, so the format contract is executable.
"""

from __future__ import annotations

import bisect
import math
import re
import threading

#: default buckets for latency histograms (seconds): sub-ms resolution at
#: the low end (CPU inter-token gaps on small models), up to the serving
#: timeout at the top. The +Inf overflow bucket is implicit.
DEFAULT_LATENCY_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABELNAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def _check_name(name: str) -> str:
    if not _NAME_RE.match(name):
        raise ValueError(f"invalid metric name {name!r}")
    return name


class Counter:
    """Monotonically increasing value."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counters only go up, got inc({amount})")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time value (set wins; inc/dec for running levels)."""

    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket streaming histogram (per-bucket counts + sum + count;
    never stores samples). ``buckets`` are the upper bounds (``le``,
    inclusive), strictly increasing; the +Inf overflow bucket is
    implicit."""

    __slots__ = ("buckets", "_counts", "_sum", "_count", "_lock")

    def __init__(self, buckets: tuple[float, ...] = DEFAULT_LATENCY_BUCKETS):
        b = tuple(float(x) for x in buckets)
        if not b or any(not math.isfinite(x) for x in b):
            raise ValueError(f"need >= 1 finite bucket bound, got {buckets!r}")
        if any(y <= x for x, y in zip(b, b[1:])):
            raise ValueError(f"bucket bounds must strictly increase: {b!r}")
        self.buckets = b
        self._counts = [0] * (len(b) + 1)  # last = +Inf overflow
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        # le is inclusive: a value exactly on a bound lands in that bucket
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    def snapshot(self) -> tuple[list[int], float, int]:
        """Consistent (bucket_counts, sum, count) under one lock hold."""
        with self._lock:
            return list(self._counts), self._sum, self._count

    def _quantile_from(self, counts: list[int], total: int,
                       q: float) -> float:
        return _estimate_quantile(self.buckets, counts, total, q)

    def quantile(self, q: float) -> float:
        counts, _, total = self.snapshot()
        return self._quantile_from(counts, total, q)

    @property
    def value(self) -> float:  # uniform read surface with Counter/Gauge
        with self._lock:
            return float(self._count)

    def summary(self) -> dict:
        # ONE snapshot: count/sum/p50/p99 must describe the same sample
        # set even while another thread is observing
        counts, s, total = self.snapshot()
        out = {"count": total, "sum": round(s, 6)}
        if total:
            out["p50"] = round(self._quantile_from(counts, total, 0.5), 6)
            out["p99"] = round(self._quantile_from(counts, total, 0.99), 6)
        return out


def _estimate_quantile(buckets: tuple[float, ...], counts: list[int],
                       total: int, q: float) -> float:
    """Estimated q-quantile over one consistent ``counts`` snapshot:
    linear interpolation inside the bucket holding the target rank —
    Prometheus' ``histogram_quantile`` estimate, computed locally.
    NaN when empty; clamped to the largest finite bound for
    overflow-bucket ranks. Module-level so a MERGED multi-child count
    vector (``_Family.aggregate``) summarises exactly like a single
    child's."""
    if total == 0:
        return float("nan")
    rank = q * total
    cum = 0.0
    for i, c in enumerate(counts):
        if c and cum + c >= rank:
            if i >= len(buckets):  # overflow bucket: no upper bound
                return buckets[-1]
            lo = buckets[i - 1] if i > 0 else 0.0
            hi = buckets[i]
            return lo + (hi - lo) * ((rank - cum) / c)
        cum += c
    return buckets[-1]


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class _Family:
    """One named metric family: labelled children (or a single anonymous
    child for label-less metrics, to which the convenience methods
    ``inc``/``set``/``dec``/``observe`` delegate)."""

    def __init__(self, kind: str, name: str, help_: str,
                 labelnames: tuple[str, ...], buckets=None):
        for ln in labelnames:
            if not _LABELNAME_RE.match(ln) or ln == "le":
                raise ValueError(f"invalid label name {ln!r} for {name}")
        self.kind = kind
        self.name = _check_name(name)
        self.help = help_
        self.labelnames = tuple(labelnames)
        self._buckets = buckets
        self._children: dict[tuple[str, ...], object] = {}
        self._lock = threading.Lock()
        if not self.labelnames:
            # eager anonymous child: a label-less metric exports 0 from
            # registration on (absent-vs-zero matters to alert rules)
            self._children[()] = self._make()

    def _make(self):
        if self.kind == "histogram":
            return Histogram(self._buckets or DEFAULT_LATENCY_BUCKETS)
        return _KINDS[self.kind]()

    def labels(self, **kv):
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"{self.name} takes labels {self.labelnames}, got "
                f"{tuple(kv)}")
        key = tuple(str(kv[n]) for n in self.labelnames)
        # resolution path, not the record path (record sites hold the
        # resolved child): always lock — the unlocked-get fast path read
        # _children while another thread's setdefault mutated it
        with self._lock:
            return self._children.setdefault(key, self._make())

    def children(self) -> list[tuple[tuple[str, ...], object]]:
        with self._lock:
            return sorted(self._children.items())

    def _merge_instances(self, insts: list):
        """Aggregate a group of children: counters/gauges sum; histograms
        merge per-bucket counts EXACTLY (every child shares the family's
        bucket bounds) and summarise the merged distribution — so the
        merged view quantizes identically to a single child's summary."""
        if self.kind != "histogram":
            return sum(inst.value for inst in insts)
        merged: list[int] | None = None
        msum, mtotal = 0.0, 0
        for inst in insts:
            counts, s, total = inst.snapshot()
            merged = (counts if merged is None
                      else [a + b for a, b in zip(merged, counts)])
            msum += s
            mtotal += total
        out = {"count": mtotal, "sum": round(msum, 6)}
        if mtotal and merged is not None:
            bounds = tuple(float(b) for b in
                           (self._buckets or DEFAULT_LATENCY_BUCKETS))
            out["p50"] = round(
                _estimate_quantile(bounds, merged, mtotal, 0.5), 6)
            out["p99"] = round(
                _estimate_quantile(bounds, merged, mtotal, 0.99), 6)
        return out

    def aggregate_over(self, label: str) -> dict:
        """Aggregates with ``label`` summed out, keyed by the residual
        label string (``""`` when ``label`` is the only one). Summing a
        SPECIFIC label keeps the residual series meaningful — e.g.
        ``serve_requests_total{outcome=,replica=}`` aggregated over
        ``replica`` yields per-``outcome`` fleet totals, exactly the key
        shapes consumers used before the ``replica`` label existed —
        whereas a blind all-children sum would fold unrelated label
        values (states, outcomes) into one meaningless number."""
        if label not in self.labelnames:
            return {}
        idx = self.labelnames.index(label)
        residual = tuple(n for n in self.labelnames if n != label)
        groups: dict[tuple[str, ...], list] = {}
        for key, inst in self.children():
            rkey = tuple(v for i, v in enumerate(key) if i != idx)
            groups.setdefault(rkey, []).append(inst)
        return {_labelstr(residual, rkey): self._merge_instances(insts)
                for rkey, insts in groups.items()}

    def snapshot_delta(self, cursor: dict | None = None):
        """Windowed (delta-since-``cursor``) view of this family, merged
        across every labelled child. Returns ``(view, new_cursor)`` —
        pass the returned cursor back to the next call to advance the
        window; ``None`` means "since registration".

        The registry's instruments are CUMULATIVE over the process life,
        so any consumer reacting to ``summary()`` reacts to boot-time
        history: a controller watching lifetime p99s would still see
        yesterday's burst. The delta view subtracts the cursor's bucket
        counts per child before merging, so the estimated quantiles
        describe ONLY the samples recorded inside the window — the
        recent-biased signal the serve autotuner steers on.

        View shapes: histograms → ``{count, sum[, p50, p99]}`` over the
        delta distribution; counters → the float increment over the
        window; gauges → the current summed level (a gauge is a level,
        not a flow — there is no meaningful delta). Each consumer holds
        its own cursor, so independent readers never reset each other
        (unlike a read-and-clear API)."""
        children = self.children()
        prev = cursor or {}
        new_cursor: dict = {}
        if self.kind == "histogram":
            bounds = tuple(float(b) for b in
                           (self._buckets or DEFAULT_LATENCY_BUCKETS))
            merged = [0] * (len(bounds) + 1)
            msum, mtotal = 0.0, 0
            for key, inst in children:
                counts, s, total = inst.snapshot()
                new_cursor[key] = (list(counts), s, total)
                pc = prev.get(key)
                if pc is not None:
                    counts = [a - b for a, b in zip(counts, pc[0])]
                    s -= pc[1]
                    total -= pc[2]
                merged = [a + b for a, b in zip(merged, counts)]
                msum += s
                mtotal += total
            out: dict = {"count": mtotal, "sum": round(msum, 6)}
            if mtotal:
                out["p50"] = round(
                    _estimate_quantile(bounds, merged, mtotal, 0.5), 6)
                out["p99"] = round(
                    _estimate_quantile(bounds, merged, mtotal, 0.99), 6)
            return out, new_cursor
        total = 0.0
        for key, inst in children:
            v = inst.value
            new_cursor[key] = v
            if self.kind == "counter":
                total += v - prev.get(key, 0.0)
            else:  # gauge: a level, reported as-is
                total += v
        return total, new_cursor

    # -- label-less convenience (delegates to the anonymous child) -------

    def inc(self, amount: float = 1.0) -> None:
        self.labels().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self.labels().dec(amount)

    def set(self, value: float) -> None:
        self.labels().set(value)

    def observe(self, value: float) -> None:
        self.labels().observe(value)

    def snapshot(self):
        return self.labels().snapshot()

    def quantile(self, q: float) -> float:
        return self.labels().quantile(q)

    @property
    def value(self) -> float:
        return self.labels().value

    def summary(self) -> dict:
        return self.labels().summary()


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    if v != v:
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _labelstr(names: tuple[str, ...], values: tuple[str, ...],
              extra: tuple[tuple[str, str], ...] = ()) -> str:
    parts = [f'{n}="{_escape_label(v)}"' for n, v in zip(names, values)]
    parts += [f'{n}="{_escape_label(v)}"' for n, v in extra]
    return "{" + ",".join(parts) + "}" if parts else ""


class MetricsRegistry:
    """A namespace of metric families. One process-wide default lives at
    ``obs.REGISTRY``; components accept a registry parameter so tests and
    benchmarks can scope measurements to one server."""

    def __init__(self):
        self._families: dict[str, _Family] = {}
        self._lock = threading.Lock()

    def _family(self, kind: str, name: str, help_: str,
                labelnames: tuple[str, ...], buckets=None) -> _Family:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}{fam.labelnames}, cannot re-register "
                        f"as {kind}{tuple(labelnames)}")
                if (kind == "histogram"
                        and tuple(float(b) for b in buckets)
                        != tuple(float(b) for b in fam._buckets)):
                    # silently folding a caller's observations into buckets
                    # it didn't ask for would quantize its quantiles to the
                    # wrong resolution — same one-name-one-meaning rule
                    raise ValueError(
                        f"histogram {name!r} already registered with "
                        f"buckets {fam._buckets}, cannot re-register with "
                        f"{tuple(buckets)}")
                return fam
            fam = _Family(kind, name, help_, tuple(labelnames), buckets)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labelnames: tuple[str, ...] = ()) -> _Family:
        return self._family("counter", name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: tuple[str, ...] = ()) -> _Family:
        return self._family("gauge", name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  buckets: tuple[float, ...] = DEFAULT_LATENCY_BUCKETS,
                  labelnames: tuple[str, ...] = ()) -> _Family:
        return self._family("histogram", name, help, labelnames, buckets)

    def families(self) -> list[_Family]:
        with self._lock:
            return [self._families[k] for k in sorted(self._families)]

    # -- output surfaces -------------------------------------------------

    def render_prometheus(self) -> str:
        """Prometheus text exposition (format 0.0.4): histograms as
        cumulative ``_bucket{le=}`` series + ``_sum``/``_count``."""
        lines: list[str] = []
        for fam in self.families():
            children = fam.children()
            if not children:
                continue
            if fam.help:
                lines.append(f"# HELP {fam.name} {fam.help}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for key, inst in children:
                if fam.kind != "histogram":
                    lines.append(
                        f"{fam.name}{_labelstr(fam.labelnames, key)} "
                        f"{_fmt_value(inst.value)}")
                    continue
                counts, s, total = inst.snapshot()
                cum = 0
                for bound, c in zip(inst.buckets, counts):
                    cum += c
                    ls = _labelstr(fam.labelnames, key,
                                   (("le", _fmt_value(bound)),))
                    lines.append(f"{fam.name}_bucket{ls} {cum}")
                ls = _labelstr(fam.labelnames, key, (("le", "+Inf"),))
                lines.append(f"{fam.name}_bucket{ls} {total}")
                ls = _labelstr(fam.labelnames, key)
                lines.append(f"{fam.name}_sum{ls} {_fmt_value(s)}")
                lines.append(f"{fam.name}_count{ls} {total}")
        return "\n".join(lines) + "\n"

    def summaries(self) -> dict:
        """JSON-ready view for ``/stats``: counters/gauges as values,
        histograms as {count, sum, p50, p99}. ``replica``-labelled
        families ALSO export aggregates with the replica label summed
        out, under the residual-label keys (the bare family name for
        replica-only families) — so consumers keyed on
        ``serve_ttft_seconds`` or ``serve_requests_total{outcome="..."}``
        keep working when a family grows the ``replica`` label, and the
        per-child ``name{...,replica="r"}`` entries carry the split."""
        out: dict = {}
        for fam in self.families():
            children = fam.children()
            for key, inst in children:
                name = fam.name + _labelstr(fam.labelnames, key)
                out[name] = (inst.summary() if fam.kind == "histogram"
                             else inst.value)
            if "replica" in fam.labelnames and children:
                for suffix, val in fam.aggregate_over("replica").items():
                    out[fam.name + suffix] = val
        return out

    def snapshot(self) -> dict:
        """Flat {metric: number} for one JSONL record (histograms expand
        to _count/_sum/_p50/_p99 keys)."""
        out: dict = {}
        for fam in self.families():
            for key, inst in fam.children():
                name = fam.name + _labelstr(fam.labelnames, key)
                if fam.kind != "histogram":
                    out[name] = inst.value
                    continue
                s = inst.summary()
                out[name + "_count"] = s["count"]
                out[name + "_sum"] = s["sum"]
                if "p50" in s:
                    out[name + "_p50"] = s["p50"]
                    out[name + "_p99"] = s["p99"]
        return out


class _NullInstrument:
    """No-op counter/gauge/histogram: the disabled-telemetry fast path."""

    __slots__ = ()

    def inc(self, amount: float = 1.0) -> None:
        pass

    def dec(self, amount: float = 1.0) -> None:
        pass

    def set(self, value: float) -> None:
        pass

    def observe(self, value: float) -> None:
        pass

    def labels(self, **kv):
        return self

    def aggregate_over(self, label: str) -> dict:
        # mirrors _Family.aggregate_over for disabled telemetry: the
        # router reads the queue-wait p99 through this to size Retry-After
        return {}

    def snapshot_delta(self, cursor: dict | None = None):
        # mirrors _Family.snapshot_delta: a histogram-shaped empty window
        # — a controller on a --telemetry off stack sees zero traffic and
        # never moves a knob (the CLI refuses the combination anyway)
        return {"count": 0, "sum": 0.0}, {}

    @property
    def value(self) -> float:
        return 0.0

    def summary(self) -> dict:
        return {}


_NULL = _NullInstrument()


class NullRegistry:
    """Registry that hands out no-op instruments (``--telemetry off``)."""

    def counter(self, *a, **k):
        return _NULL

    def gauge(self, *a, **k):
        return _NULL

    def histogram(self, *a, **k):
        return _NULL

    def families(self):
        return []

    def render_prometheus(self) -> str:
        return "# telemetry disabled\n"

    def summaries(self) -> dict:
        return {}

    def snapshot(self) -> dict:
        return {}


#: the process-wide default registry (train loop, supervisor, and any
#: component not given an explicit one record here)
REGISTRY = MetricsRegistry()
#: shared no-op registry for disabled telemetry
NULL_REGISTRY = NullRegistry()


# ---- exposition validation (the format contract, executable) -----------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"          # metric name
    r"(?:\{(.*)\})?"                          # optional label block
    r" (-?(?:[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?)|NaN|[+-]Inf)$")
_LABEL_PAIR_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _parse_number(s: str) -> float:
    if s == "NaN":
        return float("nan")
    if s == "+Inf":
        return float("inf")
    if s == "-Inf":
        return float("-inf")
    return float(s)


def parse_exposition(text: str) -> dict:
    """Parse + validate Prometheus text exposition. Returns
    ``{family_name: {"type": kind, "samples": [(name, labels, value)]}}``
    and raises ``ValueError`` on any format violation: unparseable lines,
    samples without a ``# TYPE``, non-monotonic histogram buckets, a
    missing/mismatched ``+Inf`` bucket, or ``_count`` disagreeing with it.
    """
    types: dict[str, str] = {}
    fams: dict[str, dict] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            parts = line.split(" ", 3)
            if len(parts) != 4 or parts[3] not in _KINDS:
                raise ValueError(f"line {lineno}: bad TYPE line {line!r}")
            types[parts[2]] = parts[3]
            fams.setdefault(parts[2], {"type": parts[3], "samples": []})
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {lineno}: unparseable sample {line!r}")
        name, labelblock, value = m.group(1), m.group(2), m.group(3)
        labels: dict[str, str] = {}
        if labelblock:
            consumed = sum(
                len(p.group(0)) for p in _LABEL_PAIR_RE.finditer(labelblock))
            n_pairs = len(_LABEL_PAIR_RE.findall(labelblock))
            # every char must belong to a pair or a separating comma
            if consumed + max(n_pairs - 1, 0) != len(labelblock):
                raise ValueError(
                    f"line {lineno}: bad label block {{{labelblock}}}")
            labels = dict(_LABEL_PAIR_RE.findall(labelblock))
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            stripped = name[: -len(suffix)] if name.endswith(suffix) else None
            if stripped and types.get(stripped) == "histogram":
                base = stripped
                break
        if base not in types:
            raise ValueError(
                f"line {lineno}: sample {name!r} has no # TYPE declaration")
        fams[base]["samples"].append((name, labels, _parse_number(value)))

    for fname, fam in fams.items():
        if fam["type"] != "histogram":
            continue
        # group bucket series by their non-le labelset
        series: dict[tuple, dict] = {}
        for name, labels, value in fam["samples"]:
            key = tuple(sorted((k, v) for k, v in labels.items()
                               if k != "le"))
            s = series.setdefault(key, {"buckets": [], "sum": None,
                                        "count": None})
            if name == fname + "_bucket":
                if "le" not in labels:
                    raise ValueError(f"{fname}: bucket sample without le=")
                s["buckets"].append((_parse_number(labels["le"]), value))
            elif name == fname + "_sum":
                s["sum"] = value
            elif name == fname + "_count":
                s["count"] = value
        for key, s in series.items():
            if not s["buckets"]:
                raise ValueError(f"{fname}{dict(key)}: no bucket samples")
            bounds = [b for b, _ in s["buckets"]]
            counts = [c for _, c in s["buckets"]]
            if bounds != sorted(bounds) or len(set(bounds)) != len(bounds):
                raise ValueError(f"{fname}{dict(key)}: le bounds not "
                                 "strictly increasing")
            if counts != sorted(counts):
                raise ValueError(f"{fname}{dict(key)}: cumulative bucket "
                                 f"counts decrease: {counts}")
            if bounds[-1] != float("inf"):
                raise ValueError(f"{fname}{dict(key)}: missing +Inf bucket")
            if s["count"] is None or s["sum"] is None:
                raise ValueError(f"{fname}{dict(key)}: missing _sum/_count")
            if s["count"] != counts[-1]:
                raise ValueError(
                    f"{fname}{dict(key)}: _count {s['count']} != +Inf "
                    f"bucket {counts[-1]}")
    return fams
