"""Corpus loading and vocabularies.

Reference parity: SURVEY.md §2 "Data pipeline" [P][I] — the reference loads a
text corpus into an RDD and tokenizes/vectorizes into (seq, label) pairs.
Here loading is host-side numpy (the RDD partitioning job is replaced by
device sharding in parallel/), with char- and word-level vocabularies.

No-network environment (SURVEY.md §7): real corpora (PTB/WikiText/IMDB)
cannot be downloaded, so every loader falls back to a deterministic synthetic
stand-in with the same interface; pointing ``data_path`` at real files uses
them unchanged.
"""

from __future__ import annotations

import os

import numpy as np

# Seed paragraph for the synthetic corpus generator: public-domain text
# (Lincoln, Gettysburg Address) — gives the Markov chain English-like
# structure so a language model has something learnable to fit.
_SEED_TEXT = """
four score and seven years ago our fathers brought forth on this continent a
new nation conceived in liberty and dedicated to the proposition that all men
are created equal now we are engaged in a great civil war testing whether that
nation or any nation so conceived and so dedicated can long endure we are met
on a great battle field of that war we have come to dedicate a portion of that
field as a final resting place for those who here gave their lives that that
nation might live it is altogether fitting and proper that we should do this
but in a larger sense we can not dedicate we can not consecrate we can not
hallow this ground the brave men living and dead who struggled here have
consecrated it far above our poor power to add or detract the world will
little note nor long remember what we say here but it can never forget what
they did here it is for us the living rather to be dedicated here to the
unfinished work which they who fought here have thus far so nobly advanced
"""


class Vocab:
    """Token ↔ id mapping. Reserved id 0 = <pad>, id 1 = <unk>."""

    PAD, UNK = 0, 1

    def __init__(self, tokens: list[str], *, reserve_special: bool = True):
        specials = ["<pad>", "<unk>"] if reserve_special else []
        self.itos = specials + [t for t in tokens if t not in ("<pad>", "<unk>")]
        self.stoi = {t: i for i, t in enumerate(self.itos)}

    def __len__(self) -> int:
        return len(self.itos)

    def encode(self, tokens) -> np.ndarray:
        unk = self.stoi.get("<unk>", 0)
        return np.asarray([self.stoi.get(t, unk) for t in tokens], dtype=np.int32)

    def encode_text(self, text: str, level: str) -> np.ndarray:
        """Encode raw text at "char" or "word" level — native C++ fast path
        (data/native.py) with pure-Python fallback."""
        from . import native

        unk = self.stoi.get("<unk>", 0)
        if level == "char":
            return native.encode_chars(text, self.stoi, unk)
        n_special = sum(1 for t in self.itos if t in ("<pad>", "<unk>"))
        return native.encode_words(
            text, self.itos[n_special:], unk, id_base=n_special
        )

    def decode(self, ids) -> list[str]:
        return [self.itos[int(i)] for i in ids]


def build_char_vocab(text: str) -> Vocab:
    return Vocab(sorted(set(text)))


def build_word_vocab(text: str, max_size: int | None = None) -> Vocab:
    """Most-common-first word vocabulary — native C++ count+sort fast path
    (data/native.py `most_common_words`) with Counter fallback, identical
    ordering."""
    from . import native

    words = native.most_common_words(text, max_size - 2 if max_size else None)
    return Vocab(words)


def load_text(path: str) -> str:
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        return f.read()


def synthetic_word_corpus(n_tokens: int, vocab_size: int, seed: int = 0,
                          *, noise: float = 0.05, branch: int = 20) -> str:
    """Controlled-entropy pseudo-word stream for DISCRIMINATING quality
    races (VERDICT r3 weak 2: the seed-paragraph chain has ~113 distinct
    words, so word-LM stand-ins saturated within ~40 steps and the race
    measured launch costs, not training).

    Structure: ``vocab_size`` pseudo-words with a Zipfian unigram law;
    each word gets a ``branch``-wide successor table (drawn from the
    unigram law), successors picked with a geometric bias; with
    probability ``noise`` the next word is instead a fresh unigram draw.
    A model descends in stages — uniform (ppl ~V) → unigram law →
    bigram structure (the V x branch transition tables) — and the last
    stage is large enough that the eval curve keeps falling across
    hundreds of optimizer steps instead of plateauing at step ~20.
    Deterministic per (n_tokens, vocab_size, seed, noise, branch)."""
    rng = np.random.RandomState(seed)
    ranks = np.arange(1, vocab_size + 1, dtype=np.float64)
    uni = 1.0 / ranks
    uni /= uni.sum()
    succ = rng.choice(vocab_size, size=(vocab_size, branch), p=uni)
    sp = 0.5 ** np.arange(branch, dtype=np.float64)
    sp /= sp.sum()
    choice_cols = rng.choice(branch, size=n_tokens, p=sp)
    noise_mask = rng.rand(n_tokens) < noise
    noise_draws = rng.choice(vocab_size, size=n_tokens, p=uni)
    succ_rows = succ.tolist()  # python lists: ~10x faster scalar indexing
    cols = choice_cols.tolist()
    nmask = noise_mask.tolist()
    ndraw = noise_draws.tolist()
    out = [0] * n_tokens
    cur = 0
    for t in range(n_tokens):
        cur = ndraw[t] if nmask[t] else succ_rows[cur][cols[t]]
        out[t] = cur
    words = [f"w{i:05d}" for i in range(vocab_size)]
    return " ".join(words[i] for i in out)


def synthetic_text(n_tokens: int, seed: int = 0) -> str:
    """Deterministic English-like word stream via a bigram Markov chain over
    the embedded seed paragraph."""
    words = _SEED_TEXT.split()
    successors: dict[str, list[str]] = {}
    for a, b in zip(words[:-1], words[1:]):
        successors.setdefault(a, []).append(b)
    rng = np.random.RandomState(seed)
    out = [words[0]]
    for _ in range(n_tokens - 1):
        nxt = successors.get(out[-1])
        if not nxt:
            nxt = words
        out.append(nxt[rng.randint(len(nxt))])
    return " ".join(out)


def resolve_split_files(data_path: str, basenames: list[str]) -> dict[str, str] | None:
    """Find train/valid/test files under data_path matching any of the
    conventional naming schemes; None if absent."""
    if not data_path or not os.path.isdir(data_path):
        return None
    for pattern in ("{b}.{s}.txt", "{s}.txt", "{b}.{s}.tokens"):
        for b in basenames:
            files = {
                s: os.path.join(data_path, pattern.format(b=b, s=s))
                for s in ("train", "valid", "test")
            }
            if all(os.path.isfile(p) for p in files.values()):
                return files
    return None
